#!/usr/bin/env python
"""ffobs — render flexflow_tpu telemetry (JSONL event logs) as a
strategy-explanation report.

The obs event bus (flexflow_tpu/obs, enabled via FLEXFLOW_TPU_OBS or
FFConfig.obs_log_file / --obs-log) records why the search chose what
it chose — substitutions applied/rejected, DP splits and memo hit
rates, the champion-vs-DP floor decision, the final per-node view
table with its predicted compute/sync breakdown — and what execution
then measured (profile summaries, predicted-vs-measured DriftReports).
This tool turns that log back into something a human debugs with.

Stdlib-only on the hot path (no jax import), so it runs anywhere the
log file lands.

Usage:
  ffobs.py report <log.jsonl> [--top N]   strategy-explanation report
  ffobs.py validate <log.jsonl>           schema-check every line
  ffobs.py metrics <log.jsonl>            Prometheus text from the
                                          last metrics.snapshot event
  ffobs.py trace <log.jsonl>              render request/episode span
                                          trees (also reads
                                          flight-recorder dumps)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter, defaultdict
from typing import Dict, List, Optional

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def read_events(path: str) -> List[dict]:
    events = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{lineno}: not JSON: {e}")
    return events


def _ms(v: Optional[float]) -> str:
    if v is None:
        return "—"
    try:
        if v != v or v in (float("inf"), float("-inf")):
            return str(v)
        return f"{v * 1e3:.4f}"
    except TypeError:
        return str(v)


def _view_str(view: dict) -> str:
    dims = "x".join(str(d) for d in view.get("dims", []))
    s = dims or "1"
    if view.get("replica", 1) != 1:
        s += f" r{view['replica']}"
    if view.get("start", 0):
        s += f" @{view['start']}"
    return s


def last_run(events: List[dict]) -> List[dict]:
    """Events of the most recent run only: the JSONL sink appends
    across runs (crash-safe), and each run opens with an ``obs.meta``
    — counting sections would otherwise aggregate every past run."""
    for i in range(len(events) - 1, -1, -1):
        if events[i].get("kind") == "obs.meta":
            return events[i:]
    return events


def render_report(events: List[dict], top: int = 10,
                  all_runs: bool = False) -> str:
    runs = sum(1 for e in events if e.get("kind") == "obs.meta")
    if not all_runs:
        events = last_run(events)
    lines: List[str] = ["# ffobs strategy-explanation report", ""]
    if runs > 1:
        lines.append(
            f"({runs} runs in this log; reporting "
            + ("ALL of them summed" if all_runs else "the LAST only —")
            + (" use --all-runs for the aggregate)" if not all_runs
               else ")"))
        lines.append("")

    # ---- search outer loop ------------------------------------------------
    begins = [e for e in events if e.get("kind") == "search.begin"]
    baselines = [e for e in events if e.get("kind") == "search.baseline"]
    results = [e for e in events if e.get("kind") == "search.result"]
    floors = [e for e in events if e.get("kind") == "search.floor"]
    if begins:
        b = begins[-1]
        lines.append(
            f"Search: {b.get('nodes')} nodes on {b.get('devices')} devices "
            f"(budget {b.get('budget')}, timeout {b.get('timeout_s')}s, "
            f"calibrated={b.get('calibrated')})"
        )
    if baselines:
        lines.append(
            f"Baseline DP-search cost: {_ms(baselines[-1].get('cost_s'))} ms")
    if floors:
        fl = floors[-1]
        verdict = ("kept plain data parallelism (win below margin)"
                   if fl.get("kept_dp") else "accepted searched strategy")
        lines.append(
            f"Champion-vs-DP floor: {verdict} — DP "
            f"{_ms(fl.get('dp_cost_s'))} ms vs searched "
            f"{_ms(fl.get('searched_cost_s'))} ms"
        )
    if results:
        r = results[-1]
        lines.append(
            f"Result: {_ms(r.get('cost_s'))} ms/iter, "
            f"rewritten={r.get('rewritten')}, {r.get('nodes')} nodes"
        )
    lines.append("")

    # ---- substitution provenance -----------------------------------------
    subs = [e for e in events if e.get("kind") == "search.substitution"]
    if subs:
        by_action = Counter(e.get("action") for e in subs)
        lines.append(
            "Substitution candidates: "
            + ", ".join(f"{a}={n}" for a, n in sorted(by_action.items()))
        )
        by_xfer = defaultdict(Counter)
        for e in subs:
            by_xfer[e.get("xfer")][e.get("action")] += 1
        pushed = sorted(
            by_xfer.items(), key=lambda kv: -kv[1].get("pushed", 0))
        shown = [x for x in pushed if x[1].get("pushed")][:top]
        if shown:
            lines.append("Top pushed rewrites:")
            for name, actions in shown:
                lines.append(
                    f"  {name}: pushed={actions.get('pushed', 0)} "
                    f"pruned={actions.get('pruned', 0)} "
                    f"duplicate={actions.get('duplicate', 0)}"
                )
    cands = [e for e in events if e.get("kind") == "search.candidate"]
    if cands:
        improved = sum(1 for e in cands if e.get("improved"))
        lines.append(
            f"Fully-costed candidates: {len(cands)} ({improved} improved "
            f"the champion)"
        )
    splits = [e for e in events if e.get("kind") in ("search.split", "dp.split")]
    if splits:
        ops = Counter(e.get("op") for e in splits)
        lines.append(
            "Split points: "
            + ", ".join(f"{op} x{n}" for op, n in ops.most_common(top))
        )
    dpsum = [e for e in events if e.get("kind") == "dp.summary"]
    if dpsum:
        d = dpsum[-1]
        hits, misses = d.get("memo_hits", 0), d.get("memo_misses", 0)
        rate = hits / max(1, hits + misses)
        lines.append(
            f"DP memo: {hits} hits / {misses} misses ({rate:.0%} hit rate), "
            f"native={d.get('native_hits', 0)}, "
            f"greedy-fallbacks={d.get('greedy_hits', 0)}"
        )
    perf = [e for e in events if e.get("kind") == "search.perf"]
    if perf:
        p = perf[-1]
        ds, fs = p.get("delta_sims", 0), p.get("full_sims", 0)
        drate = ds / max(1, ds + fs)
        rh = p.get("cache_row_hits", 0)
        rm = p.get("cache_row_misses", 0)
        line = (
            f"Search perf: {p.get('search_seconds')}s search + "
            f"{p.get('calibration_seconds')}s calibration; "
            f"{len(cands)} candidates fully costed; simulations: "
            f"{ds} delta / {fs} full ({drate:.0%} delta-served, "
            f"{p.get('delta_bails', 0)} bails)"
        )
        if rh + rm:
            line += (f"; cost-cache rows: {rh}/{rh + rm} hits "
                     f"({rh / (rh + rm):.0%})")
        if p.get("result_cache_hit"):
            line += "; RESULT served from the persistent cost cache"
        lines.append(line)
        cp, cr = p.get("ctx_patch_hits", 0), p.get("ctx_rebuilds", 0)
        if cp + cr:
            lines.append(
                f"Native DP ctx assembly: {cp} patched from the parent's "
                f"ctx / {cr} full rebuilds "
                f"({cp / max(1, cp + cr):.0%} incremental)")
        stamped = p.get("segments_stamped", 0)
        served = p.get("dp_rows_served", 0)
        if stamped or served:
            lines.append(
                f"Segment reuse: {stamped} isomorphic segments stamped "
                f"(lint-gated), {served} tier-2 DP results served from "
                f"persisted memo rows")
        md = p.get("match_delta_scans", 0)
        if md:
            scanned = p.get("match_nodes_rescanned", 0)
            skipped = p.get("match_nodes_skipped", 0)
            denom = max(1, scanned + skipped)
            lines.append(
                f"Delta matching: {md} dirty-region rescans / "
                f"{p.get('match_full_scans', 0)} full scans; "
                f"{scanned} nodes rescanned, {skipped} served from the "
                f"parent ({skipped / denom:.0%} of match work skipped)")
        mi = p.get("match_index_skips", 0)
        if mi:
            lines.append(
                f"Match seed index: {mi} matcher calls skipped (node op "
                f"type cannot anchor the pattern)")
        mv = p.get("match_vec_skips", 0)
        if mv:
            lines.append(
                f"Vectorized matcher: {mv} matcher calls pruned by the "
                f"numpy predicate filters before the python matcher ran")
        mw = p.get("match_worker_batches", 0)
        if mw:
            lines.append(
                f"Match workers: {mw} full-scan sweeps dispatched to the "
                f"process pool (FLEXFLOW_TPU_MATCH_WORKERS)")
        sps = p.get("sp_rows_served", 0)
        if sps:
            lines.append(
                f"SP segment memo: {sps} whole-segment solves served "
                f"from persisted sp-rows (re-linted before serving)")
        cps = p.get("comm_plan_serves")
        cpr = p.get("comm_plan_searches")
        if cps is not None:
            total = max(1, (cps or 0) + (cpr or 0))
            lines.append(
                f"Co-search comm plans: {cps} served from the "
                f"signature memo / {cpr} re-searched "
                f"({(cps or 0) / total:.0%} serve rate) — every "
                f"candidate priced with its best sync "
                f"schedule/precision/zero plan")
    # series-parallel decomposition decisions (search.decompose): one
    # line per oversized (sub)graph — a fallback to binary recursion is
    # REPORTED here instead of being a mystery slowdown
    decos = [e for e in events if e.get("kind") == "search.decompose"]
    for e in decos:
        mode = e.get("mode")
        if mode == "fallback":
            lines.append(
                f"Decomposition: {e.get('nodes')} nodes FELL BACK to "
                f"binary recursion (reason: {e.get('reason')}) — no "
                f"bounded-width series cuts")
        else:
            lines.append(
                f"Decomposition: {e.get('nodes')} nodes via "
                f"{'bottleneck chain (width-1)' if mode == 'chain' else 'series-parallel frontier cuts'} "
                f"— {e.get('cuts')} cuts (max width "
                f"{e.get('max_width')}), {e.get('segments')} segments "
                f"(largest {e.get('max_segment')})")
    dones = [e for e in events if e.get("kind") == "search.decompose_done"]
    if dones:
        d = dones[-1]
        lines.append(
            f"Decomposition result ({d.get('mode')}): DP bound "
            f"{_ms(d.get('bound_s'))} ms -> merged+simulated "
            f"{_ms(d.get('cost_s'))} ms over {d.get('segments')} segments")
    # per-candidate comm-plan decision lines (search.comm_plan events):
    # one roll-up by source so a chatty search stays one line each
    plans = [e for e in events if e.get("kind") == "search.comm_plan"]
    if plans:
        from collections import Counter as _Counter

        by_src = _Counter(e.get("source", "?") for e in plans)
        adopted = sum(1 for e in plans
                      if not e.get("served") and e.get("adopted"))
        lines.append(
            f"Comm-plan decisions: "
            + ", ".join(f"{src} x{n}" for src, n in by_src.most_common())
            + (f"; {adopted} fresh searches adopted bucketing"
               if adopted else ""))
    zg = [e for e in events if e.get("kind") == "search.zero_groups"]
    if zg and zg[-1].get("groups"):
        z = zg[-1]
        lines.append(
            f"Optimizer-state sharding (ZeRO-1, per-group): "
            f"{len(z['groups'])} group(s) "
            f"[{', '.join(z['groups'][:6])}"
            + ("…" if len(z["groups"]) > 6 else "")
            + f"] — credited {_ms(z.get('credit_s'))} ms/iter update win")
    lines.append("")

    # ---- strategy table ---------------------------------------------------
    # prefer the last JOINT-SEARCH table: bench runs also compile
    # forced-DP baselines and sweep variants after the searched program
    tables = [e for e in events if e.get("kind") == "strategy.table"]
    searched_tables = [e for e in tables if e.get("searched")]
    table = (searched_tables or tables)[-1] if tables else None
    rows = table.get("rows", []) if table else []
    if not rows and results:
        rows = results[-1].get("table", []) or []
    if rows:
        lines.append(
            f"## Chosen strategy ({len(rows)} ops, predicted "
            f"{_ms(table.get('predicted_s')) if table else '—'} ms/iter"
            + (f", {len(tables)} strategies compiled this run"
               if len(tables) > 1 else "")
            + ")"
        )
        lines.append("")
        lines.append("| op | type | view | fwd ms | full ms | sync ms | "
                     "sync precision |")
        lines.append("|---|---|---|---|---|---|---|")
        for row in rows:
            lines.append(
                f"| {row.get('op')} | {row.get('type')} | "
                f"{_view_str(row.get('view', {}))} | "
                f"{_ms(row.get('fwd_s'))} | {_ms(row.get('full_s'))} | "
                f"{_ms(row.get('sync_s'))} | "
                f"{row.get('sync_precision', '—')} |"
            )
        lines.append("")
        costly = sorted(
            (r for r in rows if isinstance(r.get("full_s"), (int, float))),
            key=lambda r: -r["full_s"])[:top]
        if costly:
            lines.append(
                "Top predicted-cost ops (the drift candidates to check "
                "first when measured steps run slow):")
            for r in costly:
                lines.append(
                    f"  {r['op']}: {_ms(r['full_s'])} ms compute + "
                    f"{_ms(r.get('sync_s'))} ms sync "
                    f"[{_view_str(r.get('view', {}))}]"
                )
        lines.append("")

    # ---- runtime: profile + drift ----------------------------------------
    profs = [e for e in events if e.get("kind") == "profile.summary"]
    if profs:
        p = profs[-1]
        note = " (INCLUDES COMPILE STEP)" if p.get("includes_compile") else ""
        lines.append(
            f"Measured steps: {p.get('steps')}  mean "
            f"{_ms(p.get('mean_s'))} ms  p95 {_ms(p.get('p95_s'))} ms{note}"
        )
    drifts = [e for e in events if e.get("kind") == "drift.report"]
    if drifts:
        d = drifts[-1]
        lines.append("")
        lines.append("## Drift (predicted vs measured)")
        lines.append("")
        flag = (" — CALIBRATION STALE" if d.get("calibration_stale")
                else " — STALE" if d.get("stale") else "")
        lines.append(
            f"Step: predicted {_ms(d.get('predicted_s'))} ms, measured "
            f"{_ms(d.get('measured_s'))} ms, ratio "
            f"{d.get('ratio'):.2f}{flag}"
        )
        phases = d.get("phases", {})
        if phases:
            lines.append("| phase | predicted ms | measured ms | ratio |")
            lines.append("|---|---|---|---|")
            for k, v in phases.items():
                r = v.get("ratio")
                lines.append(
                    f"| {k} | {_ms(v.get('predicted_s'))} | "
                    f"{_ms(v.get('measured_s'))} | "
                    f"{f'{r:.2f}' if isinstance(r, (int, float)) else '—'} |"
                )
        buckets = d.get("sync_buckets") or []
        if buckets:
            measured_any = any(
                b.get("measured_s") is not None for b in buckets)
            lines.append("")
            lines.append(
                "Sync-schedule buckets (predicted lanes"
                + (", measured side from a tag-matched device-trace "
                   "capture)" if measured_any else
                   "; measured side None until a device_trace capture "
                   "is tag-matched — obs/trace_ingest.py):"))
            lines.append(
                "| bucket | groups | precision | plan | issue-ready ms | "
                "sync ms | exposed ms | measured issue ms | "
                "measured sync ms | per-level ms |")
            lines.append("|---|---|---|---|---|---|---|---|---|---|")
            for b in buckets:
                lv = b.get("predicted_levels_s") or {}
                lv_cell = " ".join(
                    f"{k}={_ms(v)}" for k, v in lv.items()) or "—"
                lines.append(
                    f"| {b.get('name')} | {b.get('ops')} | "
                    f"{b.get('precision')} | "
                    f"{b.get('plan') or 'flat'} | "
                    f"{_ms(b.get('predicted_ready_s'))} | "
                    f"{_ms(b.get('predicted_sync_s'))} | "
                    f"{_ms(b.get('predicted_exposed_s'))} | "
                    f"{_ms(b.get('measured_issue_s'))} | "
                    f"{_ms(b.get('measured_s'))} | "
                    f"{lv_cell} |")
        # only the aggregate step has both sides (single-sided phases
        # carry no ratio by design); rank the measured host phases by
        # their share of the step instead to point at where time went
        measured = d.get("measured_s")
        shares = sorted(
            ((k, v["measured_s"]) for k, v in phases.items()
             if k != "step" and isinstance(v.get("measured_s"),
                                           (int, float))),
            key=lambda kv: -kv[1])
        if measured and shares:
            k, v = shares[0]
            lines.append(
                f"Largest measured phase: {k!r} at {_ms(v)} ms "
                f"({v / measured:.0%} of the step)")
    # ---- measured lanes: device-trace ingestion + tag matching ------------
    ingests = [e for e in events if e.get("kind") == "trace.ingest"]
    matches = [e for e in events if e.get("kind") == "trace.lane_match"]
    if ingests or matches:
        lines.append("")
        lines.append("## Measured lanes (device-trace capture)")
        lines.append("")
        if ingests:
            i = ingests[-1]
            lines.append(
                f"Ingested {i.get('path')}: {i.get('events')} trace "
                f"events, {i.get('lanes')} annotated lane(s), "
                f"{i.get('steps')} step window(s)")
        if matches:
            matched = sum(1 for e in matches if e.get("matched"))
            lines.append(
                f"Lane matching (by annotation tag, never kernel "
                f"names): {matched}/{len(matches)} predicted sync "
                f"lanes matched")
            lines.append(
                "| lane | matched | samples | predicted sync ms | "
                "measured sync ms | sync-share ratio |")
            lines.append("|---|---|---|---|---|---|")
            for e in matches:
                r = e.get("sync_frac_ratio")
                lines.append(
                    f"| {e.get('lane')} | "
                    f"{'yes' if e.get('matched') else 'NO'} | "
                    f"{e.get('samples', 0)} | "
                    f"{_ms(e.get('predicted_sync_s'))} | "
                    f"{_ms(e.get('measured_sync_s'))} | "
                    f"{f'{r:.3f}' if isinstance(r, (int, float)) else '—'} |")
            lines.append(
                "(sync-share ratio: each side's lane duration as a "
                "fraction of its own step — the scale-free drift "
                "signal a host-clock capture supports; ICI/DCN wire "
                "behavior stays simulated until a TPU capture)")

    # ---- serving: serve-objective result + decode executor phase ---------
    serves = [e for e in events if e.get("kind") == "search.serve"]
    if serves:
        s = serves[-1]
        budget = s.get("budget_ms") or 0
        kv = s.get("kv_bytes_per_device") or 0
        lines.append("")
        lines.append(
            f"Serve objective: predicted p99 decode step "
            f"{_ms(s.get('p99_s'))} ms"
            + (f" (SLO budget {budget:.3f} ms)" if budget else "")
            + f", KV residency {kv / 1e6:.1f} MB/device"
            + (" — champion-vs-DP floor kept plain DP"
               if s.get("kept_dp") else ""))
    kvs = [e for e in events if e.get("kind") == "search.kv"]
    if kvs:
        k = kvs[-1]
        p99 = k.get("p99_ms") or {}
        priced = ", ".join(f"{d} {v} ms" for d, v in sorted(p99.items()))
        lines.append(
            f"KV lane: pool dtype {k.get('dtype')!r} "
            + ("searched" if k.get("searched") else "pinned")
            + (f" (priced: {priced})" if priced else "")
            + (f"; {k.get('shared_prefix_pages')} shared prefix "
               f"page(s)/seq priced into residency"
               if k.get("shared_prefix_pages") else ""))
    disaggs = [e for e in events if e.get("kind") == "search.disagg"]
    if disaggs:
        d = disaggs[-1]
        verdict = (
            f"ADOPTED prefill[0:{d.get('prefill_devices')}) + "
            f"decode[{d.get('prefill_devices')}:"
            f"{(d.get('prefill_devices') or 0) + (d.get('decode_devices') or 0)})"
            if d.get("adopted") else "colocated stays optimal")
        lines.append(
            f"Disaggregation search: colocated "
            f"{d.get('colocated_ms')} ms vs disaggregated "
            f"{d.get('disagg_ms')} ms per frame (KV handoff "
            f"{d.get('handoff_ms')} ms"
            + (", spans DCN" if d.get("spans_dcn") else "")
            + f") — {verdict}")
    # ---- serving fleet: N-replica search + router + elastic re-size ------
    fleets = [e for e in events if e.get("kind") == "search.fleet"]
    scales = [e for e in events if e.get("kind") == "fleet.scale"]
    routes = [e for e in events if e.get("kind") == "fleet.route"]
    if fleets or scales or routes:
        lines.append("")
        lines.append("## Serving fleet")
        lines.append("")
        if fleets:
            f = fleets[-1]
            verdict = (f"ADOPTED {f.get('replicas')} replica(s) "
                       f"{f.get('partition')} policy "
                       f"{f.get('policy')!r}" if f.get("adopted")
                       else "single replica stays optimal")
            lines.append(
                f"Fleet search: single-replica {f.get('single_ms')} ms "
                f"vs fleet {f.get('fleet_ms')} ms weighted per-class "
                f"p99 (offered load x{f.get('load_scale')}) — "
                f"{verdict}")
            blocks = f.get("blocks") or []
            if blocks:
                lines.append("")
                lines.append("| replica | devices | span | phase split | "
                             "share | slots | step ms |")
                lines.append("|---|---|---|---|---|---|---|")
                for b in blocks:
                    s0 = b.get("start") or 0
                    split = (f"{b.get('prefill_devices')}+"
                             f"{b.get('decode_devices')}"
                             if b.get("prefill_devices") else "colocated")
                    lines.append(
                        f"| {b.get('replica')} | {b.get('devices')} | "
                        f"[{s0}, {s0 + (b.get('devices') or 0)}) | "
                        f"{split} | {b.get('share')} | "
                        f"{b.get('occupancy_slots')} | "
                        f"{b.get('step_ms')} |")
            routing = f.get("routing") or {}
            per_class = f.get("per_class_ms") or {}
            if routing:
                lines.append("")
                lines.append("| SLO class | routing fractions | "
                             "predicted p99 ms |")
                lines.append("|---|---|---|")
                for name, row in sorted(routing.items()):
                    lines.append(f"| {name} | {row} | "
                                 f"{per_class.get(name)} |")
        for e in scales:
            lines.append(
                f"Elastic re-size at step {e.get('step')}: "
                f"{e.get('from_replicas')} -> {e.get('to_replicas')} "
                f"replica(s) at offered load x{e.get('load_scale')}"
                + (" — RESIZED" if e.get("resized") else ""))
        if routes:
            per_rep: Dict[object, int] = {}
            for e in routes:
                per_rep[e.get("replica")] = \
                    per_rep.get(e.get("replica"), 0) + 1
            dist = ", ".join(f"replica {r}: {c}"
                             for r, c in sorted(per_rep.items(),
                                                key=lambda kv: str(kv[0])))
            lines.append(f"Router: {len(routes)} request(s) routed "
                         f"({dist})")
        # measured per-class p99 from the per-request stream — the
        # other side of the search's predicted per-class table
        fin = [e for e in events if e.get("kind") == "decode.request"
               and e.get("phase") == "finish"]
        if fin:
            by_slo: Dict[str, list] = {}
            for e in fin:
                if isinstance(e.get("ttft_s"), (int, float)):
                    by_slo.setdefault(e.get("slo") or "standard",
                                      []).append(float(e["ttft_s"]))
            if by_slo:
                lines.append("")
                lines.append("| SLO class | completions | measured "
                             "TTFT p99 ms |")
                lines.append("|---|---|---|")
                for name, vals in sorted(by_slo.items()):
                    vals.sort()
                    p99 = vals[min(len(vals) - 1,
                                   int(0.99 * (len(vals) - 1)))]
                    lines.append(f"| {name} | {len(vals)} | "
                                 f"{_ms(p99)} |")
    frames = [e for e in events if e.get("kind") == "decode.frame"]
    summaries = [e for e in events if e.get("kind") == "decode.summary"]
    if frames or summaries:
        lines.append("")
        lines.append("## Decode phase (continuous-batching executor)")
        lines.append("")
        if summaries:
            s = summaries[-1]
            lines.append(
                f"{s.get('frames')} frames, {s.get('completed')} "
                f"sequences completed ({s.get('admitted')} admitted / "
                f"{s.get('evicted')} evicted); measured frame latency "
                f"p50 {_ms(s.get('measured_p50_s'))} ms, p99 "
                f"{_ms(s.get('measured_p99_s'))} ms"
                + (f"; predicted {_ms(s.get('predicted_step_s'))} ms"
                   if s.get("predicted_step_s") else ""))
            if s.get("requests_recorded"):
                lines.append(
                    f"Per-request telemetry ({s['requests_recorded']} "
                    f"completions): TTFT p50 {_ms(s.get('ttft_p50_s'))} "
                    f"/ p99 {_ms(s.get('ttft_p99_s'))} ms, TPOT p50 "
                    f"{_ms(s.get('tpot_p50_s'))} / p99 "
                    f"{_ms(s.get('tpot_p99_s'))} ms, e2e p99 "
                    f"{_ms(s.get('e2e_p99_s'))} ms, queue wait p99 "
                    f"{_ms(s.get('queue_p99_s'))} ms")
            if s.get("prefill_p50_s") is not None:
                # the TTFT split (queue + prefill + first decode frame
                # sum to TTFT): which phase the prompt path's cost
                # lives in — the attribution that makes the chunked-
                # prefill win a number per phase, not a vibe
                lines.append(
                    f"TTFT split (p50): queue "
                    f"{_ms(s.get('queue_p50_s'))} + prefill "
                    f"{_ms(s.get('prefill_p50_s'))} + first frame "
                    f"{_ms(s.get('first_frame_p50_s'))} ms "
                    f"(p99: {_ms(s.get('queue_p99_s'))} + "
                    f"{_ms(s.get('prefill_p99_s'))} + "
                    f"{_ms(s.get('first_frame_p99_s'))} ms)")
            if s.get("prefill_chunks"):
                lines.append(
                    f"Chunked prefill lane: {s.get('prefill_tokens')} "
                    f"prompt tokens in {s.get('prefill_chunks')} "
                    f"chunk pass(es) — vs one decode frame per token "
                    f"without the lane")
            if "prefix_hits" in s:
                # radix prefix sharing roll-up (PageAllocator trie):
                # claimed vs privately-allocated pages and the CoW
                # copies the reserve-on-divergence path paid
                total_pg = ((s.get("shared_pages") or 0)
                            + (s.get("private_pages") or 0))
                rate = (100.0 * (s.get("prefix_hits") or 0)
                        / max(1, s.get("admitted") or 0))
                lines.append(
                    f"Prefix sharing: {s.get('prefix_hits')} of "
                    f"{s.get('admitted')} admission(s) hit the trie "
                    f"({rate:.0f}%), {s.get('shared_pages')} page(s) "
                    f"claimed shared vs {s.get('private_pages')} "
                    f"private"
                    + (f" ({100.0 * (s.get('shared_pages') or 0) / total_pg:.0f}% of the pool walk)"
                       if total_pg else "")
                    + f", {s.get('prefix_tokens')} prompt token(s) "
                      f"skipped, {s.get('cow_copies')} copy-on-write "
                      f"page cop(ies)")
            if s.get("expired") or s.get("preempted"):
                lines.append(
                    f"SLO scheduling: {s.get('expired', 0)} request(s) "
                    f"expired past their deadline, "
                    f"{s.get('preempted', 0)} preemption(s)")
            if s.get("slo_classes"):
                lines.append("")
                lines.append("| SLO class | completed | TTFT p99 ms | "
                             "e2e p99 ms |")
                lines.append("|---|---|---|---|")
                for name, row in sorted(s["slo_classes"].items()):
                    lines.append(
                        f"| {name} | {row.get('completed')} | "
                        f"{_ms(row.get('ttft_p99_s'))} | "
                        f"{_ms(row.get('e2e_p99_s'))} |")
        requests = [e for e in events if e.get("kind") == "decode.request"]
        if requests:
            lines.append("")
            lines.append("| request | tokens | frames | queue ms | "
                         "TTFT ms | TPOT ms | e2e ms |")
            lines.append("|---|---|---|---|---|---|---|")
            for e in requests[-8:]:  # tail; the full stream is JSONL
                lines.append(
                    f"| {e.get('rid')} | {e.get('tokens')} | "
                    f"{e.get('frames')} | {_ms(e.get('queue_s'))} | "
                    f"{_ms(e.get('ttft_s'))} | {_ms(e.get('tpot_s'))} | "
                    f"{_ms(e.get('e2e_s'))} |")
        if frames:
            admitted = sum(e.get("admitted") or 0 for e in frames)
            evicted = sum(e.get("evicted") or 0 for e in frames)
            peak_pages = max(e.get("pages_in_use") or 0 for e in frames)
            lines.append(
                f"Admission/eviction across {len(frames)} frames: "
                f"{admitted} admitted, {evicted} evicted, peak page "
                f"residency {peak_pages} pages")
            lines.append("")
            lines.append("| frame | live | +admit | -evict | pages | "
                         "predicted ms | measured ms |")
            lines.append("|---|---|---|---|---|---|---|")
            for e in frames[-8:]:  # the tail tells the story; full
                # trace stays in the JSONL
                lines.append(
                    f"| {e.get('frame')} | {e.get('active')} | "
                    f"{e.get('admitted')} | {e.get('evicted')} | "
                    f"{e.get('pages_in_use')} | "
                    f"{_ms(e.get('predicted_s'))} | "
                    f"{_ms(e.get('measured_s'))} |")
    # ---- always-on controller: faults, swaps, recoveries ------------------
    faults = [e for e in events if e.get("kind") == "fault.injected"]
    researches = [e for e in events
                  if e.get("kind") == "controller.research"]
    swaps = [e for e in events if e.get("kind") == "controller.swap"]
    recoveries = [e for e in events
                  if e.get("kind") == "controller.recovery"]
    fallbacks = [e for e in events
                 if e.get("kind") == "controller.fallback"]
    csummaries = [e for e in events
                  if e.get("kind") == "controller.summary"]
    if faults or swaps or recoveries or csummaries:
        lines.append("")
        lines.append("## Always-on controller (swap/recovery phases)")
        lines.append("")
        if csummaries:
            s = csummaries[-1]
            lines.append(
                f"{s.get('steps')} steps driven: {s.get('swaps')} hot "
                f"swap(s), {s.get('recoveries')} recover(ies), "
                f"{s.get('retries')} retr(ies), {s.get('fallbacks')} "
                f"monolithic-fp32 fallback(s)")
        for e in faults:
            lines.append(
                f"Fault injected at step {e.get('step')}: "
                f"{e.get('fault')}"
                + (f" (arg {e.get('arg')})"
                   if e.get("arg") is not None else ""))
        for e in researches:
            cal_s = e.get("calibration_seconds") or 0.0
            lines.append(
                f"Re-search at step {e.get('step')} "
                f"({e.get('trigger')}): "
                f"{(e.get('search_seconds') or 0.0):.3f}s"
                + (f" (+{cal_s:.3f}s re-probe)" if cal_s else "")
                + (" — served WARM from the result cache"
                   if e.get("warm") else ""))
        for e in swaps:
            lines.append(
                f"Hot swap at step {e.get('step')}: "
                f"{(e.get('swap_seconds') or 0.0):.3f}s, "
                f"{e.get('fresh') or 0} fresh / "
                f"{e.get('dropped') or 0} dropped state entries"
                + (" — FELL BACK to monolithic fp32 sync"
                   if e.get("fallback") else ""))
        for e in recoveries:
            extra = ""
            if e.get("cause") == "device_loss":
                extra = f" onto {e.get('devices')} surviving device(s)"
            elif e.get("cause") == "checkpoint":
                extra = (f" from newest complete step "
                         f"{e.get('restored_step')}")
            lines.append(
                f"Recovery at step {e.get('step')}: "
                f"{e.get('cause')}{extra}")
        for e in fallbacks:
            lines.append(
                f"Fallback at step {e.get('step')}: {e.get('reason')}")
    p99s = [e for e in events if e.get("kind") == "controller.p99_drift"]
    for e in p99s:
        r = e.get("ratio")
        lines.append(
            f"Serving p99 watch at step {e.get('step')}: measured "
            f"{_ms(e.get('measured_s'))} ms vs searched "
            f"{_ms(e.get('predicted_s'))} ms "
            f"(ratio {f'{r:.2f}' if isinstance(r, (int, float)) else '—'})"
            + (" — DRIFTED, re-search triggered" if e.get("drifted")
               else ""))
    burns = [e for e in events if e.get("kind") == "controller.burn_rate"]
    for e in burns:

        def _b(v):
            return f"{v:.1f}x" if isinstance(v, (int, float)) else "—"

        lines.append(
            f"SLO burn-rate watch at step {e.get('step')} "
            f"[{e.get('slo')}]: fast {_b(e.get('fast'))} / slow "
            f"{_b(e.get('slow'))} of budget"
            + (" — FIRED, re-search triggered" if e.get("fired")
               else ""))
    dumps = [e for e in events if e.get("kind") == "flight.dump"]
    for e in dumps:
        lines.append(
            f"Flight-recorder dump ({e.get('reason')}): "
            f"{e.get('events')} ring event(s) + {e.get('open_spans')} "
            f"open span(s) -> {e.get('path')}")

    # ---- request traces ---------------------------------------------------
    from flexflow_tpu.obs.tracing import forest_stats, span_forest

    forest = span_forest(events)
    if forest:
        total, depth, orphans = forest_stats(forest)
        lines.append("")
        lines.append("## Request traces")
        lines.append("")
        lines.append(
            f"{len(forest)} trace(s), {total} span(s), max depth "
            f"{depth}, {orphans} orphan span(s)"
            + (" — ORPHANS ARE A VALIDATION FAILURE (a span named a "
               "parent the log never closed)" if orphans else ""))
        outcomes: Counter = Counter()
        for spans in forest.values():
            for e in spans:
                if e.get("parent_id") is None:
                    outcomes[e.get("outcome") or
                             ("open" if e.get("kind") == "trace.open"
                              else "?")] += 1
        if outcomes:
            lines.append(
                "Root outcomes: "
                + ", ".join(f"{k}={v}"
                            for k, v in sorted(outcomes.items())))
        lines.append("(render the trees with `ffobs.py trace <log>`)")

    stale = [e for e in events if e.get("kind") == "calibration.staleness"]
    if stale:
        s = stale[-1]
        lines.append(
            f"CALIBRATION STALENESS flagged: measured/predicted = "
            f"{s.get('ratio'):.2f} beyond threshold "
            f"{s.get('threshold')} — re-probe with --calibrate"
        )
    ignored = [e for e in events if e.get("kind") == "calibration.ignored"]
    for e in ignored:
        lines.append(
            f"Calibration ignored: probed on {e.get('backend')!r} but the "
            f"machine model is {e.get('machine')!r}"
        )

    logs = [e for e in events if e.get("kind") == "search.log"]
    if logs:
        lines.append("")
        lines.append(f"(search log: {len(logs)} lines captured; last: "
                     f"{logs[-1].get('msg')!r})")
    return "\n".join(lines) + "\n"


def cmd_report(args) -> int:
    events = read_events(args.log)
    sys.stdout.write(
        render_report(events, top=args.top, all_runs=args.all_runs))
    return 0


def cmd_metrics(args) -> int:
    """Render the newest ``metrics.snapshot`` event of a JSONL log in
    Prometheus text format — the offline twin of the live
    ``FLEXFLOW_TPU_METRICS_PORT`` endpoint (obs/exposition.py)."""
    from flexflow_tpu.obs.exposition import render_prometheus

    events = read_events(args.log)
    snaps = [e for e in events if e.get("kind") == "metrics.snapshot"]
    if not snaps:
        print(f"{args.log}: no metrics.snapshot event "
              f"(call METRICS.emit_snapshot() with the bus armed)",
              file=sys.stderr)
        return 1
    snap = snaps[-1]
    sys.stdout.write(render_prometheus({
        "counters": snap.get("counters") or {},
        "gauges": snap.get("gauges") or {},
        "histograms": snap.get("histograms") or {},
    }))
    return 0


_SPAN_META = ("ts", "kind", "trace_id", "span", "span_id", "parent_id",
              "start_s", "dur_s", "end_s")


def _span_label(e: dict) -> str:
    bits = [str(e.get("span"))]
    dur = e.get("dur_s")
    if dur is not None:
        bits.append(f"{dur * 1e3:.3f} ms")
    elif e.get("kind") == "trace.open":
        bits.append("OPEN")
    attrs = dict(e.get("attrs") or {})
    attrs.update({k: v for k, v in e.items()
                  if k not in _SPAN_META and k != "attrs"})
    if attrs:
        bits.append(", ".join(f"{k}={v}"
                              for k, v in sorted(attrs.items())))
    return "  ".join(bits)


def render_trace_trees(events: List[dict],
                       trace_id: Optional[str] = None,
                       limit: int = 0) -> str:
    """Span forests as indented trees — from a bus JSONL
    (``trace.span`` events) or a flight-recorder dump (``trace.span``
    + ``trace.open`` lines).  Orphan spans (a ``parent_id`` the log
    holds no span for) are listed per trace as validation failures."""
    from flexflow_tpu.obs.tracing import span_forest

    forest = span_forest(events)
    if trace_id is not None:
        forest = {t: s for t, s in forest.items() if t == trace_id}
        if not forest:
            return f"no spans for trace {trace_id!r}\n"
    lines: List[str] = []
    shown = 0
    for tid, spans in forest.items():
        if limit and shown >= limit:
            lines.append(
                f"... {len(forest) - shown} more trace(s) "
                f"(raise --limit)")
            lines.append("")
            break
        shown += 1
        by_id = {e.get("span_id"): e for e in spans
                 if e.get("span_id") is not None}
        children: Dict[int, List[dict]] = defaultdict(list)
        roots: List[dict] = []
        orphans: List[dict] = []
        for e in spans:
            pid = e.get("parent_id")
            if pid is None:
                roots.append(e)
            elif pid in by_id:
                children[pid].append(e)
            else:
                orphans.append(e)
        lines.append(f"trace {tid}  ({len(spans)} spans)")

        def walk(e: dict, depth: int, seen: tuple) -> None:
            lines.append("  " * depth + _span_label(e))
            sid = e.get("span_id")
            if sid in seen:  # defensive: a cyclic log must not hang
                return
            for c in sorted(children.get(sid, ()),
                            key=lambda c: (c.get("start_s")
                                           or c.get("ts") or 0)):
                walk(c, depth + 1, seen + (sid,))

        for r in sorted(roots, key=lambda e: (e.get("start_s")
                                              or e.get("ts") or 0)):
            walk(r, 1, ())
        for o in orphans:
            lines.append(f"  ORPHAN (parent {o.get('parent_id')} "
                         f"missing): {_span_label(o)}")
        lines.append("")
    if not lines:
        return ("no trace.span events (arm the tracer: "
                "FLEXFLOW_TPU_TRACE=1 with the bus on, or read a "
                "flight dump)\n")
    return "\n".join(lines)


def cmd_trace(args) -> int:
    events = read_events(args.log)
    out = render_trace_trees(events, trace_id=args.trace,
                            limit=args.limit)
    sys.stdout.write(out)
    from flexflow_tpu.obs.tracing import forest_stats, span_forest

    forest = span_forest(events)
    if forest:
        total, depth, orphans = forest_stats(forest)
        print(f"{len(forest)} trace(s), {total} span(s), max depth "
              f"{depth}, {orphans} orphan span(s)")
        return 1 if orphans else 0
    return 0


def cmd_validate(args) -> int:
    from flexflow_tpu.obs.events import validate_event

    events = read_events(args.log)
    bad = 0
    for i, e in enumerate(events, 1):
        errors = validate_event(e)
        if errors:
            bad += 1
            print(f"{args.log}:{i}: {'; '.join(errors)}")
    print(f"{len(events)} events, {bad} invalid")
    return 1 if bad else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ffobs", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_rep = sub.add_parser("report", help="render a strategy-explanation "
                           "report from a JSONL event log")
    p_rep.add_argument("log")
    p_rep.add_argument("--top", type=int, default=10)
    p_rep.add_argument("--all-runs", action="store_true",
                       help="aggregate every run appended to the log "
                            "instead of the last one")
    p_rep.set_defaults(fn=cmd_report)
    p_val = sub.add_parser("validate", help="schema-check every event line")
    p_val.add_argument("log")
    p_val.set_defaults(fn=cmd_validate)
    p_met = sub.add_parser(
        "metrics", help="render the last metrics.snapshot event as "
                        "Prometheus text (offline exposition)")
    p_met.add_argument("log")
    p_met.set_defaults(fn=cmd_metrics)
    p_tr = sub.add_parser(
        "trace", help="render request/controller span trees from a "
                      "trace JSONL or flight-recorder dump (exit 1 on "
                      "orphan spans)")
    p_tr.add_argument("log")
    p_tr.add_argument("--trace", default=None,
                      help="render only this trace id")
    p_tr.add_argument("--limit", type=int, default=20,
                      help="max trees to render (0 = all)")
    p_tr.set_defaults(fn=cmd_trace)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
