#!/usr/bin/env python
"""fflint — static-analysis linter for flexflow_tpu artifacts and the
rewrite registry (flexflow_tpu/analysis as a CI-friendly CLI).

Subcommands:

  fflint strategy FILE...     lint exported strategy files (STR2xx):
                              provenance digest present, views
                              well-formed — stdlib-only, no jax
  fflint cache FILE...        lint persistent cost-cache files (CCH4xx):
                              schema/signature shape, row
                              well-formedness, staleness — stdlib-only
  fflint registry [--devices N]
                              prove the substitution registry: graph
                              invariants (PCG0xx) + numeric equivalence
                              (EQV3xx) for every registered GraphXfer;
                              imports the package (needs jax)
  fflint all [--root DIR]     the CI entry point: lint every committed
                              COST_CACHE*.json / *strategy*.json under
                              DIR (default .) plus the full registry

Exit codes: 0 clean, 1 findings, 2 usage/unreadable input.  Artifact
subcommands never import jax, so they run anywhere the files land
(same discipline as tools/ffobs.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import List, Tuple

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

META_KEY = "__meta__"  # mirrors search/strategy_io.py (stdlib path)
CACHE_SCHEMA_VERSIONS = (1,)  # mirrors search/cost_cache.SCHEMA_VERSION


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f), None
    except OSError as e:
        return None, f"unreadable: {e}"
    except ValueError as e:
        return None, f"not JSON: {e}"


# ---------------------------------------------------------------------------
# strategy files (stdlib)


def lint_strategy_file(path: str) -> List[Tuple[str, str, str]]:
    """(severity, code, message) findings for one exported strategy
    file.  Graph-side checks (digest match, coverage, view legality
    against the op) need the graph and run at import time
    (search/strategy_io.import_strategy) — this lints what a file alone
    can prove."""
    data, err = _load_json(path)
    if err:
        return [("error", "STR200", err)]
    if not isinstance(data, dict):
        return [("error", "STR200", "top level is not a JSON object")]
    out: List[Tuple[str, str, str]] = []
    meta = data.get(META_KEY)
    if not isinstance(meta, dict) or not meta.get("graph_digest"):
        # warn, matching import_strategy's severity for the same code:
        # legacy pre-digest files import (with a warning), so they must
        # not fail CI either
        out.append((
            "warn", "STR203",
            "no __meta__.graph_digest — import cannot prove the file "
            "matches its target graph (re-export with this tree)"))
    views = {k: v for k, v in data.items() if k != META_KEY}
    if not views:
        out.append(("error", "STR202", "file names no ops at all"))
    for name, v in sorted(views.items()):
        if not isinstance(v, dict):
            out.append(("error", "STR204", f"op {name!r}: entry is not an "
                        "object"))
            continue
        dims = v.get("dims")
        # an empty dims list is legal: a scalar-output op's trivial view
        if (not isinstance(dims, list)
                or any(not isinstance(d, int) or d < 1 for d in dims)):
            out.append(("error", "STR204",
                        f"op {name!r}: malformed dims {dims!r}"))
        rep = v.get("replica", 1)
        if not isinstance(rep, int) or rep < 1:
            out.append(("error", "STR204",
                        f"op {name!r}: malformed replica {rep!r}"))
        start = v.get("start", 0)
        if not isinstance(start, int) or start < 0:
            out.append(("error", "STR204",
                        f"op {name!r}: malformed start {start!r}"))
    return out


# ---------------------------------------------------------------------------
# cost-cache files (stdlib)


def lint_cache_file(path: str) -> List[Tuple[str, str, str]]:
    data, err = _load_json(path)
    if err:
        return [("error", "CCH400", err)]
    if not isinstance(data, dict):
        return [("error", "CCH400", "top level is not a JSON object")]
    out: List[Tuple[str, str, str]] = []
    if data.get("schema") not in CACHE_SCHEMA_VERSIONS:
        out.append(("error", "CCH401",
                    f"unknown schema {data.get('schema')!r} (known: "
                    f"{list(CACHE_SCHEMA_VERSIONS)})"))
    sig = data.get("signature")
    if (not isinstance(sig, str) or len(sig) != 16
            or any(c not in "0123456789abcdef" for c in sig)):
        out.append(("error", "CCH401",
                    f"malformed cost-surface signature {sig!r} (expect 16 "
                    "hex chars)"))
    if data.get("calibration_stale"):
        out.append(("warn", "CCH403",
                    "calibration_stale is set: the cache refuses to serve "
                    "until recalibration (drift gate, obs/drift.py)"))
    rows = data.get("rows", [])
    if not isinstance(rows, list):
        return out + [("error", "CCH402", "rows is not a list")]
    seen = set()
    for i, r in enumerate(rows):
        ok = (
            isinstance(r, dict)
            and isinstance(r.get("sig"), str)
            and isinstance(r.get("degrees"), list)
            and all(isinstance(d, int) and d >= 1 for d in r["degrees"])
            and isinstance(r.get("replica"), int) and r["replica"] >= 1
            and isinstance(r.get("row"), list) and len(r["row"]) == 4
            and all(isinstance(x, (int, float)) and math.isfinite(x)
                    and x >= 0 for x in r["row"])
        )
        if not ok:
            out.append(("error", "CCH402", f"rows[{i}] malformed: "
                        f"{str(r)[:120]}"))
            continue
        key = (r["sig"], tuple(r["degrees"]), r["replica"])
        if key in seen:
            out.append(("error", "CCH402",
                        f"rows[{i}] duplicates key for degrees "
                        f"{r['degrees']} replica {r['replica']}"))
        seen.add(key)
    sidecar = path + ".results.pkl"
    if os.path.exists(sidecar) and os.path.getsize(sidecar) == 0:
        out.append(("error", "CCH404", f"empty results sidecar {sidecar}"))
    return out


# ---------------------------------------------------------------------------
# rewrite registry (imports flexflow_tpu — jax required)


def lint_registry(num_devices: int) -> List[Tuple[str, str, str]]:
    from flexflow_tpu.analysis.equivalence import verify_registry

    return [(f.severity, f.code, f.message) for f in verify_registry(
        num_devices=num_devices)]


# ---------------------------------------------------------------------------


def _report(path: str, findings: List[Tuple[str, str, str]]) -> int:
    errors = 0
    for sev, code, msg in findings:
        print(f"{path}: {sev.upper()} [{code}] {msg}")
        if sev == "error":
            errors += 1
    return errors


def cmd_strategy(args) -> int:
    errors = 0
    for path in args.files:
        errors += _report(path, lint_strategy_file(path))
    print(f"fflint strategy: {len(args.files)} file(s), {errors} error(s)")
    return 1 if errors else 0


def cmd_cache(args) -> int:
    errors = 0
    for path in args.files:
        errors += _report(path, lint_cache_file(path))
    print(f"fflint cache: {len(args.files)} file(s), {errors} error(s)")
    return 1 if errors else 0


def cmd_registry(args) -> int:
    findings = lint_registry(args.devices)
    errors = _report("registry", findings)
    print(f"fflint registry: {args.devices}-device rewrite registry, "
          f"{errors} error(s)")
    return 1 if errors else 0


def cmd_all(args) -> int:
    errors = 0
    caches = sorted(glob.glob(
        os.path.join(args.root, "**", "COST_CACHE*.json"), recursive=True))
    strategies = sorted(
        p for p in glob.glob(os.path.join(args.root, "**", "*.json"),
                             recursive=True)
        if "strategy" in os.path.basename(p).lower()
    )
    for path in caches:
        errors += _report(path, lint_cache_file(path))
    for path in strategies:
        errors += _report(path, lint_strategy_file(path))
    findings = lint_registry(args.devices)
    errors += _report("registry", findings)
    print(f"fflint all: {len(caches)} cache file(s), "
          f"{len(strategies)} strategy file(s), registry @ "
          f"{args.devices} devices — {errors} error(s)")
    return 1 if errors else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fflint", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("strategy", help="lint exported strategy files")
    p.add_argument("files", nargs="+")
    p.set_defaults(fn=cmd_strategy)
    p = sub.add_parser("cache", help="lint persistent cost-cache files")
    p.add_argument("files", nargs="+")
    p.set_defaults(fn=cmd_cache)
    p = sub.add_parser("registry",
                       help="numeric-equivalence proof of the rewrite "
                            "registry (imports jax)")
    p.add_argument("--devices", type=int, default=8)
    p.set_defaults(fn=cmd_registry)
    p = sub.add_parser("all", help="lint committed artifacts + registry")
    p.add_argument("--root", default=".")
    p.add_argument("--devices", type=int, default=8)
    p.set_defaults(fn=cmd_all)
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
