#!/usr/bin/env python
"""fflint — static-analysis linter for flexflow_tpu artifacts and the
rewrite registry (flexflow_tpu/analysis as a CI-friendly CLI).

Subcommands:

  fflint strategy FILE...     lint exported strategy files (STR2xx):
                              provenance digest present, views
                              well-formed — stdlib-only, no jax
  fflint cache FILE...        lint persistent cost-cache files (CCH4xx):
                              schema/signature shape, row
                              well-formedness, staleness — stdlib-only
  fflint registry [--devices N] [--substitution-json FILE]
                              prove the substitution registry: the
                              hand-zoo regression proof PLUS the
                              generative proof (analysis/proofgen.py —
                              proof graphs synthesized from each
                              rewrite's own anchor_types; EQV305 =
                              factory coverage hole, EQV306 = unproven
                              JSON rule).  Reports both passes'
                              wall-clock so the CI verification budget
                              stays a number.  Imports the package
                              (needs jax)
  fflint all [--root DIR]     the CI entry point: lint every committed
                              COST_CACHE*.json / *strategy*.json under
                              DIR (default .) plus the full registry
  fflint pre-commit [--skip-registry]
                              the git hook gate: lint the STAGED
                              artifact files + prove the registry
                              (.githooks/pre-commit runs this; enable
                              with `git config core.hooksPath .githooks`)

Exit codes: 0 clean, 1 findings, 2 usage error.  ``--json`` switches
every subcommand to machine-readable output: one JSON object per line
(findings first, a ``{"summary": ...}`` object last) — the exit-code
contract is identical.  Artifact subcommands never import jax, so
they run anywhere the files land (same discipline as tools/ffobs.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import List, Tuple

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

META_KEY = "__meta__"  # mirrors search/strategy_io.py (stdlib path)
CACHE_SCHEMA_VERSIONS = (1,)  # mirrors search/cost_cache.SCHEMA_VERSION
DP_SCHEMA_VERSIONS = (2,)  # mirrors search/cost_cache.DP_SCHEMA
COMM_SCHEMA_VERSIONS = (1,)  # mirrors search/cost_cache.COMM_SCHEMA
SP_SCHEMA_VERSIONS = (1,)  # mirrors search/cost_cache.SP_SCHEMA


def _load_json(path: str):
    try:
        with open(path) as f:
            return json.load(f), None
    except OSError as e:
        return None, f"unreadable: {e}"
    except ValueError as e:
        return None, f"not JSON: {e}"


# ---------------------------------------------------------------------------
# strategy files (stdlib)


def _calibration_digest(data) -> str:
    """Stdlib mirror of ``search/cost_cache.calibration_digest`` over a
    CALIBRATION.json payload: identical bytes hashed in identical order
    to what ``CalibrationTable.load`` + the package digest produce, so
    the STR210 comparison below proves the same signature the search
    keyed its caches (and the exported ``__meta__``) under."""
    import hashlib

    h = hashlib.sha256()
    h.update(repr(data.get("backend")).encode())
    records = {}
    for r in data.get("records", []):
        records[(r["sig"], tuple(r["degrees"]), int(r["replica"]))] = \
            float(r["seconds"])
    for k, v in sorted(records.items()):
        h.update(repr((k, v)).encode())
    clusters = {}
    for r in data.get("clusters", []):
        clusters[(tuple(r["sigs"]), tuple(r["degrees"]),
                  int(r["replica"]))] = float(r["seconds"])
    for k, v in sorted(clusters.items()):
        h.update(repr((k, v)).encode())
    return h.hexdigest()[:16]


def _lint_calibration_signature(meta, strategy_path: str,
                                calibration_path) -> List[Tuple[str, str, str]]:
    """STR210: a persisted ``__meta__.calibration_signature`` that no
    longer matches the LIVE calibration table is a STALE strategy —
    the cost surface it was ranked under has rotated (a re-probe, a
    drift fix) and the file's predicted numbers no longer describe this
    machine.  Warn, matching the import-side severity philosophy for
    provenance that is suspicious but not provably wrong."""
    sig = meta.get("calibration_signature")
    if not isinstance(sig, str) or not sig:
        return []
    if calibration_path is None:
        # default: the CALIBRATION.json living next to the strategy
        # file is "the live table" for that artifact set
        calibration_path = os.path.join(
            os.path.dirname(os.path.abspath(strategy_path)),
            "CALIBRATION.json")
    if not os.path.exists(calibration_path):
        return []
    data, err = _load_json(calibration_path)
    if err or not isinstance(data, dict):
        return [("warn", "STR210",
                 f"cannot check calibration_signature: live table "
                 f"{calibration_path} is unreadable ({err})")]
    try:
        live = _calibration_digest(data)
    except (KeyError, TypeError, ValueError) as e:
        # valid JSON, malformed rows: STR210 is warn-only by contract —
        # the hook must not traceback over a table the package itself
        # would refuse to load
        return [("warn", "STR210",
                 f"cannot check calibration_signature: live table "
                 f"{calibration_path} has malformed rows "
                 f"({type(e).__name__}: {e})")]
    if live != sig:
        return [("warn", "STR210",
                 f"STALE: exported under calibration signature {sig} "
                 f"but the live table ({calibration_path}) digests to "
                 f"{live} — the cost surface rotated since this "
                 f"strategy was searched; re-search or re-export")]
    return []


def lint_strategy_file(path: str,
                       calibration_path=None) -> List[Tuple[str, str, str]]:
    """(severity, code, message) findings for one exported strategy
    file.  Graph-side checks (digest match, coverage, view legality
    against the op) need the graph and run at import time
    (search/strategy_io.import_strategy) — this lints what a file alone
    can prove.  ``calibration_path`` pins the live CALIBRATION.json the
    STR210 staleness check compares against (default: the strategy
    file's sibling)."""
    data, err = _load_json(path)
    if err:
        return [("error", "STR200", err)]
    if not isinstance(data, dict):
        return [("error", "STR200", "top level is not a JSON object")]
    out: List[Tuple[str, str, str]] = []
    meta = data.get(META_KEY)
    if not isinstance(meta, dict) or not meta.get("graph_digest"):
        # warn, matching import_strategy's severity for the same code:
        # legacy pre-digest files import (with a warning), so they must
        # not fail CI either
        out.append((
            "warn", "STR203",
            "no __meta__.graph_digest — import cannot prove the file "
            "matches its target graph (re-export with this tree)"))
    if isinstance(meta, dict) and "sync_schedule" in meta:
        out += _lint_sync_schedule_meta(meta["sync_schedule"])
    if isinstance(meta, dict) and "zero_groups" in meta:
        out += _lint_zero_groups_meta(
            meta["zero_groups"],
            {k for k in data if k != META_KEY})
    if isinstance(meta, dict) and "placement" in meta:
        out += _lint_placement_meta(
            meta["placement"],
            {k: v for k, v in data.items() if k != META_KEY})
    if isinstance(meta, dict) and "pipeline" in meta:
        out += _lint_pipeline_meta(
            meta["pipeline"], {k for k in data if k != META_KEY})
    if isinstance(meta, dict) and "serving" in meta:
        out += _lint_serving_meta(meta["serving"])
    if isinstance(meta, dict) and "disaggregation" in meta:
        out += _lint_disagg_meta(meta["disaggregation"], meta)
    if isinstance(meta, dict) and "fleet" in meta:
        out += _lint_fleet_meta(meta["fleet"], meta)
    if isinstance(meta, dict) and "kv" in meta:
        out += _lint_kv_meta(meta["kv"], meta)
    if isinstance(meta, dict):
        out += _lint_calibration_signature(meta, path, calibration_path)
    views = {k: v for k, v in data.items() if k != META_KEY}
    if not views:
        out.append(("error", "STR202", "file names no ops at all"))
    for name, v in sorted(views.items()):
        if not isinstance(v, dict):
            out.append(("error", "STR204", f"op {name!r}: entry is not an "
                        "object"))
            continue
        dims = v.get("dims")
        # an empty dims list is legal: a scalar-output op's trivial view
        if (not isinstance(dims, list)
                or any(not isinstance(d, int) or d < 1 for d in dims)):
            out.append(("error", "STR204",
                        f"op {name!r}: malformed dims {dims!r}"))
        rep = v.get("replica", 1)
        if not isinstance(rep, int) or rep < 1:
            out.append(("error", "STR204",
                        f"op {name!r}: malformed replica {rep!r}"))
        start = v.get("start", 0)
        if not isinstance(start, int) or start < 0:
            out.append(("error", "STR204",
                        f"op {name!r}: malformed start {start!r}"))
    return out


_SCHEDULE_SCHEMA = 1  # mirrors search/sync_schedule.SCHEDULE_SCHEMA
_BUCKET_PRECISIONS = ("fp32", "bf16", "int8", "int8_ef")


def _lint_serving_meta(sv) -> List[Tuple[str, str, str]]:
    """STR209: structural lint of a persisted ``__meta__.serving``
    block (the serve-objective provenance, search/serving.py).
    Graph-side legality (frame-geometry coherence with the decode ops,
    KV residency vs HBM — SHD160-163) needs the graph + machine model
    and runs at import/compile time."""
    if not isinstance(sv, dict):
        return [("error", "STR209", "serving meta is not an object")]
    out: List[Tuple[str, str, str]] = []
    if sv.get("objective") != "serve":
        out.append(("error", "STR209",
                    f"serving meta objective {sv.get('objective')!r} is "
                    f"not 'serve'"))
    for k in ("max_seqs", "page_size", "pages_per_seq"):
        v = sv.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            out.append(("error", "STR209",
                        f"serving meta {k} is not a positive int: {v!r}"))
    q = sv.get("quantile", 0.99)
    if not isinstance(q, (int, float)) or isinstance(q, bool) \
            or not (0.0 < float(q) < 1.0):
        out.append(("error", "STR209",
                    f"serving meta quantile {q!r} outside (0, 1)"))
    b = sv.get("p99_budget_ms", 0.0)
    if not isinstance(b, (int, float)) or isinstance(b, bool) \
            or float(b) < 0.0:
        out.append(("error", "STR209",
                    f"serving meta p99_budget_ms {b!r} is negative or "
                    f"non-numeric"))
    p99 = sv.get("predicted_p99_step_ms")
    if p99 is not None and (
            not isinstance(p99, (int, float)) or isinstance(p99, bool)
            or not math.isfinite(float(p99)) or float(p99) <= 0.0):
        out.append(("error", "STR209",
                    f"serving meta predicted_p99_step_ms {p99!r} is not "
                    f"a positive finite number"))
    kv = sv.get("kv_bytes_per_device")
    if kv is not None and (
            not isinstance(kv, (int, float)) or isinstance(kv, bool)
            or not math.isfinite(float(kv)) or float(kv) < 0.0):
        out.append(("error", "STR209",
                    f"serving meta kv_bytes_per_device {kv!r} is not a "
                    f"non-negative finite number"))
    return out


_KV_DTYPES = ("fp32", "bf16", "int8")


def _lint_kv_meta(kv, meta) -> List[Tuple[str, str, str]]:
    """STR213: structural lint of a persisted ``__meta__.kv`` block
    (the searched KV-precision + prefix-sharing provenance,
    search/driver.py ``_choose_kv_precision``).  Graph-side legality
    (dtype agreement with the decode ops' own attrs, refcount-factor
    coherence with the armed ServingSpec — SHD168/169) needs the graph
    and runs at import/compile time; this proves what the file alone
    can: a known pool dtype, the scale-layout discipline (int8 carries
    per-(page, slot) scales, fp32/bf16 carry none), sharing accounting
    coherent with itself and with the sibling ``__meta__.serving``
    frame, and finite per-dtype prices."""
    if not isinstance(kv, dict):
        return [("error", "STR213", "kv meta is not an object")]
    out: List[Tuple[str, str, str]] = []
    dt = kv.get("dtype")
    if dt not in _KV_DTYPES:
        out.append(("error", "STR213",
                    f"kv meta pool dtype {dt!r} is not one of "
                    f"{'/'.join(_KV_DTYPES)}"))
    layout = kv.get("scale_layout", "none")
    if dt == "int8" and layout != "page_slot":
        out.append(("error", "STR213",
                    f"int8 pool requires scale_layout 'page_slot', got "
                    f"{layout!r}"))
    if dt in ("fp32", "bf16") and layout not in ("none", None):
        out.append(("error", "STR213",
                    f"{dt} pool must not carry scales "
                    f"(scale_layout={layout!r})"))
    if not isinstance(kv.get("searched", False), bool):
        out.append(("error", "STR213",
                    f"kv meta searched flag is not a bool: "
                    f"{kv.get('searched')!r}"))
    shared = kv.get("shared_prefix_pages", 0)
    if not isinstance(shared, int) or isinstance(shared, bool) \
            or shared < 0:
        out.append(("error", "STR213",
                    f"kv meta shared_prefix_pages is not a "
                    f"non-negative int: {shared!r}"))
        shared = 0
    sv = meta.get("serving") if isinstance(meta, dict) else None
    pps = sv.get("pages_per_seq") if isinstance(sv, dict) else None
    mseq = sv.get("max_seqs") if isinstance(sv, dict) else None
    if isinstance(pps, int) and not isinstance(pps, bool) \
            and shared >= pps > 0:
        out.append(("error", "STR213",
                    f"kv meta shared_prefix_pages={shared} >= the "
                    f"sibling __meta__.serving pages_per_seq={pps} — a "
                    f"sequence cannot share its whole allotment (the "
                    f"last token's scatter needs a private page)"))
    factor = kv.get("shared_residency_factor", 1.0)
    if not isinstance(factor, (int, float)) or isinstance(factor, bool) \
            or not math.isfinite(float(factor)) \
            or not (0.0 < float(factor) <= 1.0):
        out.append(("error", "STR213",
                    f"kv meta shared_residency_factor {factor!r} "
                    f"outside (0, 1]"))
    elif shared == 0 and float(factor) != 1.0:
        out.append(("error", "STR213",
                    f"kv meta claims a residency discount "
                    f"(factor={factor!r}) with shared_prefix_pages=0 — "
                    f"sharing that prices but never happens is an OOM "
                    f"deferred"))
    elif (shared > 0 and isinstance(pps, int) and isinstance(mseq, int)
          and not isinstance(pps, bool) and not isinstance(mseq, bool)
          and mseq > 0 and pps > shared):
        expect = (mseq * (pps - shared) + shared) / float(mseq * pps)
        if abs(float(factor) - expect) > 1e-9:
            out.append(("error", "STR213",
                        f"kv meta shared_residency_factor {factor!r} "
                        f"does not match the refcount arithmetic for "
                        f"shared_prefix_pages={shared} over the sibling "
                        f"serving frame ({mseq}x{pps} pages): expected "
                        f"{expect:.9f}"))
    p99 = kv.get("predicted_p99_step_ms")
    if p99 is not None:
        if not isinstance(p99, dict):
            out.append(("error", "STR213",
                        f"kv meta predicted_p99_step_ms is not an "
                        f"object: {p99!r}"))
        else:
            for k, v in sorted(p99.items()):
                if k not in _KV_DTYPES:
                    out.append(("error", "STR213",
                                f"kv meta predicted_p99_step_ms keys an "
                                f"unknown dtype {k!r}"))
                if not isinstance(v, (int, float)) or isinstance(v, bool) \
                        or not math.isfinite(float(v)) or float(v) <= 0.0:
                    out.append(("error", "STR213",
                                f"kv meta predicted_p99_step_ms[{k!r}] "
                                f"{v!r} is not a positive finite number"))
            if dt in _KV_DTYPES and p99 and dt not in p99:
                out.append(("error", "STR213",
                            f"kv meta chose dtype {dt!r} but the priced "
                            f"map never priced it: "
                            f"{sorted(p99.keys())}"))
    b = kv.get("kv_bytes_per_device")
    if b is not None and (
            not isinstance(b, (int, float)) or isinstance(b, bool)
            or not math.isfinite(float(b)) or float(b) < 0.0):
        out.append(("error", "STR213",
                    f"kv meta kv_bytes_per_device {b!r} is not a "
                    f"non-negative finite number"))
    return out


def _lint_disagg_meta(dm, meta) -> List[Tuple[str, str, str]]:
    """STR211: structural lint of a persisted
    ``__meta__.disaggregation`` block (the searched prefill/decode
    two-block placement + SLO classes, search/disaggregation.py).
    Graph-side legality (pool-geometry agreement with the decode ops,
    the shared-parameter-set bridge — SHD164/165) needs the graph and
    runs at import/compile time; this proves what the file alone can:
    a coherent disjoint frame, a sane chunk, pool geometry that agrees
    with the sibling ``__meta__.serving`` block, finite prices, and a
    well-formed SLO-class table."""
    if not isinstance(dm, dict):
        return [("error", "STR211", "disaggregation meta is not an "
                 "object")]
    out: List[Tuple[str, str, str]] = []
    ints = {}
    for k in ("num_devices", "prefill_devices", "decode_devices",
              "chunk", "prefill_seq_len", "max_seqs", "page_size",
              "pages_per_seq"):
        v = dm.get(k)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            out.append(("error", "STR211",
                        f"disaggregation meta {k} is not a positive "
                        f"int: {v!r}"))
        else:
            ints[k] = v
    if ("prefill_devices" in ints and "decode_devices" in ints
            and "num_devices" in ints
            and ints["prefill_devices"] + ints["decode_devices"]
            > ints["num_devices"]):
        out.append(("error", "STR211",
                    f"disaggregation blocks overflow: prefill "
                    f"{ints['prefill_devices']} + decode "
                    f"{ints['decode_devices']} devices on a "
                    f"{ints['num_devices']}-device machine"))
    sv = meta.get("serving") if isinstance(meta, dict) else None
    if isinstance(sv, dict):
        for k in ("max_seqs", "page_size", "pages_per_seq"):
            if k in ints and isinstance(sv.get(k), int) \
                    and sv[k] != ints[k]:
                out.append(("error", "STR211",
                            f"disaggregation meta {k}={ints[k]} "
                            f"disagrees with __meta__.serving "
                            f"{k}={sv[k]} — one page allocator must "
                            f"serve both sides of the handoff"))
    for k in ("colocated_step_ms", "disagg_step_ms", "handoff_ms",
              "prefill_tokens_per_frame"):
        v = dm.get(k)
        if v is not None and (
                not isinstance(v, (int, float)) or isinstance(v, bool)
                or not math.isfinite(float(v)) or float(v) < 0.0):
            out.append(("error", "STR211",
                        f"disaggregation meta {k} {v!r} is not a "
                        f"non-negative finite number"))
    classes = dm.get("slo_classes", [])
    if not isinstance(classes, list):
        return out + [("error", "STR211",
                       f"disaggregation meta slo_classes is not a "
                       f"list: {str(classes)[:60]}")]
    seen = set()
    for i, c in enumerate(classes):
        if not isinstance(c, dict) or not isinstance(c.get("name"), str) \
                or not c.get("name"):
            out.append(("error", "STR211",
                        f"slo_classes[{i}] is not a named class "
                        f"object"))
            continue
        if c["name"] in seen:
            out.append(("error", "STR211",
                        f"slo_classes[{i}] duplicates {c['name']!r}"))
        seen.add(c["name"])
        p = c.get("priority", 0)
        if not isinstance(p, int) or isinstance(p, bool):
            out.append(("error", "STR211",
                        f"slo class {c['name']!r} priority {p!r} is "
                        f"not an int"))
        df = c.get("deadline_frames", 0)
        if not isinstance(df, int) or isinstance(df, bool) or df < 0:
            out.append(("error", "STR211",
                        f"slo class {c['name']!r} deadline_frames "
                        f"{df!r} is not a non-negative int"))
        q = c.get("quantile", 0.99)
        if not isinstance(q, (int, float)) or isinstance(q, bool) \
                or not (0.0 < float(q) < 1.0):
            out.append(("error", "STR211",
                        f"slo class {c['name']!r} quantile {q!r} "
                        f"outside (0, 1)"))
    return out


def _lint_fleet_meta(fm, meta) -> List[Tuple[str, str, str]]:
    """STR212: structural lint of a persisted ``__meta__.fleet`` block
    (the searched N-replica serving fleet + per-SLO-class routing,
    search/fleet.py).  Graph-side legality (per-block view legality,
    pool-geometry agreement with the decode ops — SHD166/167) needs the
    graph and runs at import/compile time; this proves what the file
    alone can: disjoint replica blocks that fit the machine, replicas
    that actually carry a strategy, routing rows that sum to one over
    known classes, pool geometry that agrees with the sibling
    ``__meta__.serving`` block, and finite prices."""
    if not isinstance(fm, dict):
        return [("error", "STR212", "fleet meta is not an object")]
    out: List[Tuple[str, str, str]] = []
    n = fm.get("num_devices")
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        out.append(("error", "STR212",
                    f"fleet meta num_devices is not a positive int: "
                    f"{n!r}"))
        n = None
    reps = fm.get("replicas")
    if not isinstance(reps, list) or not reps:
        return out + [("error", "STR212",
                       "fleet meta replicas is not a non-empty list")]
    spans = []
    for i, r in enumerate(reps):
        if not isinstance(r, dict):
            out.append(("error", "STR212",
                        f"replicas[{i}] is not an object"))
            continue
        ok = True
        for k in ("devices", "decode_devices"):
            v = r.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                out.append(("error", "STR212",
                            f"replicas[{i}] {k} is not a positive int: "
                            f"{v!r}"))
                ok = False
        for k in ("start", "prefill_devices"):
            v = r.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                out.append(("error", "STR212",
                            f"replicas[{i}] {k} is not a non-negative "
                            f"int: {v!r}"))
                ok = False
        if ok:
            pre, dec, dev = (r["prefill_devices"], r["decode_devices"],
                             r["devices"])
            if (pre + dec > dev) if pre else (dec != dev):
                out.append(("error", "STR212",
                            f"replicas[{i}] phase split prefill={pre} "
                            f"decode={dec} does not fit its "
                            f"{dev}-device block"))
            spans.append((r["start"], dev, i))
            if n is not None and r["start"] + dev > n:
                out.append(("error", "STR212",
                            f"replicas[{i}] overflows the machine: "
                            f"start {r['start']} + {dev} devices > "
                            f"{n}"))
        share = r.get("share")
        if not isinstance(share, (int, float)) or isinstance(share, bool) \
                or not math.isfinite(float(share)) \
                or not (0.0 <= float(share) <= 1.0):
            out.append(("error", "STR212",
                        f"replicas[{i}] share {share!r} outside "
                        f"[0, 1]"))
        for k in ("step_ms", "handoff_ms"):
            v = r.get(k)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or not math.isfinite(float(v)) or float(v) < 0.0:
                out.append(("error", "STR212",
                            f"replicas[{i}] {k} {v!r} is not a "
                            f"non-negative finite number"))
        ops = r.get("strategy_ops")
        if not isinstance(ops, int) or isinstance(ops, bool) or ops < 1:
            out.append(("error", "STR212",
                        f"replicas[{i}] carries no searched strategy "
                        f"(strategy_ops={ops!r}) — a replica without "
                        f"one cannot be deployed"))
    spans.sort()
    for (s0, w0, i0), (s1, w1, i1) in zip(spans, spans[1:]):
        if s0 + w0 > s1:
            out.append(("error", "STR212",
                        f"replicas[{i0}] and replicas[{i1}] overlap: "
                        f"[{s0}, {s0 + w0}) vs [{s1}, {s1 + w1})"))
    sv = meta.get("serving") if isinstance(meta, dict) else None
    if isinstance(sv, dict):
        for k in ("max_seqs", "page_size", "pages_per_seq"):
            fv = fm.get(k)
            if isinstance(fv, int) and isinstance(sv.get(k), int) \
                    and sv[k] != fv:
                out.append(("error", "STR212",
                            f"fleet meta {k}={fv} disagrees with "
                            f"__meta__.serving {k}={sv[k]} — every "
                            f"replica's page allocator must match the "
                            f"decode graph's frame"))
    for k in ("single_step_ms", "fleet_step_ms"):
        v = fm.get(k)
        if v is not None and (
                not isinstance(v, (int, float)) or isinstance(v, bool)
                or not math.isfinite(float(v)) or float(v) < 0.0):
            out.append(("error", "STR212",
                        f"fleet meta {k} {v!r} is not a non-negative "
                        f"finite number"))
    classes = fm.get("slo_classes", [])
    names = set()
    if not isinstance(classes, list):
        out.append(("error", "STR212",
                    f"fleet meta slo_classes is not a list: "
                    f"{str(classes)[:60]}"))
        classes = []
    for i, c in enumerate(classes):
        if not isinstance(c, dict) or not isinstance(c.get("name"), str) \
                or not c.get("name"):
            out.append(("error", "STR212",
                        f"slo_classes[{i}] is not a named class "
                        f"object"))
            continue
        if c["name"] in names:
            out.append(("error", "STR212",
                        f"slo_classes[{i}] duplicates {c['name']!r}"))
        names.add(c["name"])
        w = c.get("weight", 1.0)
        if not isinstance(w, (int, float)) or isinstance(w, bool) \
                or not math.isfinite(float(w)) or float(w) <= 0.0:
            out.append(("error", "STR212",
                        f"slo class {c['name']!r} weight {w!r} is not "
                        f"a positive finite number"))
    routing = fm.get("routing")
    if not isinstance(routing, dict) or not routing:
        return out + [("error", "STR212",
                       "fleet meta routing is not a non-empty object")]
    for cname, row in sorted(routing.items()):
        if names and cname not in names:
            out.append(("error", "STR212",
                        f"routing names unknown SLO class {cname!r}"))
        if not isinstance(row, list) or len(row) != len(reps):
            out.append(("error", "STR212",
                        f"routing[{cname!r}] is not a "
                        f"{len(reps)}-replica fraction row: {row!r}"))
            continue
        bad = [f for f in row
               if not isinstance(f, (int, float)) or isinstance(f, bool)
               or not math.isfinite(float(f))
               or not (0.0 <= float(f) <= 1.0)]
        if bad:
            out.append(("error", "STR212",
                        f"routing[{cname!r}] has fractions outside "
                        f"[0, 1]: {bad!r}"))
            continue
        total = sum(float(f) for f in row)
        if abs(total - 1.0) > 1e-3:
            out.append(("error", "STR212",
                        f"routing[{cname!r}] fractions sum to "
                        f"{total:.6f}, not 1"))
    for cname in sorted(names - set(routing)):
        out.append(("error", "STR212",
                    f"SLO class {cname!r} has no routing row — its "
                    f"requests would route nowhere"))
    return out


def _lint_zero_groups_meta(zg, op_names) -> List[Tuple[str, str, str]]:
    """STR207: structural lint of a persisted ``__meta__.zero_groups``
    map (the co-searched per-group optimizer-state sharding,
    search/comm_plan.py).  Graph-side legality (the op actually syncs,
    the shard factor is achievable — SHD140/141) needs the graph and
    runs at import/compile time; this proves what the file alone can:
    a list of unique op names the file itself covers."""
    out: List[Tuple[str, str, str]] = []
    if not isinstance(zg, list):
        return [("error", "STR207", "zero_groups is not a list")]
    if not zg:
        out.append(("error", "STR207",
                    "zero_groups is empty — an empty map is persisted "
                    "as ABSENT, so an empty list is a writer bug"))
    seen = set()
    for i, name in enumerate(zg):
        if not isinstance(name, str) or not name:
            out.append(("error", "STR207",
                        f"zero_groups[{i}] is not an op name: {name!r}"))
            continue
        if name in seen:
            out.append(("error", "STR207",
                        f"zero_groups[{i}] duplicates {name!r}"))
        seen.add(name)
        if name not in op_names:
            out.append(("error", "STR207",
                        f"zero_groups[{i}] names op {name!r} the "
                        f"strategy file does not cover"))
    return out


def _view_parts(v) -> int:
    """Total parts of a strategy-file view entry (product of dim
    degrees x replica) — 0 when the entry is malformed (STR204 owns
    that failure)."""
    dims = v.get("dims") if isinstance(v, dict) else None
    rep = v.get("replica", 1) if isinstance(v, dict) else None
    if (not isinstance(dims, list)
            or any(not isinstance(d, int) or d < 1 for d in dims)
            or not isinstance(rep, int) or rep < 1):
        return 0
    parts = rep
    for d in dims:
        parts *= d
    return parts


def _lint_placement_meta(pm, views) -> List[Tuple[str, str, str]]:
    """STR208: structural lint of a persisted ``__meta__.placement``
    block (the 2-block device frame a placed proposal executes under,
    analysis/placement.py).  Graph-side legality (cut shape, sink
    ownership, crossing tensors — SHD153-155) needs the graph and runs
    at proposal/import time; this proves what the file alone can: a
    coherent disjoint 2-block frame that the file's own start_part
    views actually inhabit."""
    out: List[Tuple[str, str, str]] = []
    if not isinstance(pm, dict):
        return [("error", "STR208", "placement meta is not an object")]
    n = pm.get("num_devices")
    if not isinstance(n, int) or n < 2:
        out.append(("error", "STR208",
                    f"placement meta has malformed num_devices {n!r}"))
        n = None
    blocks = pm.get("blocks")
    ok_blocks = (
        isinstance(blocks, list) and len(blocks) == 2
        and all(isinstance(b, list) and len(b) == 2
                and all(isinstance(x, int) and x >= 0 for x in b)
                and b[1] >= 1 for b in blocks)
    )
    if not ok_blocks:
        return out + [("error", "STR208",
                       f"placement meta needs exactly 2 [start, parts] "
                       f"blocks, got {str(blocks)[:80]}")]
    (s0, p0), (s1, p1) = blocks
    if s0 != 0:
        out.append(("error", "STR208",
                    f"placement block A starts at device {s0}, not 0"))
    if s1 < s0 + p0:
        out.append(("error", "STR208",
                    f"placement blocks overlap: A spans [0, {p0}) but B "
                    f"starts at {s1}"))
    if n is not None and s1 + p1 > n:
        out.append(("error", "STR208",
                    f"placement blocks overflow: B spans [{s1}, "
                    f"{s1 + p1}) on a {n}-device machine"))
    starts = {s0, s1}
    for name, v in sorted(views.items()):
        sv = v.get("start", 0) if isinstance(v, dict) else 0
        if sv not in starts:
            out.append(("error", "STR208",
                        f"op {name!r} starts at device {sv!r}, outside "
                        f"the declared blocks {sorted(starts)}"))
            continue
        cap = p0 if sv == s0 else p1
        parts = _view_parts(v)
        if parts > cap:
            out.append(("error", "STR208",
                        f"op {name!r} needs {parts} parts but its block "
                        f"at device {sv} spans only {cap}"))
    return out


def _lint_pipeline_meta(pm, op_names) -> List[Tuple[str, str, str]]:
    """STR208: structural lint of a persisted ``__meta__.pipeline``
    block (a staged proposal's S x M frame + optional explicit stage
    cut, analysis/placement.py).  Graph-side legality (coverage vs the
    actual graph, boundary-edge coherence — SHD150-152) runs at
    proposal/import time."""
    out: List[Tuple[str, str, str]] = []
    if not isinstance(pm, dict):
        return [("error", "STR208", "pipeline meta is not an object")]
    s = pm.get("num_stages")
    m = pm.get("num_microbatches")
    if not isinstance(s, int) or s < 2:
        out.append(("error", "STR208",
                    f"pipeline meta has malformed num_stages {s!r} "
                    f"(need an int >= 2)"))
        s = None
    if not isinstance(m, int) or m < 1 or (s is not None and m < s):
        out.append(("error", "STR208",
                    f"pipeline meta has malformed num_microbatches "
                    f"{m!r} (need an int >= num_stages)"))
    stages = pm.get("stages")
    if stages is None:
        return out
    if not isinstance(stages, list) or (
            s is not None and len(stages) != s):
        return out + [("error", "STR208",
                       f"pipeline meta declares num_stages {s!r} but "
                       f"carries {len(stages) if isinstance(stages, list) else stages!r} stage lists")]
    seen = set()
    for i, stage in enumerate(stages):
        if not isinstance(stage, list) or not stage:
            out.append(("error", "STR208",
                        f"pipeline meta stages[{i}] is empty or not a "
                        f"list"))
            continue
        for op in stage:
            if not isinstance(op, str) or not op:
                out.append(("error", "STR208",
                            f"pipeline meta stages[{i}] has a non-name "
                            f"entry {op!r}"))
                continue
            if op in seen:
                out.append(("error", "STR208",
                            f"pipeline meta covers op {op!r} twice — it "
                            f"would run twice per tick"))
            seen.add(op)
            if op not in op_names:
                out.append(("error", "STR208",
                            f"pipeline meta stages[{i}] names op {op!r} "
                            f"the strategy file does not cover"))
    return out


def _lint_sync_schedule_meta(sched) -> List[Tuple[str, str, str]]:
    """STR205: structural lint of a persisted ``__meta__.sync_schedule``
    (the searched comm plan, search/sync_schedule.py).  Graph-side
    legality (coverage, issue order vs readiness, precision coherence —
    SHD12x) needs the graph and runs at import/compile time."""
    out: List[Tuple[str, str, str]] = []
    if not isinstance(sched, dict):
        return [("error", "STR205", "sync_schedule is not an object")]
    if sched.get("schema") != _SCHEDULE_SCHEMA:
        out.append(("error", "STR205",
                    f"sync_schedule schema {sched.get('schema')!r} unknown "
                    f"(known: {_SCHEDULE_SCHEMA})"))
    buckets = sched.get("buckets")
    if not isinstance(buckets, list) or not buckets:
        return out + [("error", "STR205", "sync_schedule has no buckets")]
    seen_ops = set()
    for i, b in enumerate(buckets):
        if not isinstance(b, dict):
            out.append(("error", "STR205",
                        f"sync_schedule buckets[{i}] is not an object"))
            continue
        if not isinstance(b.get("name"), str) or not b.get("name"):
            out.append(("error", "STR205",
                        f"sync_schedule buckets[{i}] has no name"))
        if b.get("precision", "fp32") not in _BUCKET_PRECISIONS:
            out.append(("error", "STR205",
                        f"sync_schedule buckets[{i}] precision "
                        f"{b.get('precision')!r} unknown"))
        ops = b.get("ops")
        if (not isinstance(ops, list) or not ops
                or any(not isinstance(o, str) for o in ops)):
            out.append(("error", "STR205",
                        f"sync_schedule buckets[{i}] has malformed ops "
                        f"{str(ops)[:80]}"))
            continue
        for o in ops:
            if o in seen_ops:
                out.append(("error", "STR205",
                            f"sync_schedule covers op {o!r} twice — its "
                            f"gradient would sync twice"))
            seen_ops.add(o)
        if b.get("plan") is not None:
            out += _lint_reduction_plan_meta(b["plan"], i)
    return out


_PLAN_STAGE_KINDS = ("reduce_scatter", "allreduce", "all_gather")
# mirrors search/reduction_plan.STAGE_KINDS (stdlib path)


def _lint_reduction_plan_meta(plan, bi: int) -> List[Tuple[str, str, str]]:
    """STR206: structural lint of a persisted per-bucket reduction plan
    (the staged hierarchical comm shape, search/reduction_plan.py).
    Machine-side legality (level coverage vs the topology the groups
    span — SHD13x) needs the graph + machine model and runs at
    import/compile time."""
    where = f"sync_schedule buckets[{bi}] plan"
    out: List[Tuple[str, str, str]] = []
    if not isinstance(plan, dict):
        return [("error", "STR206", f"{where} is not an object")]
    if not isinstance(plan.get("name"), str) or not plan.get("name"):
        out.append(("error", "STR206", f"{where} has no name"))
    stages = plan.get("stages")
    if not isinstance(stages, list) or not stages:
        return out + [("error", "STR206", f"{where} has no stages")]
    ar_levels = []
    for j, s in enumerate(stages):
        if not isinstance(s, dict):
            out.append(("error", "STR206",
                        f"{where} stages[{j}] is not an object"))
            continue
        kind = s.get("kind")
        if kind not in _PLAN_STAGE_KINDS:
            out.append(("error", "STR206",
                        f"{where} stages[{j}] kind {kind!r} unknown "
                        f"(known: {list(_PLAN_STAGE_KINDS)})"))
        level = s.get("level")
        if not isinstance(level, int) or level < 0:
            out.append(("error", "STR206",
                        f"{where} stages[{j}] malformed level {level!r}"))
        prec = s.get("precision", "fp32")
        if prec not in _BUCKET_PRECISIONS:
            out.append(("error", "STR206",
                        f"{where} stages[{j}] precision {prec!r} unknown"))
        elif kind != "allreduce" and prec != "fp32":
            out.append(("error", "STR206",
                        f"{where} stages[{j}] compresses a {kind} stage "
                        f"— only the cross-level allreduce may"))
        if kind == "allreduce":
            ar_levels.append(level)
    if len(ar_levels) != 1:
        out.append(("error", "STR206",
                    f"{where} must have exactly one cross-level "
                    f"allreduce stage (found {len(ar_levels)})"))
    return out


# ---------------------------------------------------------------------------
# cost-cache files (stdlib)


def lint_cache_file(path: str) -> List[Tuple[str, str, str]]:
    data, err = _load_json(path)
    if err:
        return [("error", "CCH400", err)]
    if not isinstance(data, dict):
        return [("error", "CCH400", "top level is not a JSON object")]
    out: List[Tuple[str, str, str]] = []
    if data.get("schema") not in CACHE_SCHEMA_VERSIONS:
        out.append(("error", "CCH401",
                    f"unknown schema {data.get('schema')!r} (known: "
                    f"{list(CACHE_SCHEMA_VERSIONS)})"))
    sig = data.get("signature")
    if (not isinstance(sig, str) or len(sig) != 16
            or any(c not in "0123456789abcdef" for c in sig)):
        out.append(("error", "CCH401",
                    f"malformed cost-surface signature {sig!r} (expect 16 "
                    "hex chars)"))
    if data.get("calibration_stale"):
        out.append(("warn", "CCH403",
                    "calibration_stale is set: the cache refuses to serve "
                    "until recalibration (drift gate, obs/drift.py)"))
    rows = data.get("rows", [])
    if not isinstance(rows, list):
        return out + [("error", "CCH402", "rows is not a list")]
    seen = set()
    for i, r in enumerate(rows):
        ok = (
            isinstance(r, dict)
            and isinstance(r.get("sig"), str)
            and isinstance(r.get("degrees"), list)
            and all(isinstance(d, int) and d >= 1 for d in r["degrees"])
            and isinstance(r.get("replica"), int) and r["replica"] >= 1
            and isinstance(r.get("row"), list) and len(r["row"]) == 4
            and all(isinstance(x, (int, float)) and math.isfinite(x)
                    and x >= 0 for x in r["row"])
        )
        if not ok:
            out.append(("error", "CCH402", f"rows[{i}] malformed: "
                        f"{str(r)[:120]}"))
            continue
        key = (r["sig"], tuple(r["degrees"]), r["replica"])
        if key in seen:
            out.append(("error", "CCH402",
                        f"rows[{i}] duplicates key for degrees "
                        f"{r['degrees']} replica {r['replica']}"))
        seen.add(key)
    sidecar = path + ".results.pkl"
    if os.path.exists(sidecar) and os.path.getsize(sidecar) == 0:
        out.append(("error", "CCH404", f"empty results sidecar {sidecar}"))
    out += _lint_dp_rows(data)
    out += _lint_sp_rows(data)
    out += _lint_comm_plans(data)
    return out


def _lint_digest_row_layer(data, rows_key, schema_key, versions,
                           code_schema, code_row,
                           ) -> List[Tuple[str, str, str]]:
    """Shared shape lint for the digest-keyed memo-row layers — the
    dp-row layer (tier-2 segment strategies) and the sp-row layer
    (whole series-parallel segment solves) persist the SAME row layout:
    ``{"cost": float, "strategy": [[hex digest, degrees, replica,
    start], ...]}`` under '<graph digest>:<pin/knob digest>' keys.  An
    unknown sub-schema is the DISTINCT loud-drop error; malformed rows
    get the layer's row code."""
    layer = data.get(rows_key)
    if layer is None:
        return []
    out: List[Tuple[str, str, str]] = []
    if data.get(schema_key) not in versions:
        out.append(("error", code_schema,
                    f"{rows_key} present but {schema_key} "
                    f"{data.get(schema_key)!r} unknown (known: "
                    f"{list(versions)}) — the loader will drop "
                    f"the whole {rows_key} layer"))
    if not isinstance(layer, dict):
        return out + [("error", code_row,
                       f"{rows_key} is not an object")]
    for key, row in sorted(layer.items()):
        where = f"{rows_key}[{key[:32]}...]" if len(key) > 32 else \
            f"{rows_key}[{key}]"
        if not isinstance(key, str) or ":" not in key:
            out.append(("error", code_row,
                        f"{where}: malformed key (expect "
                        f"'<graph digest>:<pin/knob digest>')"))
        if not isinstance(row, dict):
            out.append(("error", code_row, f"{where}: row is not an "
                        "object"))
            continue
        cost = row.get("cost")
        if not isinstance(cost, (int, float)) or not math.isfinite(cost) \
                or cost < 0:
            out.append(("error", code_row,
                        f"{where}: malformed cost {cost!r}"))
        strat = row.get("strategy")
        if not isinstance(strat, list) or not strat:
            out.append(("error", code_row, f"{where}: no strategy rows"))
            continue
        for j, entry in enumerate(strat):
            ok = (
                isinstance(entry, list) and len(entry) == 4
                and isinstance(entry[0], str) and entry[0]
                and all(c in "0123456789abcdef" for c in entry[0])
                and isinstance(entry[1], list)
                and all(isinstance(d, int) and d >= 1 for d in entry[1])
                and isinstance(entry[2], int) and entry[2] >= 1
                and isinstance(entry[3], int) and entry[3] >= 0
            )
            if not ok:
                out.append(("error", code_row,
                            f"{where}: strategy[{j}] malformed: "
                            f"{str(entry)[:100]}"))
    return out


def _lint_dp_rows(data) -> List[Tuple[str, str, str]]:
    """CCH405/406: the persisted DP-memo-row layer (search/cost_cache.py
    dp_rows — tier-2 segment strategies under process-stable digests).
    An unknown ``dp_schema`` is a DISTINCT error (CCH405): the loader
    drops the layer loudly rather than serving rows written under
    another layout; malformed rows are CCH406."""
    return _lint_digest_row_layer(
        data, "dp_rows", "dp_schema", DP_SCHEMA_VERSIONS,
        "CCH405", "CCH406")


def _lint_sp_rows(data) -> List[Tuple[str, str, str]]:
    """CCH409/410: the persisted SP-SEGMENT memo-row layer
    (search/cost_cache.py sp_rows — whole series-parallel segment
    solves keyed by segment digest + boundary-view-tuple pins + search
    knobs, driver._persist_sp_row).  Same row layout and fail-LOUD
    discipline as the dp layer: unknown ``sp_schema`` is CCH409 (the
    loader drops the layer, segments re-solve), malformed rows are
    CCH410 (the in-process reader treats them as a miss — one
    re-solve, never a wrong stamped strategy)."""
    return _lint_digest_row_layer(
        data, "sp_rows", "sp_schema", SP_SCHEMA_VERSIONS,
        "CCH409", "CCH410")


def _lint_comm_plans(data) -> List[Tuple[str, str, str]]:
    """CCH407/408: the persisted comm-plan memo layer
    (search/cost_cache.py ``comm_plans`` — the co-search's chosen sync
    schedules/precision maps/zero choices per synced-group signature,
    search/comm_plan.py).  An unknown ``comm_schema`` is a DISTINCT
    error (CCH407): the loader drops the layer loudly rather than
    serving plans written under another layout; malformed rows are
    CCH408 (the in-process reader treats them as a miss — one
    re-search, never a wrong plan)."""
    cp = data.get("comm_plans")
    if cp is None:
        return []
    out: List[Tuple[str, str, str]] = []
    if data.get("comm_schema") not in COMM_SCHEMA_VERSIONS:
        out.append(("error", "CCH407",
                    f"comm_plans present but comm_schema "
                    f"{data.get('comm_schema')!r} unknown (known: "
                    f"{list(COMM_SCHEMA_VERSIONS)}) — the loader will "
                    f"drop the whole comm-plan layer"))
    if not isinstance(cp, dict):
        return out + [("error", "CCH408", "comm_plans is not an object")]
    for key, row in sorted(cp.items()):
        where = f"comm_plans[{key[:32]}...]" if len(key) > 32 else \
            f"comm_plans[{key}]"
        if (not isinstance(key, str) or len(key) != 24
                or any(c not in "0123456789abcdef" for c in key)):
            out.append(("error", "CCH408",
                        f"{where}: malformed key (expect a 24-hex-char "
                        f"signature digest)"))
        if not isinstance(row, dict):
            out.append(("error", "CCH408",
                        f"{where}: row is not an object"))
            continue
        sched = row.get("schedule")
        if not isinstance(sched, dict):
            out.append(("error", "CCH408", f"{where}: no schedule"))
        else:
            for sev, _code, msg in _lint_sync_schedule_meta(sched):
                out.append((sev, "CCH408", f"{where}: {msg}"))
        if not isinstance(row.get("adopted"), bool):
            out.append(("error", "CCH408",
                        f"{where}: malformed adopted "
                        f"{row.get('adopted')!r}"))
        pmap = row.get("pmap", {})
        if (not isinstance(pmap, dict)
                or any(not isinstance(k, str) or v not in
                       _BUCKET_PRECISIONS for k, v in pmap.items())):
            out.append(("error", "CCH408",
                        f"{where}: malformed pmap {str(pmap)[:80]}"))
        zero = row.get("zero", [])
        if (not isinstance(zero, list)
                or any(not isinstance(z, str) or not z for z in zero)):
            out.append(("error", "CCH408",
                        f"{where}: malformed zero list "
                        f"{str(zero)[:80]}"))
        credit = row.get("credit", 0.0)
        if (not isinstance(credit, (int, float))
                or not math.isfinite(credit) or credit < 0):
            out.append(("error", "CCH408",
                        f"{where}: malformed credit {credit!r}"))
    return out


# ---------------------------------------------------------------------------
# rewrite registry (imports flexflow_tpu — jax required)


def lint_registry(num_devices: int, substitution_json: str = "",
                  ) -> Tuple[List[Tuple[str, str, str]], dict]:
    """(findings, info) for the registry proof: the hand-zoo pass (the
    regression anchor) over the factory xfers, then the GENERATIVE
    pass (analysis/proofgen.py) over factory + any JSON rules —
    factory xfers must anchor on generated graphs (EQV305 closed by
    construction), unproven JSON rules are listed as EQV306.  ``info``
    carries both passes' wall-clock (the CI verification budget) and
    the generation stats."""
    import time as _time

    from flexflow_tpu.analysis.equivalence import verify_registry
    from flexflow_tpu.analysis.proofgen import verify_registry_generated
    from flexflow_tpu.search.substitution import generate_all_pcg_xfers

    factory = generate_all_pcg_xfers(num_devices)
    t0 = _time.perf_counter()
    findings = list(verify_registry(num_devices=num_devices,
                                    xfers=factory))
    t_zoo = _time.perf_counter() - t0
    xfers = list(factory)
    if substitution_json:
        from flexflow_tpu.search.substitution_loader import (
            load_substitution_json,
        )

        xfers += load_substitution_json(substitution_json)
    t0 = _time.perf_counter()
    gen_findings, stats = verify_registry_generated(
        num_devices=num_devices, xfers=xfers)
    t_gen = _time.perf_counter() - t0
    findings += gen_findings
    info = {
        "zoo_seconds": round(t_zoo, 3),
        "proofgen_seconds": round(t_gen, 3),
        "xfers": stats["xfers"],
        "graphs_generated": stats["graphs_generated"],
        "proofs": stats["proofs"],
        "lanes": stats["lanes"],
        "unproven": stats["unproven"],
    }
    return ([(f.severity, f.code, f.message) for f in findings], info)


# ---------------------------------------------------------------------------


def _report(path: str, findings: List[Tuple[str, str, str]],
            as_json: bool = False) -> int:
    errors = 0
    for sev, code, msg in findings:
        if as_json:
            # machine-readable contract: one JSON object per finding
            # line (exit codes unchanged — CI keys on both)
            print(json.dumps({"path": path, "severity": sev,
                              "code": code, "msg": msg}))
        else:
            print(f"{path}: {sev.upper()} [{code}] {msg}")
        if sev == "error":
            errors += 1
    return errors


def _summary(args, text: str, **payload) -> None:
    if getattr(args, "json", False):
        print(json.dumps({"summary": True, "cmd": args.cmd, **payload}))
    else:
        print(text)


def cmd_strategy(args) -> int:
    errors = 0
    for path in args.files:
        errors += _report(
            path,
            lint_strategy_file(
                path, calibration_path=getattr(args, "calibration", None)),
            args.json)
    _summary(args,
             f"fflint strategy: {len(args.files)} file(s), {errors} "
             f"error(s)", files=len(args.files), errors=errors)
    return 1 if errors else 0


def cmd_cache(args) -> int:
    errors = 0
    for path in args.files:
        errors += _report(path, lint_cache_file(path), args.json)
    _summary(args,
             f"fflint cache: {len(args.files)} file(s), {errors} "
             f"error(s)", files=len(args.files), errors=errors)
    return 1 if errors else 0


def cmd_registry(args) -> int:
    findings, info = lint_registry(
        args.devices, getattr(args, "substitution_json", "") or "")
    errors = _report("registry", findings, args.json)
    _summary(
        args,
        f"fflint registry: {args.devices}-device rewrite registry, "
        f"{errors} error(s)\n"
        f"  zoo proof {info['zoo_seconds']}s; generative proof "
        f"{info['proofgen_seconds']}s — {info['proofs']} proofs over "
        f"{info['graphs_generated']} generated graphs "
        f"({info['xfers']} xfers, lanes {info['lanes']}, "
        f"{info['unproven']} unproven)",
        errors=errors, **info)
    return 1 if errors else 0


def _staged_blobs(root: str, tmpdir: str) -> Optional[List[Tuple[str, str]]]:
    """``(repo-relative path, staged-blob temp file under tmpdir)`` for
    every artifact path staged for commit, or None when git is
    unavailable / not a repository — pre-commit then lints the whole
    tree like ``all``.  The lint must read the STAGED content
    (``git show :path``), not the working tree: a file fixed after
    ``git add`` would otherwise let the corrupt staged blob land (and
    vice versa)."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "diff", "--cached", "--name-only", "--diff-filter=d"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out: List[Tuple[str, str]] = []
    for rel in proc.stdout.splitlines():
        if not rel or not rel.endswith(".json"):
            continue
        base = os.path.basename(rel)
        if not (base.startswith("COST_CACHE") or "strategy" in base.lower()):
            continue
        blob = subprocess.run(
            ["git", "show", f":{rel}"], cwd=root, capture_output=True,
            timeout=30)
        if blob.returncode != 0:
            continue
        # mirror the repo-relative path: same-basename artifacts in
        # different directories must not overwrite each other's blobs
        tmp = os.path.join(tmpdir, rel)
        os.makedirs(os.path.dirname(tmp) or tmpdir, exist_ok=True)
        with open(tmp, "wb") as f:
            f.write(blob.stdout)
        out.append((rel, tmp))
    return out


def cmd_precommit(args) -> int:
    """The git pre-commit gate (ROADMAP PR 4 follow-up): lint the
    STAGED artifact blobs (cost caches / strategy files — stdlib, fast)
    and prove the rewrite registry (``fflint registry`` — imports jax).
    Install via the committed hook file:

        git config core.hooksPath .githooks

    Skip once with ``git commit --no-verify``; skip the slow registry
    proof with ``--skip-registry`` (artifact lints still run)."""
    import tempfile

    errors = 0
    # the staged blobs live in one throwaway dir — the hook runs on
    # every commit, so leaking it would accumulate unboundedly
    with tempfile.TemporaryDirectory(prefix="fflint_staged_") as tmpdir:
        staged = _staged_blobs(args.root, tmpdir)
        if staged is None:
            print("fflint pre-commit: no git staging info — linting the "
                  "whole tree")
            staged = [
                (p, p) for p in sorted(glob.glob(
                    os.path.join(args.root, "**", "*.json"),
                    recursive=True))
                if os.path.basename(p).startswith("COST_CACHE")
                or "strategy" in os.path.basename(p).lower()
            ]
        caches = [(rel, p) for rel, p in staged
                  if os.path.basename(rel).startswith("COST_CACHE")]
        strategies = [(rel, p) for rel, p in staged
                      if "strategy" in os.path.basename(rel).lower()]
        for rel, path in caches:
            errors += _report(rel, lint_cache_file(path), args.json)
        for rel, path in strategies:
            # the staged blob lives in the temp mirror, but its "live
            # CALIBRATION.json sibling" (STR210) is the one in the repo
            errors += _report(
                rel,
                lint_strategy_file(path, calibration_path=os.path.join(
                    args.root, os.path.dirname(rel), "CALIBRATION.json")),
                args.json)
    if not args.skip_registry:
        findings, _info = lint_registry(args.devices)
        errors += _report("registry", findings, args.json)
    _summary(args,
             f"fflint pre-commit: {len(caches)} cache file(s), "
             f"{len(strategies)} strategy file(s)"
             + ("" if args.skip_registry else
                f", registry @ {args.devices} devices")
             + f" — {errors} error(s)",
             caches=len(caches), strategies=len(strategies),
             errors=errors)
    return 1 if errors else 0


def cmd_all(args) -> int:
    errors = 0
    caches = sorted(glob.glob(
        os.path.join(args.root, "**", "COST_CACHE*.json"), recursive=True))
    strategies = sorted(
        p for p in glob.glob(os.path.join(args.root, "**", "*.json"),
                             recursive=True)
        if "strategy" in os.path.basename(p).lower()
    )
    for path in caches:
        errors += _report(path, lint_cache_file(path), args.json)
    for path in strategies:
        errors += _report(path, lint_strategy_file(path), args.json)
    findings, info = lint_registry(args.devices)
    errors += _report("registry", findings, args.json)
    _summary(args,
             f"fflint all: {len(caches)} cache file(s), "
             f"{len(strategies)} strategy file(s), registry @ "
             f"{args.devices} devices — {errors} error(s) "
             f"(registry proofs: zoo {info['zoo_seconds']}s + "
             f"generative {info['proofgen_seconds']}s)",
             caches=len(caches), strategies=len(strategies),
             errors=errors, **info)
    return 1 if errors else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="fflint", description=__doc__)
    common = argparse.ArgumentParser(add_help=False)
    common.add_argument("--json", action="store_true",
                        help="machine-readable output: one JSON object "
                             "per finding line, a summary object last "
                             "(exit codes unchanged)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("strategy", parents=[common],
                       help="lint exported strategy files")
    p.add_argument("files", nargs="+")
    p.add_argument("--calibration", default=None,
                   help="live CALIBRATION.json the STR210 staleness "
                        "check compares __meta__.calibration_signature "
                        "against (default: each strategy file's "
                        "sibling)")
    p.set_defaults(fn=cmd_strategy)
    p = sub.add_parser("cache", parents=[common],
                       help="lint persistent cost-cache files")
    p.add_argument("files", nargs="+")
    p.set_defaults(fn=cmd_cache)
    p = sub.add_parser("registry", parents=[common],
                       help="numeric-equivalence proof of the rewrite "
                            "registry — hand zoo + generated proof "
                            "graphs (imports jax)")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--substitution-json", default="",
                   help="also prove the rules of this JSON collection "
                        "(unproven rules are listed as EQV306)")
    p.set_defaults(fn=cmd_registry)
    p = sub.add_parser("all", parents=[common],
                       help="lint committed artifacts + registry")
    p.add_argument("--root", default=".")
    p.add_argument("--devices", type=int, default=8)
    p.set_defaults(fn=cmd_all)
    p = sub.add_parser("pre-commit", parents=[common],
                       help="git pre-commit gate: lint STAGED artifact "
                            "files + prove the rewrite registry "
                            "(install: git config core.hooksPath "
                            ".githooks)")
    p.add_argument("--root", default=".")
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--skip-registry", action="store_true",
                   help="artifact lints only (skips the jax-importing "
                        "registry proof)")
    p.set_defaults(fn=cmd_precommit)
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 2 if e.code else 0
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
