#!/usr/bin/env python
"""benchdiff — guard the bench numbers against silent regression.

``BENCH_SEARCH.json`` is the repo's measured record (bench_search.py
appends a section per landed subsystem); ``BENCH_LASTGOOD.json`` is
the blessed snapshot.  This tool compares a fresh bench run against
the snapshot and exits non-zero when any shared metric regressed past
a tolerance band — the opt-in pre-commit leg next to fflint
(``FF_PRECOMMIT_BENCHDIFF=1``, see .githooks/pre-commit).

Direction is inferred from the metric name: latency-shaped leaves
(``*_s``, ``*_ms``, ``p99``, ``ttft``, ``e2e``, ``wall``, ``cost``)
regress UP; rate-shaped leaves (``throughput``, ``samples``, ``mfu``,
``win``, ``speedup``) regress DOWN.  Leaves matching neither are
informational only — a count changing is not a regression.  Missing
files, no metric overlap, and new/removed sections all exit 0: the
guard refuses only on MEASURED regression, never on shape drift (an
opt-in hook that blocks commits spuriously gets turned off, which
guards nothing).

Usage:
  benchdiff.py check   [--fresh BENCH_SEARCH.json]
                       [--lastgood BENCH_LASTGOOD.json]
                       [--tolerance 0.25]
  benchdiff.py snapshot [--fresh ...] [--lastgood ...]
                        write the fresh run's metrics into the
                        lastgood snapshot (blessing a new baseline;
                        legacy headline keys are preserved)

Stdlib-only; no jax import.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, Optional, Tuple

# substrings that mark a numeric leaf as lower-is-better (latency /
# cost shaped) vs higher-is-better (rate shaped); checked in order,
# first hit wins, no hit = informational
_LOWER = ("_ms", "_s", "seconds", "p99", "p95", "p50", "ttft", "tpot",
          "e2e", "wall", "cost", "latency", "bubble", "staleness")
_HIGHER = ("throughput", "samples_per", "mfu", "win", "speedup",
           "tokens_per", "hit_rate", "vs_baseline", "value")

# KV-lane metrics (the --kv sweep, PR 18) need EXPLICIT leaf names
# checked before the substring scan: "kv_shared_bytes" would otherwise
# hit the "_s" latency pattern ("_shared") and judge MORE sharing as a
# regression, and "kv_pool_bytes" matches nothing.  Shared bytes /
# concurrency up = better; pool residency / CoW copies down = better.
_KV_UP = ("kv_shared_bytes", "max_concurrent", "prefix_hits",
          "shared_pages")
_KV_DOWN = ("kv_pool_bytes", "kv_bytes_per_device", "cow_copies",
            "private_pages")


def direction(path: str) -> Optional[str]:
    """'down' = lower is better, 'up' = higher is better, None =
    informational (counts, ids, flags-as-ints)."""
    leaf = path.rsplit(".", 1)[-1].lower()
    if leaf in _KV_UP:
        return "up"
    if leaf in _KV_DOWN:
        return "down"
    for pat in _LOWER:
        if pat in leaf:
            return "down"
    for pat in _HIGHER:
        if pat in leaf:
            return "up"
    return None


def extract(doc, prefix: str = "") -> Dict[str, float]:
    """Every finite numeric leaf of a bench JSON as dotted.path ->
    value.  Booleans are skipped (adopted flags are shape, not
    measurement); list elements index into the path."""
    out: Dict[str, float] = {}
    if isinstance(doc, dict):
        # legacy single-headline shape: {"metric": name, "value": v}
        if "metric" in doc and "value" in doc and prefix == "":
            name = str(doc["metric"])
            for k, v in doc.items():
                if k in ("metric", "unit", "measured_at"):
                    continue
                key = name if k == "value" else f"{name}.{k}"
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool) \
                        and math.isfinite(v):
                    out[key] = float(v)
            return out
        for k, v in doc.items():
            out.update(extract(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            out.update(extract(v, f"{prefix}[{i}]"))
    elif isinstance(doc, (int, float)) and not isinstance(doc, bool) \
            and math.isfinite(doc):
        out[prefix] = float(doc)
    return out


def _load(path: str) -> Optional[dict]:
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def compare(fresh: Dict[str, float], base: Dict[str, float],
            tolerance: float) -> Tuple[list, int]:
    """(regressions, compared): regressions are (path, base, fresh,
    ratio, direction) rows past the tolerance band on shared,
    direction-bearing metrics."""
    regressions = []
    compared = 0
    for path in sorted(set(fresh) & set(base)):
        d = direction(path)
        if d is None:
            continue
        b, f = base[path], fresh[path]
        compared += 1
        if b == 0:
            continue  # ratio undefined; an honest zero is not a base
        ratio = f / b
        if d == "down" and ratio > 1.0 + tolerance:
            regressions.append((path, b, f, ratio, "slower"))
        elif d == "up" and ratio < 1.0 / (1.0 + tolerance):
            regressions.append((path, b, f, ratio, "lower"))
    return regressions, compared


def cmd_check(args) -> int:
    fresh_doc = _load(args.fresh)
    base_doc = _load(args.lastgood)
    if fresh_doc is None or base_doc is None:
        missing = args.fresh if fresh_doc is None else args.lastgood
        print(f"benchdiff: {missing} missing/unreadable — nothing to "
              f"compare (ok)")
        return 0
    fresh = extract(fresh_doc)
    base = extract(base_doc.get("metrics", base_doc))
    regressions, compared = compare(fresh, base, args.tolerance)
    if not compared:
        print("benchdiff: no shared direction-bearing metrics — "
              "nothing to compare (ok)")
        return 0
    if not regressions:
        print(f"benchdiff: {compared} shared metric(s) within "
              f"{args.tolerance:.0%} of {args.lastgood} — ok")
        return 0
    print(f"benchdiff: {len(regressions)} regression(s) past "
          f"{args.tolerance:.0%} (of {compared} compared):")
    for path, b, f, ratio, word in regressions:
        print(f"  {path}: {b:g} -> {f:g}  ({ratio:.2f}x, {word})")
    print(f"(bless the new numbers with `benchdiff.py snapshot` if "
          f"they are intentional)")
    return 2


def cmd_snapshot(args) -> int:
    fresh_doc = _load(args.fresh)
    if fresh_doc is None:
        print(f"benchdiff: {args.fresh} missing/unreadable — nothing "
              f"to snapshot", file=sys.stderr)
        return 1
    base_doc = _load(args.lastgood) or {}
    out = dict(base_doc)  # legacy headline keys survive the blessing
    out["metrics"] = extract(fresh_doc)
    with open(args.lastgood, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"benchdiff: snapshotted {len(out['metrics'])} metric(s) "
          f"from {args.fresh} into {args.lastgood}")
    return 0


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(prog="benchdiff", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, fn in (("check", cmd_check), ("snapshot", cmd_snapshot)):
        p = sub.add_parser(name)
        p.add_argument("--fresh",
                       default=os.path.join(root, "BENCH_SEARCH.json"))
        p.add_argument("--lastgood",
                       default=os.path.join(root, "BENCH_LASTGOOD.json"))
        p.add_argument("--tolerance", type=float, default=0.25,
                       help="relative band a metric may move against "
                            "its direction before it counts as a "
                            "regression (default 0.25)")
        p.set_defaults(fn=fn)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
