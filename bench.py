#!/usr/bin/env python
"""Benchmark entry point — prints ONE JSON line.

Measures training throughput (samples/s) and MFU of the flagship model
(Transformer encoder, the reference's examples/cpp/Transformer workload:
transformer.cc:112-211 self-reports THROUGHPUT the same way) on the
available accelerator.  The reference repo publishes no absolute
numbers (BASELINE.md), so vs_baseline reports delivered MFU against a
0.40 good-utilization bar for this workload — exceeding 1.0 means the
chip is running at better than 40% of bf16 MXU peak.
"""

import json
import os
import sys
import time

import numpy as np


def _probe_backend(timeout_s: float = 120.0):
    """Fail fast if the accelerator is unreachable.  A wedged device
    tunnel hangs backend INITIALIZATION (jax.devices()) or the first
    computation forever (observed: a remote-compile failure left the
    relay claiming forever) — a bench that hangs records nothing; a
    loud early exit records the cause.  Returns jax.devices()."""
    import threading

    done = threading.Event()
    out = []

    def _try():
        try:
            import jax
            import jax.numpy as jnp

            devs = jax.devices()
            x = jnp.ones((64, 64))
            (x @ x).block_until_ready()
            out.append(devs)
        except Exception as e:  # pragma: no cover
            out.append(e)
        finally:
            done.set()

    t = threading.Thread(target=_try, daemon=True)
    t.start()
    if not done.wait(timeout_s):
        print(
            f"# bench: accelerator backend unresponsive after "
            f"{timeout_s:.0f}s — device tunnel down?",
            file=sys.stderr,
        )
        os._exit(3)  # the hung init/compile thread cannot be joined
    if isinstance(out[0], Exception):
        raise out[0]
    return out[0]


def main():
    import jax

    devices = _probe_backend()
    on_tpu = devices[0].platform == "tpu" or "TPU" in str(devices[0])
    # sized for a single v5e chip; shrink on CPU so CI-style runs finish
    if on_tpu:
        batch, seq, hidden, layers, heads, ff_dim = 64, 256, 512, 6, 8, 2048
        steps = 30
        dtype = "bfloat16"
    else:
        batch, seq, hidden, layers, heads, ff_dim = 8, 32, 64, 2, 4, 128
        steps = 5
        dtype = "float32"

    import flexflow_tpu as ff
    from flexflow_tpu.models import build_transformer

    cfg = ff.FFConfig(
        batch_size=batch,
        epochs=1,
        num_devices=len(devices),
        only_data_parallel=len(devices) == 1,
        compute_dtype=dtype,
    )
    model = build_transformer(
        cfg, num_layers=layers, hidden=hidden, num_heads=heads,
        ff_dim=ff_dim, seq_len=seq,
    )
    model.compile(
        optimizer=ff.AdamOptimizer(alpha=1e-4),
        loss_type="mean_squared_error",
        metrics=["mean_squared_error"],
    )

    rng = np.random.default_rng(0)
    # N distinct batches stacked on a leading step axis: one
    # train_steps() call scans all N inside a single compiled program —
    # the XLA analogue of the reference's Legion iteration tracing
    # (flexflow_cffi.py:1867-1874), amortizing per-call dispatch (which
    # dominates through a remote-device tunnel)
    trace_n = 10 if on_tpu else steps
    xs = rng.normal(size=(trace_n, batch, seq, hidden)).astype(np.float32)
    ys = rng.normal(size=(trace_n, batch, seq, hidden)).astype(np.float32)
    xs_d = jax.device_put(xs, model.compiled.stacked_input_sharding(0))
    ys_d = jax.device_put(ys, model.compiled.stacked_batch_sharding())

    import jax.random as jrandom

    # warmup: first call compiles; later calls through the device tunnel
    # still need a few rounds to reach steady state
    params, opt_state, state = model.params, model.opt_state, model.state
    for i in range(3 if on_tpu else 1):
        params, opt_state, state, losses, m = model.compiled.train_steps(
            params, opt_state, state, jrandom.key(1000 + i), [xs_d], ys_d
        )
    float(losses[-1])  # host readback — block_until_ready may not fence
    # through remote-device tunnels, a readback always does

    # Timed block: reps calls dispatched back-to-back (async dispatch
    # keeps the device pipelined, as a real training loop would), one
    # readback fence at the end.  The block repeats and the MEDIAN block
    # time is reported — robust to tunnel-latency outliers that made
    # single-block runs swing by ~8%.  Per-call fencing would serialize
    # the pipeline and measure round-trips, not training.
    reps = max(1, steps // trace_n)
    block_times = []
    for _ in range(3 if on_tpu else 1):
        t0 = time.perf_counter()
        for i in range(reps):
            params, opt_state, state, losses, m = model.compiled.train_steps(
                params, opt_state, state, jrandom.key(i + 1), [xs_d], ys_d
            )
        float(losses[-1])
        block_times.append(time.perf_counter() - t0)
    elapsed = float(np.median(block_times))
    steps = reps * trace_n
    throughput = steps * batch / elapsed

    # MFU = model FLOPs actually trained / elapsed / chip peak.  Forward
    # FLOPs come from the PCG's own per-op estimates (the same numbers the
    # cost model ranks strategies with); training ≈ 3x forward (bwd does
    # the two grad matmuls per fwd matmul).
    fwd_flops = sum(
        n.op.flops() for n in model.graph.nodes.values()
    )
    train_flops_per_step = 3.0 * fwd_flops
    from flexflow_tpu.core.machine import MachineSpec

    if on_tpu:
        kind = getattr(devices[0], "device_kind", "").lower().replace(" ", "")
        # bf16 MXU peaks per chip by generation
        known_peaks = {
            "v5p": 4.59e14,
            "v5e": 1.97e14,
            "v5litepod": 1.97e14,
            "v6e": 9.2e14,
            "v6": 9.2e14,
            "v4": 2.75e14,
            "v3": 1.23e14,
        }
        peak = next(
            (p for k, p in known_peaks.items() if k in kind),
            MachineSpec.tpu_v5e(1).peak_flops,
        )
        if not any(k in kind for k in known_peaks):
            print(f"# warning: unknown TPU kind {kind!r}, assuming v5e peak",
                  file=sys.stderr)
    else:
        peak = MachineSpec.host_cpu(1).peak_flops
    mfu = train_flops_per_step * steps / elapsed / (peak * len(devices))
    # vs_baseline: the reference publishes no absolute numbers
    # (BASELINE.md); its per-chip contract is utilization, so report the
    # ratio of delivered MFU to a 40% good-MFU bar for this workload.
    print(
        json.dumps(
            {
                "metric": "transformer_train_throughput",
                "value": round(throughput, 2),
                "unit": "samples/s",
                "mfu": round(mfu, 4),
                "vs_baseline": round(mfu / 0.40, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
