"""Static analysis for the PCG pipeline — the correctness layer that
PROVES what the rest of the system assumes (reference inspiration:
GSPMD's decidable sharding propagation, arXiv:2105.04663; placement
legality as a constraint system, arXiv:2110.10548).

Three passes, one finding vocabulary (``findings.py``):

1. ``invariants``  — graph well-formedness after every rewrite
   (``PCG0xx``), armed by ``FLEXFLOW_TPU_VERIFY=1`` / ``--verify``.
2. ``equivalence`` — executable numeric proofs for the substitution
   registry (``EQV3xx``); ``proofgen`` generates the proof graphs
   from each rewrite's own matcher contract (EQV305 closed by
   construction for factory xfers, EQV306 reports unproven rules).
3. ``sharding``    — strategy/MachineView legality + search/lowering
   coherence (``SHD1xx``), the always-on gate in ``optimize_strategy``.
4. ``placement``   — pipeline stage cuts and ``start_part`` device
   blocks (``SHD150``-``SHD155``), the always-on gate on every
   pipeline/placement proposal the search returns, persists or
   imports.
5. ``swap``        — hot-swap legality (``SHD170``-``SHD172``): a live
   mid-run strategy swap must preserve every weight/op-state shape and
   cover the target graph, the always-on gate of
   ``FFModel.swap_strategy`` / the always-on training controller.

``tools/fflint.py`` exposes all of it as a CI-friendly CLI; findings
also flow through the obs event bus as ``analysis.finding`` events.

``equivalence`` and ``proofgen`` are intentionally NOT imported here:
they import the substitution machinery, which itself imports
``invariants`` — load them explicitly
(``from flexflow_tpu.analysis.equivalence import …``).
"""

from flexflow_tpu.analysis.findings import (
    AnalysisError,
    Finding,
    emit_findings,
    errors_only,
)
from flexflow_tpu.analysis.invariants import (
    CHECK_STATS,
    GraphInvariantError,
    assert_graph_ok,
    check_graph,
    scoped_verify,
    set_verify,
    verification_enabled,
)
from flexflow_tpu.analysis.placement import (
    lint_pipeline_stages,
    lint_placement,
    placement_meta,
)
from flexflow_tpu.analysis.sharding import (
    lint_disaggregation,
    lint_fleet,
    lint_kv,
    lint_reduction_plan,
    lint_serving,
    lint_strategy,
    lint_sync_schedule,
    lint_zero_map,
)
from flexflow_tpu.analysis.swap import lint_swap

__all__ = [
    "AnalysisError",
    "Finding",
    "emit_findings",
    "errors_only",
    "CHECK_STATS",
    "GraphInvariantError",
    "assert_graph_ok",
    "check_graph",
    "scoped_verify",
    "set_verify",
    "verification_enabled",
    "lint_disaggregation",
    "lint_fleet",
    "lint_kv",
    "lint_pipeline_stages",
    "lint_placement",
    "lint_reduction_plan",
    "lint_serving",
    "lint_strategy",
    "lint_swap",
    "lint_sync_schedule",
    "lint_zero_map",
    "placement_meta",
]
