"""Strategy/sharding legality linter — pass 3 of the static-analysis
stack (GSPMD-style, arXiv:2105.04663: sharding consistency is a
decidable static check; arXiv:2110.10548: placement legality as a
constraint system).

For a (graph, ``{guid: MachineView}``) pair this proves what the
lowering (``compiler/lowering.py``) will otherwise discover at XLA
compile time — or worse, not discover at all:

* **SHD101** view rank matches the op's output rank
* **SHD102** every partitioned dim is divisible by its degree
* **SHD103** mesh-capacity fit: total parts divide the device count
  (the divisor rule ``views.boundary_views``/``candidate_views``
  generate under; an imported or cache-served strategy may not)
* **SHD104** ops with a pinned view (``fixed_machine_view``) get it
* **SHD105** the op's own degree propagation accepts the view
* **SHD106** only splittable dims are partitioned; replica degree
  within ``max_replica_degree``
* **SHD107** propagation/lowering coherence: every sharded dim of every
  propagated annotation maps to a view slot of EXACTLY its degree, and
  no slot is consumed twice by one tensor — the condition under which
  ``parallel.mesh.annot_partition_spec`` produces a PartitionSpec whose
  realized degrees equal the annotated ones (search/lowering drift
  check)
* **SHD108** the view's degrees factor onto the mesh's prime-factor
  axis pool (``view_slot_axes`` succeeds — what the lowering will run)
* **SHD109** strategy coverage: every node has a view
* **SHD110** per-edge compatibility: a consumer's input constraint has
  the rank of the producer's output (boundary-view handoff, the
  invariant split-boundary enumeration relies on —
  ``views.boundary_views`` pins one view to both segments)

Gradient-sync SCHEDULE legality (``lint_sync_schedule`` — the
searched, persisted comm plan of search/sync_schedule.py, gated
always-on wherever a schedule is produced or imported):

* **SHD120** structural sanity: bucket precision is a known wire
  precision; every named op exists in the graph and carries weights
* **SHD121** coverage: every weight group that actually syncs under the
  strategy is covered EXACTLY once (no duplicates, no holes — an
  uncovered group silently falls back to the exposed post-backward
  monolithic path)
* **SHD122** issue order respects grad readiness: buckets are ordered
  by non-increasing earliest-member topo position — the backward
  produces grads in reverse topo order, so a bucket issued before its
  grads exist is a plan the executed step cannot honor
* **SHD123** precision coherence: a compressed bucket's ops must be
  gradient-safe to compress and agree with the sync-precision map
  (search/sync_precision.py) — the two artifacts are built together
  and must not contradict

Per-group optimizer-state sharding legality (``lint_zero_map`` — the
co-searched ZeRO-1 dimension of search/comm_plan.py, gated always-on
wherever the map is produced or imported):

* **SHD140** membership: every named op exists in the graph, carries
  weights, and actually SYNCS under the strategy (some propagated
  weight annot is replicated — optimizer state only shards over
  replication axes, so a non-synced entry is incoherent)
* **SHD141** shardability: the op's achieved ZeRO shard factor under
  the shared placement rule (``comm_plan.zero_update_factor`` — the
  same evenly-divisible ``place_zero_factors`` rule the lowering's
  ``_zero_augmented`` and ``CostModel.op_memory`` apply) must exceed
  1 — a map entry whose optimizer state cannot shard was credited a
  win execution will never realize

Staged REDUCTION-PLAN legality (``lint_reduction_plan`` — the
per-bucket hierarchical reduction strategies of
search/reduction_plan.py, gated always-on with the schedule):

* **SHD130** structural sanity: stages form the canonical RS..AR..AG
  bracketing, kinds/precisions known, levels within the machine's
  link hierarchy
* **SHD131** level coverage: the plan's cross level equals the deepest
  link level the bucket's replication groups actually span — too
  shallow leaves the coarse links mispriced, too deep prices stages
  the wire never runs
* **SHD132** group/slice coherence: a staged bucket must contain at
  least one group whose replication provably decomposes across the
  slice boundary (a plan on a within-slice bucket is incoherent)
* **SHD133** precision-per-level validity: only the cross-level
  allreduce stage may compress, and its wire precision must be fp32 or
  the bucket's own (sync-precision-map-coherent) precision — per-level
  precision composes with the map, never contradicts it

Serving-objective legality (``lint_serving`` — the serve/p99 artifacts
of search/serving.py, gated always-on under
``FFConfig.objective="serve"`` and re-run at import):

* **SHD160** spec/graph coherence: the ServingSpec's frame geometry
  matches every decode op's own attrs, decode ops exist, arrival
  quantile in (0, 1)
* **SHD161** KV residency fits: per-device memory incl. the
  full-occupancy page pool within HBM capacity — the "rejected during
  search, not at OOM" budget, re-proven on persisted artifacts
* **SHD162** decode view legality: head-split divides the head count,
  batch degree divides the frame's sequence slots (fixed frames must
  shard evenly)
* **SHD163** SLO coherence (warn): predicted p99 over the declared
  budget is reported, never silently clamped

KV-lane legality (``lint_kv`` — the ``__meta__.kv`` artifact of the
searched KV-precision + prefix-sharing lane, gated always-on when the
lane is armed and re-run at import):

* **SHD168** sharing/refcount accounting coherence: the declared
  shared-prefix page count is a sane fraction of the frame (>= 0,
  < pages_per_seq), agrees with the armed ServingSpec, and the
  recorded shared-residency factor matches the refcount arithmetic —
  residency priced against sharing the runtime will not deliver is an
  OOM deferred, not saved
* **SHD169** pool-dtype legality: the persisted pool dtype is one of
  fp32/bf16/int8, every decode op's own ``kv_dtype`` attr (when
  present) agrees with it and with its siblings, and the scale layout
  matches the dtype discipline (int8 ⇒ per-(page, slot) "page_slot"
  scales; fp32/bf16 ⇒ no scales)

Pure host-side: no mesh construction, no XLA — safe to run inside
``optimize_strategy`` as an always-on gate.
"""

from __future__ import annotations

import math

from typing import Dict, List, Optional

from flexflow_tpu.analysis.findings import Finding, errors_only


def _f(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="sharding", message=message, **kw)


def _annot_findings(annot, slot_sizes, what: str, guid, name) -> List[Finding]:
    """SHD107 for one propagated ShardAnnot."""
    out: List[Finding] = []
    used = set()
    idx = annot.parallel_idx()
    for i, (deg, slot) in enumerate(zip(annot.degrees, idx)):
        if deg <= 1:
            continue
        if slot == -1 or slot not in slot_sizes:
            out.append(_f(
                "SHD107",
                f"{what} dim {i} sharded {deg}-way but maps to no view "
                f"slot", node=guid, op=name))
        elif slot_sizes[slot] != deg:
            out.append(_f(
                "SHD107",
                f"{what} dim {i} annotated degree {deg} but its view "
                f"slot {slot} has degree {slot_sizes[slot]} — the "
                f"lowered PartitionSpec would realize a different "
                f"sharding", node=guid, op=name))
        elif slot in used:
            out.append(_f(
                "SHD107",
                f"{what} maps two dims onto view slot {slot} — the "
                f"PartitionSpec would reuse mesh axes", node=guid, op=name))
        else:
            used.add(slot)
    return out


def lint_strategy(graph, strategy: Dict[int, object],
                  num_devices: int) -> List[Finding]:
    """All legality findings for a (graph, MachineView map) pair on a
    ``num_devices`` mesh ([] = legal).  ``start_part`` offsets are
    placement hints the GSPMD lowering ignores and are not linted."""
    from flexflow_tpu.ops.base import REPLICA_SLOT
    from flexflow_tpu.parallel.mesh import mesh_axis_sizes, view_slot_axes

    findings: List[Finding] = []
    axis_pool = mesh_axis_sizes(num_devices)

    for node in graph.topo_order():
        guid, op = node.guid, node.op
        name = getattr(op, "name", None)
        out_shapes = getattr(op, "output_shapes", None)
        if not out_shapes:
            continue
        out = out_shapes[0]
        mv = strategy.get(guid)
        if mv is None:
            findings.append(_f(
                "SHD109", "node has no view in the strategy",
                node=guid, op=name))
            continue
        if len(mv.dim_degrees) != out.ndim:
            findings.append(_f(
                "SHD101",
                f"view {mv} has {len(mv.dim_degrees)} dim degrees but "
                f"the op output has rank {out.ndim}", node=guid, op=name))
            continue  # every later check indexes dims by rank
        for d, deg in enumerate(mv.dim_degrees):
            if deg < 1:
                findings.append(_f(
                    "SHD102", f"dim {d} degree {deg} < 1",
                    node=guid, op=name))
            elif deg > 1 and out.sizes[d] % deg != 0:
                findings.append(_f(
                    "SHD102",
                    f"dim {d} (size {out.sizes[d]}) not divisible by "
                    f"degree {deg}", node=guid, op=name))
        parts = mv.num_parts
        if parts > num_devices or num_devices % max(1, parts) != 0:
            findings.append(_f(
                "SHD103",
                f"view {mv} needs {parts} parts on a {num_devices}-device "
                f"mesh (must divide)", node=guid, op=name))
        fixed = op.fixed_machine_view() if hasattr(
            op, "fixed_machine_view") else None
        if fixed is not None:
            if (mv.dim_degrees != fixed.dim_degrees
                    or mv.replica_degree != fixed.replica_degree):
                findings.append(_f(
                    "SHD104",
                    f"op pins view {fixed} but the strategy assigns {mv}",
                    node=guid, op=name))
                continue  # propagate would assert; already reported
        elif hasattr(op, "splittable_output_dims"):
            splittable = set(op.splittable_output_dims())
            for d, deg in enumerate(mv.dim_degrees):
                if deg > 1 and d not in splittable:
                    findings.append(_f(
                        "SHD106",
                        f"dim {d} partitioned {deg}-way but the op only "
                        f"splits dims {sorted(splittable)}",
                        node=guid, op=name))
            max_r = op.max_replica_degree()
            r = mv.replica_degree
            if r > 1 and (r > max_r or max_r % r != 0):
                findings.append(_f(
                    "SHD106",
                    f"replica degree {r} outside the op's contraction "
                    f"capacity {max_r}", node=guid, op=name))
        osh = None
        try:
            osh = op.propagate(mv)
        except AssertionError as e:
            findings.append(_f(
                "SHD105", f"degree propagation rejected {mv}: {e}",
                node=guid, op=name))
        except Exception as e:  # malformed views can out-of-range index
            findings.append(_f(
                "SHD105",
                f"degree propagation failed on {mv}: "
                f"{type(e).__name__}: {e}", node=guid, op=name))
        slot_axes: Optional[dict] = None
        if parts <= num_devices and num_devices % max(1, parts) == 0:
            try:
                slot_axes = view_slot_axes(mv, axis_pool)
            except ValueError as e:
                findings.append(_f(
                    "SHD108",
                    f"view {mv} does not factor onto the mesh axis pool "
                    f"{axis_pool}: {e}", node=guid, op=name))
        if osh is not None and slot_axes is not None:
            slot_sizes = {i: d for i, d in enumerate(mv.dim_degrees)}
            slot_sizes[REPLICA_SLOT] = mv.replica_degree
            for i, annot in enumerate(osh.outputs):
                findings += _annot_findings(
                    annot, slot_sizes, f"output {i}", guid, name)
            for i, annot in enumerate(osh.weights):
                findings += _annot_findings(
                    annot, slot_sizes, f"weight {i}", guid, name)
            for i, annot in enumerate(osh.inputs):
                if annot is not None:
                    findings += _annot_findings(
                        annot, slot_sizes, f"input {i}", guid, name)
            # SHD110: consumer input constraints must have the rank of
            # the tensor the edge actually carries
            for e in graph.in_edges.get(guid, ()):
                producer = graph.nodes.get(e.src)
                if producer is None:
                    continue
                p_outs = getattr(producer.op, "output_shapes", None)
                if p_outs is None or e.src_idx >= len(p_outs):
                    continue  # invariants pass owns that failure
                if e.dst_idx < len(osh.inputs):
                    annot = osh.inputs[e.dst_idx]
                    if (annot is not None
                            and len(annot.degrees) != p_outs[e.src_idx].ndim):
                        findings.append(_f(
                            "SHD110",
                            f"input {e.dst_idx} constraint has rank "
                            f"{len(annot.degrees)} but the producing edge "
                            f"carries a rank-{p_outs[e.src_idx].ndim} "
                            f"tensor", node=guid, op=name))
    return findings


def _s(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="sync_schedule", message=message,
                   **kw)


def lint_sync_schedule(graph, strategy: Dict[int, object], schedule,
                       precision_map: Optional[Dict[str, str]] = None,
                       ) -> List[Finding]:
    """Legality findings for a gradient-sync schedule against its
    (graph, strategy) — SHD120-123 ([] = legal).  ``schedule`` is a
    ``search.sync_schedule.SyncSchedule`` or any duck-typed bucket list
    (objects with ``.name``/``.ops``/``.precision``)."""
    # one source of truth for legal wire precisions: the schedule
    # module is deliberately jax-free, so this stays pure host-side
    from flexflow_tpu.search.sync_schedule import (
        BUCKET_PRECISIONS as _BUCKET_PRECISIONS,
    )

    findings: List[Finding] = []
    buckets = list(getattr(schedule, "buckets", schedule) or [])
    if not buckets:
        return [_s("SHD121", "schedule has no buckets")]

    # which ops actually sync under this strategy (some propagated
    # weight annot is replicated) — the coverage universe
    pos: Dict[str, int] = {}
    synced: Dict[str, bool] = {}
    weighted: Dict[str, object] = {}
    for i, node in enumerate(graph.topo_order()):
        name = getattr(node.op, "name", None)
        if name is None:
            continue
        pos[name] = i
        if not getattr(node.op, "_weight_specs", ()):
            continue
        weighted[name] = node.op
        mv = strategy.get(node.guid)
        if mv is None and hasattr(node.op, "fixed_machine_view"):
            mv = node.op.fixed_machine_view()
        if mv is None:
            continue
        try:
            osh = node.op.propagate(mv)
        except Exception:
            continue  # SHD105 owns that failure
        synced[name] = any(
            a is not None and a.replica > 1 for a in osh.weights)

    seen: Dict[str, str] = {}  # op name -> bucket that claimed it
    prev_min_pos: Optional[int] = None
    prev_name: Optional[str] = None
    pmap = precision_map or {}
    for bucket in buckets:
        bname = getattr(bucket, "name", "?")
        prec = getattr(bucket, "precision", "fp32")
        if prec not in _BUCKET_PRECISIONS:
            findings.append(_s(
                "SHD120",
                f"bucket {bname!r} carries unknown precision {prec!r} "
                f"(known: {list(_BUCKET_PRECISIONS)})"))
        min_pos: Optional[int] = None
        for op_name in getattr(bucket, "ops", ()):
            if op_name not in pos:
                findings.append(_s(
                    "SHD120",
                    f"bucket {bname!r} names op {op_name!r} the graph "
                    f"does not have", op=op_name))
                continue
            if op_name not in weighted:
                findings.append(_s(
                    "SHD120",
                    f"bucket {bname!r} names op {op_name!r}, which "
                    f"carries no weights to sync", op=op_name))
                continue
            if op_name in seen:
                findings.append(_s(
                    "SHD121",
                    f"op {op_name!r} is covered twice (buckets "
                    f"{seen[op_name]!r} and {bname!r}) — its gradient "
                    f"would sync twice", op=op_name))
            seen[op_name] = bname
            p = pos[op_name]
            min_pos = p if min_pos is None else min(min_pos, p)
            if prec != "fp32":
                from flexflow_tpu.search.sync_precision import (
                    grad_safe_to_compress,
                )

                mapped = pmap.get(op_name, "fp32")
                if mapped != prec:
                    findings.append(_s(
                        "SHD123",
                        f"bucket {bname!r} compresses {op_name!r} at "
                        f"{prec} but the sync-precision map says "
                        f"{mapped!r} — the two artifacts contradict",
                        op=op_name))
                elif not grad_safe_to_compress(weighted[op_name]):
                    findings.append(_s(
                        "SHD123",
                        f"bucket {bname!r} compresses {op_name!r}, which "
                        f"the gradient-safety heuristic excludes",
                        op=op_name))
        if min_pos is None:
            continue
        if prev_min_pos is not None and min_pos > prev_min_pos:
            findings.append(_s(
                "SHD122",
                f"issue order violates grad readiness: bucket "
                f"{prev_name!r} (earliest member at topo position "
                f"{prev_min_pos}) issues BEFORE bucket {bname!r} "
                f"(earliest member at {min_pos}), but the backward "
                f"produces {bname!r}'s grads first — the serialized "
                f"collective chain would stall a ready bucket behind "
                f"one whose grads do not exist yet"))
        prev_min_pos, prev_name = min_pos, bname
    uncovered = sorted(
        n for n, is_synced in synced.items() if is_synced and n not in seen)
    if uncovered:
        findings.append(_s(
            "SHD121",
            f"{len(uncovered)} synced weight group(s) uncovered (e.g. "
            f"{uncovered[:4]}) — they would fall back to the exposed "
            f"post-backward monolithic sync"))
    return findings


def _z(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="zero_map", message=message, **kw)


def lint_zero_map(graph, strategy: Dict[int, object], zero_map,
                  cost_model) -> List[Finding]:
    """Legality findings for a per-group optimizer-state sharding map
    (op names whose ZeRO-1 state/update shards — search/comm_plan.py
    ``choose_zero_groups``) against its (graph, strategy) — SHD140/141
    ([] = legal, and an empty map trivially is).  ``cost_model``
    supplies the device count for the shared placement rule, so a map
    that lints clean is credited and executed coherently."""
    from flexflow_tpu.search.comm_plan import zero_update_factor

    names = list(zero_map or ())
    if not names:
        return []
    findings: List[Finding] = []
    by_name: Dict[str, object] = {}
    mv_of: Dict[str, object] = {}
    for node in graph.topo_order():
        n = getattr(node.op, "name", None)
        if n is None:
            continue
        by_name[n] = node.op
        mv = strategy.get(node.guid)
        if mv is None and hasattr(node.op, "fixed_machine_view"):
            mv = node.op.fixed_machine_view()
        mv_of[n] = mv
    seen = set()
    for name in names:
        if name in seen:
            findings.append(_z(
                "SHD140", f"op {name!r} appears twice in the "
                f"optimizer-sharding map", op=name))
            continue
        seen.add(name)
        op = by_name.get(name)
        if op is None:
            findings.append(_z(
                "SHD140", f"optimizer-sharding map names op {name!r} "
                f"the graph does not have", op=name))
            continue
        if not getattr(op, "_weight_specs", ()):
            findings.append(_z(
                "SHD140", f"op {name!r} carries no weights — nothing "
                f"to shard optimizer state for", op=name))
            continue
        mv = mv_of.get(name)
        if mv is None:
            from flexflow_tpu.core.machine import MachineView

            mv = MachineView.trivial(op.output_shapes[0].ndim)
        synced = False
        try:
            osh = op.propagate(mv)
            synced = any(
                a is not None and a.replica > 1 for a in osh.weights)
        except Exception:
            pass  # SHD105 owns propagation failures
        if not synced:
            findings.append(_z(
                "SHD140", f"op {name!r} has no replicated weight under "
                f"this strategy — optimizer state only shards over "
                f"replication axes, so the entry is incoherent",
                op=name))
            continue
        f = zero_update_factor(cost_model, op, mv)
        if f <= 1.0:
            findings.append(_z(
                "SHD141", f"op {name!r} achieves no ZeRO shard factor "
                f"under the shared placement rule (achieved {f:g}) — "
                f"the credited update win would never be realized",
                op=name))
    return findings


def _p(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="reduction_plan", message=message,
                   **kw)


def lint_reduction_plan(graph, strategy: Dict[int, object], schedule,
                        cost_model) -> List[Finding]:
    """Legality findings for the staged reduction plans a schedule's
    buckets carry, against (graph, strategy, machine) — SHD130-133
    ([] = legal; a plan-free schedule is trivially legal).
    ``cost_model`` supplies the link hierarchy and the slot→axis
    replica decomposition — the SAME classifier the pricing used, so a
    plan that lints clean is priced and executed coherently."""
    from flexflow_tpu.search.reduction_plan import validate_stages_split
    from flexflow_tpu.search.sync_schedule import synced_weight_groups

    buckets = list(getattr(schedule, "buckets", schedule) or [])
    if not any(getattr(b, "plan", None) is not None for b in buckets):
        return []
    findings: List[Finding] = []
    levels = cost_model.levels()
    num_levels = len(levels)
    parts_by_op: Dict[str, list] = {}
    for node, _mv, parts in synced_weight_groups(graph, strategy,
                                                 cost_model):
        parts_by_op[node.op.name] = parts
    for bucket in buckets:
        plan = getattr(bucket, "plan", None)
        if plan is None:
            continue
        bname = getattr(bucket, "name", "?")
        structural, prec_errs = validate_stages_split(
            plan.stages, num_levels)
        for e in structural:
            findings.append(_p(
                "SHD130", f"bucket {bname!r} plan {plan.name!r}: {e}"))
        for e in prec_errs:
            findings.append(_p(
                "SHD133", f"bucket {bname!r} plan {plan.name!r}: {e}"))
        if structural:
            continue
        # group/slice coherence + level coverage
        deepest = 0
        spanning = 0
        for op in getattr(bucket, "ops", ()):
            for part in parts_by_op.get(op, ()):
                _nbytes, replica, _spans, _n, key = part
                if replica <= 1:
                    continue
                factors = cost_model.replica_level_split(key, replica)
                if factors is None:
                    continue
                d = max((i for i, f in enumerate(factors) if f > 1),
                        default=0)
                deepest = max(deepest, d)
                if d > 0:
                    spanning += 1
        if spanning == 0:
            findings.append(_p(
                "SHD132",
                f"bucket {bname!r} carries staged plan {plan.name!r} but "
                f"none of its replication groups provably spans a slice "
                f"boundary — the staged stages have no cross-level wire "
                f"to ride"))
        elif plan.cross_level != deepest:
            findings.append(_p(
                "SHD131",
                f"bucket {bname!r} plan {plan.name!r} reaches link level "
                f"{plan.cross_level} but the bucket's groups span level "
                f"{deepest} — the plan's level coverage does not match "
                f"the topology the groups actually cross"))
        # SHD133: cross precision composes with the bucket precision
        # (int8_ef buckets stage at the plain int8 wire — wire_base)
        from flexflow_tpu.search.sync_schedule import wire_base

        bprec = getattr(bucket, "precision", "fp32")
        for s in plan.stages:
            if s.kind == "allreduce" and s.precision not in (
                    "fp32", wire_base(bprec)):
                findings.append(_p(
                    "SHD133",
                    f"bucket {bname!r} plan {plan.name!r} compresses the "
                    f"cross-level allreduce at {s.precision} but the "
                    f"bucket's (sync-precision-map-coherent) precision "
                    f"is {bprec!r} — per-level precision must compose "
                    f"with the map, not contradict it"))
    return findings


# ---------------------------------------------------------------------------
# serving-objective legality (SHD160-163)
# ---------------------------------------------------------------------------
def _srv(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="serving", message=message, **kw)


def lint_serving(graph, strategy: Dict[int, object], serving,
                 cost_model, predicted_p99_s: Optional[float] = None,
                 ) -> List[Finding]:
    """Legality of a serve-objective result against its ServingSpec
    (search/serving.py) — the always-on gate ``optimize_strategy`` runs
    under ``FFConfig.objective="serve"`` before the strategy is
    returned, persisted or imported:

    * **SHD160** spec/graph coherence: the spec's frame geometry
      (max_seqs, page_size, pages_per_seq) is positive and matches
      every decode op's own attrs; the graph HAS decode ops (a serve
      artifact for a graph with nothing ragged is a provenance bug);
      the arrival quantile lies in (0, 1).
    * **SHD161** KV residency fits: per-device memory under the
      strategy — weights + activations + every decode op's page pool
      at FULL occupancy — must fit the machine's HBM capacity (the
      "rejected during search, not at OOM" budget, checked again here
      so imported/cached artifacts cannot smuggle an over-budget map).
    * **SHD162** decode view legality: each decode op's replica (head
      split) degree must divide its head count and the batch degree
      must divide the frame's sequence slots — frames shard evenly or
      the executor's fixed frame composition breaks.
    * **SHD163** SLO coherence (warn): a declared p99 budget that the
      PREDICTED p99 already exceeds is reported — the deployment is
      mis-sized, but prediction is not proof, so this warns rather
      than gates.
    """
    from flexflow_tpu.core.machine import MachineView
    from flexflow_tpu.search.serving import decode_nodes

    findings: List[Finding] = []
    nodes = decode_nodes(graph)
    if serving is None:
        return [_srv("SHD160", "serve artifact carries no serving spec")]
    if not nodes:
        return [_srv(
            "SHD160",
            "serve objective on a graph with no decode-attention ops — "
            "nothing here is ragged; the artifact's objective is "
            "mislabeled")]
    if (serving.max_seqs < 1 or serving.page_size < 1
            or serving.pages_per_seq < 1):
        findings.append(_srv(
            "SHD160",
            f"serving spec has non-positive frame geometry "
            f"(max_seqs={serving.max_seqs}, "
            f"page_size={serving.page_size}, "
            f"pages_per_seq={serving.pages_per_seq})"))
    if not (0.0 < serving.quantile < 1.0):
        findings.append(_srv(
            "SHD160",
            f"arrival quantile {serving.quantile} outside (0, 1)"))
    if serving.p99_budget_ms < 0:
        findings.append(_srv(
            "SHD163",
            f"declared p99 budget is negative "
            f"({serving.p99_budget_ms} ms)"))
    mem = 0.0
    for node in graph.topo_order():
        mv = strategy.get(node.guid)
        if mv is None:
            mv = node.op.fixed_machine_view() or MachineView.trivial(
                node.op.output_shapes[0].ndim)
        if node in nodes:
            geo = (node.op.max_seqs, node.op.attrs["page_size"],
                   node.op.attrs["pages_per_seq"])
            if geo != (serving.max_seqs, serving.page_size,
                       serving.pages_per_seq):
                findings.append(_srv(
                    "SHD160",
                    f"decode op frame geometry {geo} disagrees with the "
                    f"serving spec "
                    f"({serving.max_seqs}, {serving.page_size}, "
                    f"{serving.pages_per_seq})",
                    node=node.guid, op=node.op.name))
            r = max(mv.replica_degree, 1)
            heads = node.op.attrs["num_heads"]
            if heads % r != 0:
                findings.append(_srv(
                    "SHD162",
                    f"head-split degree {r} does not divide the op's "
                    f"{heads} heads", node=node.guid, op=node.op.name))
            b = max(mv.dim_degrees[0], 1) if mv.dim_degrees else 1
            if node.op.max_seqs % b != 0:
                findings.append(_srv(
                    "SHD162",
                    f"batch degree {b} does not divide the frame's "
                    f"{node.op.max_seqs} sequence slots — frames cannot "
                    f"shard evenly", node=node.guid, op=node.op.name))
        m = cost_model.op_memory(node.op, mv)
        if math.isfinite(m):  # NaN/inf views: SHD105's propagation
            mem += m  # findings own those failures
    cap = cost_model.machine.hbm_capacity
    if mem > cap:
        findings.append(_srv(
            "SHD161",
            f"per-device memory under this strategy "
            f"({mem / 1e9:.2f} GB incl. full-occupancy KV residency) "
            f"exceeds the HBM capacity ({cap / 1e9:.2f} GB) — the "
            f"decode deployment cannot hold its page pool"))
    if (predicted_p99_s is not None and serving.p99_budget_ms > 0
            and predicted_p99_s * 1e3 > serving.p99_budget_ms):
        findings.append(_srv(
            "SHD163",
            f"predicted p99 decode latency "
            f"({predicted_p99_s * 1e3:.3f} ms) exceeds the declared "
            f"SLO budget ({serving.p99_budget_ms:.3f} ms)",
            severity="warn"))
    return findings


# ---------------------------------------------------------------------------
# KV-lane legality (SHD168/169)
# ---------------------------------------------------------------------------
def lint_kv(graph, strategy: Dict[int, object], kv_meta,
            serving=None) -> List[Finding]:
    """Legality of a KV-lane result (``__meta__.kv``,
    FFConfig.kv_precision / serve_shared_prefix_pages) against the
    decode graph it targets — the always-on gate the driver runs on
    fresh AND cache-served serve results, re-run at import before the
    dtype is adopted onto the graph's decode ops:

    * **SHD168** sharing/refcount accounting coherence (see module
      docstring): shared_prefix_pages in [0, pages_per_seq), coherent
      with the armed ServingSpec, and the recorded
      shared_residency_factor equal to the refcount arithmetic
      ``(max_seqs*(pps-s)+s) / (max_seqs*pps)``.
    * **SHD169** pool-dtype legality: dtype in fp32/bf16/int8; decode
      ops' own ``kv_dtype`` attrs (pre-adoption these are absent —
      vacuously coherent) agree with the meta and each other; int8
      carries "page_slot" scales, fp32/bf16 carry none.
    """
    from flexflow_tpu.search.serving import decode_nodes

    findings: List[Finding] = []
    if not isinstance(kv_meta, dict):
        return [_srv("SHD169",
                     f"__meta__.kv is not a mapping: {type(kv_meta).__name__}")]
    nodes = decode_nodes(graph)
    if not nodes:
        return [_srv(
            "SHD169",
            "kv lane armed on a graph with no decode-attention ops — "
            "there is no page pool to retype or share")]
    # ---- SHD169: pool dtype discipline ----------------------------------
    dtype = kv_meta.get("dtype")
    if dtype not in ("fp32", "bf16", "int8"):
        findings.append(_srv(
            "SHD169",
            f"__meta__.kv pool dtype {dtype!r} is not one of "
            f"fp32|bf16|int8"))
    layout = kv_meta.get("scale_layout", "none")
    if dtype == "int8" and layout != "page_slot":
        findings.append(_srv(
            "SHD169",
            f"int8 pool requires per-(page, slot) scales "
            f"(scale_layout='page_slot'), got {layout!r} — dequant "
            f"inside the page loop has no scales to read"))
    if dtype in ("fp32", "bf16") and layout not in ("none", None):
        findings.append(_srv(
            "SHD169",
            f"{dtype} pool must not carry scales "
            f"(scale_layout={layout!r}) — a scale tensor nothing "
            f"dequants is residency the pricing never saw"))
    op_dtypes = {n.op.attrs.get("kv_dtype", None) for n in nodes}
    declared = {d for d in op_dtypes if d is not None}
    if len(declared) > 1:
        findings.append(_srv(
            "SHD169",
            f"decode ops disagree on kv_dtype ({sorted(declared)}) — "
            f"one page pool cannot hold two dtypes"))
    elif declared and dtype in ("fp32", "bf16", "int8") \
            and declared != {dtype}:
        findings.append(_srv(
            "SHD169",
            f"decode ops carry kv_dtype={next(iter(declared))!r} but "
            f"__meta__.kv persists {dtype!r} — the artifact does not "
            f"describe the graph it rides"))
    # ---- SHD168: sharing accounting coherence ---------------------------
    pps = nodes[0].op.attrs["pages_per_seq"]
    max_seqs = nodes[0].op.max_seqs
    shared = kv_meta.get("shared_prefix_pages", 0)
    if not isinstance(shared, int) or shared < 0 or shared >= pps:
        findings.append(_srv(
            "SHD168",
            f"shared_prefix_pages={shared!r} outside [0, "
            f"pages_per_seq={pps}) — a sequence cannot share its whole "
            f"allotment (the last token's scatter needs a private "
            f"page)"))
        shared = 0
    if serving is not None:
        sv = int(getattr(serving, "shared_prefix_pages", 0) or 0)
        if sv != shared:
            findings.append(_srv(
                "SHD168",
                f"__meta__.kv declares shared_prefix_pages={shared} "
                f"but the serving spec prices {sv} — residency was "
                f"ranked under sharing the artifact does not record"))
    factor = kv_meta.get("shared_residency_factor", 1.0)
    expect = 1.0
    if shared and max_seqs > 0 and pps > 0:
        expect = (max_seqs * (pps - shared) + shared) / float(
            max_seqs * pps)
    try:
        ok = abs(float(factor) - expect) <= 1e-9
    except (TypeError, ValueError):
        ok = False
    if not ok:
        findings.append(_srv(
            "SHD168",
            f"shared_residency_factor={factor!r} does not match the "
            f"refcount arithmetic for shared_prefix_pages={shared} "
            f"over a {max_seqs}x{pps}-page frame (expected "
            f"{expect:.9f}) — the residency discount is not the one "
            f"the allocator's refcounts deliver"))
    return findings


# ---------------------------------------------------------------------------
# prefill/decode disaggregation legality (SHD164/165)
# ---------------------------------------------------------------------------
def lint_disaggregation(decode_graph, meta, config, prefill_graph=None,
                        prefill_strategy=None, decode_strategy=None,
                        ) -> List[Finding]:
    """Legality of a disaggregation proposal/artifact
    (``__meta__.disaggregation``, search/disaggregation.py) against the
    decode graph it targets — the always-on gate at proposal time and
    the re-lint at import:

    * **SHD164** two-block structure: positive prefill/decode block
      widths that are disjoint and fit the machine; a chunk size >= 1;
      the decode graph actually HAS decode-attention ops (and the
      prefill graph, when available, has none — a decode op on the
      prefill block would drag the page pool across the cut).
    * **SHD165** handoff coherence: the persisted pool geometry
      (max_seqs, page_size, pages_per_seq) matches every decode op's
      own attrs — ONE allocator's pages cross the boundary, so the
      writer and the reader must agree on the frame; the prefill graph
      shares one parameter set with the decode graph
      (``prefill_weight_bridge``); the SLO-class table is structurally
      sound (unique names, non-negative deadlines, quantiles in
      (0, 1)).

    When per-phase strategies are supplied (proposal time), each block
    additionally passes the flat SHD101-110 lint under ITS OWN submesh
    width — the same per-segment discipline as ``lint_placement``."""
    from flexflow_tpu.search.serving import decode_nodes

    def _d(code, message, **kw):
        return Finding(code=code, pass_name="disaggregation",
                       message=message, **kw)

    findings: List[Finding] = []
    if not isinstance(meta, dict):
        return [_d("SHD164", "disaggregation meta is not an object")]
    nodes = decode_nodes(decode_graph)
    if not nodes:
        findings.append(_d(
            "SHD164",
            "disaggregation artifact targets a graph with no "
            "decode-attention ops — there is no decode phase to "
            "disaggregate"))
    try:
        a = int(meta.get("prefill_devices", 0))
        b = int(meta.get("decode_devices", 0))
        chunk = int(meta.get("chunk", 0))
    except (TypeError, ValueError):
        return findings + [_d(
            "SHD164",
            f"disaggregation meta has non-integer block/chunk fields "
            f"({meta.get('prefill_devices')!r}, "
            f"{meta.get('decode_devices')!r}, {meta.get('chunk')!r})")]
    n = getattr(config, "search_devices", 0) or config.num_devices
    if a < 1 or b < 1:
        findings.append(_d(
            "SHD164",
            f"disaggregation blocks must both be non-empty "
            f"(prefill={a}, decode={b})"))
    elif a + b > n:
        findings.append(_d(
            "SHD164",
            f"disaggregation blocks overflow the machine: prefill {a} "
            f"+ decode {b} devices on a {n}-device mesh"))
    if chunk < 1:
        findings.append(_d(
            "SHD164",
            f"prefill chunk must be >= 1, got {chunk!r}"))
    if prefill_graph is not None and decode_nodes(prefill_graph):
        findings.append(_d(
            "SHD164",
            "prefill graph carries decode-attention ops — the page "
            "pool would live on BOTH sides of the cut"))

    # SHD165: pool geometry must agree across the handoff
    geo = (meta.get("max_seqs"), meta.get("page_size"),
           meta.get("pages_per_seq"))
    for node in nodes:
        got = (node.op.max_seqs, node.op.attrs["page_size"],
               node.op.attrs["pages_per_seq"])
        if got != geo:
            findings.append(_d(
                "SHD165",
                f"decode op {node.op.name!r} frame geometry {got} "
                f"disagrees with the persisted handoff geometry {geo} "
                f"— the prefill writer and the decode reader would "
                f"index different pools",
                node=node.guid, op=node.op.name))
    # shared-parameter-set bridge: proven on the META-ONLY path (import
    # re-lint — derive the prompt twin from the decode graph itself).
    # At proposal time the bridge was already proven on the ORIGINAL
    # graph pair before any block search ran; the block solves may
    # rewrite op names, so re-bridging rewritten block graphs here
    # would manufacture false mismatches.
    if (prefill_strategy is None and decode_strategy is None and nodes
            and prefill_graph is None):
        try:
            from flexflow_tpu.models.decode import derive_prefill_model
            from flexflow_tpu.runtime.prefill import (
                prefill_weight_bridge,
            )

            twin = derive_prefill_model(
                decode_graph, config,
                seq_len=int(meta.get("prefill_seq_len") or 1),
            )[0].graph
            prefill_weight_bridge(twin, decode_graph)
        except ValueError as e:
            findings.append(_d(
                "SHD165",
                f"prefill and decode graphs do not share one parameter "
                f"set: {e}"))
        except Exception as e:
            findings.append(_d(
                "SHD165",
                f"cannot derive the prefill twin of this decode graph "
                f"({e}) — the shared-parameter-set contract is "
                f"unprovable"))
    classes = meta.get("slo_classes", [])
    if not isinstance(classes, list):
        findings.append(_d(
            "SHD165", f"slo_classes is not a list: {classes!r}"))
    else:
        seen = set()
        for i, c in enumerate(classes):
            if not isinstance(c, dict) or not c.get("name") \
                    or not isinstance(c.get("name"), str):
                findings.append(_d(
                    "SHD165",
                    f"slo_classes[{i}] is not a named class object"))
                continue
            if c["name"] in seen:
                findings.append(_d(
                    "SHD165",
                    f"slo_classes[{i}] duplicates {c['name']!r}"))
            seen.add(c["name"])
            if not isinstance(c.get("priority", 0), int) \
                    or isinstance(c.get("priority", 0), bool):
                findings.append(_d(
                    "SHD165",
                    f"slo class {c['name']!r} priority is not an int"))
            df = c.get("deadline_frames", 0)
            if not isinstance(df, int) or isinstance(df, bool) or df < 0:
                findings.append(_d(
                    "SHD165",
                    f"slo class {c['name']!r} deadline_frames {df!r} "
                    f"is not a non-negative int"))
            q = c.get("quantile", 0.99)
            if not isinstance(q, (int, float)) or isinstance(q, bool) \
                    or not (0.0 < float(q) < 1.0):
                findings.append(_d(
                    "SHD165",
                    f"slo class {c['name']!r} quantile {q!r} outside "
                    f"(0, 1)"))

    # per-block flat lint (proposal time only — imports carry no
    # per-phase strategies): each phase compiles over its OWN submesh,
    # so its views must pass the gate in that geometry
    if not errors_only(findings):
        from flexflow_tpu.compiler.placement_lowering import _strip_start

        for graph, strategy, width in (
                (prefill_graph, prefill_strategy, a),
                (decode_graph, decode_strategy, b)):
            if graph is None or strategy is None:
                continue
            stripped = {g: _strip_start(mv)
                        for g, mv in strategy.items() if mv is not None}
            findings += lint_strategy(graph, stripped, width)
    return findings


# ---------------------------------------------------------------------------
# serving-fleet legality (SHD166/167)
# ---------------------------------------------------------------------------
def lint_fleet(decode_graph, meta, config,
               replica_blocks=None) -> List[Finding]:
    """Legality of a serving-fleet proposal/artifact (``__meta__.fleet``,
    search/fleet.py) against the decode graph it targets — the
    always-on gate at proposal time and the re-lint at import:

    * **SHD166** N-block frame structure: a non-empty replica list with
      positive integer widths, non-negative starts, blocks pairwise
      DISJOINT and inside the machine; each replica's intra split
      (prefill_devices/decode_devices) fits its own width; the decode
      graph actually HAS decode-attention ops.
    * **SHD167** routing + pool coherence: every SLO class the table
      names is covered by a routing row whose per-replica fractions
      are in [0, 1] and sum to 1; routing rows name no unknown class
      and are sized to the replica list; the persisted pool geometry
      (max_seqs, page_size, pages_per_seq) matches every decode op's
      own attrs — every replica runs the SAME deployment frame, one
      request must be servable anywhere its class routes; the
      SLO-class table is structurally sound.

    When per-replica ``replica_blocks`` — (graph, strategy, width)
    triples — are supplied (proposal time), each block additionally
    passes the flat SHD101-110 lint under ITS OWN submesh width, the
    same per-segment discipline as ``lint_disaggregation``."""
    from flexflow_tpu.search.serving import decode_nodes

    def _f(code, message, **kw):
        return Finding(code=code, pass_name="fleet", message=message,
                       **kw)

    findings: List[Finding] = []
    if not isinstance(meta, dict):
        return [_f("SHD166", "fleet meta is not an object")]
    nodes = decode_nodes(decode_graph)
    if not nodes:
        findings.append(_f(
            "SHD166",
            "fleet artifact targets a graph with no decode-attention "
            "ops — there is nothing to replicate"))
    reps = meta.get("replicas")
    if not isinstance(reps, list) or not reps:
        return findings + [_f(
            "SHD166",
            f"fleet meta carries no replica list: {reps!r}")]
    n = getattr(config, "search_devices", 0) or config.num_devices
    spans = []
    for i, r in enumerate(reps):
        if not isinstance(r, dict):
            findings.append(_f(
                "SHD166", f"replicas[{i}] is not an object: {r!r}"))
            continue
        try:
            w = int(r.get("devices", 0))
            s = int(r.get("start", -1))
            a = int(r.get("prefill_devices", 0))
            b = int(r.get("decode_devices", 0))
        except (TypeError, ValueError):
            findings.append(_f(
                "SHD166",
                f"replicas[{i}] has non-integer block fields "
                f"({r.get('devices')!r}, {r.get('start')!r}, "
                f"{r.get('prefill_devices')!r}, "
                f"{r.get('decode_devices')!r})"))
            continue
        if w < 1 or s < 0:
            findings.append(_f(
                "SHD166",
                f"replicas[{i}] block [{s}, {s + w}) is not a "
                f"non-empty in-range device block"))
            continue
        if s + w > n:
            findings.append(_f(
                "SHD166",
                f"replicas[{i}] block [{s}, {s + w}) overflows the "
                f"{n}-device mesh"))
        if a < 0 or b < 1 or a + b > w:
            findings.append(_f(
                "SHD166",
                f"replicas[{i}] intra split prefill={a} + decode={b} "
                f"does not fit its {w}-device block"))
        spans.append((s, s + w, i))
    spans.sort()
    for (s0, e0, i0), (s1, e1, i1) in zip(spans, spans[1:]):
        if s1 < e0:
            findings.append(_f(
                "SHD166",
                f"replica blocks overlap: replicas[{i0}] "
                f"[{s0}, {e0}) and replicas[{i1}] [{s1}, {e1}) share "
                f"devices — two page pools cannot own one HBM"))

    # SHD167: pool geometry must agree across every replica
    geo = (meta.get("max_seqs"), meta.get("page_size"),
           meta.get("pages_per_seq"))
    for node in nodes:
        got = (node.op.max_seqs, node.op.attrs["page_size"],
               node.op.attrs["pages_per_seq"])
        if got != geo:
            findings.append(_f(
                "SHD167",
                f"decode op {node.op.name!r} frame geometry {got} "
                f"disagrees with the persisted fleet geometry {geo} — "
                f"a request routed across replicas would land in a "
                f"different pool shape",
                node=node.guid, op=node.op.name))
    classes = meta.get("slo_classes", [])
    names = set()
    if not isinstance(classes, list):
        findings.append(_f(
            "SHD167", f"slo_classes is not a list: {classes!r}"))
        classes = []
    for i, c in enumerate(classes):
        if not isinstance(c, dict) or not c.get("name") \
                or not isinstance(c.get("name"), str):
            findings.append(_f(
                "SHD167",
                f"slo_classes[{i}] is not a named class object"))
            continue
        if c["name"] in names:
            findings.append(_f(
                "SHD167",
                f"slo_classes[{i}] duplicates {c['name']!r}"))
        names.add(c["name"])
        df = c.get("deadline_frames", 0)
        if not isinstance(df, int) or isinstance(df, bool) or df < 0:
            findings.append(_f(
                "SHD167",
                f"slo class {c['name']!r} deadline_frames {df!r} is "
                f"not a non-negative int"))
        q = c.get("quantile", 0.99)
        if not isinstance(q, (int, float)) or isinstance(q, bool) \
                or not (0.0 < float(q) < 1.0):
            findings.append(_f(
                "SHD167",
                f"slo class {c['name']!r} quantile {q!r} outside "
                f"(0, 1)"))
    routing = meta.get("routing")
    if not isinstance(routing, dict) or not routing:
        findings.append(_f(
            "SHD167", f"fleet meta carries no routing table: "
                      f"{routing!r}"))
        routing = {}
    for cname, fr in sorted(routing.items()):
        if names and cname not in names:
            findings.append(_f(
                "SHD167",
                f"routing row {cname!r} names an unknown SLO class "
                f"(table: {sorted(names)})"))
        if (not isinstance(fr, list) or len(fr) != len(reps)
                or not all(isinstance(v, (int, float))
                           and not isinstance(v, bool) for v in fr)):
            findings.append(_f(
                "SHD167",
                f"routing row {cname!r} is not a list of "
                f"{len(reps)} fractions: {fr!r}"))
            continue
        if any(v < 0.0 or v > 1.0 for v in fr):
            findings.append(_f(
                "SHD167",
                f"routing row {cname!r} has fractions outside "
                f"[0, 1]: {fr}"))
        elif abs(sum(fr) - 1.0) > 1e-3:
            findings.append(_f(
                "SHD167",
                f"routing row {cname!r} fractions sum to "
                f"{sum(fr):.6f}, not 1 — traffic would be dropped or "
                f"duplicated"))
    for cname in sorted(names - set(routing)):
        findings.append(_f(
            "SHD167",
            f"SLO class {cname!r} has no routing row — its requests "
            f"have nowhere to go"))

    # per-replica flat lint (proposal time only — imports carry no
    # per-replica strategies): every replica compiles over its OWN
    # submesh, so its views must pass the gate in that geometry
    if replica_blocks and not errors_only(findings):
        from flexflow_tpu.compiler.placement_lowering import _strip_start

        for graph, strategy, width in replica_blocks:
            if graph is None or strategy is None:
                continue
            stripped = {g: _strip_start(mv)
                        for g, mv in strategy.items() if mv is not None}
            findings += lint_strategy(graph, stripped, width)
    return findings
