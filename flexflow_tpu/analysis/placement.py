"""Pipeline/placement proposal legality — SHD150-155.

PR 4 gated every FLAT strategy the search emits (SHD101-110, always-on
in ``optimize_strategy``), but the two proposal classes compile() can
adopt on top of the flat search — pipeline stage cuts
(``search/pipeline_search.py``) and 2-block ``start_part`` placements
(``search/placement_search.py``) — bypassed that gate entirely.  Unity
(OSDI'22) ships its joint parallelization proposals only through a
legality checker; this pass closes the gap with the same always-on
discipline: every proposal is linted before it is returned, persisted
(strategy ``__meta__``) or imported.

Pipeline stage cuts (``lint_pipeline_stages``):

* **SHD150** structure: stage count matches the partition, >= 2
  stages, the device count splits into the stages, microbatch count
  amortizes the bubble (M >= S) and divides the batch, no empty stage,
  no unknown guid
* **SHD151** exact-once node coverage: every graph node in exactly one
  stage (a duplicated node would train twice per tick; an uncovered
  one would never run)
* **SHD152** contiguity / boundary-edge coherence: every edge crosses
  stages FORWARD (stage(src) <= stage(dst)) — equivalently the stage
  prefixes are predecessor-closed topo intervals, the shape both the
  scan lowering and the staged wavefront executor require

``start_part`` placement blocks (``lint_placement``):

* **SHD153** block structure: exactly 2 distinct ``start_part`` blocks
  and the first starts at device 0 (the placed executor's fixed frame)
* **SHD154** device capacity / disjointness: block B starts at or
  after block A's width and fits inside the machine — the EXACT
  overlap/overflow rule ``PlacedCompiledModel.__init__`` enforces
* **SHD155** lowering-schedule agreement: the cut is the one the
  placed executor can actually run — both blocks non-empty, no edge
  from block B back into block A (the fwd_A/step_B/grad_A composition
  is forward-only), the graph sink owned by block B (the loss program
  lives there), and 1..MAX_CROSSING_TENSORS distinct crossing tensors

``lint_placement`` also re-runs the flat SHD101-110 lint PER SEGMENT
against each block's own submesh size — the same per-block device
count the placed lowering compiles each ``CompiledModel`` with — so a
placed proposal passes exactly the gate every flat strategy passes,
in the geometry it will actually execute under.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from flexflow_tpu.analysis.findings import Finding


def _f(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="placement", message=message, **kw)


def lint_pipeline_stages(graph, stage_guids: Optional[List[List[int]]],
                         num_stages: int, num_microbatches: int,
                         config) -> List[Finding]:
    """Legality findings for an S-stage pipeline partition of ``graph``
    ([] = legal).  ``stage_guids=None`` checks the scalar structure
    only (a persisted stacked-block proposal records no explicit cut —
    the scan lowering re-derives it from the block names)."""
    findings: List[Finding] = []
    # the device frame the proposal was costed under (search_devices
    # == num_devices except in search-for-a-bigger-machine mode)
    n = getattr(config, "search_devices", 0) or config.num_devices
    if num_stages < 2:
        findings.append(_f(
            "SHD150", f"pipeline proposal has {num_stages} stage(s) — "
            f"inter-op pipelining needs at least 2"))
    elif n % num_stages:
        findings.append(_f(
            "SHD150", f"{n} devices do not split into {num_stages} "
            f"stages"))
    if num_microbatches < max(num_stages, 1):
        findings.append(_f(
            "SHD150",
            f"{num_microbatches} microbatch(es) < {num_stages} stages — "
            f"the (M + S - 1)/M bubble would exceed the pipelining win "
            f"by construction"))
    if num_microbatches >= 1 and config.batch_size % num_microbatches:
        findings.append(_f(
            "SHD150",
            f"batch {config.batch_size} does not divide into "
            f"{num_microbatches} microbatches"))
    if stage_guids is None:
        return findings
    if len(stage_guids) != num_stages:
        findings.append(_f(
            "SHD150",
            f"proposal declares {num_stages} stages but carries "
            f"{len(stage_guids)} stage node lists"))
    stage_of: Dict[int, int] = {}
    dup = False
    for si, stage in enumerate(stage_guids):
        if not stage:
            findings.append(_f("SHD150", f"stage {si} is empty"))
        for guid in stage:
            if guid not in graph.nodes:
                findings.append(_f(
                    "SHD150",
                    f"stage {si} names node {guid} the graph does not "
                    f"have", node=guid))
                continue
            if guid in stage_of:
                dup = True
                findings.append(_f(
                    "SHD151",
                    f"node {guid} ({graph.nodes[guid].op.name!r}) is in "
                    f"stages {stage_of[guid]} and {si} — it would run "
                    f"twice per tick", node=guid,
                    op=graph.nodes[guid].op.name))
            else:
                stage_of[guid] = si
    uncovered = sorted(g for g in graph.nodes if g not in stage_of)
    if uncovered:
        findings.append(_f(
            "SHD151",
            f"{len(uncovered)} graph node(s) in no stage (e.g. "
            f"{[graph.nodes[g].op.name for g in uncovered[:4]]}) — they "
            f"would never execute"))
    if dup or uncovered:
        return findings  # span checks below need a well-defined map
    for guid in graph.nodes:
        for e in graph.out_edges.get(guid, ()):
            if e.dst not in stage_of:
                continue
            if stage_of[e.dst] < stage_of[guid]:
                findings.append(_f(
                    "SHD152",
                    f"edge {graph.nodes[e.src].op.name!r} -> "
                    f"{graph.nodes[e.dst].op.name!r} crosses BACKWARD "
                    f"from stage {stage_of[e.src]} to stage "
                    f"{stage_of[e.dst]} — the stages are not a "
                    f"predecessor-closed topo-interval partition, so no "
                    f"forward wavefront can honor the cut",
                    node=e.src, op=graph.nodes[e.src].op.name))
    return findings


def placement_meta(graph, strategy, config) -> Optional[dict]:
    """The jsonable ``__meta__.placement`` block for a 2-block placed
    strategy: the device-block frame the cut executes under (what
    ``fflint strategy`` can re-check stdlib-only, STR208).  None when
    the strategy is not a 2-block placement."""
    from flexflow_tpu.compiler.placement_lowering import (
        placement_block_widths,
        placement_blocks,
        placement_cut,
    )

    blocks = placement_blocks(strategy)
    if len(blocks) != 2:
        return None
    in_a, in_b, _crossing, _back = placement_cut(graph, strategy)
    n_a, n_b = placement_block_widths(in_a, in_b, strategy)
    return {
        "num_devices": config.num_devices,
        "blocks": [[0, n_a], [blocks[1], n_b]],
    }


def lint_placement(graph, strategy, config) -> List[Finding]:
    """Legality findings for a ``start_part``-carrying placed strategy
    against the placed executor's actual schedule
    (``compiler/placement_lowering.py``) — SHD153-155 plus the flat
    SHD101-110 lint per segment ([] = legal)."""
    from flexflow_tpu.analysis.sharding import lint_strategy
    from flexflow_tpu.compiler.placement_lowering import (
        MAX_CROSSING_TENSORS,
        placement_block_widths,
        placement_blocks,
        placement_cut,
    )

    findings: List[Finding] = []
    blocks = placement_blocks(strategy)
    if len(blocks) != 2:
        return [_f(
            "SHD153",
            f"placed strategy must carry exactly 2 start_part device "
            f"blocks, found start_parts {blocks}")]
    if blocks[0] != 0:
        findings.append(_f(
            "SHD153",
            f"first device block starts at {blocks[0]}, not 0 — the "
            f"placed executor's frame pins block A to device 0"))
    start_b = blocks[1]
    in_a, in_b, crossing, back = placement_cut(graph, strategy)

    # SHD154: the constructor's overlap/overflow rule, via the SHARED
    # width helper (same anti-drift discipline as placement_cut)
    n_a, n_b = placement_block_widths(in_a, in_b, strategy)
    if start_b < n_a:
        findings.append(_f(
            "SHD154",
            f"device blocks overlap: block A needs {n_a} devices from "
            f"0 but block B starts at {start_b}"))
    if start_b + n_b > config.num_devices:
        findings.append(_f(
            "SHD154",
            f"device blocks overflow: block B needs {n_b} devices from "
            f"{start_b} but the machine has {config.num_devices}"))

    # SHD155: the structural cut placeable()/the constructor require
    if not in_a or not in_b:
        findings.append(_f(
            "SHD155", "a placement block is empty — there is no cut to "
            "execute"))
    for e in back:
        findings.append(_f(
            "SHD155",
            f"edge {graph.nodes[e.src].op.name!r} -> "
            f"{graph.nodes[e.dst].op.name!r} flows from block B back "
            f"into block A — the fwd_A/step_B/grad_A composition is "
            f"forward-only", node=e.src, op=graph.nodes[e.src].op.name))
    sinks = graph.sinks()
    b_guids = {n.guid for n in in_b}
    if sinks and sinks[-1].guid not in b_guids:
        findings.append(_f(
            "SHD155",
            f"graph sink {sinks[-1].op.name!r} is not in block B — the "
            f"loss program lives on block B, so a cut whose second "
            f"block does not own the sink has no training step",
            node=sinks[-1].guid, op=sinks[-1].op.name))
    n_crossing = len({(e.src, e.src_idx) for e in crossing})
    if not 0 < n_crossing <= MAX_CROSSING_TENSORS:
        findings.append(_f(
            "SHD155",
            f"{n_crossing} distinct tensors cross the blocks — the "
            f"placed executor supports 1..{MAX_CROSSING_TENSORS}"))
    if findings:
        return findings  # segment lint below needs a coherent frame

    # per-segment flat lint: each block compiles as an ordinary
    # CompiledModel over ITS OWN submesh, so its views must pass the
    # same SHD101-110 gate flat strategies pass — against the block's
    # device count, which is the mesh the lowering will build
    from flexflow_tpu.compiler.placement_lowering import _strip_start

    for members, n_block in ((in_a, n_a), (in_b, n_b)):
        sub = graph._subgraph({n.guid for n in members})
        sub_strategy = {
            n.guid: _strip_start(strategy[n.guid])
            for n in members if strategy.get(n.guid) is not None
        }
        findings += lint_strategy(sub, sub_strategy, n_block)
    return findings
