"""Hot-swap legality lint — the always-on gate of the live
strategy-swap path (``FFModel.swap_strategy`` / runtime/controller.py).

A mid-run swap re-lowers the model under a new (graph, strategy) and
re-shards the LIVE training state onto the new views (fp32 re-shard is
a value-identity operation).  That is only sound when the new pair can
actually RECEIVE the state: every trainable weight, optimizer slot and
mutable op state (batch-norm stats, caches, EF residuals, KV page
pools) must have an identically-shaped home on the other side, and the
new strategy must cover the new graph completely — an uncovered node
would silently train under a default view the swap gate never priced.

* **SHD170** weight preservation: every ``(op, weight)`` the old graph
  owns exists in the new graph with identical shape + dtype, and the
  new graph introduces no NEW trainable weight (a fresh-initialized
  weight mid-run silently breaks value continuity — the caller must
  fall back to a strategy-only swap on the current graph instead)
* **SHD171** op-state preservation: same rule for the ops' declared
  ``state_specs`` (``{op}/{name}`` keys of the model-state dict) —
  the KV pools and cache/BN state the ISSUE's swap contract names
* **SHD172** swap coverage: every node of the new graph has a view in
  the new strategy (group coverage of the comm plan derives from the
  weighted nodes' views, so a hole here is a hole in the sync groups)

``lint_swap`` composes the flat SHD101-110 strategy legality lint on
the new pair, so a swap target is at least as checked as a fresh
search result.  Lowering-created state keys (EF residuals) are NOT
linted here: they are derived from the comm plan, and dropping them on
a plan change (e.g. the fp32 monolithic fallback) is the intended
semantics — the restore helper reports them as dropped instead.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from flexflow_tpu.analysis.findings import Finding


def _f(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="swap", message=message, **kw)


def _weight_map(graph) -> Dict[Tuple[str, str], Tuple[tuple, str]]:
    out = {}
    for node in graph.topo_order():
        for ws in getattr(node.op, "_weight_specs", ()):
            out[(node.op.name, ws.name)] = (
                tuple(ws.shape), ws.dtype.value)
    return out


def _state_map(graph) -> Dict[str, Tuple[tuple, str]]:
    out = {}
    for node in graph.topo_order():
        ss = getattr(node.op, "state_specs", None)
        if ss is None:
            continue
        for name, shape, dtype, _fill in ss():
            out[f"{node.op.name}/{name}"] = (tuple(shape), str(dtype))
    return out


def lint_swap(old_graph, new_graph, new_strategy,
              num_devices: int) -> List[Finding]:
    """All findings for hot-swapping a live model from ``old_graph``
    onto ``(new_graph, new_strategy)`` ([] = the swap is legal)."""
    findings: List[Finding] = []

    old_w, new_w = _weight_map(old_graph), _weight_map(new_graph)
    for key in sorted(set(old_w) | set(new_w)):
        op, w = key
        if key not in new_w:
            findings.append(_f(
                "SHD170",
                f"weight {op}/{w} {old_w[key][0]} has no home in the "
                f"swap target graph — its live value would be lost",
                op=op))
        elif key not in old_w:
            findings.append(_f(
                "SHD170",
                f"swap target graph introduces a NEW trainable weight "
                f"{op}/{w} {new_w[key][0]} — a fresh init mid-run "
                f"breaks value continuity",
                op=op))
        elif old_w[key] != new_w[key]:
            findings.append(_f(
                "SHD170",
                f"weight {op}/{w} changes shape/dtype across the swap: "
                f"{old_w[key]} -> {new_w[key]}",
                op=op))

    old_s, new_s = _state_map(old_graph), _state_map(new_graph)
    for key in sorted(set(old_s) | set(new_s)):
        if key not in new_s:
            findings.append(_f(
                "SHD171",
                f"op state {key} {old_s[key][0]} has no home in the "
                f"swap target graph — live state (cache/KV pool/BN "
                f"stats) would be lost", op=key.split("/")[0]))
        elif key not in old_s:
            findings.append(_f(
                "SHD171",
                f"swap target graph introduces NEW op state {key} "
                f"{new_s[key][0]} with no live value to carry",
                op=key.split("/")[0]))
        elif old_s[key][0] != new_s[key][0]:
            findings.append(_f(
                "SHD171",
                f"op state {key} changes shape across the swap: "
                f"{old_s[key][0]} -> {new_s[key][0]}", op=key.split("/")[0]))

    for node in new_graph.topo_order():
        if (node.guid not in new_strategy
                and node.op.fixed_machine_view() is None):
            findings.append(_f(
                "SHD172",
                f"swap strategy does not cover node {node.op.name!r} "
                f"(guid {node.guid}) — it would silently train under a "
                f"default view the swap gate never checked",
                node=node.guid, op=node.op.name))

    from flexflow_tpu.analysis.sharding import lint_strategy

    findings += lint_strategy(new_graph, new_strategy, num_devices)
    return findings
