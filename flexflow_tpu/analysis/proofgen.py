"""Generative equivalence proofs: proof graphs derived from the
rewrite matchers themselves.

The hand-curated proof-graph zoo (``equivalence._proof_graphs``) proved
the registry but left one hole class: a newly registered rewrite whose
anchor shape the zoo misses was only *reported* as an EQV305 coverage
warning, never proven.  TASO (SysML'19) verifies every substitution
against generated witnesses rather than a fixed suite; this module
brings that property here: for each registered rewrite the declared
``anchor_types`` (the op types its matcher can provably anchor on —
the same contract the per-op-type seed index keys on) drive a per-op-
family graph synthesizer, and the generated graphs feed the SAME
executable numeric proof (``equivalence.verify_rewrite``) the zoo
does.  Factory xfers therefore cannot have an EQV305 hole by
construction — every anchor type they declare has a generated witness
family — and the zoo stays as a regression anchor.

Synthesis is deterministic under a fixed seed and sweeps three axes:

* **degree sweep** — anchor dims sized so every divisor degree of the
  device count divides them (sizes ``n``-multiples at x1 and x2), so
  every generated ``partition_*``/``replicate_*`` degree anchors;
* **dtype variants** — a float32 and a bfloat16 input lane for float
  families (embedding ids are int32 by construction);
* **randomized context padding** — seeded draws of shape-preserving
  compute ops (relu/identity/dense) around the anchor.  Pads are
  never parallel ops: a pad must not trip a matcher's
  no-REPARTITION-predecessor guard.

Each rewrite is proven once per (lane x size x padding) CELL that
yields a match, so every sweep axis is executed as a proof — a
rewrite sound on the bare motif but unsound in a padded or
x2-degree context cannot hide behind a single bare-motif proof.

Finding codes (extending ``equivalence``'s EQV3xx range):

* **EQV305** (error) — a *factory* rewrite (``GraphXfer`` /
  ``BatchEmbeddingsXfer``) anchored on NO generated graph: a
  synthesizer coverage hole, loud by design.
* **EQV306** (warn) — a non-factory rewrite (JSON
  ``substitution_loader`` rule, or anything without a usable anchor
  contract) matched neither a generated graph nor the hand zoo: it is
  explicitly reported as un-proven instead of silently skipped.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.analysis.findings import Finding


def _f(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="equivalence", message=message, **kw)


# dtype lanes for float anchor families; embedding id feeds are int32
# by construction (the integer lane is not optional there)
FLOAT_LANES = ("float32", "bfloat16")
# padding-pattern draws per (motif, size, lane) cell
PAD_VARIANTS = 2


def _sizes(num_devices: int, mult: int) -> Tuple[int, int, int]:
    """(batch, width, seq/heads) such that every divisor degree of
    ``num_devices`` divides batch, width and seq — the degree-sweep
    guarantee (same rule as the hand zoo's ``_proof_graphs``)."""
    n = max(2, num_devices)
    b = max(8, n)
    if b % n:
        b = n
    return b * mult, 2 * n * mult, n


def _namer(tag: str):
    counter = [0]

    def nm(base: str) -> str:
        counter[0] += 1
        return f"pg_{tag}_{base}_{counter[0]}"

    return nm


def _pads(m, t, rng, nm, width: Optional[int] = None):
    """0-2 shape-preserving compute pads around the anchor.  Only
    compute ops (relu/identity/dense): a parallel-op pad would trip the
    matchers' no-REPARTITION-predecessor guards and turn padding into
    match suppression."""
    for _ in range(int(rng.integers(0, 3))):
        k = int(rng.integers(0, 3))
        if k == 0:
            t = m.relu(t, name=nm("pad_relu"))
        elif k == 1:
            t = m.identity(t, name=nm("pad_id"))
        elif width is not None:
            t = m.dense(t, width, name=nm("pad_fc"))
        else:
            t = m.identity(t, name=nm("pad_id"))
    return t


def synthesize_anchor_graphs(op_type, num_devices: int,
                             seed: int = 0,
                             ) -> List[Tuple[str, int, int, object]]:
    """Deterministic ``(dtype lane, size mult, pad variant, Graph)``
    proof-graph family anchored on ``op_type``: every structural motif
    a factory matcher anchoring on that type needs (plain op,
    linear+sole-activation, parallel-op pairs/chains,
    combine-before-concat, unary-fanout-to-repartitions, twin
    embeddings), swept over sizes x dtype lanes x padding draws.  The
    (lane, mult, pad) cell key is part of the return so the verifier
    can prove one graph PER CELL — every sweep axis is executed as a
    proof, not just generated.  Returns [] for op types without a
    motif family — the caller turns that into a loud EQV305/EQV306,
    never silence."""
    import flexflow_tpu as ff
    from flexflow_tpu.core.optype import OperatorType as T

    unary_fns = {
        T.RELU: "relu", T.SIGMOID: "sigmoid", T.TANH: "tanh",
        T.GELU: "gelu", T.EXP: "exp", T.IDENTITY: "identity",
    }
    binary_fns = {
        T.EW_ADD: "add", T.EW_MUL: "multiply", T.EW_SUB: "subtract",
        T.EW_DIV: "divide", T.EW_MAX: "max", T.EW_MIN: "min",
    }

    out: List[Tuple[str, int, int, object]] = []
    n_dev = max(2, num_devices)
    for mult in (1, 2):
        b, w, n = _sizes(num_devices, mult)
        d_b = next((d for d in (4, 3, 2) if b % d == 0), b)
        lanes = ("int32",) if op_type is T.EMBEDDING else FLOAT_LANES
        for li, lane in enumerate(lanes):
            for pv in range(PAD_VARIANTS):
                rng = np.random.default_rng(
                    seed * 1_000_003 + mult * 10_007 + li * 101 + pv)
                for motif in _motif_builders(
                        op_type, unary_fns, binary_fns):
                    nm = _namer(op_type.value)
                    cfg = ff.FFConfig(
                        batch_size=b, num_devices=n_dev,
                        only_data_parallel=True)
                    m = ff.FFModel(cfg)
                    ok = motif(m, b, w, n, d_b, lane, rng, nm)
                    if ok:
                        out.append((lane, mult, pv, m.graph))
    return out


def _motif_builders(op_type, unary_fns, binary_fns):
    """Motif callables for one anchor op family.  Each builds a full
    model into ``m`` and returns True, or False when the family cannot
    express the motif (the caller simply skips it)."""
    from flexflow_tpu.core.optype import OperatorType as T

    def head(m, t, nm):
        m.dense(t, 4, name=nm("head"))

    def plain(m, b, w, n, d_b, lane, rng, nm):
        if op_type in unary_fns or op_type in (
                T.LINEAR, T.SOFTMAX, T.LAYERNORM, T.CONCAT) or (
                op_type in binary_fns):
            x = m.create_tensor([b, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm, width=w)
            if op_type is T.LINEAR:
                y = m.dense(x, w, name=nm("anchor"))
            elif op_type is T.SOFTMAX:
                y = m.softmax(x, name=nm("anchor"))
            elif op_type is T.LAYERNORM:
                y = m.layer_norm(x, name=nm("anchor"))
            elif op_type is T.CONCAT:
                y = m.concat(
                    [m.dense(x, w, name=nm("br0")),
                     m.dense(x, w, name=nm("br1"))],
                    axis=1, name=nm("anchor"))
            elif op_type in binary_fns:
                y = getattr(m, binary_fns[op_type])(
                    m.dense(x, w, name=nm("ba")),
                    m.dense(x, w, name=nm("bb")), name=nm("anchor"))
            else:
                y = getattr(m, unary_fns[op_type])(x, name=nm("anchor"))
            y = _pads(m, y, rng, nm, width=None)
            head(m, y, nm)
            return True
        if op_type is T.MULTIHEAD_ATTENTION:
            x = m.create_tensor([b, n, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm)
            y = m.multihead_attention(x, x, x, w, n, name=nm("anchor"))
            head(m, y, nm)
            return True
        if op_type in (T.CONV2D, T.POOL2D, T.FLAT):
            x = m.create_tensor([b, 8, 8, 8], dtype=lane, name=nm("img"))
            x = _pads(m, x, rng, nm)
            if op_type is T.CONV2D:
                y = m.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name=nm("anchor"))
            elif op_type is T.POOL2D:
                y = m.pool2d(x, 2, 2, stride_h=2, stride_w=2,
                             name=nm("anchor"))
            else:
                y = x
            y = m.flat(y, name=nm("anchor") if op_type is T.FLAT
                       else nm("flat"))
            head(m, y, nm)
            return True
        if op_type is T.EMBEDDING:
            ids = m.create_tensor([b, 2], dtype="int32", name=nm("ids"))
            y = m.embedding(ids, 4 * n, n, aggr="sum", name=nm("anchor"))
            y = _pads(m, y, rng, nm, width=None)
            head(m, y, nm)
            return True
        if op_type is T.REPARTITION:
            x = m.create_tensor([b, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm, width=w)
            t = m.repartition(x, dim=0, degree=d_b, name=nm("anchor"))
            t = m.combine(t, dim=0, degree=1, name=nm("comb"))
            head(m, t, nm)
            return True
        if op_type is T.COMBINE:
            x = m.create_tensor([b, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm, width=w)
            t = m.combine(x, dim=0, degree=1, name=nm("anchor"))
            t = m.repartition(t, dim=0, degree=d_b, name=nm("rep"))
            head(m, t, nm)
            return True
        if op_type is T.REPLICATE:
            x = m.create_tensor([b, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm, width=w)
            t = m.replicate(x, degree=2, name=nm("anchor"))
            t = m.reduction(t, degree=2, name=nm("red"))
            head(m, t, nm)
            return True
        return False

    motifs = [plain]

    if op_type is T.LINEAR:
        # linear with a SOLE-consumer activation: fuse_linear_activation
        def act_follow(m, b, w, n, d_b, lane, rng, nm):
            x = m.create_tensor([b, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm, width=w)
            t = m.dense(x, w, name=nm("anchor"))
            t = m.relu(t, name=nm("act"))
            t = _pads(m, t, rng, nm, width=None)
            head(m, t, nm)
            return True

        motifs.append(act_follow)

    if op_type in unary_fns:
        # unary fanning out to k same-(dim, degree) repartitions:
        # hoist_partition_above_unary
        def fanout(m, b, w, n, d_b, lane, rng, nm):
            x = m.create_tensor([b, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm, width=w)
            t = getattr(m, unary_fns[op_type])(x, name=nm("anchor"))
            outs = []
            for i in range(3):
                p = m.repartition(t, dim=0, degree=d_b, name=nm(f"p{i}"))
                outs.append(m.dense(p, w, name=nm(f"fc{i}")))
            y = m.concat(outs, axis=1, name=nm("cat"))
            head(m, y, nm)
            return True

        motifs.append(fanout)

    if op_type is T.CONCAT:
        # k branches each ending Combine feeding the concat:
        # sink_combine_through_concat
        def sink(m, b, w, n, d_b, lane, rng, nm):
            x = m.create_tensor([b, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm, width=w)
            outs = []
            for i in range(3):
                t = m.dense(x, w, name=nm(f"br{i}"))
                outs.append(m.combine(t, dim=0, degree=1, name=nm(f"c{i}")))
            y = m.concat(outs, axis=1, name=nm("anchor"))
            head(m, y, nm)
            return True

        motifs.append(sink)

    if op_type is T.REPARTITION:
        # adjacent repartitions: fuse_parallel_op_chain
        def chain(m, b, w, n, d_b, lane, rng, nm):
            x = m.create_tensor([b, w], dtype=lane, name=nm("in"))
            x = _pads(m, x, rng, nm, width=w)
            t = m.repartition(x, dim=0, degree=2, name=nm("anchor"))
            t = m.repartition(t, dim=1, degree=2, name=nm("rep2"))
            head(m, t, nm)
            return True

        motifs.append(chain)

    if op_type is T.EMBEDDING:
        # two same-signature embeddings: BatchEmbeddingsXfer
        def twin(m, b, w, n, d_b, lane, rng, nm):
            outs = []
            for i in range(2):
                ids = m.create_tensor([b, 2], dtype="int32",
                                      name=nm(f"ids{i}"))
                outs.append(m.embedding(ids, 4 * n, n, aggr="sum",
                                        name=nm(f"emb{i}")))
            t = m.concat(outs, axis=1, name=nm("cat"))
            t = _pads(m, t, rng, nm, width=None)
            head(m, t, nm)
            return True

        motifs.append(twin)

    return motifs


def instantiate_pattern_graph(rule, num_devices: int):
    """Build a ``PatternRule``'s SOURCE pattern directly as a PCG — the
    multi-node-JSON proof instantiator (the PR 9 remainder): a rule
    whose source pattern spans several ops rarely anchors on the
    single-motif synthesizer graphs or the hand zoo, so it used to be
    EQV306-reported un-proven.  Here the pattern ops themselves become
    model calls (externals -> input tensors, weight-slot externals ->
    the op's own weight, parallel ops from their PM_* params, compute
    ops from the donor-less construction families), a dense head is
    added on every MAPPED output (mapped outputs are the tensors the
    matcher allows to escape), and the result feeds the SAME
    ``verify_rewrite`` numeric proof as everything else.  Returns None
    when the pattern uses an op family outside the supported subset or
    a weight-sharing external our ops cannot express — those rules
    stay honestly EQV306."""
    import flexflow_tpu as ff
    from flexflow_tpu.core.optype import OperatorType as T
    from flexflow_tpu.search.substitution_loader import (
        _ACTI_MAP,
        _PARALLEL_TYPES,
        _logical_dim,
    )

    unary_calls = {T.RELU: "relu", T.SIGMOID: "sigmoid", T.TANH: "tanh",
                   T.ELU: "elu", T.IDENTITY: "identity"}
    binary_calls = {T.EW_ADD: "add", T.EW_MUL: "multiply",
                    T.EW_SUB: "subtract", T.EW_DIV: "divide",
                    T.EW_MAX: "max", T.EW_MIN: "min"}
    # data-input arity per op family: pattern slots past it are the
    # reference corpus' explicit weight tensors, which our ops OWN —
    # they bind to the matched op's own weight at match time, so the
    # instantiated graph simply omits them
    data_arity = {T.LINEAR: 1, T.SOFTMAX: 1, T.LAYERNORM: 1}
    data_arity.update({t: 1 for t in unary_calls})
    data_arity.update({t: 2 for t in binary_calls})
    data_arity.update({t: 1 for t in _PARALLEL_TYPES})

    n = max(2, num_devices)
    b = max(8, n)
    if b % n:
        b = n
    w = 2 * n
    cfg = ff.FFConfig(batch_size=b, num_devices=n,
                      only_data_parallel=True)
    m = ff.FFModel(cfg)
    nm = _namer("pat")
    ext: Dict[int, object] = {}
    outs: Dict[Tuple[int, int], object] = {}
    # weight-sharing externals (one negative id feeding two ops'
    # weight slots) cannot be expressed with op-owned weights — the
    # matcher could never bind them anyway, so decline
    weight_ext_owner: Dict[int, int] = {}
    for i, pat in enumerate(rule.src_ops):
        t_type = pat.type
        if t_type is T.CONCAT:
            arity = len(pat.inputs)
        else:
            arity = data_arity.get(t_type)
            if arity is None:
                return None
        ins = []
        for slot, (src_id, ts_id) in enumerate(pat.inputs):
            if slot >= arity:
                if src_id >= 0:
                    return None  # an internal tensor in a weight slot
                if src_id in weight_ext_owner or src_id in ext:
                    return None  # shared weight external
                weight_ext_owner[src_id] = i
                continue
            if src_id >= 0:
                t = outs.get((src_id, ts_id))
                if t is None:
                    return None
            else:
                if src_id in weight_ext_owner:
                    return None
                if src_id not in ext:
                    ext[src_id] = m.create_tensor(
                        [b, w], name=nm(f"ext{-src_id}"))
                t = ext[src_id]
            ins.append(t)
        if len(ins) < arity:
            return None  # pattern op missing a data input
        try:
            if t_type is T.LINEAR:
                act = _ACTI_MAP.get(pat.params.get("PM_ACTI", 0))
                y = m.dense(ins[0], w, activation=act, name=nm("lin"))
            elif t_type is T.SOFTMAX:
                y = m.softmax(ins[0], name=nm("sm"))
            elif t_type is T.LAYERNORM:
                y = m.layer_norm(ins[0], name=nm("ln"))
            elif t_type is T.CONCAT:
                y = m.concat(ins, axis=1, name=nm("cat"))
            elif t_type in unary_calls:
                y = getattr(m, unary_calls[t_type])(ins[0], name=nm("un"))
            elif t_type in binary_calls:
                y = getattr(m, binary_calls[t_type])(
                    ins[0], ins[1], name=nm("bin"))
            elif t_type in _PARALLEL_TYPES:
                dim, deg = pat.parallel_dim_degree()
                if deg is None:
                    return None
                if t_type is T.REPARTITION:
                    ld = _logical_dim(dim or 0, 2)
                    if (b, w)[ld] % deg:
                        return None
                    y = m.repartition(ins[0], dim=ld, degree=deg,
                                      name=nm("rep"))
                elif t_type is T.COMBINE:
                    ld = _logical_dim(dim or 0, 2)
                    y = m.combine(ins[0], dim=ld, degree=deg,
                                  name=nm("comb"))
                elif t_type is T.REPLICATE:
                    y = m.replicate(ins[0], degree=deg, name=nm("repl"))
                else:
                    y = m.reduction(ins[0], degree=deg, name=nm("red"))
            else:
                return None
        except Exception:
            return None  # shape/param mismatch: the family declines
        outs[(i, 0)] = y
    # heads on MAPPED outputs only — the matcher's escape check rejects
    # any other internal tensor leaving the pattern
    headed = set()
    for s_op, s_ts, _d_op, _d_ts in rule.mapped_outputs:
        t = outs.get((s_op, s_ts))
        if t is None:
            return None
        if (s_op, s_ts) not in headed:
            headed.add((s_op, s_ts))
            try:
                m.dense(t, 4, name=nm("head"))
            except Exception:
                return None
    return m.graph


def verify_registry_generated(
    num_devices: int = 8, seed: int = 0, xfers=None,
) -> Tuple[List[Finding], Dict[str, object]]:
    """Generative proof of a rewrite registry: every xfer must anchor
    on a graph synthesized FROM ITS OWN ``anchor_types`` and pass
    ``verify_rewrite`` there, once per (dtype lane x size mult x pad
    variant) CELL that yields a match — so the degree sweep and the
    padded contexts are executed as proofs, not merely generated (a
    matcher-sound-but-apply-unsound rewrite in a padded or x2-degree
    context cannot hide behind a bare-motif proof).  Returns
    ``(findings, stats)``; findings == [] means every rewrite is
    generatively proven.  Non-factory rewrites (JSON rules) that
    anchor nowhere — generated graphs or the zoo fallback — are
    reported as EQV306 (warn), factory holes as EQV305 (error)."""
    from flexflow_tpu.analysis.equivalence import (
        _proof_graphs,
        verify_rewrite,
    )
    from flexflow_tpu.search.substitution import (
        BatchEmbeddingsXfer,
        GraphXfer,
        generate_all_pcg_xfers,
    )

    if xfers is None:
        xfers = generate_all_pcg_xfers(num_devices)
    bank: Dict[object, List[Tuple[str, object]]] = {}
    zoo = None  # lazy: only built when a rule needs the fallback
    findings: List[Finding] = []
    stats: Dict[str, object] = {
        "xfers": len(xfers), "graphs_generated": 0, "proofs": 0,
        "lanes": {}, "zoo_fallbacks": 0, "unproven": 0,
    }
    for xf in xfers:
        name = getattr(xf, "name", type(xf).__name__)
        anchors = getattr(xf, "anchor_types", None)
        factory = isinstance(xf, (GraphXfer, BatchEmbeddingsXfer))
        proven_lanes: List[str] = []
        proven_cells: set = set()  # (lane, mult, pad) across anchor types
        if anchors:
            for t in sorted(anchors, key=lambda a: a.value):
                if t not in bank:
                    bank[t] = synthesize_anchor_graphs(
                        t, num_devices, seed=seed)
                    stats["graphs_generated"] += len(bank[t])
                for lane, mult, pv, g in bank[t]:
                    cell = (lane, mult, pv)
                    if cell in proven_cells:
                        continue
                    matches = xf.find_matches(g)
                    if not matches:
                        continue
                    findings += verify_rewrite(g, xf, matches[0],
                                               seed=seed)
                    proven_cells.add(cell)
                    if lane not in proven_lanes:
                        proven_lanes.append(lane)
                    stats["proofs"] += 1
                    stats["lanes"][lane] = stats["lanes"].get(lane, 0) + 1
        if not proven_lanes and not factory:
            # non-factory rules: multi-node JSON patterns rarely anchor
            # on the single-motif bank — instantiate the rule's OWN
            # source pattern as a PCG and prove there (the PR 9
            # remainder; closes the EQV306 hole for every rule the
            # instantiator can express)
            from flexflow_tpu.search.substitution_loader import (
                PatternRule,
            )

            if isinstance(xf, PatternRule):
                g = instantiate_pattern_graph(xf, num_devices)
                if g is not None:
                    matches = xf.find_matches(g)
                    if matches:
                        findings += verify_rewrite(g, xf, matches[0],
                                                   seed=seed)
                        proven_lanes.append("pattern")
                        stats["proofs"] += 1
                        stats["pattern_proofs"] = stats.get(
                            "pattern_proofs", 0) + 1
            # the hand zoo stays as the regression anchor / last resort
            if not proven_lanes:
                if zoo is None:
                    zoo = _proof_graphs(num_devices)
                for g in zoo:
                    matches = xf.find_matches(g)
                    if matches:
                        findings += verify_rewrite(g, xf, matches[0],
                                                   seed=seed)
                        proven_lanes.append("zoo")
                        stats["proofs"] += 1
                        stats["zoo_fallbacks"] += 1
                        break
        if not proven_lanes:
            stats["unproven"] += 1
            if factory:
                findings.append(_f(
                    "EQV305",
                    f"factory rewrite {name!r} anchored on no GENERATED "
                    f"proof graph (anchor_types="
                    f"{sorted(t.value for t in anchors) if anchors else None}"
                    f") — the synthesizer has a motif hole for this "
                    f"family"))
            else:
                findings.append(_f(
                    "EQV306",
                    f"rewrite {name!r} matched no generated or zoo proof "
                    f"graph — it carries no executable soundness proof "
                    f"(multi-node JSON patterns outside the synthesizer's "
                    f"motif families are reported here, never silently "
                    f"skipped)", severity="warn"))
    return findings, stats
