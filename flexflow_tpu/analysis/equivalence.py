"""Substitution soundness verifier — pass 2 of the static-analysis
stack (TASO/Unity discipline: every rewrite in the registry carries an
EXECUTABLE proof, not a comment).

For each registered ``GraphXfer`` (including the duck-typed
``BatchEmbeddingsXfer``) this materializes a small proof graph the
rewrite matches, applies it, evaluates BOTH graphs in the global
(single-device logical) view on random inputs with deterministically
derived weights, and asserts the values of every node surviving the
rewrite agree within dtype tolerance.  Parallel ops are identity
computations in the global view, so a legal rewrite must be value-
preserving node-by-node — a much stronger check than comparing sinks.

Weight correspondence across a rewrite ("the bridge"):

* surviving nodes (same guid) reuse the source graph's weights;
* a new weighted op whose weight specs equal a removed op's specs
  inherits that op's weights (linear+activation fusion);
* a new weighted op whose weight shape is ``(K, *removed_shape)``
  stacks the K removed ops' weights in topo order (the
  ``BatchEmbeddingsXfer`` stacked-table contract);
* anything else is an **EQV303** finding — a registry rewrite with no
  executable weight bridge has no proof.

Finding codes: EQV300 apply declined a reported match, EQV301 value
mismatch, EQV302 evaluation failure, EQV303 unbridgeable weights,
EQV305 a registered rewrite matched no proof graph (coverage hole).
``analysis/proofgen.py`` extends the range: proof graphs GENERATED
from each rewrite's own ``anchor_types`` close the EQV305 hole class
for factory xfers by construction, and EQV306 explicitly reports
rules (JSON ``substitution_loader`` patterns) the generator cannot
prove.  The hand-curated ``_proof_graphs`` zoo below stays as the
regression anchor.  Invariant findings (PCG0xx) from the rewritten
graph are passed through — an unsound splice usually fails
well-formedness first.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from flexflow_tpu.analysis.findings import Finding
from flexflow_tpu.analysis.invariants import check_graph

DEFAULT_RTOL = 1e-4
DEFAULT_ATOL = 1e-5


def _f(code: str, message: str, **kw) -> Finding:
    return Finding(code=code, pass_name="equivalence", message=message, **kw)


# ---------------------------------------------------------------------------
# evaluation in the global view


def make_inputs(graph, seed: int = 0) -> Dict[int, np.ndarray]:
    """Random feed arrays for every InputOp, keyed by guid.  Integer
    inputs are bounded by the smallest vocab among direct embedding
    consumers so lookups stay in range."""
    from flexflow_tpu.ops.inout import InputOp

    rng = np.random.default_rng(seed)
    out: Dict[int, np.ndarray] = {}
    for node in graph.topo_order():
        if not isinstance(node.op, InputOp):
            continue
        shape = node.op.output_shapes[0]
        dtype = shape.dtype.to_numpy()
        if np.issubdtype(dtype, np.integer):
            high = 16
            for e in graph.out_edges[node.guid]:
                n_entries = graph.nodes[e.dst].op.attrs.get("num_entries")
                if n_entries:
                    high = min(high, int(n_entries))
            out[node.guid] = rng.integers(
                0, high, size=shape.sizes).astype(dtype)
        elif dtype == np.bool_:
            out[node.guid] = rng.integers(0, 2, size=shape.sizes) > 0
        else:
            out[node.guid] = rng.standard_normal(
                shape.sizes).astype(np.float32).astype(dtype)
    return out


def make_weights(graph, seed: int = 0) -> Dict[int, Dict[str, np.ndarray]]:
    """Deterministic per-op weights via each spec's own initializer,
    keyed by guid; the fold key depends on the op NAME so sibling ops
    (e.g. K parallel embedding tables) get distinct values and a
    rewrite that permutes them cannot pass by accident."""
    import jax

    out: Dict[int, Dict[str, np.ndarray]] = {}
    base = jax.random.key(seed)
    for node in graph.topo_order():
        specs = node.op._weight_specs
        if not specs:
            continue
        ws = {}
        for w in specs:
            k = jax.random.fold_in(
                base,
                zlib.crc32(f"{node.op.name}/{w.name}".encode()) & 0x7FFFFFFF,
            )
            ws[w.name] = np.asarray(w.initializer.init(
                k, w.shape, w.dtype.to_numpy()))
        out[node.guid] = ws
    return out


def evaluate_graph(graph, inputs: Dict[int, np.ndarray],
                   weights: Dict[int, Dict[str, np.ndarray]],
                   ) -> Dict[Tuple[int, int], np.ndarray]:
    """Forward the whole PCG in the global view (single logical device,
    float32 compute, eval mode) and return every ``(guid, out_idx)``
    value."""
    import jax.numpy as jnp

    from flexflow_tpu.ops.base import LoweringContext
    from flexflow_tpu.ops.inout import InputOp

    ctx = LoweringContext(compute_dtype=jnp.float32, train=False, rng=None)
    values: Dict[Tuple[int, int], np.ndarray] = {}
    for node in graph.topo_order():
        if isinstance(node.op, InputOp):
            values[(node.guid, 0)] = inputs[node.guid]
            continue
        in_edges = sorted(graph.in_edges[node.guid], key=lambda e: e.dst_idx)
        ins = [values[(e.src, e.src_idx)] for e in in_edges]
        outs = node.op.forward(ctx, ins, weights.get(node.guid, {}))
        for i, y in enumerate(outs):
            values[(node.guid, i)] = np.asarray(y)
    return values


# ---------------------------------------------------------------------------
# weight bridging across a rewrite


def _spec_key(w) -> Tuple:
    return (w.name, tuple(w.shape), w.dtype.value)


def bridge_weights(src_graph, dst_graph,
                   src_weights: Dict[int, Dict[str, np.ndarray]],
                   ) -> Tuple[Dict[int, Dict[str, np.ndarray]], List[Finding]]:
    findings: List[Finding] = []
    dst_w: Dict[int, Dict[str, np.ndarray]] = {}
    # removed weighted ops, in source topo order (the order
    # BatchEmbeddingsXfer stacks its match groups in)
    pool = [n for n in src_graph.topo_order()
            if n.guid not in dst_graph.nodes and n.op._weight_specs]
    for node in dst_graph.topo_order():
        specs = node.op._weight_specs
        if not specs:
            continue
        if node.guid in src_graph.nodes:
            dst_w[node.guid] = src_weights[node.guid]
            continue
        spec_keys = [_spec_key(w) for w in specs]
        direct = next(
            (p for p in pool
             if [_spec_key(w) for w in p.op._weight_specs] == spec_keys),
            None,
        )
        if direct is not None:
            dst_w[node.guid] = src_weights[direct.guid]
            pool.remove(direct)
            continue
        ws: Dict[str, np.ndarray] = {}
        ok = True
        for w in specs:
            k = w.shape[0] if w.shape else 0
            donors = [p for p in pool
                      if any(_spec_key(x) == (w.name, tuple(w.shape[1:]),
                                              w.dtype.value)
                             for x in p.op._weight_specs)]
            if k >= 2 and len(donors) >= k:
                take = donors[:k]
                ws[w.name] = np.stack(
                    [src_weights[p.guid][w.name] for p in take], axis=0)
                for p in take:
                    pool.remove(p)
            else:
                ok = False
                break
        if ok:
            dst_w[node.guid] = ws
        else:
            findings.append(_f(
                "EQV303",
                f"no weight bridge from the removed ops to new op "
                f"{node.op.name!r} (specs {spec_keys})",
                node=node.guid, op=node.op.name))
    return dst_w, findings


# ---------------------------------------------------------------------------
# the proof


def verify_rewrite(graph, xfer, match, seed: int = 0,
                   rtol: float = DEFAULT_RTOL, atol: float = DEFAULT_ATOL,
                   ) -> List[Finding]:
    """Numeric-equivalence findings for applying ``xfer`` at ``match``
    ([] = the rewrite is a sound, well-formed, value-preserving
    transformation of this graph)."""
    from flexflow_tpu.analysis.invariants import GraphInvariantError

    name = getattr(xfer, "name", type(xfer).__name__)
    try:
        g2 = xfer.apply(graph, match)
    except GraphInvariantError as e:
        # with FLEXFLOW_TPU_VERIFY armed the apply hook raises at the
        # rewrite; surface its findings instead of dying — fflint's
        # exit-code contract holds either way
        return list(e.findings)
    if g2 is None:
        return [_f("EQV300",
                   f"{name}: apply declined a match find_matches reported")]
    findings = check_graph(g2)
    if findings:
        return findings
    inputs = make_inputs(graph, seed)
    src_w = make_weights(graph, seed)
    try:
        src_vals = evaluate_graph(graph, inputs, src_w)
    except Exception as e:
        return [_f("EQV302",
                   f"{name}: source graph failed to evaluate: "
                   f"{type(e).__name__}: {e}")]
    dst_w, findings = bridge_weights(graph, g2, src_w)
    if findings:
        return findings
    try:
        dst_vals = evaluate_graph(g2, inputs, dst_w)
    except Exception as e:
        return [_f("EQV302",
                   f"{name}: rewritten graph failed to evaluate: "
                   f"{type(e).__name__}: {e}")]
    for guid in sorted(graph.nodes.keys() & g2.nodes.keys()):
        node = g2.nodes[guid]
        for i in range(len(node.op.output_shapes)):
            a = src_vals.get((guid, i))
            b = dst_vals.get((guid, i))
            if a is None or b is None:
                continue
            a, b = np.asarray(a), np.asarray(b)
            if a.shape != b.shape:
                findings.append(_f(
                    "EQV301",
                    f"{name}: output {i} of {node.op.name!r} changed "
                    f"shape {a.shape} -> {b.shape}",
                    node=guid, op=node.op.name))
            elif not np.issubdtype(a.dtype, np.integer) \
                    and a.dtype != np.bool_:
                # float path.  NOT spelled issubdtype(floating): the
                # bfloat16 proof lane's extension dtype is no numpy
                # float subtype, and exact-equality on it would reject
                # legal summation-order changes
                if not np.allclose(a.astype(np.float64),
                                   b.astype(np.float64),
                                   rtol=rtol, atol=atol):
                    diff = float(np.max(np.abs(
                        a.astype(np.float64) - b.astype(np.float64))))
                    findings.append(_f(
                        "EQV301",
                        f"{name}: output {i} of {node.op.name!r} diverges "
                        f"(max abs diff {diff:.3e})",
                        node=guid, op=node.op.name))
            elif not np.array_equal(a, b):
                findings.append(_f(
                    "EQV301",
                    f"{name}: integer output {i} of {node.op.name!r} "
                    f"diverges", node=guid, op=node.op.name))
    return findings


# ---------------------------------------------------------------------------
# proof graphs: small models that together match EVERY registered xfer.
# Sizes scale with the device count so every divisor degree the
# registry generates divides the partitioned dims (a dim of size N or
# 2N is divisible by every divisor of N).


def _proof_graphs(num_devices: int = 8) -> List:
    import flexflow_tpu as ff

    n = max(2, num_devices)
    b = max(8, n)  # batch: every divisor of n divides b (b = n or 8|n…)
    if b % n:
        b = n
    w = 2 * n  # feature width
    # a batch-dividing degree for the hand-placed repartitions (hoist's
    # apply re-checks divisibility and declines otherwise)
    d_b = next((d for d in (4, 3, 2) if b % d == 0), b)
    graphs = []
    cfg = lambda: ff.FFConfig(batch_size=b, num_devices=num_devices,  # noqa: E731
                              only_data_parallel=True)

    # linear / relu / fusion / replicate-reduce
    m = ff.FFModel(cfg())
    x = m.create_tensor([b, w], name="pf_mlp_in")
    t = m.dense(x, w, name="pf_fc1")
    t = m.relu(t, name="pf_act")
    t = m.dense(t, w, name="pf_fc2")
    m.dense(t, 4, name="pf_mlp_head")
    graphs.append(m.graph)

    # attention (dims 0/1 + head-parallel replicate-reduce): seq = n,
    # heads = n (head_dim 2), so every divisor degree fits
    m = ff.FFModel(cfg())
    x = m.create_tensor([b, n, w], name="pf_attn_in")
    t = m.multihead_attention(x, x, x, w, n, name="pf_attn")
    m.dense(t, 4, name="pf_attn_head")
    graphs.append(m.graph)

    # conv / pool / flat (batch-dim partitions only in the registry)
    m = ff.FFModel(cfg())
    x = m.create_tensor([b, 8, 8, 8], name="pf_img")
    t = m.conv2d(x, 8, 3, 3, 1, 1, 1, 1, name="pf_conv")
    t = m.pool2d(t, 2, 2, stride_h=2, stride_w=2, name="pf_pool")
    t = m.flat(t, name="pf_flat")
    m.dense(t, 4, name="pf_conv_head")
    graphs.append(m.graph)

    # embeddings (x2 same-signature: BatchEmbeddingsXfer) / concat /
    # layernorm / softmax / ew_add
    m = ff.FFModel(cfg())
    outs = []
    for i in range(2):
        ids = m.create_tensor([b, 2], dtype="int32", name=f"pf_ids{i}")
        outs.append(m.embedding(ids, 4 * n, n, aggr="sum",
                                name=f"pf_emb{i}"))
    t = m.concat(outs, axis=1, name="pf_cat")
    a = m.dense(t, w, name="pf_ba")
    b_ = m.dense(t, w, name="pf_bb")
    t = m.add(a, b_, name="pf_add")
    t = m.layer_norm(t, name="pf_ln")
    t = m.softmax(t, name="pf_sm")
    m.dense(t, 4, name="pf_emb_head")
    graphs.append(m.graph)

    # cancel_repartition_combine
    m = ff.FFModel(cfg())
    x = m.create_tensor([b, w], name="pf_cc_in")
    t = m.repartition(x, dim=0, degree=d_b, name="pf_cc_rep")
    t = m.combine(t, dim=0, degree=1, name="pf_cc_comb")
    m.dense(t, 4, name="pf_cc_head")
    graphs.append(m.graph)

    # fuse_parallel_op_chain
    m = ff.FFModel(cfg())
    x = m.create_tensor([b, w], name="pf_ch_in")
    t = m.repartition(x, dim=0, degree=2, name="pf_ch_r1")
    t = m.repartition(t, dim=1, degree=2, name="pf_ch_r2")
    m.dense(t, 4, name="pf_ch_head")
    graphs.append(m.graph)

    # sink_combine_through_concat
    m = ff.FFModel(cfg())
    x = m.create_tensor([b, w], name="pf_sk_in")
    outs = []
    for i in range(3):
        t = m.dense(x, w, name=f"pf_sk_b{i}")
        outs.append(m.combine(t, dim=0, degree=1, name=f"pf_sk_c{i}"))
    t = m.concat(outs, axis=1, name="pf_sk_cat")
    m.dense(t, 4, name="pf_sk_head")
    graphs.append(m.graph)

    # hoist_partition_above_unary
    m = ff.FFModel(cfg())
    x = m.create_tensor([b, w], name="pf_ho_in")
    t = m.relu(x, name="pf_ho_act")
    outs = []
    for i in range(3):
        p = m.repartition(t, dim=0, degree=d_b, name=f"pf_ho_p{i}")
        outs.append(m.dense(p, w, name=f"pf_ho_fc{i}"))
    m.concat(outs, axis=1, name="pf_ho_cat")
    graphs.append(m.graph)

    return graphs


def verify_registry(num_devices: int = 8, seed: int = 0,
                    xfers=None) -> List[Finding]:
    """Executable proof for the whole rewrite registry: every xfer from
    ``generate_all_pcg_xfers(num_devices)`` must match at least one
    proof graph and pass ``verify_rewrite`` there.  [] = the registry
    is sound."""
    from flexflow_tpu.search.substitution import generate_all_pcg_xfers

    if xfers is None:
        xfers = generate_all_pcg_xfers(num_devices)
    graphs = _proof_graphs(num_devices)
    findings: List[Finding] = []
    for xf in xfers:
        name = getattr(xf, "name", type(xf).__name__)
        matched = False
        for g in graphs:
            matches = xf.find_matches(g)
            if not matches:
                continue
            matched = True
            findings += verify_rewrite(g, xf, matches[0], seed=seed)
            break
        if not matched:
            findings.append(_f(
                "EQV305",
                f"registered rewrite {name!r} matched no proof graph — "
                f"it carries no executable soundness proof"))
    return findings
