"""Indent-scoped search logging (reference:
src/runtime/recursive_logger.cc + include/flexflow/utils/
recursive_logger.h — TAG_ENTER/TAG_EXIT indented traces of the search
recursion, e.g. substitution.cc:2011)."""

from __future__ import annotations

import contextlib
import os
import sys
from typing import Optional, TextIO


class RecursiveLogger:
    """Depth-indented logger; enabled via FLEXFLOW_TPU_SEARCH_LOG=1 or
    explicitly."""

    def __init__(self, category: str = "search",
                 enabled: Optional[bool] = None, stream: TextIO = None):
        self.category = category
        if enabled is None:
            enabled = os.environ.get("FLEXFLOW_TPU_SEARCH_LOG", "") not in ("", "0")
        self.enabled = enabled
        self.stream = stream or sys.stderr
        self.depth = 0

    def log(self, msg: str) -> None:
        if self.enabled:
            self.stream.write(f"[{self.category}] {'  ' * self.depth}{msg}\n")

    @contextlib.contextmanager
    def enter(self, msg: str = ""):
        """TAG_ENTER equivalent: indent everything logged inside."""
        if msg:
            self.log(msg)
        self.depth += 1
        try:
            yield self
        finally:
            self.depth -= 1


SEARCH_LOG = RecursiveLogger("search")
