"""PCG → XLA lowering.

The TPU counterpart of the entire execution half of the reference
(FFModel::compile region mapping model.cc:2703-2836 + per-op Legion
index launches + Legion tracing): the whole training iteration becomes
ONE jitted SPMD program over the global mesh.  Per-op "machine views"
are realized as GSPMD sharding constraints on tensor edges; XLA inserts
the collectives the reference delegated to Legion/Realm (activations)
and NCCL (gradients), fuses elementwise chains (the reference's FusedOp
pass, model.cc:2343, is obsolete by construction), and overlaps
compute/communication in its scheduler.

There are no backward methods anywhere: ``jax.value_and_grad`` of the
lowered forward replaces every hand-written backward task of the
reference (src/ops/ backward kernels), and gradient synchronization falls
out of params' shardings.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.graph import Graph, Node
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.core.optype import OperatorType
from flexflow_tpu.core.ptensor import DataType
from flexflow_tpu.losses import LossType, compute_loss
from flexflow_tpu.metrics import MetricsType, compute_metrics
from flexflow_tpu.ops.base import LoweringContext, OpSharding, ShardAnnot
from flexflow_tpu.ops.inout import InputOp
from flexflow_tpu.optimizers import Optimizer
from flexflow_tpu.parallel.mesh import (
    annot_partition_spec,
    build_mesh,
    mesh_axis_sizes,
    view_slot_axes,
)


def weight_fold_key(base_key, op_name: str, w_name: str):
    """Per-weight init key derived from the weight's NAME, not its
    position in the topo enumeration: initialization is then invariant
    to how a strategy partitions the graph into programs (a placed
    2-segment lowering and the flat lowering draw identical weights for
    the same seed) and to graph rewrites that preserve op names."""
    import zlib

    return jax.random.fold_in(
        base_key, np.uint32(zlib.crc32(f"{op_name}/{w_name}".encode()))
    )


def data_parallel_strategy(graph: Graph, degree: int) -> Dict[int, MachineView]:
    """Batch-dim partitioning for every op — the reference's
    --only-data-parallel path (graph.cc:1572-1597)."""
    # candidate degrees: divisors of the device count, descending, so the
    # chosen degree always factors into the mesh's prime-factor axis pool
    divisors = sorted(
        (d for d in range(1, degree + 1) if degree % d == 0), reverse=True
    )
    strategy: Dict[int, MachineView] = {}
    for node in graph.topo_order():
        fixed = node.op.fixed_machine_view()
        if fixed is not None:
            strategy[node.guid] = fixed
            continue
        out = node.op.output_shapes[0]
        batch = out.sizes[0] if out.ndim else 1
        d = 1
        if out.ndim and 0 in node.op.splittable_output_dims():
            d = next(dd for dd in divisors if batch % dd == 0)
        strategy[node.guid] = (
            MachineView.data_parallel(out.ndim, d) if d > 1 else MachineView.trivial(out.ndim)
        )
    return strategy


class CompiledModel:
    """A PCG + strategy compiled to jitted train/eval steps over a mesh."""

    def __init__(
        self,
        graph: Graph,
        strategy: Dict[int, MachineView],
        config: FFConfig,
        loss_type: LossType,
        metric_types: Sequence[MetricsType],
        optimizer: Optional[Optimizer],
        mesh=None,
        label_dtype: str = "int32",
        sync_precision: Optional[Dict[str, str]] = None,
        sync_schedule=None,
        zero_groups: Optional[Sequence[str]] = None,
    ):
        self.graph = graph
        self.strategy = strategy
        self.config = config
        # op name -> bf16/int8: weight groups whose gradient sync runs
        # through the compressed collective (comm/quantized.py); the
        # search builds this map (search/sync_precision.py) and absent
        # /empty means the historical bit-exact fp32 psum
        self.sync_precision: Dict[str, str] = dict(sync_precision or {})
        # searched gradient-sync schedule (search/sync_schedule.py):
        # when present, _sync_grads executes its buckets in issue order
        # via comm/bucketed.py — fused per-bucket wire payloads with
        # optimization_barrier anchoring inside the backward; None (the
        # default) keeps the monolithic post-backward path
        self.sync_schedule = sync_schedule
        # per-group optimizer-state sharding (the co-searched ZeRO-1
        # dimension, search/comm_plan.py): op names whose optimizer
        # state (and update) shards over their replication axes — the
        # per-group generalization of config.zero_dp_shard, which
        # still arms ALL ops when set.  Linted (SHD140/141) before it
        # gets here.
        self.zero_groups: Tuple[str, ...] = tuple(zero_groups or ())
        self.loss_type = LossType.from_any(loss_type)
        self.metric_types = [MetricsType.from_any(m) for m in metric_types]
        self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else build_mesh(
            jax.devices()[: config.num_devices]
        )
        self.label_dtype = label_dtype
        self.compute_dtype = DataType.from_any(config.compute_dtype).to_numpy()

        self._topo = graph.topo_order()
        self._input_nodes: List[Node] = [
            n for n in self._topo if isinstance(n.op, InputOp)
        ]
        # order inputs by frontend tensor guid for stable binding
        self._input_nodes.sort(key=lambda n: n.op.attrs.get("tensor_guid", n.guid))
        sinks = graph.sinks()
        assert sinks, "empty graph"
        self._sink = sinks[-1]

        # axis pool = the mesh's own axes (minus any pipeline axis, which
        # only the pipelined lowering may consume); for default meshes
        # this equals mesh_axis_sizes(num_devices).
        _pl = getattr(self, "pipeline", None)
        pp_axis = _pl.axis_name if _pl is not None else "pp"
        axis_pool = [(n, s) for n, s in self.mesh.shape.items() if n != pp_axis]
        self._shardings: Dict[int, OpSharding] = {}
        self._slot_axes: Dict[int, Dict[int, Tuple[str, ...]]] = {}
        for node in self._topo:
            mv = strategy.get(node.guid)
            if mv is None:
                mv = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            self._shardings[node.guid] = node.op.propagate(mv)
            self._slot_axes[node.guid] = view_slot_axes(mv, axis_pool)

        self._multi_device = int(np.prod(list(self.mesh.shape.values()))) > 1
        self._train_step_fn = None
        self._eval_step_fn = None

    # ------------------------------------------------------------------
    def _constrain(self, x, annot: ShardAnnot, slot_axes) -> jax.Array:
        if not self._multi_device or annot.partial:
            return x
        spec = annot_partition_spec(annot, slot_axes)
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(self.mesh, spec)
        )

    def input_sharding(self, i: int):
        """NamedSharding for the i-th frontend input (dataloader uses it)."""
        node = self._input_nodes[i]
        annot = self._shardings[node.guid].outputs[0]
        spec = annot_partition_spec(annot, self._slot_axes[node.guid])
        return jax.sharding.NamedSharding(self.mesh, spec)

    def batch_sharding(self):
        """Batch-dim sharding of the label tensor = sink's batch annot."""
        annot = self._shardings[self._sink.guid].outputs[0]
        axes = self._slot_axes[self._sink.guid].get(0, ())
        from jax.sharding import PartitionSpec

        spec = PartitionSpec(tuple(axes) if len(axes) > 1 else (axes[0] if axes else None))
        return jax.sharding.NamedSharding(self.mesh, spec)

    # ------------------------------------------------------------------
    def apply(
        self,
        params: Dict[str, Dict[str, jax.Array]],
        state: Dict[str, jax.Array],
        inputs: Sequence[jax.Array],
        rng: Optional[jax.Array],
        train: bool,
    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """Forward through the PCG (global view). Returns (logits, new_state)."""
        outs, new_state = self.apply_multi(
            params, state, inputs, rng, train,
            outputs=((self._sink.guid, 0),),
        )
        return outs[0], new_state

    def apply_multi(
        self,
        params: Dict[str, Dict[str, jax.Array]],
        state: Dict[str, jax.Array],
        inputs: Sequence[jax.Array],
        rng: Optional[jax.Array],
        train: bool,
        outputs: Sequence[Tuple[int, int]],
    ) -> Tuple[Tuple[jax.Array, ...], Dict[str, jax.Array]]:
        """Forward returning the requested ``(guid, output_idx)`` tensors
        instead of the sink's — the placed lowering pulls every tensor
        that crosses its segment boundary from one forward pass."""
        ctx = LoweringContext(
            compute_dtype=self.compute_dtype,
            train=train,
            rng=rng,
            seq_length=self.config.iteration.seq_length,
            state_in=state,
            mesh=self.mesh if self._multi_device else None,
        )
        values: Dict[Tuple[int, int], jax.Array] = {}
        input_pos = {n.guid: i for i, n in enumerate(self._input_nodes)}
        for node in self._topo:
            self._run_node(node, ctx, values, params, inputs, input_pos)
        new_state = dict(state)
        new_state.update(ctx.state_out)
        return tuple(values[key] for key in outputs), new_state

    def value_sharding(self, guid: int, idx: int = 0):
        """NamedSharding of op ``guid``'s ``idx``-th output under this
        program's mesh (boundary cotangents re-enter under it)."""
        annot = self._shardings[guid].outputs[idx]
        spec = annot_partition_spec(annot, self._slot_axes[guid])
        return jax.sharding.NamedSharding(self.mesh, spec)

    def _run_node(self, node, ctx, values, params, inputs, input_pos):
        """Lower one PCG node into ``values`` (shared by the pipelined
        subclass's apply)."""
        osh = self._shardings[node.guid]
        axes = self._slot_axes[node.guid]
        if node.guid in input_pos:
            x = inputs[input_pos[node.guid]]
            values[(node.guid, 0)] = self._constrain(x, osh.outputs[0], axes)
            return
        in_edges = sorted(self.graph.in_edges[node.guid], key=lambda e: e.dst_idx)
        ins = []
        for e in in_edges:
            x = values[(e.src, e.src_idx)]
            if e.dst_idx < len(osh.inputs) and osh.inputs[e.dst_idx] is not None:
                x = self._constrain(x, osh.inputs[e.dst_idx], axes)
            ins.append(x)
        ctx.slot_axes = axes
        ws = params.get(node.op.name, {})
        if self._multi_device:
            # ops with an explicit-SPMD lowering (shard_map +
            # collectives) take it when the sharding calls for it —
            # e.g. vocab-split embedding emits a masked local gather +
            # psum instead of whatever GSPMD would pick for the global
            # jnp.take (SURVEY.md §7 hard part (e))
            outs = node.op.forward_sharded(ctx, ins, ws, osh)
            if outs is not None:
                for i, y in enumerate(outs):
                    values[(node.guid, i)] = y
                return
        if (
            self.config.remat
            and getattr(node.op, "state_specs", None) is None
            and node.op._weight_specs
        ):
            # rematerialize weighted stateless ops in backward: their
            # activations are recomputed instead of saved (state-mutating
            # ops can't be checkpointed — forward must be pure)
            outs = jax.checkpoint(
                lambda i, w: node.op.forward(ctx, i, w)
            )(ins, ws)
        else:
            outs = node.op.forward(ctx, ins, ws)
        for i, y in enumerate(outs):
            if i < len(osh.outputs):
                y = self._constrain(y, osh.outputs[i], axes)
            values[(node.guid, i)] = y

    # ------------------------------------------------------------------
    def init_params(self, seed: int = 0):
        """Initialize sharded params + model state (reference: per-weight
        initializer tasks, initializer.cc; here one jitted program whose
        out_shardings place every weight shard directly)."""
        specs = []  # (op_name, weight_name, shape, dtype, init, sharding)
        for node in self._topo:
            osh = self._shardings[node.guid]
            axes = self._slot_axes[node.guid]
            for wi, ws in enumerate(node.op._weight_specs):
                annot = osh.weights[wi] if wi < len(osh.weights) else None
                spec = (
                    annot_partition_spec(annot, axes)
                    if annot is not None
                    else jax.sharding.PartitionSpec()
                )
                specs.append(
                    (
                        node.op.name,
                        ws.name,
                        ws.shape,
                        ws.dtype.to_numpy(),
                        ws.initializer,
                        jax.sharding.NamedSharding(self.mesh, spec),
                    )
                )

        def _init(key):
            out = {}
            for op_name, w_name, shape, dtype, init, _ in specs:
                k = weight_fold_key(key, op_name, w_name)
                out.setdefault(op_name, {})[w_name] = init.init(k, shape, dtype)
            return out

        shardings = {}
        for op_name, w_name, _, _, _, sh in specs:
            shardings.setdefault(op_name, {})[w_name] = sh
        key = jax.random.key(seed)
        params = jax.jit(_init, out_shardings=(shardings or None))(key)

        state: Dict[str, jax.Array] = {}
        # replicate state vars over the whole mesh so eager (un-jitted)
        # multi-device forward sees consistently-placed operands
        rep = jax.sharding.NamedSharding(self.mesh, jax.sharding.PartitionSpec())
        for node in self._topo:
            ss = getattr(node.op, "state_specs", None)
            if ss is None:
                continue
            # ops that declare per-state shardings (the decode op's
            # paged KV cache) get their state PLACED under the
            # strategy's view instead of replicated — the KV residency
            # the cost model credits to a sharded view is residency the
            # compiled program actually realizes
            st_annots = {}
            ssh = getattr(node.op, "state_shardings", None)
            if ssh is not None and self._multi_device:
                mv = self.strategy.get(node.guid)
                if mv is None:
                    mv = node.op.fixed_machine_view() or MachineView.trivial(
                        node.op.output_shapes[0].ndim)
                st_annots = ssh(mv) or {}
            for name, shape, dtype, fill in ss():
                v = jnp.full(shape, fill, dtype)
                if self._multi_device:
                    annot = st_annots.get(name)
                    sh = rep if annot is None else jax.sharding.NamedSharding(
                        self.mesh,
                        annot_partition_spec(
                            annot, self._slot_axes[node.guid]),
                    )
                    v = jax.device_put(v, sh)
                state[f"{node.op.name}/{name}"] = v
        self.param_shardings = shardings
        self._zero_shardings = None
        zero_all = getattr(self.config, "zero_dp_shard", False)
        zg = set(self.zero_groups)
        if (zero_all or zg) and self._multi_device:
            # global flag = every op; the co-searched per-group map
            # restricts the augmented shardings to its members — ops
            # outside it keep replicated optimizer state (and the
            # update credit the joint currency never claimed for them)
            zs: Dict[str, Dict[str, jax.sharding.NamedSharding]] = {}
            for op_name, w_name, shape, _, _, sh in specs:
                if not zero_all and op_name not in zg:
                    continue
                zs.setdefault(op_name, {})[w_name] = self._zero_augmented(
                    sh, shape
                )
            self._zero_shardings = zs or None
        # error-feedback residual state (comm.quantized_allreduce_ef):
        # one fp32 residual per int8_ef weight, sharded like the param
        # so the shard_map-local block aligns with the grad's — carried
        # in the model-state dict like any other training-loop state
        # (checkpoints round-trip it for free)
        self._ef_keys: Dict[str, Dict[str, str]] = {}
        ef_ops = {op for op, p in self.sync_precision.items()
                  if p == "int8_ef"}
        if ef_ops and self._multi_device:
            from flexflow_tpu.comm.quantized import (
                MIN_COMPRESS_ELEMS,
                replication_axes,
            )

            for op_name, w_name, shape, _, _, sh in specs:
                if op_name not in ef_ops:
                    continue
                nelems = 1
                for d in shape:
                    nelems *= d
                if nelems < MIN_COMPRESS_ELEMS:
                    continue  # sub-floor weights never compress
                rep, _n = replication_axes(sh, self.mesh)
                if not rep:
                    continue
                key = f"{op_name}/{w_name}/ef_residual"
                self._ef_keys.setdefault(op_name, {})[w_name] = key
                state[key] = jax.device_put(
                    jnp.zeros(shape, jnp.float32), sh)
        return params, state

    # ------------------------------------------------------------------
    def _zero_augmented(self, sh, shape):
        """ZeRO-1 / weight-update sharding (arXiv:2004.13336): extend a
        weight's PartitionSpec with the mesh axes the weight is
        replicated over, placed on the largest evenly-divisible dim.
        Optimizer state stored with this sharding makes GSPMD lower the
        grad psum to reduce-scatter and the updated-weight broadcast to
        all-gather — same ring bytes, 1/replication the memory and
        update compute."""
        from flexflow_tpu.parallel.mesh import place_zero_factors

        spec = list(sh.spec) + [None] * (len(shape) - len(sh.spec))
        used = set()
        for e in spec:
            if e is None:
                continue
            for a in (e if isinstance(e, tuple) else (e,)):
                used.add(a)
        free = [(n, s) for n, s in self.mesh.shape.items()
                if n not in used and s > 1]
        if not free:
            return sh
        extents = []
        for d in range(len(shape)):
            cur = spec[d]
            cur_axes = () if cur is None else (
                cur if isinstance(cur, tuple) else (cur,)
            )
            deg = 1
            for a in cur_axes:
                deg *= self.mesh.shape[a]
            extents.append(
                shape[d] // deg if deg and shape[d] % deg == 0 else 1
            )
        for d, fi in place_zero_factors(extents, [s for _, s in free]):
            cur = spec[d]
            cur_axes = () if cur is None else (
                cur if isinstance(cur, tuple) else (cur,)
            )
            spec[d] = tuple(cur_axes) + (free[fi][0],)
        while spec and spec[-1] is None:
            spec.pop()
        return jax.sharding.NamedSharding(
            self.mesh, jax.sharding.PartitionSpec(*spec)
        )

    @staticmethod
    def _map_param_slots(opt_state, leaf_fn):
        """Apply ``leaf_fn(op, w, x)`` to every leaf of the optimizer
        slots that mirror the params tree (Adam m/v, SGD momentum v);
        scalar slots (step) pass through."""
        out = {}
        for slot, sub in opt_state.items():
            if isinstance(sub, dict):
                out[slot] = {
                    op: {w: leaf_fn(op, w, x) for w, x in ws.items()}
                    for op, ws in sub.items()
                }
            else:
                out[slot] = sub
        return out

    def shard_opt_state(self, opt_state):
        """Re-place freshly initialized optimizer state under the
        ZeRO-1 shardings (no-op unless config.zero_dp_shard or a
        per-group ``zero_groups`` map armed some ops; non-member ops'
        slots pass through untouched)."""
        if getattr(self, "_zero_shardings", None) is None:
            return opt_state
        zs = self._zero_shardings

        def place(op, w, x):
            sh = zs.get(op, {}).get(w)
            return x if sh is None else jax.device_put(x, sh)

        return self._map_param_slots(opt_state, place)

    def _constrain_update(self, new_params, new_opt_state):
        """Pin the post-update shardings inside the jitted step: params
        back to their layer shardings (the all-gather side of ZeRO),
        optimizer slots to the augmented shardings (the reduce-scatter
        side).  With a per-group map only the member ops are pinned —
        the others' update stays wherever GSPMD placed it, exactly the
        pre-ZeRO behavior."""
        if getattr(self, "_zero_shardings", None) is None:
            return new_params, new_opt_state
        zs = self._zero_shardings
        new_params = {
            op: {
                w: (
                    jax.lax.with_sharding_constraint(
                        x, self.param_shardings[op][w]
                    )
                    if zs.get(op, {}).get(w) is not None else x
                )
                for w, x in ws.items()
            }
            for op, ws in new_params.items()
        }

        def pin(op, w, x):
            sh = zs.get(op, {}).get(w)
            return x if sh is None else jax.lax.with_sharding_constraint(
                x, sh)

        new_opt_state = self._map_param_slots(new_opt_state, pin)
        return new_params, new_opt_state

    # ------------------------------------------------------------------
    def _sync_grads(self, grads, ef_state=None):
        """Gradient sync inside the jitted step, before the optimizer
        update.

        ``ef_state`` — the model-state dict carrying the error-feedback
        residuals for ``int8_ef`` groups (``init_params`` created them
        under ``{op}/{w}/ef_residual`` keys): the call then returns
        ``(grads, updates)`` where ``updates`` maps those state keys to
        the new residuals — the training step merges them into its
        ``new_state`` so the feedback persists across steps.  With
        ``ef_state=None`` (direct callers, pre-EF tests) the legacy
        single-value return is kept and int8_ef runs the plain int8
        wire.

        With a searched ``sync_schedule`` the buckets execute in issue
        order (comm/bucketed.py): each compressed bucket's member grads
        flatten into ONE fused wire payload over their replication
        axes, and buckets chain through ``optimization_barrier`` so XLA
        issues the collectives in backward grad-readiness order — the
        overlap the simulator prices (exposed-comm semantics).  fp32
        buckets contribute only their value-identity ordering barrier,
        so an all-fp32 schedule stays bit-exact with the monolithic
        path.

        Without a schedule, the weight groups ``self.sync_precision``
        names run the quantized quantize → compressed all_to_all →
        requantize → all_gather round trip (EQuARX, comm/quantized.py).
        With neither (or a single device) this returns ``grads``
        untouched — bit-exact with the historical lowering.  Both paths
        compose with ZeRO-1: the round trip runs before the optimizer
        update, so _constrain_update's reduce-scatter/all-gather
        placement of the update is unchanged; with grad accumulation
        the AVERAGED grads sync once per optimizer step.
        """
        def ret(g, updates=None):
            return g if ef_state is None else (g, updates or {})

        if not self._multi_device:
            return ret(grads)
        shardings = getattr(self, "param_shardings", None)
        if shardings is None:  # init_params not run yet — nothing to map
            return ret(grads)
        residuals = None
        ef_keys = getattr(self, "_ef_keys", None)
        if ef_state is not None and ef_keys:
            residuals = {
                op: {w: ef_state[key] for w, key in ws.items()
                     if key in ef_state}
                for op, ws in ef_keys.items()
            }
        schedule = self.sync_schedule
        if schedule is not None and getattr(schedule, "buckets", None):
            from flexflow_tpu.comm import bucketed_grad_sync
            from flexflow_tpu.obs.annotate import lane_stamps_armed

            # the machine spec arms staged (hierarchical) execution of
            # buckets carrying a reduction plan — the nested axis split
            # follows the spec's slice structure, not the live backend.
            # lane_stamps (device_trace_dir captures only) brackets
            # each bucket with its stable lane id so the real trace
            # tag-matches the predicted comm lanes.
            got = bucketed_grad_sync(
                grads, self.mesh, shardings, schedule,
                machine=self.config.machine_spec, residuals=residuals,
                lane_stamps=lane_stamps_armed(self.config))
            if residuals is None:
                return ret(got)
            merged, new_res = got
            return ret(merged, self._ef_updates(new_res))
        if not self.sync_precision:
            return ret(grads)
        from flexflow_tpu.comm import quantized_grad_sync

        got = quantized_grad_sync(
            grads, self.mesh, shardings, self.sync_precision,
            residuals=residuals,
        )
        if residuals is None:
            return ret(got)
        merged, new_res = got
        return ret(merged, self._ef_updates(new_res))

    def _ef_updates(self, new_res):
        """Map the sync path's returned residual tree back onto its
        model-state keys."""
        updates = {}
        for op, ws in (new_res or {}).items():
            for w, r in ws.items():
                key = self._ef_keys.get(op, {}).get(w)
                if key is not None:
                    updates[key] = r
        return updates

    def _loss_from(self, logits, labels, new_state):
        loss = compute_loss(self.loss_type, logits, labels)
        for k, v in new_state.items():
            if k.endswith("/aux_loss"):
                loss = loss + v
        return loss

    def _raw_step(self, params, opt_state, state, rng, inputs, labels):
        optimizer = self.optimizer
        ga = max(1, getattr(self.config, "grad_accum_steps", 1))
        if ga > 1:
            return self._raw_step_accum(
                params, opt_state, state, rng, inputs, labels, ga
            )

        def loss_fn(p):
            logits, new_state = self.apply(p, state, inputs, rng, train=True)
            loss = self._loss_from(logits, labels, new_state)
            return loss, (logits, new_state)

        (loss, (logits, new_state)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(params)
        grads, ef_updates = self._sync_grads(grads, ef_state=state)
        new_state.update(ef_updates)
        new_params, new_opt_state = optimizer.apply(params, grads, opt_state)
        new_params, new_opt_state = self._constrain_update(
            new_params, new_opt_state
        )
        m = compute_metrics(self.metric_types, self.loss_type, logits, labels)
        return new_params, new_opt_state, new_state, loss, m

    def _raw_step_accum(self, params, opt_state, state, rng, inputs, labels, ga):
        """Gradient accumulation: the batch is processed as ``ga``
        microbatches inside a lax.scan, grads averaged, ONE optimizer
        update — activation memory scales with batch/ga while the
        effective batch stays the full batch: the loss is the mean of
        equal-sized microbatch means and metrics are per-batch SUMS
        (compute_metrics semantics), so they add across the disjoint
        microbatches.  The reference has no analogue — its
        per-iteration batch is bounded by what fits.  Together with
        config.remat this is the second memory lever."""
        B = labels.shape[0]
        assert B % ga == 0, (
            f"batch {B} must divide by grad_accum_steps {ga}"
        )

        def resh(x):
            return x.reshape((ga, B // ga) + x.shape[1:])

        keys = jax.random.split(rng, ga)

        def loss_fn(p, s, inp, lab, key):
            logits, new_state = self.apply(p, s, list(inp), key, train=True)
            loss = self._loss_from(logits, lab, new_state)
            return loss, (logits, new_state)

        gzero = jax.tree.map(jnp.zeros_like, params)

        def body(carry, xs):
            s, gacc = carry
            key, inp, lab = xs
            (loss, (logits, new_s)), g = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, s, inp, lab, key)
            gacc = jax.tree.map(jnp.add, gacc, g)
            m = compute_metrics(self.metric_types, self.loss_type, logits, lab)
            return (new_s, gacc), (loss, m)

        (new_state, gsum), (losses, ms) = jax.lax.scan(
            body, (state, gzero),
            (keys, tuple(resh(x) for x in inputs), resh(labels)),
        )
        grads = jax.tree.map(lambda g: g / ga, gsum)
        # the AVERAGED grads sync once per optimizer step, so the EF
        # residual advances once per step too (state, not per-microbatch)
        grads, ef_updates = self._sync_grads(grads, ef_state=state)
        new_state.update(ef_updates)
        new_params, new_opt_state = self.optimizer.apply(
            params, grads, opt_state
        )
        new_params, new_opt_state = self._constrain_update(
            new_params, new_opt_state
        )
        loss = jnp.mean(losses)
        m = jax.tree.map(lambda x: jnp.sum(x, axis=0), ms)
        return new_params, new_opt_state, new_state, loss, m

    def _build_train_step(self):
        return jax.jit(self._raw_step, donate_argnums=(0, 1, 2))

    def _build_train_steps(self):
        def multi(params, opt_state, state, rng, inputs_stacked, labels_stacked):
            n = labels_stacked.shape[0]
            keys = jax.random.split(rng, n)

            def body(carry, xs):
                p, o, s = carry
                key, inp, lab = xs
                p, o, s, loss, m = self._raw_step(p, o, s, key, list(inp), lab)
                return (p, o, s), (loss, m)

            (p, o, s), (losses, ms) = jax.lax.scan(
                body, (params, opt_state, state),
                (keys, tuple(inputs_stacked), labels_stacked),
            )
            return p, o, s, losses, ms

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    def train_steps(self, params, opt_state, state, rng, inputs_stacked,
                    labels_stacked):
        """Run N training steps inside ONE compiled program
        (jax.lax.scan over stacked batches) — the XLA-native analogue
        of Legion iteration tracing (reference: begin_trace/end_trace,
        flexflow_cffi.py:1867-1874): per-call dispatch overhead is paid
        once per N steps instead of every step.

        ``inputs_stacked``: list of arrays [N, B, ...]; ``labels_stacked``
        [N, B, ...].  Returns (params, opt_state, state, losses [N],
        metrics stacked over N)."""
        if getattr(self, "_train_steps_fn", None) is None:
            self._train_steps_fn = self._build_train_steps()
        return self._train_steps_fn(params, opt_state, state, rng,
                                    tuple(inputs_stacked), labels_stacked)

    def stacked_input_sharding(self, i: int):
        """Sharding for a [N, B, ...] stack of the i-th input (leading
        step axis unsharded)."""
        from jax.sharding import NamedSharding, PartitionSpec

        base = self.input_sharding(i).spec
        return NamedSharding(self.mesh, PartitionSpec(None, *base))

    def stacked_batch_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec

        base = self.batch_sharding().spec
        return NamedSharding(self.mesh, PartitionSpec(None, *base))

    def _build_eval_step(self):
        def step(params, state, inputs, labels):
            logits, new_state = self.apply(params, state, inputs, None, train=False)
            loss = self._loss_from(logits, labels, new_state)
            m = compute_metrics(self.metric_types, self.loss_type, logits, labels)
            return loss, m

        return jax.jit(step)

    def train_step(self, params, opt_state, state, rng, inputs, labels):
        if self._train_step_fn is None:
            self._train_step_fn = self._build_train_step()
        return self._train_step_fn(params, opt_state, state, rng, inputs, labels)

    def eval_step(self, params, state, inputs, labels):
        if self._eval_step_fn is None:
            self._eval_step_fn = self._build_eval_step()
        return self._eval_step_fn(params, state, inputs, labels)

    def forward_fn(self):
        """(params, state, inputs) -> logits — for export/inspection.
        Jitted once and cached (a fresh closure per call would recompile
        every time)."""
        if getattr(self, "_forward_fn", None) is None:

            @jax.jit
            def fwd(params, state, inputs):
                logits, _ = self.apply(params, state, inputs, None, train=False)
                return logits

            self._forward_fn = fwd
        return self._forward_fn
