"""Executed inter-op (VERTICAL) placement: disjoint device blocks.

The reference's mapper places different operators on disjoint device
sets and Legion executes that placement
(reference: src/mapper/mapper.cc:371-475; VERTICAL/HORIZONTAL resource
splits src/runtime/graph.cc:161-295).  Until round 4 this framework
could only *plan* such strategies (the simulator's placement_overlap
mode); this module executes them, TPU-style.

A strategy whose MachineViews carry two distinct ``start_part`` device
blocks splits the PCG into segment A (block starting at 0) and segment
B (the other block).  Each segment lowers as an ordinary
``CompiledModel`` over a SUBMESH of the devices — segment views keep
their degrees, placement comes from the submesh itself — and the
training step is a host-side composition of per-mesh jitted programs,
the XLA analogue of Legion issuing per-region tasks:

    boundary      = fwd_A(params_A, x_A)            on devices[block A]
    loss, g_B, db = step_B(params_B, boundary, ...) on devices[block B]
    g_A           = grad_A(params_A, x_A, db)       on devices[block A]

``grad_A`` re-runs A's forward under ``jax.vjp`` (activation
rematerialization — the standard TPU memory/comm trade) with the same
dropout rng, so the recomputed forward is bit-identical.  Because jax
dispatch is asynchronous and the three programs run on DISJOINT device
sets, consecutive fit() steps genuinely overlap across segments: while
block B trains on step i's boundary, block A is already computing step
i+1's forward — the inter-op parallelism the reference's mapper buys.

The cut may cross up to MAX_CROSSING_TENSORS distinct tensors (a
multi-tower DLRM places every embedding tower in block A and the
interaction + top MLP in block B; each tower output crosses).

Unsupported (loud): >2 device blocks, >16 crossing tensors, gradient
accumulation, zero_dp_shard, traced multi-step scans.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from flexflow_tpu.compiler.lowering import CompiledModel
from flexflow_tpu.core.graph import Graph, Node
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.losses import LossType
from flexflow_tpu.metrics import compute_metrics
from flexflow_tpu.ops.inout import InputOp


def placement_blocks(strategy: Dict[int, MachineView]) -> List[int]:
    """Sorted distinct start_part values in ``strategy``."""
    return sorted({v.start_part for v in strategy.values() if v is not None})


def _cut(graph: Graph, strategy: Dict[int, MachineView]):
    """(nodes_a, nodes_b, crossing_edges, back_edges) for a 2-block
    strategy — the structural cut both placeable() and the constructor
    share."""
    in_a, in_b = [], []
    for guid, node in graph.nodes.items():
        mv = strategy.get(guid)
        block = (mv.start_part if mv is not None else 0)
        (in_a if block == 0 else in_b).append(node)
    a_guids = {n.guid for n in in_a}
    b_guids = {n.guid for n in in_b}
    crossing = [
        e for guid in a_guids for e in graph.out_edges[guid]
        if e.dst in b_guids
    ]
    back = [
        e for guid in b_guids for e in graph.out_edges[guid]
        if e.dst in a_guids
    ]
    return in_a, in_b, crossing, back


# the SAME structural cut the constructor and placeable() compute —
# shared with the placement legality lint (analysis/placement.py,
# SHD153-155) so "what the lint checks" and "what the executor runs"
# cannot drift apart
placement_cut = _cut


def placement_block_widths(in_a, in_b, strategy) -> Tuple[int, int]:
    """(block A width, block B width) — the submesh size each segment
    compiles over (max view parts per side).  ONE rule shared by the
    constructor, the legality lint and the persisted ``__meta__``
    frame, same anti-drift discipline as ``placement_cut``."""
    n_a = max((strategy[n.guid].num_parts for n in in_a
               if strategy.get(n.guid) is not None), default=1)
    n_b = max((strategy[n.guid].num_parts for n in in_b
               if strategy.get(n.guid) is not None), default=1)
    return n_a, n_b


MAX_CROSSING_TENSORS = 16


def placeable(graph: Graph, strategy: Dict[int, MachineView], config) -> bool:
    """Can this strategy go down the placed lowering?  False keeps the
    HISTORICAL behavior for multi-block strategies outside its support
    (>2 blocks, grad accumulation, ZeRO): offsets stay inert and the
    single SPMD program replicates small-degree ops — strategies that
    compiled before inter-op execution existed must keep compiling."""
    if getattr(config, "grad_accum_steps", 1) > 1:
        return False
    if getattr(config, "zero_dp_shard", False):
        return False
    if jax.process_count() > 1:
        # the host-composed multi-mesh step cannot device_put across
        # processes; multihost keeps the historical single-SPMD lowering
        return False
    blocks = placement_blocks(strategy)
    if len(blocks) != 2:
        return False  # 1 block = flat; >2 blocks = unsupported, inert
    in_a, in_b, crossing, back = _cut(graph, strategy)
    if back or not in_a or not in_b:
        return False
    sinks = graph.sinks()
    if not sinks or sinks[-1].guid not in {n.guid for n in in_b}:
        # the loss is computed from B's sink; a cut whose second block
        # does not own the graph sink has no loss program
        return False
    return 0 < len({(e.src, e.src_idx) for e in crossing}) <= MAX_CROSSING_TENSORS


def _strip_start(mv: MachineView) -> MachineView:
    if mv.start_part == 0:
        return mv
    return MachineView(
        dim_degrees=mv.dim_degrees,
        replica_degree=mv.replica_degree,
        start_part=0,
    )


class PlacedCompiledModel:
    """Two-segment vertical placement over disjoint device blocks."""

    def __init__(self, graph: Graph, strategy: Dict[int, MachineView],
                 config, loss_type, metric_types, optimizer,
                 label_dtype: str = "int32"):
        from flexflow_tpu.parallel.mesh import build_mesh

        self.graph = graph
        self.strategy = strategy
        self.config = config
        self.optimizer = optimizer
        if getattr(config, "grad_accum_steps", 1) > 1:
            raise NotImplementedError(
                "grad_accum_steps > 1 is not supported with inter-op "
                "placement")
        if getattr(config, "zero_dp_shard", False):
            raise NotImplementedError(
                "zero_dp_shard is not supported with inter-op placement")

        blocks = placement_blocks(strategy)
        if len(blocks) != 2:
            raise NotImplementedError(
                f"inter-op placement supports exactly 2 device blocks, "
                f"strategy has start_parts {blocks}")
        start_b = blocks[1]

        in_a, in_b, crossing, back = _cut(graph, strategy)
        a_guids = {n.guid for n in in_a}
        b_guids = {n.guid for n in in_b}
        if back:
            raise NotImplementedError(
                "inter-op placement requires a forward-only cut (edges "
                "from the second block back into the first exist)")
        boundary_srcs = sorted({(e.src, e.src_idx) for e in crossing})
        if not 0 < len(boundary_srcs) <= MAX_CROSSING_TENSORS:
            raise NotImplementedError(
                f"inter-op placement supports 1..{MAX_CROSSING_TENSORS} "
                f"tensors crossing the blocks, found {len(boundary_srcs)}")
        # ordered boundary tensors: every A-produced tensor B consumes
        # (a multi-tower DLRM cut crosses one tensor per tower —
        # reference: mapper.cc places the towers and the interaction on
        # disjoint device sets the same way)
        self._boundary_srcs = boundary_srcs
        boundary_shapes = [
            graph.nodes[s].op.output_shapes[i] for s, i in boundary_srcs
        ]

        # ---- segment graphs -------------------------------------------
        graph_a = Graph()
        for n in in_a:
            graph_a.add_node(n)
        for guid in a_guids:
            for e in graph.in_edges[guid]:
                if e.src in a_guids:
                    graph_a.add_edge(graph.nodes[e.src], graph.nodes[e.dst],
                                     e.src_idx, e.dst_idx)

        graph_b = Graph()
        # each boundary enters B as a synthetic input; negative
        # tensor_guids in boundary order sort them FIRST (and in order)
        # in CompiledModel's stable input ordering
        K = len(boundary_srcs)
        boundary_ins = []
        next_guid = max(graph.nodes) + 1
        for bi, ((b_src, b_src_idx), shp) in enumerate(
                zip(boundary_srcs, boundary_shapes)):
            node = Node(
                next_guid + bi,
                InputOp(f"placement_boundary_{bi}", shp,
                        tensor_guid=bi - K),
            )
            boundary_ins.append(node)
            graph_b.add_node(node)
        bmap = {key: n for key, n in zip(boundary_srcs, boundary_ins)}
        for n in in_b:
            graph_b.add_node(n)
        for guid in b_guids:
            for e in graph.in_edges[guid]:
                if e.src in b_guids:
                    graph_b.add_edge(graph.nodes[e.src], graph.nodes[e.dst],
                                     e.src_idx, e.dst_idx)
                else:
                    graph_b.add_edge(bmap[(e.src, e.src_idx)],
                                     graph.nodes[e.dst], 0, e.dst_idx)

        # ---- per-segment strategies / meshes / compiled models --------
        strat_a = {
            n.guid: _strip_start(strategy[n.guid])
            for n in in_a if strategy.get(n.guid) is not None
        }
        strat_b = {
            n.guid: _strip_start(strategy[n.guid])
            for n in in_b if strategy.get(n.guid) is not None
        }
        devices = jax.devices()[: config.num_devices]
        n_a, n_b = placement_block_widths(in_a, in_b, strategy)
        if start_b < n_a or start_b + n_b > len(devices):
            raise ValueError(
                f"device blocks overlap or overflow: A needs {n_a} from 0, "
                f"B needs {n_b} from {start_b}, have {len(devices)}")
        mesh_a = build_mesh(devices[:n_a])
        mesh_b = build_mesh(devices[start_b:start_b + n_b])

        # each boundary enters B under B's OWN mesh geometry: batch-dp
        # over B's devices when divisible, replicated otherwise — the
        # producer's view may not factor into an asymmetric B submesh
        for node, shp in zip(boundary_ins, boundary_shapes):
            if shp.ndim and shp.sizes[0] % n_b == 0:
                strat_b[node.guid] = MachineView.data_parallel(shp.ndim, n_b)
            else:
                strat_b[node.guid] = MachineView.trivial(shp.ndim)

        cfg_a = dataclasses.replace(config, num_devices=n_a)
        cfg_b = dataclasses.replace(config, num_devices=n_b)
        self._comp_a = CompiledModel(
            graph_a, strat_a, cfg_a, LossType.IDENTITY, [], optimizer,
            mesh=mesh_a, label_dtype=label_dtype)
        self._comp_b = CompiledModel(
            graph_b, strat_b, cfg_b, loss_type, metric_types, optimizer,
            mesh=mesh_b, label_dtype=label_dtype)

        self._a_op_names = {n.op.name for n in in_a}
        self._b_op_names = {n.op.name for n in in_b}
        # original input binding order (FFModel feeds inputs by this
        # order): map global input index -> (segment, segment-local idx)
        self._input_map: List[Tuple[str, int]] = []
        all_inputs = sorted(
            (n for n in graph.topo_order() if isinstance(n.op, InputOp)),
            key=lambda n: n.op.attrs.get("tensor_guid", n.guid),
        )
        for n in all_inputs:
            comp, seg = ((self._comp_a, "a") if n.guid in a_guids
                         else (self._comp_b, "b"))
            local = [m.guid for m in comp._input_nodes].index(n.guid)
            self._input_map.append((seg, local))
        self._n_b_extra = sum(1 for seg, _ in self._input_map if seg == "b")
        self._n_boundaries = K

        self._fwd_a = None
        self._step_b = None
        self._grad_a = None
        self._eval_fwd_a = None
        self._eval_fwd_b = None
        self.supports_trace = False  # no single traced program exists

    # -- param/state splitting -----------------------------------------
    def _split(self, tree: dict, state: bool = False):
        a, b = {}, {}
        for k, v in tree.items():
            op = k.split("/")[0] if state else k
            (a if op in self._a_op_names else b)[k] = v
        return a, b

    def _split_opt(self, opt):
        """Optimizer state nests param-shaped trees under keys like
        'm'/'v' with scalars ('step') alongside — split the param-trees
        by segment op name; scalars are duplicated AND re-placed onto
        each segment's mesh (a committed array from one mesh would make
        the other mesh's jit reject the whole call)."""
        from jax.sharding import NamedSharding, PartitionSpec

        names = self._a_op_names | self._b_op_names
        repl_a = NamedSharding(self._comp_a.mesh, PartitionSpec())
        repl_b = NamedSharding(self._comp_b.mesh, PartitionSpec())
        a, b = {}, {}
        for k, v in (opt or {}).items():
            if isinstance(v, dict) and v and set(v) <= names:
                a[k] = {op: w for op, w in v.items()
                        if op in self._a_op_names}
                b[k] = {op: w for op, w in v.items()
                        if op in self._b_op_names}
            else:
                a[k] = jax.device_put(v, repl_a)
                b[k] = jax.device_put(v, repl_b)
        return a, b

    @staticmethod
    def _merge_opt(a, b):
        out = dict(b)  # scalars advanced identically; b's copy wins
        for k, va in a.items():
            vb = out.get(k)
            if isinstance(va, dict) and isinstance(vb, dict):
                out[k] = {**va, **vb}
            elif k not in out:
                out[k] = va
        return out

    # -- public sharding surface ---------------------------------------
    def input_sharding(self, i: int):
        seg, local = self._input_map[i]
        comp = self._comp_a if seg == "a" else self._comp_b
        return comp.input_sharding(local)

    def batch_sharding(self):
        return self._comp_b.batch_sharding()

    def boundary_shardings(self):
        """B-side shardings of the crossing tensors, in boundary order.
        Cached — this sits in the per-step host loop between the two
        jitted programs."""
        if getattr(self, "_boundary_shardings", None) is None:
            self._boundary_shardings = [
                self._comp_b.input_sharding(i)
                for i in range(self._n_boundaries)
            ]
        return self._boundary_shardings

    def _boundaries_to_b(self, boundaries):
        return tuple(
            jax.device_put(x, sh)
            for x, sh in zip(boundaries, self.boundary_shardings())
        )

    def _cotangents_to_a(self, db):
        """Each boundary cotangent re-enters A under the producing
        tensor's own sharding on A's mesh."""
        return tuple(
            jax.device_put(g, self._comp_a.value_sharding(src, idx))
            for g, (src, idx) in zip(db, self._boundary_srcs)
        )

    # -- init ----------------------------------------------------------
    def init_params(self, seed: int = 0):
        # same seed for both segments: the base lowering's name-keyed
        # weight rng (weight_fold_key) makes initialization identical to
        # the flat lowering's for the same model+seed — a strategy
        # change must not silently change the training trajectory
        pa, sa = self._comp_a.init_params(seed)
        pb, sb = self._comp_b.init_params(seed)
        return {**pa, **pb}, {**sa, **sb}

    def shard_opt_state(self, opt_state):
        a, b = self._split_opt(opt_state)
        a = self._comp_a.shard_opt_state(a)
        b = self._comp_b.shard_opt_state(b)
        return self._merge_opt(a, b)

    # -- per-mesh programs ----------------------------------------------
    def _programs(self):
        comp_a, comp_b = self._comp_a, self._comp_b
        optimizer = self.optimizer

        boundary_srcs = self._boundary_srcs

        if self._fwd_a is None:

            @jax.jit
            def fwd_a(pa, sa, inputs_a, rng):
                outs, _ = comp_a.apply_multi(
                    pa, sa, inputs_a, rng, train=True, outputs=boundary_srcs)
                return outs

            @jax.jit
            def step_b(pb, ob, sb, boundaries, inputs_b, labels, rng):
                def loss_fn(p, bounds):
                    logits, new_state = comp_b.apply(
                        p, sb, list(bounds) + list(inputs_b), rng, train=True)
                    loss = comp_b._loss_from(logits, labels, new_state)
                    return loss, (logits, new_state)

                (loss, (logits, new_state)), (gb, db) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1), has_aux=True)(pb, boundaries)
                new_pb, new_ob = optimizer.apply(pb, gb, ob)
                m = compute_metrics(
                    comp_b.metric_types, comp_b.loss_type, logits, labels)
                return new_pb, new_ob, new_state, loss, m, db

            @jax.jit
            def grad_a(pa, oa, sa, inputs_a, db, rng):
                def f(p):
                    outs, new_state = comp_a.apply_multi(
                        p, sa, inputs_a, rng, train=True,
                        outputs=boundary_srcs)
                    return outs, new_state

                _, vjp, new_state = jax.vjp(f, pa, has_aux=True)
                (ga,) = vjp(db)
                new_pa, new_oa = optimizer.apply(pa, ga, oa)
                return new_pa, new_oa, new_state

            self._fwd_a, self._step_b, self._grad_a = fwd_a, step_b, grad_a
        return self._fwd_a, self._step_b, self._grad_a

    def _bind_inputs(self, inputs):
        K = self._n_boundaries
        ins_a = [None] * len(self._comp_a._input_nodes)
        ins_b = [None] * max(len(self._comp_b._input_nodes) - K, 0)
        for (seg, local), x in zip(self._input_map, inputs):
            if seg == "a":
                ins_a[local] = x
            else:
                ins_b[local - K] = x  # locals 0..K-1 are the boundaries
        return ins_a, ins_b

    # -- steps ----------------------------------------------------------
    def train_step(self, params, opt_state, state, rng, inputs, labels):
        fwd_a, step_b, grad_a = self._programs()
        pa, pb = self._split(params)
        oa, ob = self._split_opt(opt_state)
        sa, sb = self._split(state, state=True)
        ins_a, ins_b = self._bind_inputs(inputs)
        rng_a, rng_b = jax.random.split(rng)

        boundaries = fwd_a(pa, sa, ins_a, rng_a)
        boundaries_b = self._boundaries_to_b(boundaries)
        new_pb, new_ob, new_sb, loss, m, db = step_b(
            pb, ob, sb, boundaries_b, ins_b, labels, rng_b)
        # each cotangent crosses back under its producer's own sharding
        db_a = self._cotangents_to_a(db)
        new_pa, new_oa, new_sa = grad_a(pa, oa, sa, ins_a, db_a, rng_a)
        return (
            {**new_pa, **new_pb},
            self._merge_opt(new_oa, new_ob),
            {**new_sa, **new_sb},
            loss,
            m,
        )

    def _eval_programs(self):
        """Jitted-and-cached per-mesh eval forwards — an eager apply()
        per batch would pay Python per-op dispatch with no XLA fusion."""
        if self._eval_fwd_a is None:
            comp_a, comp_b = self._comp_a, self._comp_b
            boundary_srcs = self._boundary_srcs

            @jax.jit
            def eval_fwd_a(pa, sa, ins):
                outs, _ = comp_a.apply_multi(
                    pa, sa, ins, None, train=False, outputs=boundary_srcs)
                return outs

            @jax.jit
            def eval_fwd_b(pb, sb, ins):
                logits, _ = comp_b.apply(pb, sb, ins, None, train=False)
                return logits

            self._eval_fwd_a, self._eval_fwd_b = eval_fwd_a, eval_fwd_b
        return self._eval_fwd_a, self._eval_fwd_b

    def eval_step(self, params, state, inputs, labels):
        eval_fwd_a, _ = self._eval_programs()
        pa, pb = self._split(params)
        sa, sb = self._split(state, state=True)
        ins_a, ins_b = self._bind_inputs(inputs)
        outs = eval_fwd_a(pa, sa, ins_a)
        boundaries_b = self._boundaries_to_b(outs)
        return self._comp_b.eval_step(
            pb, sb, list(boundaries_b) + ins_b, labels)

    def forward_fn(self):
        eval_fwd_a, eval_fwd_b = self._eval_programs()

        def fwd(params, state, inputs):
            pa, pb = self._split(dict(params))
            sa, sb = self._split(dict(state), state=True)
            ins_a, ins_b = self._bind_inputs(list(inputs))
            outs = eval_fwd_a(pa, sa, ins_a)
            boundaries_b = self._boundaries_to_b(outs)
            return eval_fwd_b(pb, sb, list(boundaries_b) + ins_b)

        return fwd

    def train_steps(self, *a, **k):
        raise NotImplementedError(
            "traced multi-step scans (trace_steps) are not supported with "
            "inter-op placement — the step is a multi-mesh composition")
