"""Serving-phase zoo: prefill + single-token decode variants of the
GPT configs (ROADMAP item 4 — "open the inference/serving workload").

A serving step has two phases with OPPOSITE cost shapes:

* **prefill** — the prompt's full forward pass: compute-bound causal
  attention over the whole prompt, exactly the training-side GPT graph
  minus the loss.  ``build_gpt_prefill`` reuses the causal encoder
  stack (models/transformer.py) so the strategy search prices it with
  everything it already knows (flash attention, ring/ulysses SP).
* **decode** — one token per live sequence per step: memory-bound
  streaming of the RAGGED paged KV cache.  ``build_gpt_decode`` builds
  the decode-frame graph whose attention ops are
  ``DecodeAttentionOp`` — explicit KV-cache state (page-pool indexed),
  ``page_table``/``seq_lens`` frame inputs, ragged paged attention
  kernel lowering.

The decode graph's batch dim is the frame's SEQUENCE-SLOT count
(``max_seqs``), fixed so the compiled program never re-specializes;
the continuous-batching executor (runtime/decode.py) composes ragged
requests into frames of this exact shape.
"""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel

# the canonical small decode config the executor tests lower and run
# on the CPU mesh: 2 layers deep enough to exercise cache state
# threading, small enough to compile in seconds
GPT_DECODE_KW = dict(vocab=2048, num_layers=2, hidden=256, num_heads=8,
                     ff_dim=512, page_size=16, pages_per_seq=16)

# the serving-regime decode config the serve bench + objective tests
# SEARCH (never lowered on the CPU mesh): long caches at modest width,
# the window where the ragged-KV stream dominates the step — per
# sequence 4096 cached tokens x 4 KB/token, 32-slot frames = 1 GB of
# pool per layer — so the batch-split max-shard imbalance the serve
# objective prices is the first-order term, while the weight stream
# (4 MB/layer of projections) is small enough that the train (mean
# step) objective still prefers the pure batch split.  This is the
# configuration where throughput and p99 provably part ways
# (BENCH_SEARCH.md "Inference serving").
GPT_DECODE_SERVE_KW = dict(vocab=4096, num_layers=2, hidden=512,
                           num_heads=8, ff_dim=1024, page_size=32,
                           pages_per_seq=128)
SERVE_FRAME_SLOTS = 32  # config.batch_size the serve sweep uses


def decode_layer(model, t, page_table, seq_lens, hidden, num_heads,
                 ff_dim, name, page_size, pages_per_seq, num_pages=0,
                 layer_norm=True):
    """One decode-step transformer layer: paged-cache attention +
    residual + LN + FFN (the decode twin of transformer.encoder_layer,
    which this must mirror so prefill/decode weights correspond
    layer-for-layer)."""
    a = model.decode_attention(
        t, page_table, seq_lens, embed_dim=hidden, num_heads=num_heads,
        page_size=page_size, pages_per_seq=pages_per_seq,
        num_pages=num_pages, name=f"{name}_mha",
    )
    t = model.add(a, t, name=f"{name}_res1")
    if layer_norm:
        t = model.layer_norm(t, name=f"{name}_ln1")
    f = model.dense(t, ff_dim, activation="relu", name=f"{name}_ff1")
    f = model.dense(f, hidden, name=f"{name}_ff2")
    t = model.add(f, t, name=f"{name}_res2")
    if layer_norm:
        t = model.layer_norm(t, name=f"{name}_ln2")
    return t


def build_gpt_decode(config: FFConfig, vocab: int = 2048,
                     num_layers: int = 2, hidden: int = 256,
                     num_heads: int = 8, ff_dim: int = 512,
                     page_size: int = 16, pages_per_seq: int = 16,
                     num_pages: int = 0):
    """The single-token decode-step graph: token ids [B, 1] -> next-token
    logits [B, 1, vocab], where B = config.batch_size is the decode
    frame's sequence-slot count (max concurrent sequences).

    Inputs, in binding order: ``token_ids`` [B, 1] i32, ``page_table``
    [B, pages_per_seq] i32, ``seq_lens`` [B] i32.  Every layer's
    attention reads/writes its OWN page-pool KV cache (model state);
    all layers share one page-table geometry, so one allocator serves
    the whole stack."""
    model = FFModel(config)
    b = config.batch_size
    ids = model.create_tensor([b, 1], dtype="int32", name="token_ids")
    page_table = model.create_tensor([b, pages_per_seq], dtype="int32",
                                     name="page_table")
    seq_lens = model.create_tensor([b], dtype="int32", name="seq_lens")
    t = model.embedding(ids, vocab, hidden, aggr="none", name="tok_embed")
    # learned positional embedding indexed by the token's position
    # (= seq_lens): the decode twin of build_gpt's positional table
    pos = model.reshape(seq_lens, [b, 1], name="pos_ids")
    p = model.embedding(pos, page_size * pages_per_seq, hidden,
                        aggr="none", name="pos_embed")
    t = model.add(t, p, name="embed_sum")
    for i in range(num_layers):
        t = decode_layer(
            model, t, page_table, seq_lens, hidden, num_heads, ff_dim,
            f"layer{i}", page_size=page_size, pages_per_seq=pages_per_seq,
            num_pages=num_pages, layer_norm=True,
        )
    t = model.layer_norm(t, name="final_ln")
    t = model.dense(t, vocab, use_bias=False, name="lm_head")
    return model


def build_gpt_prefill(config: FFConfig, vocab: int = 2048,
                      num_layers: int = 2, hidden: int = 256,
                      num_heads: int = 8, ff_dim: int = 512,
                      seq_len: int = 256):
    """The prompt-phase graph: the causal GPT forward at prompt length
    (compute-bound, seq-parallelizable — the training-side strategy
    machinery applies unchanged).  Searched under
    ``comp_mode="inference"`` it ranks by forward latency.  Cache
    POPULATION runs through the chunked-prefill lane
    (runtime/prefill.py): the prompt's causal forward once per chunk,
    K/V scattered straight into the page pool, token-identical to the
    prefill-via-decode fallback.  This graph is also what the
    DISAGGREGATION search places on its own submesh
    (search/disaggregation.py) — ``prefill_weight_bridge`` proves its
    parameter set corresponds weight-for-weight to the decode
    graph's."""
    from flexflow_tpu.models.transformer import build_gpt

    return build_gpt(config, vocab=vocab, num_layers=num_layers,
                     hidden=hidden, num_heads=num_heads, ff_dim=ff_dim,
                     seq_len=seq_len)


def derive_prefill_model(decode_graph, config, seq_len: int):
    """Build the prefill twin of an existing DECODE graph by reading
    the family widths off the graph itself (vocab/hidden from the
    token embedding, heads/embed from the decode ops, ff width from
    the FFN denses) — the disaggregation search derives the prompt
    graph it places from the deployment's own decode graph instead of
    trusting a caller to pass a matching one.  Returns ``(model,
    prefill_config)``; the prefill config prices one prompt at a time
    (batch 1 — the chunked lane's per-sequence pass), everything else
    inherited.  ``prefill_weight_bridge`` (runtime/prefill.py) then
    proves the two graphs share one parameter set."""
    import dataclasses

    from flexflow_tpu.core.optype import OperatorType
    from flexflow_tpu.runtime.prefill import prefill_io_nodes

    tok_guid, _, _ = prefill_io_nodes(decode_graph)
    dec_ops = [n.op for n in decode_graph.topo_order()
               if n.op.op_type == OperatorType.DECODE_ATTENTION]
    tok_embed = next(
        n.op for n in decode_graph.topo_order()
        if n.op.op_type == OperatorType.EMBEDDING
        and any(e.src == tok_guid
                for e in decode_graph.in_edges[n.guid]))
    vocab = tok_embed.attrs["num_entries"]
    hidden = tok_embed.attrs["out_dim"]
    first = dec_ops[0]
    num_heads = first.attrs["num_heads"]
    # ff1 is the dense that feeds another dense DIRECTLY (ff1 -> ff2);
    # out_dim sets can't disambiguate it — ff_dim may collide with
    # vocab or hidden
    ff_dim = hidden
    for n in decode_graph.topo_order():
        if n.op.op_type != OperatorType.LINEAR:
            continue
        feeds_dense = any(
            decode_graph.nodes[e.dst].op.op_type == OperatorType.LINEAR
            for e in decode_graph.out_edges[n.guid])
        if feeds_dense:
            ff_dim = n.op.attrs["out_dim"]
            break
    cfg = dataclasses.replace(config, batch_size=1)
    model = build_gpt_prefill(
        cfg, vocab=vocab, num_layers=len(dec_ops), hidden=hidden,
        num_heads=num_heads, ff_dim=ff_dim, seq_len=seq_len)
    return model, cfg
