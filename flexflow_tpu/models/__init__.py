"""Model zoo — TPU-native builds of every model family the reference
ships as examples (reference: examples/cpp/*, SURVEY.md §2.6)."""

from flexflow_tpu.models.alexnet import build_alexnet, build_alexnet_cifar10
from flexflow_tpu.models.resnet import build_resnet, build_resnext50
from flexflow_tpu.models.inception import build_inception_v3
from flexflow_tpu.models.transformer import (
    build_bert,
    build_gpt,
    build_gpt_xl,
    build_transformer,
)
from flexflow_tpu.models.decode import (
    GPT_DECODE_KW,
    GPT_DECODE_SERVE_KW,
    SERVE_FRAME_SLOTS,
    build_gpt_decode,
    build_gpt_prefill,
    derive_prefill_model,
)
from flexflow_tpu.models.dlrm import build_dlrm
from flexflow_tpu.models.xdl import build_xdl
from flexflow_tpu.models.candle_uno import build_candle_uno
from flexflow_tpu.models.moe import build_moe
from flexflow_tpu.models.mlp import build_mlp_unify
from flexflow_tpu.models.synthetic import build_moe_trunk, build_multibranch

__all__ = [
    "build_alexnet",
    "build_alexnet_cifar10",
    "build_resnet",
    "build_resnext50",
    "build_inception_v3",
    "build_transformer",
    "build_bert",
    "build_gpt",
    "build_gpt_decode",
    "build_gpt_prefill",
    "derive_prefill_model",
    "build_gpt_xl",
    "GPT_DECODE_KW",
    "GPT_DECODE_SERVE_KW",
    "SERVE_FRAME_SLOTS",
    "build_dlrm",
    "build_xdl",
    "build_candle_uno",
    "build_moe",
    "build_moe_trunk",
    "build_multibranch",
    "build_mlp_unify",
]
