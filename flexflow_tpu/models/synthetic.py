"""Synthetic non-chain PCG families — the shapes the series-parallel
decomposition exists for (ROADMAP item 4 / PR 12).

Every real zoo model past ``CHAIN_MIN_NODES`` is a stacked LLM whose
bottleneck chain the PR 7 decomposition cuts.  The families here are
deliberately **bottleneck-free at depth**: a GSPMD-style sparse/MoE
trunk whose persistent skip from the input bypasses every block
(PAPERS.md arXiv:2105.04663 — the sparse expert-model shape), and a
multi-tower multibranch graph (two-tower rankers, multimodal trunks).
Both scale linearly in their repeat count to 10k+ nodes, and both are
built from ISOMORPHIC repeats so the structural segment cache stamps
one solve across the family — the property ``bench_search.py
--sp-scale`` measures.
"""

from __future__ import annotations

from flexflow_tpu.config import FFConfig
from flexflow_tpu.model import FFModel


def build_moe_trunk(config: FFConfig, num_blocks: int = 32,
                    num_experts: int = 4, hidden: int = 64,
                    num_classes: int = 8):
    """A dense-mixture trunk with NO bottleneck chain: each block fans
    ``num_experts`` expert MLPs out of the running activation, merges
    them pairwise, and adds a fresh projection of the ORIGINAL input —
    the persistent skip keeps the graph's source on every frontier, so
    no interior node is on every source→sink path and
    ``Graph.bottlenecks()`` is (near-)empty at depth.  ~(3·experts + 3)
    nodes per block: ``num_blocks`` scales it to 10k+ nodes.  Blocks
    are isomorphic — one segment solve stamps the rest."""
    model = FFModel(config)
    b = config.batch_size
    x = model.create_tensor([b, hidden], name="features")
    t = x
    for blk in range(num_blocks):
        experts = []
        for e in range(num_experts):
            h = model.dense(t, hidden, activation="relu",
                            name=f"blk{blk}_e{e}_fc1")
            experts.append(model.dense(h, hidden,
                                       name=f"blk{blk}_e{e}_fc2"))
        mix = experts[0]
        for e, out in enumerate(experts[1:]):
            mix = model.add(mix, out, name=f"blk{blk}_mix{e}")
        # persistent skip: a per-block projection of the INPUT — x's
        # out-edges bypass every earlier block, killing the bottleneck
        # chain that would otherwise form at each block boundary
        skip = model.dense(x, hidden, name=f"blk{blk}_skip")
        t = model.add(mix, skip, name=f"blk{blk}_out")
        # per-block LN keeps a deep trunk numerically trainable (the
        # expert sum grows the activation scale multiplicatively with
        # depth otherwise) — and does not re-introduce a bottleneck:
        # x still bypasses it into every later block
        t = model.layer_norm(t, name=f"blk{blk}_ln")
    out = model.dense(t, num_classes, name="head")
    return model


def build_multibranch(config: FFConfig, num_branches: int = 4,
                      depth: int = 16, hidden: int = 64,
                      num_classes: int = 8):
    """``num_branches`` independent towers from one input, concatenated
    once at the very end — the two-tower/multimodal shape.  The only
    bottlenecks are the input and the final concat/head, so the chain
    rule finds nothing to cut; frontier cuts of width ~branches+1 do.
    ~(branches · depth) nodes: scale either knob."""
    model = FFModel(config)
    b = config.batch_size
    x = model.create_tensor([b, hidden], name="features")
    outs = []
    for br in range(num_branches):
        t = model.dense(x, hidden, activation="relu",
                        name=f"br{br}_fc0")
        for d in range(1, depth):
            t = model.dense(
                t, hidden,
                activation="relu" if d % 2 else None,
                name=f"br{br}_fc{d}")
        outs.append(t)
    t = model.concat(outs, axis=1, name="merge")
    out = model.dense(t, num_classes, name="head")
    return model
