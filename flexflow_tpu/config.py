"""Runtime configuration.

The TPU-native analogue of FFConfig (reference: include/flexflow/config.h:92-157,
src/runtime/model.cc:3371 parse_args): every knob of the training run,
the search, and the cost model, parseable from argv with the reference's
flag spellings so existing launch scripts translate directly.
"""

from __future__ import annotations

import argparse
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence, Tuple

from flexflow_tpu.core.machine import MachineSpec


def parse_slo_classes(value) -> Tuple[Dict, ...]:
    """Normalize the SLO-class table: the CLI spelling
    ``"name:priority:deadline_frames[:quantile[:weight]][,...]"`` or an
    iterable of dicts -> a tuple of ``{"name", "priority",
    "deadline_frames", "quantile", "weight"}`` dicts
    (runtime/decode.py ``SLOClass`` consumes them; the winning
    disaggregation/fleet persists them in ``__meta__``).  ``weight`` is
    the class's RELATIVE arrival rate (default 1 = classes arrive
    equally often) — the fleet search prices routing against it, so an
    interactive trickle and a batch flood are different placement
    questions."""
    if isinstance(value, str):
        classes = []
        for part in value.split(","):
            fields = part.split(":")
            if len(fields) not in (3, 4, 5):
                raise ValueError(
                    f"SLO class {part!r} must be "
                    f"name:priority:deadline_frames[:quantile[:weight]]")
            classes.append({
                "name": fields[0],
                "priority": int(fields[1]),
                "deadline_frames": int(fields[2]),
                "quantile": float(fields[3]) if len(fields) >= 4 else 0.99,
                "weight": float(fields[4]) if len(fields) == 5 else 1.0,
            })
        value = classes
    out = []
    seen = set()
    for c in value:
        c = {"name": str(c["name"]), "priority": int(c["priority"]),
             "deadline_frames": int(c.get("deadline_frames", 0)),
             "quantile": float(c.get("quantile", 0.99)),
             "weight": float(c.get("weight", 1.0))}
        if not c["name"] or c["name"] in seen:
            raise ValueError(
                f"SLO class names must be unique and non-empty "
                f"(got {c['name']!r})")
        if c["deadline_frames"] < 0 or not (0.0 < c["quantile"] < 1.0):
            raise ValueError(
                f"SLO class {c['name']!r}: deadline_frames must be >= 0 "
                f"and quantile in (0, 1)")
        if not c["weight"] > 0.0:
            raise ValueError(
                f"SLO class {c['name']!r}: weight must be > 0, got "
                f"{c['weight']}")
        seen.add(c["name"])
        out.append(c)
    return tuple(out)


def parse_slice_levels(value) -> Tuple[Tuple[int, float, float], ...]:
    """Normalize a slice-level hierarchy: the CLI spelling
    ``"span:bw:lat[,span:bw:lat...]"`` or an iterable of (span,
    bandwidth, latency) triples -> MachineSpec.slice_levels tuples.
    Structural validation (ascending aligned spans) stays in
    MachineSpec.topology_levels(), the one reader."""
    if isinstance(value, str):
        levels = []
        for part in value.split(","):
            fields = part.split(":")
            if len(fields) != 3:
                raise ValueError(
                    f"slice level {part!r} must be span:bandwidth:latency")
            levels.append(
                (int(fields[0]), float(fields[1]), float(fields[2])))
        return tuple(levels)
    return tuple(
        (int(span), float(bw), float(lat)) for span, bw, lat in value)


@dataclass
class IterationConfig:
    """Per-iteration knobs threaded into forward/backward
    (reference: config.h:159-164 FFIterationConfig.seq_length)."""

    seq_length: int = -1


@dataclass
class FFConfig:
    # training
    epochs: int = 1
    batch_size: int = 64
    learning_rate: float = 0.01
    weight_decay: float = 0.0001
    # machine
    num_devices: int = 0  # 0 = all visible jax devices
    machine_spec: Optional[MachineSpec] = None
    machine_model_file: Optional[str] = None
    slice_levels: Optional[object] = None  # multi-slice link hierarchy
    # above ICI (MachineSpec.slice_levels, PR 6) without writing a
    # machine file: a tuple of (span, bandwidth, latency) tuples, or
    # the CLI spelling "span:bw:lat[,span:bw:lat...]"
    # (--slice-levels).  Applied on top of whichever machine_spec /
    # machine_model_file resolves, the way --machine-model-file itself
    # layers over the default spec.
    # parallelization search (reference: config.h:116-157; the osdi22ae
    # scripts run with budgets 10-30)
    search_budget: int = 16
    search_alpha: float = 1.05
    only_data_parallel: bool = False
    enable_parameter_parallel: bool = True
    enable_attribute_parallel: bool = True
    enable_inplace_optimizations: bool = True
    search_num_devices: int = 0  # override devices for search (search a big
    # strategy on a small machine, reference: graph.cc:1535-1540)
    base_optimize_threshold: int = 10
    search_timeout_s: float = 45.0  # wall-clock bound on the joint
    # search; <=0 disables.  The reference bounds work via --budget
    # alone (substitution.cc:2007); a hard deadline guarantees compile
    # latency at any model scale
    enable_pipeline_search: bool = True  # compile's joint search also
    # costs pp in {2,4,8} pipelined candidates for stacked-block graphs
    # (search/pipeline_search.py) and lowers a winner automatically —
    # the capability the reference stubs as OP_PIPELINE (ffconst.h:148)
    enable_placement_search: bool = True  # compile also costs 2-block
    # inter-op placed candidates (search/placement_search.py) and lowers
    # a margin-beating winner via the placed executor — the reference's
    # VERTICAL resource splits + mapper placement (graph.cc:161-295,
    # mapper.cc:371-475)
    placement_search_max_nodes: int = 80  # placement cut enumeration is
    # quadratic-ish in graph size; larger graphs skip the pass
    search_improvement_margin: float = 0.03  # a searched strategy is
    # accepted only when its simulated win over plain data parallelism
    # exceeds this fraction — the simulator has finite fidelity, and a
    # sub-margin "win" is noise that execution routinely loses to GSPMD
    # resharding (measured: a 1.4% predicted BERT win executed 7-12%
    # SLOWER than DP on the 8-device host mesh).  Within the margin the
    # search returns uniform DP, whose lowering has zero resharding
    # boundaries.
    substitution_json: Optional[str] = None
    calibration_file: Optional[str] = None  # persisted measured
    # per-(op, view) costs (search/calibration.py); the search loads it
    # when present (reference: ProfilingRecord, simulator.cc:515-554)
    calibrate: bool = False  # probe this graph's (op, view) costs on
    # the live backend at compile time and rank with them — the
    # reference's default behavior (it measures lazily mid-search,
    # simulator.cc:515; model.cu:38-74).  Off by default here because
    # probing costs real wall time per compile; combined with
    # calibration_file the probes persist and later compiles are free
    calibration_budget_s: float = 60.0  # wall bound on compile-time probes
    export_strategy_file: Optional[str] = None
    import_strategy_file: Optional[str] = None
    import_strategy_partial: bool = False  # best-effort strategy import
    # (--import-strategy-partial): downgrade the provenance checks
    # (digest/coverage, STR2xx) to warnings and apply the views whose op
    # names match — the historical behavior, now an explicit opt-in
    export_strategy_computation_graph_file: Optional[str] = None
    export_strategy_task_graph_file: Optional[str] = None  # simulated
    # schedule dot export (reference: config.h:142, simulator.cc:1008)
    objective: str = "train"  # "train" | "serve" — what the strategy
    # search optimizes.  "train" (default) ranks by mean step time
    # (throughput), bit-identical to history.  "serve" ranks a DECODE
    # graph (models/decode.py, ops/decode_attention.py) by simulated
    # p99 decode-step latency over a ragged-batch arrival model
    # (search/serving.py): batch splits pay the max-shard imbalance of
    # ragged KV loads, head splits (decode TP) don't — a different
    # Pareto point than throughput.  Per-device KV residency at full
    # page-pool occupancy enters the memory check either way, so
    # HBM-infeasible strategies are rejected during search, not at OOM.
    serve_p99_budget_ms: float = 0.0  # declared p99 SLO for the serve
    # objective (--serve-p99-budget-ms): recorded in __meta__.serving
    # and linted (SHD163 warns when the predicted p99 exceeds it);
    # 0 = no declared budget (rank-only)
    serve_disaggregation: str = "off"  # "off" | "search" — under
    # objective="serve", compile() additionally searches a
    # PREFILL/DECODE DISAGGREGATION (search/disaggregation.py): the
    # prompt graph and the decode graph placed on disjoint submeshes as
    # a two-block placement, the KV-page handoff priced as a
    # cross-block transfer and the serve load split per phase
    # (prefill = compute-bound arrivals, decode = p99 token load); a
    # margin-beating winner is lint-gated (SHD164/165) and persists as
    # __meta__.disaggregation (fflint STR211).  "off" (default) is
    # byte-identical to history.
    prefill_chunk: int = 32  # chunk size of the batched prefill lane
    # (runtime/prefill.py, --prefill-chunk): the prompt's causal
    # forward runs once per this many tokens and scatters K/V straight
    # into the page pool, instead of one decode frame per prompt token;
    # recorded in __meta__.disaggregation.  Must be >= 1.
    serve_prompt_tokens_mean: int = 0  # phase-split arrival model
    # (ServingSpec.prefill_tokens_per_frame): mean prompt length of the
    # arrival stream; 0 derives max_seq_len // 2
    serve_decode_tokens_mean: int = 0  # mean generated tokens per
    # request (slot turnover rate); 0 derives max_seq_len // 4
    serve_fleet: str = "off"  # "off" | "search" — under
    # objective="serve", compile() additionally searches a SERVING
    # FLEET (search/fleet.py): N replica blocks on disjoint submeshes,
    # each with its own full rewriting search at its width (and its own
    # intra-replica prefill/decode split), priced together with
    # per-SLO-class routing fractions in the per-class p99 currency; a
    # margin-beating fleet is lint-gated (SHD166/167) and persists as
    # __meta__.fleet (fflint STR212).  "off" (default) is byte-identical
    # to history.
    serve_fleet_max_replicas: int = 4  # fleet search bound
    # (--serve-fleet-max-replicas): the partition enumeration caps at
    # this many replica blocks.  Must be >= 1.
    serve_fleet_offered_load: float = 0.85  # steady-state offered load
    # of the whole deployment, in frames (1.0 = the arrival stream
    # exactly fills one full decode frame per frame time): sets the
    # queueing utilization the per-class p99 pricing charges each
    # replica.  The controller's elastic re-search scales it by the
    # measured/predicted drift ratio (observe_fleet).
    serve_slo_classes: Optional[object] = None  # request SLO classes
    # (--serve-slo-classes "name:priority:deadline_frames[:quantile],
    # ..."): priority admission / deadline expiry / preemption on the
    # executor's page allocator (runtime/decode.py SLOClass), per-class
    # p99 windows, persisted with the disaggregation meta
    kv_precision: str = "off"  # KV page-pool dtype lane
    # (ops/decode_attention.py kv_dtype, --kv-precision): "off"
    # (default) never touches the lane — cost-cache keys, signatures
    # and the lowered program stay byte-identical to history.  "fp32"/
    # "bf16"/"int8" pin the pool dtype (int8 adds per-(page, slot)
    # fp32 scales, dequant inside the ragged paged-attention kernel's
    # page loop); "search" makes the dtype a searched lane under
    # objective="serve" — each candidate dtype is priced through the
    # decode op's cache-stream + quantize-overhead terms (the same
    # EQuARX discipline as sync_precision) and the winner persists as
    # __meta__.kv behind the digest gate (SHD168/169 lint-gated,
    # fflint STR213).
    serve_shared_prefix_pages: int = 0  # radix prefix sharing
    # (runtime/decode.py PageAllocator, --serve-shared-prefix-pages):
    # declared number of page-pool pages per sequence expected to be
    # CLAIMED from the shared prefix trie rather than privately
    # allocated (e.g. a fleet-wide system prompt of N*page_size
    # tokens).  Enters ServingSpec.shared_residency_factor so SHD161
    # HBM residency and kv_residency_bytes price SHARED residency —
    # the search sees the multiplied effective batch.  0 (default) =
    # no sharing assumed, bit-identical to history.  Must be
    # < pages_per_seq of the decode graph (linted, SHD168).
    comp_mode: str = "training"  # "training" | "inference" — set by
    # compile(comp_mode=...); inference searches rank strategies by
    # forward latency with no weight sync (reference:
    # COMP_MODE_INFERENCE, config.h:47-50) and fit() refuses to run
    # numerics
    compute_dtype: str = "bfloat16"  # matmul dtype on TPU
    param_dtype: str = "float32"
    # execution
    profiling: bool = False
    perform_fusion: bool = True
    grad_accum_steps: int = 1  # >1: each optimizer step processes the
    # batch as this many microbatches inside a lax.scan, averaging
    # grads — full effective batch at batch/N activation memory
    # (reference has no analogue; with remat, the second memory lever)
    trace_steps: int = 1  # >1: fit() runs this many optimizer steps per
    # compiled call (lax.scan over stacked batches) — the XLA-native
    # analogue of the reference's Legion iteration tracing
    # (flexflow_cffi.py:1867-1874), amortizing per-step dispatch
    remat: bool = False  # rematerialize activations in backward
    # (jax.checkpoint) — trades FLOPs for HBM; the reference has no
    # equivalent (Legion keeps all activations resident)
    sync_precision: str = "fp32"  # gradient-sync wire precision
    # (comm/quantized.py, EQuARX arXiv:2506.17615): "fp32" keeps the
    # historical bit-exact psum; "bf16"/"int8" request compressed
    # collectives for every weight group the gradient-safety heuristic
    # admits (search/sync_precision.py); "search" makes the precision a
    # PER-WEIGHT-GROUP dimension of the strategy search — the cost
    # model prices each group's sync at its cheapest admissible
    # precision (wire bytes shrink, quantize overhead added) and the
    # chosen map is executed by the lowering's _sync_grads
    sync_schedule: str = "off"  # gradient-sync SCHEDULE
    # (search/sync_schedule.py): "search" partitions the synced weight
    # groups into issue-ordered buckets (reverse-topological, coalesced
    # to amortize collective latency, per-bucket precision composing
    # with sync_precision), priced with the simulator's exposed-comm
    # semantics and executed by comm/bucketed.py — adopted only when it
    # beats the monolithic post-backward sync.  "off" (default) keeps
    # the historical single post-backward sync (fp32 bit-exact).
    sync_bucket_bytes: int = 0  # pin the schedule search's coalescing
    # floor (fused fp32 payload bytes per bucket); 0 sweeps the
    # DEFAULT_BUCKET_BYTES thresholds plus adaptive fractions of the
    # model's total sync bytes
    sync_ef: str = "off"  # error-feedback residuals on int8 gradient
    # sync (comm.quantized_allreduce_ef, EF-SGD): "auto" upgrades every
    # int8 group the precision search picks to "int8_ef" — each device
    # re-injects its local quantization error next step, carried as
    # persistent training-loop state (the lowering threads the residual
    # through the model-state dict), so compression error stops
    # accumulating across steps.  The residual add's (real, small) HBM
    # overhead is priced into the choice; the fidelity win is the
    # point — the cost currency cannot see it, so this is a policy
    # gate, not a cost comparison.  "off" (default) keeps the plain
    # int8 wire bit-identical to history.  Deliberately independent of
    # co_search: EF shifts the pricing currency (its overhead is
    # priced), so folding it into the joint-vs-sequential comparison
    # would conflate two effects.
    co_search: bool = False  # joint strategy x comm-plan co-search
    # (search/comm_plan.py): candidate strategies inside
    # optimize_strategy — substitution proposals, DP re-validations,
    # chain-segment solves — are priced with their BEST comm plan
    # (sync schedule + per-group wire precision + staged reduction
    # plans + per-group optimizer-state sharding) through the
    # simulator's exposed-comm semantics, instead of choosing the
    # strategy first under the legacy per-node overlap credit and
    # fitting the comm plan afterwards.  A comm-plan memo keyed by the
    # strategy's synced-group signature keeps the inner loop cheap
    # (most substitutions do not change the synced-group set, so the
    # plan is served, not re-searched).  Enabling this auto-enables
    # sync_schedule="search".  False (the default) keeps the
    # sequential strategy→plan pipeline bit-identical to history.
    # observability (flexflow_tpu/obs): unified telemetry
    obs_log_file: Optional[str] = None  # JSONL structured-event sink
    # (search-decision tracing, strategy tables, drift reports); also
    # enabled process-wide via FLEXFLOW_TPU_OBS=<path>.  None (the
    # default) keeps every emit to a single boolean check — near-zero
    # overhead off.
    obs_trace_file: Optional[str] = None  # compile() writes the
    # PREDICTED task timeline here as Chrome-trace JSON (Perfetto-
    # loadable), the artifact to view next to the real device_trace
    device_trace_dir: Optional[str] = None  # fit() captures a REAL
    # jax.profiler device trace of the post-compile steps into this
    # logdir, with the lowered step's sync buckets bracketed by
    # stable-lane-id markers (obs/annotate.py) and host phases
    # annotated; after the run the capture is ingested and tag-matched
    # against the predicted lanes (obs/trace_ingest.py) into
    # model.lane_drift_report, filling the per-bucket DriftReport
    # measured fields.  None (default): no capture, no markers — the
    # lowered program is byte-identical to history.
    drift_threshold: float = 0.5  # |measured/predicted - 1| above which
    # the DriftReport flags the prediction stale (and, when a measured
    # calibration table was consulted, the TABLE as stale)
    cost_cache_file: Optional[str] = None  # persistent cost cache
    # (search/cost_cache.py): per-(op, view) cost rows + search results
    # keyed by node digest x machine view x calibration signature,
    # invalidated wholesale when the signature moves.  None falls back
    # to $FLEXFLOW_TPU_COST_CACHE (path; "0"/empty disables); empty
    # string "" disables outright (--no-cost-cache)
    verify: bool = False  # static-analysis verification
    # (flexflow_tpu/analysis, --verify, env FLEXFLOW_TPU_VERIFY=1):
    # run the graph-invariant checker after EVERY GraphXfer.apply and
    # check the compile-time graph before lowering.  The strategy/
    # sharding legality lint in optimize_strategy is always on; this
    # flag adds the per-rewrite structural proof (bench_search.py
    # --verify measures its overhead).
    zero_dp_shard: bool = False  # ZeRO-1 / weight-update sharding
    # (arXiv:2004.13336): shard optimizer state (and the update
    # compute) of replicated weights over the mesh axes they are
    # replicated on.  Grad psum becomes reduce-scatter + all-gather of
    # the update (same ring bytes), optimizer memory and update FLOPs
    # drop by the replication factor.  Beyond the reference (its PS
    # mode reduces on ONE owner device, optimizer.cc:90-155 — this
    # spreads the update over all of them)
    seed: int = 0
    iteration: IterationConfig = field(default_factory=IterationConfig)

    def __post_init__(self):
        if self.sync_precision not in ("fp32", "bf16", "int8", "search"):
            raise ValueError(
                f"sync_precision must be fp32|bf16|int8|search, got "
                f"{self.sync_precision!r}"
            )
        if self.sync_schedule not in ("off", "search"):
            raise ValueError(
                f"sync_schedule must be off|search, got "
                f"{self.sync_schedule!r}"
            )
        if self.sync_ef not in ("off", "auto"):
            raise ValueError(
                f"sync_ef must be off|auto, got {self.sync_ef!r}"
            )
        if self.objective not in ("train", "serve"):
            raise ValueError(
                f"objective must be train|serve, got {self.objective!r}"
            )
        if self.serve_disaggregation not in ("off", "search"):
            raise ValueError(
                f"serve_disaggregation must be off|search, got "
                f"{self.serve_disaggregation!r}"
            )
        if self.prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {self.prefill_chunk}"
            )
        if self.serve_fleet not in ("off", "search"):
            raise ValueError(
                f"serve_fleet must be off|search, got "
                f"{self.serve_fleet!r}"
            )
        if self.serve_fleet_max_replicas < 1:
            raise ValueError(
                f"serve_fleet_max_replicas must be >= 1, got "
                f"{self.serve_fleet_max_replicas}"
            )
        if not (0.0 < self.serve_fleet_offered_load <= 4.0):
            raise ValueError(
                f"serve_fleet_offered_load must be in (0, 4], got "
                f"{self.serve_fleet_offered_load}"
            )
        if self.serve_slo_classes is not None:
            self.serve_slo_classes = parse_slo_classes(
                self.serve_slo_classes)
        if self.kv_precision not in ("off", "fp32", "bf16", "int8",
                                     "search"):
            raise ValueError(
                f"kv_precision must be off|fp32|bf16|int8|search, got "
                f"{self.kv_precision!r}"
            )
        if self.serve_shared_prefix_pages < 0:
            raise ValueError(
                f"serve_shared_prefix_pages must be >= 0, got "
                f"{self.serve_shared_prefix_pages}"
            )
        if self.objective == "serve" and self.co_search:
            # the joint pricer's exposed-comm currency is a TRAINING
            # currency (weight-grad sync plans); mixing it with the
            # serve p99 currency would price plans a decode step never
            # executes — refuse instead of silently conflating
            raise ValueError(
                "objective='serve' does not compose with co_search "
                "(the joint comm-plan currency prices gradient sync, "
                "which a decode step does not run)"
            )
        if self.co_search and self.sync_schedule == "off":
            # the joint pricing currency IS the exposed-comm scheduled
            # sync — co-search without the schedule dimension would
            # price candidates with plans the lowering never executes
            self.sync_schedule = "search"
        if self.num_devices == 0:
            try:
                import jax

                self.num_devices = len(jax.devices())
            except Exception:
                self.num_devices = 1
        if self.machine_spec is None:
            if self.machine_model_file:
                self.machine_spec = MachineSpec.from_file(self.machine_model_file)
            else:
                self.machine_spec = MachineSpec.tpu_v5e(self.num_devices)
        if self.slice_levels:
            import dataclasses as _dc

            levels = parse_slice_levels(self.slice_levels)
            self.machine_spec = _dc.replace(
                self.machine_spec, slice_levels=levels)
            # fail at construction, not mid-search: topology_levels()
            # validates the aligned-nesting rules
            self.machine_spec.topology_levels()
            self.slice_levels = levels

    @property
    def search_devices(self) -> int:
        return self.search_num_devices or self.num_devices

    # ---- argv parsing ----------------------------------------------------
    @staticmethod
    def parse_args(argv: Optional[Sequence[str]] = None) -> "FFConfig":
        """Accepts the reference's flag spellings
        (reference: model.cc:3371-3654, README.md:79-102)."""
        p = argparse.ArgumentParser(add_help=False, allow_abbrev=False)
        p.add_argument("-e", "--epochs", type=int, default=1)
        p.add_argument("-b", "--batch-size", type=int, default=64)
        p.add_argument("--lr", "--learning-rate", dest="lr", type=float, default=0.01)
        p.add_argument("--wd", "--weight-decay", dest="wd", type=float, default=1e-4)
        p.add_argument("-ll:tpu", "--num-devices", dest="num_devices", type=int, default=0)
        p.add_argument("--budget", "--search-budget", dest="budget", type=int, default=128)
        p.add_argument("--alpha", "--search-alpha", dest="alpha", type=float, default=1.05)
        p.add_argument("--only-data-parallel", action="store_true")
        p.add_argument("--enable-parameter-parallel", action="store_true", default=True)
        p.add_argument("--enable-attribute-parallel", action="store_true", default=True)
        p.add_argument("--search-num-nodes", type=int, default=0)
        p.add_argument("--search-num-workers", type=int, default=0)
        p.add_argument("--base-optimize-threshold", type=int, default=10)
        p.add_argument("--search-timeout", dest="search_timeout", type=float, default=45.0)
        p.add_argument("--search-improvement-margin",
                       dest="search_improvement_margin", type=float,
                       default=0.03,
                       help="minimum simulated win over plain DP before a "
                            "searched strategy is accepted (champion-vs-DP "
                            "floor)")
        p.add_argument("--disable-pipeline-search",
                       dest="disable_pipeline_search", action="store_true",
                       help="compile() stops proposing pipelined lowerings "
                            "for stacked-block graphs")
        p.add_argument("--substitution-json", type=str, default=None)
        p.add_argument("--calibration-file", type=str, default=None)
        p.add_argument("--calibrate", action="store_true")
        p.add_argument("--calibration-budget", dest="calibration_budget",
                       type=float, default=60.0)
        p.add_argument("--export-strategy", dest="export_strategy", type=str, default=None)
        p.add_argument("--import-strategy", dest="import_strategy", type=str, default=None)
        p.add_argument("--import-strategy-partial",
                       dest="import_strategy_partial", action="store_true",
                       help="apply a strategy file best-effort even when "
                            "its graph digest/coverage does not match "
                            "(provenance checks downgrade to warnings)")
        p.add_argument("--machine-model-file", type=str, default=None)
        p.add_argument("--slice-levels", dest="slice_levels", type=str,
                       default=None,
                       help="multi-slice link hierarchy above ICI "
                            "without a machine file: comma list of "
                            "span:bandwidth:latency triples, e.g. "
                            "'16:3.1e9:1e-5' for one DCN class "
                            "spanning 16 devices (MachineSpec."
                            "slice_levels)")
        p.add_argument("--taskgraph", dest="export_taskgraph", type=str, default=None)
        p.add_argument("--profiling", action="store_true")
        p.add_argument("--trace-steps", dest="trace_steps", type=int, default=1)
        p.add_argument("--grad-accum-steps", dest="grad_accum_steps",
                       type=int, default=1)
        p.add_argument("--remat", action="store_true")
        p.add_argument("--zero-dp-shard", dest="zero_dp_shard",
                       action="store_true")
        p.add_argument("--sync-precision", dest="sync_precision",
                       choices=("fp32", "bf16", "int8", "search"),
                       default="fp32",
                       help="gradient-sync wire precision; 'search' "
                            "lets the strategy search pick it per "
                            "weight group")
        p.add_argument("--sync-schedule", dest="sync_schedule",
                       choices=("off", "search"), default="off",
                       help="gradient-sync schedule: 'search' buckets "
                            "the weight-grad collectives and issues "
                            "them inside the backward "
                            "(search/sync_schedule.py)")
        p.add_argument("--sync-bucket-bytes", dest="sync_bucket_bytes",
                       type=int, default=0,
                       help="pin the schedule search's per-bucket "
                            "coalescing floor in bytes (0 = sweep)")
        p.add_argument("--co-search", dest="co_search",
                       action="store_true",
                       help="joint strategy x comm-plan co-search: "
                            "price every candidate strategy with its "
                            "best sync schedule/precision/reduction "
                            "plan inside the substitution search "
                            "(search/comm_plan.py)")
        p.add_argument("--sync-ef", dest="sync_ef",
                       choices=("off", "auto"), default="off",
                       help="error-feedback residuals on int8 gradient "
                            "sync (per-group int8_ef wire choice, "
                            "residual threaded as training-loop state)")
        p.add_argument("--objective", dest="objective",
                       choices=("train", "serve"), default="train",
                       help="search objective: 'serve' ranks decode "
                            "graphs by simulated p99 latency over a "
                            "ragged arrival model under the HBM "
                            "KV-residency budget (search/serving.py)")
        p.add_argument("--serve-p99-budget-ms",
                       dest="serve_p99_budget_ms", type=float,
                       default=0.0,
                       help="declared p99 SLO for objective=serve "
                            "(recorded in __meta__.serving, linted "
                            "SHD163); 0 = rank-only")
        p.add_argument("--serve-disaggregation",
                       dest="serve_disaggregation",
                       choices=("off", "search"), default="off",
                       help="under objective=serve, also search a "
                            "prefill/decode disaggregation: prompt and "
                            "decode graphs on disjoint submeshes, the "
                            "KV handoff priced as a cross-block "
                            "transfer (search/disaggregation.py)")
        p.add_argument("--prefill-chunk", dest="prefill_chunk",
                       type=int, default=32,
                       help="chunk size of the batched prefill lane "
                            "(runtime/prefill.py): prompt tokens "
                            "written into the KV page pool per causal "
                            "forward pass")
        p.add_argument("--serve-fleet", dest="serve_fleet",
                       choices=("off", "search"), default="off",
                       help="under objective=serve, also search a "
                            "serving FLEET: N replica blocks on "
                            "disjoint submeshes, per-replica strategy "
                            "and per-SLO-class routing priced together "
                            "in per-class p99 (search/fleet.py)")
        p.add_argument("--serve-fleet-max-replicas",
                       dest="serve_fleet_max_replicas", type=int,
                       default=4,
                       help="upper bound on fleet replica count the "
                            "partition enumeration explores")
        p.add_argument("--serve-slo-classes", dest="serve_slo_classes",
                       type=str, default=None,
                       help="request SLO classes for the serving "
                            "executor: comma list of name:priority:"
                            "deadline_frames[:quantile] — priority "
                            "admission, deadline expiry, preemption "
                            "(runtime/decode.py)")
        p.add_argument("--kv-precision", dest="kv_precision",
                       choices=("off", "fp32", "bf16", "int8", "search"),
                       default="off",
                       help="KV page-pool dtype lane (ops/"
                            "decode_attention.py): pin fp32/bf16/int8 "
                            "(int8 adds per-page scales + in-kernel "
                            "dequant) or 'search' to price the lane "
                            "under objective=serve; 'off' is "
                            "byte-identical to history")
        p.add_argument("--serve-shared-prefix-pages",
                       dest="serve_shared_prefix_pages", type=int,
                       default=0,
                       help="pages per sequence expected to be CLAIMED "
                            "from the radix prefix trie instead of "
                            "privately allocated (runtime/decode.py) — "
                            "prices SHARED KV residency in SHD161 and "
                            "kv_residency_bytes")
        p.add_argument("--obs-log", dest="obs_log", type=str, default=None,
                       help="JSONL structured-event telemetry sink "
                            "(flexflow_tpu/obs; tools/ffobs.py renders it)")
        p.add_argument("--obs-trace", dest="obs_trace", type=str,
                       default=None,
                       help="write the PREDICTED task timeline as "
                            "Chrome-trace JSON at compile (Perfetto)")
        p.add_argument("--device-trace-dir", dest="device_trace_dir",
                       type=str, default=None,
                       help="capture a REAL jax.profiler device trace "
                            "of fit's post-compile steps into this "
                            "logdir, lane-stamped and tag-matched "
                            "against the predicted comm lanes "
                            "(obs/trace_ingest.py LaneDriftReport)")
        p.add_argument("--drift-threshold", dest="drift_threshold",
                       type=float, default=0.5,
                       help="predicted-vs-measured step-time drift "
                            "beyond which the DriftReport flags "
                            "calibration staleness")
        p.add_argument("--cost-cache-file", dest="cost_cache_file",
                       type=str, default=None,
                       help="persistent per-(op, view) cost-row + "
                            "search-result cache (search/cost_cache.py); "
                            "repeated searches start warm")
        p.add_argument("--no-cost-cache", dest="no_cost_cache",
                       action="store_true",
                       help="bypass the persistent cost cache even when "
                            "a file/env default is configured")
        p.add_argument("--verify", action="store_true",
                       help="static-analysis verification "
                            "(flexflow_tpu/analysis): check graph "
                            "invariants after every rewrite and the "
                            "compile-time graph before lowering")
        p.add_argument("--seed", type=int, default=0)
        args, _ = p.parse_known_args(argv)
        search_devs = args.search_num_workers * max(1, args.search_num_nodes or 1)
        return FFConfig(
            epochs=args.epochs,
            batch_size=args.batch_size,
            learning_rate=args.lr,
            weight_decay=args.wd,
            num_devices=args.num_devices,
            search_budget=args.budget,
            search_alpha=args.alpha,
            only_data_parallel=args.only_data_parallel,
            search_num_devices=search_devs,
            base_optimize_threshold=args.base_optimize_threshold,
            search_timeout_s=args.search_timeout,
            search_improvement_margin=args.search_improvement_margin,
            enable_pipeline_search=not args.disable_pipeline_search,
            substitution_json=args.substitution_json,
            calibration_file=args.calibration_file,
            calibrate=args.calibrate,
            calibration_budget_s=args.calibration_budget,
            export_strategy_file=args.export_strategy,
            import_strategy_file=args.import_strategy,
            import_strategy_partial=args.import_strategy_partial,
            export_strategy_task_graph_file=args.export_taskgraph,
            machine_model_file=args.machine_model_file,
            slice_levels=args.slice_levels,
            profiling=args.profiling,
            trace_steps=args.trace_steps,
            grad_accum_steps=args.grad_accum_steps,
            remat=args.remat,
            zero_dp_shard=args.zero_dp_shard,
            sync_precision=args.sync_precision,
            sync_schedule=args.sync_schedule,
            sync_bucket_bytes=args.sync_bucket_bytes,
            co_search=args.co_search,
            sync_ef=args.sync_ef,
            objective=args.objective,
            serve_p99_budget_ms=args.serve_p99_budget_ms,
            serve_disaggregation=args.serve_disaggregation,
            serve_fleet=args.serve_fleet,
            serve_fleet_max_replicas=args.serve_fleet_max_replicas,
            prefill_chunk=args.prefill_chunk,
            serve_slo_classes=args.serve_slo_classes,
            kv_precision=args.kv_precision,
            serve_shared_prefix_pages=args.serve_shared_prefix_pages,
            obs_log_file=args.obs_log,
            obs_trace_file=args.obs_trace,
            device_trace_dir=args.device_trace_dir,
            drift_threshold=args.drift_threshold,
            cost_cache_file="" if args.no_cost_cache else args.cost_cache_file,
            verify=args.verify,
            seed=args.seed,
        )
