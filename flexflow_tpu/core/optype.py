"""Operator type enumeration.

Covers the operator vocabulary of the reference framework
(reference: include/flexflow/ffconst.h:61-150) plus TPU-native additions
(ring attention, pipeline stages) that the reference declared but never
implemented or lacked entirely.
"""

from __future__ import annotations

import enum


class OperatorType(enum.Enum):
    # ---- sentinels -------------------------------------------------------
    NOOP = "noop"
    INPUT = "input"
    WEIGHT = "weight"
    CONSTANT = "constant"

    # ---- dense compute ops ----------------------------------------------
    CONV2D = "conv2d"
    POOL2D = "pool2d"
    BATCHNORM = "batchnorm"
    LINEAR = "linear"
    EMBEDDING = "embedding"
    MULTIHEAD_ATTENTION = "multihead_attention"
    # TPU-native serving addition: single-token decode attention over a
    # paged KV cache (ops/decode_attention.py; no reference equivalent —
    # the reference has no inference path at all)
    DECODE_ATTENTION = "decode_attention"
    BATCH_MATMUL = "batch_matmul"
    DROPOUT = "dropout"
    SOFTMAX = "softmax"
    LAYERNORM = "layernorm"
    CONCAT = "concat"
    SPLIT = "split"
    FLAT = "flat"
    RESHAPE = "reshape"
    TRANSPOSE = "transpose"
    REVERSE = "reverse"
    CAST = "cast"
    TOPK = "topk"
    MEAN = "mean"
    GATHER = "gather"
    STACK = "stack"      # TPU-native: batched-branch fusion feeds
    UNSTACK = "unstack"  # (see ops/shape_ops.py StackOp/UnstackOp)
    BATCHED_EMBEDDING = "batched_embedding"

    # elementwise binary (reference: src/ops/element_binary.cc)
    EW_ADD = "ew_add"
    EW_SUB = "ew_sub"
    EW_MUL = "ew_mul"
    EW_DIV = "ew_div"
    EW_MAX = "ew_max"
    EW_MIN = "ew_min"

    # elementwise unary (reference: src/ops/element_unary.cc)
    RELU = "relu"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    ELU = "elu"
    GELU = "gelu"
    EXP = "exp"
    LOG = "log"
    IDENTITY = "identity"
    RSQRT = "rsqrt"
    POW = "pow"
    SCALAR_ADD = "scalar_add"
    SCALAR_SUB = "scalar_sub"
    SCALAR_MUL = "scalar_mul"
    SCALAR_TRUE_DIV = "scalar_true_div"

    # ---- MoE ops (reference: src/ops/{group_by,aggregate,aggregate_spec,cache}.cc)
    GROUP_BY = "group_by"
    AGGREGATE = "aggregate"
    AGGREGATE_SPEC = "aggregate_spec"
    CACHE = "cache"

    # ---- fused -----------------------------------------------------------
    FUSED = "fused"

    # ---- parallel ops (reference: src/parallel_ops/*, ffconst.h:143-149) --
    REPARTITION = "repartition"
    COMBINE = "combine"
    REPLICATE = "replicate"
    REDUCTION = "reduction"
    FUSED_PARALLEL = "fused_parallel"
    PIPELINE = "pipeline"  # declared-only in the reference; real here
    # TPU-native additions (no reference equivalent; SURVEY.md §5 gap list)
    ALL_TO_ALL = "all_to_all"  # Ulysses-style seq<->head re-shard
    RING_EXCHANGE = "ring_exchange"  # ring attention ppermute stage

    # ---- loss / metrics pseudo-ops --------------------------------------
    LOSS = "loss"
    METRICS = "metrics"

    def is_parallel_op(self) -> bool:
        return self in _PARALLEL_OPS

    def is_elementwise_unary(self) -> bool:
        return self in _EW_UNARY

    def is_elementwise_binary(self) -> bool:
        return self in _EW_BINARY


_PARALLEL_OPS = {
    OperatorType.REPARTITION,
    OperatorType.COMBINE,
    OperatorType.REPLICATE,
    OperatorType.REDUCTION,
    OperatorType.FUSED_PARALLEL,
    OperatorType.PIPELINE,
    OperatorType.ALL_TO_ALL,
    OperatorType.RING_EXCHANGE,
}

_EW_UNARY = {
    OperatorType.RELU,
    OperatorType.SIGMOID,
    OperatorType.TANH,
    OperatorType.ELU,
    OperatorType.GELU,
    OperatorType.EXP,
    OperatorType.LOG,
    OperatorType.IDENTITY,
    OperatorType.RSQRT,
    OperatorType.POW,
    OperatorType.SCALAR_ADD,
    OperatorType.SCALAR_SUB,
    OperatorType.SCALAR_MUL,
    OperatorType.SCALAR_TRUE_DIV,
}

_EW_BINARY = {
    OperatorType.EW_ADD,
    OperatorType.EW_SUB,
    OperatorType.EW_MUL,
    OperatorType.EW_DIV,
    OperatorType.EW_MAX,
    OperatorType.EW_MIN,
}
