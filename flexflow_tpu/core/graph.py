"""The Parallel Computation Graph (PCG).

A DAG of operator nodes connected by tensor edges — the IR that the
auto-parallelization search rewrites and costs.  Re-implements the
capabilities of the reference's PCG (reference: src/runtime/graph.cc:299-362,
include/flexflow/graph.h:240, dominators.h) in pure Python with no
runtime coupling: nodes hold immutable operator descriptors, and
parallelization strategies live *outside* the graph as
``{node_guid: MachineView}`` maps, so one graph can be costed under
many strategies without copying.

Provides the graph algorithms the search needs: topological order,
dominators/post-dominators, bottleneck (articulation) node finding
(reference: graph.cc:580), sequence/horizontal splits
(reference: graph.cc:96-295), structural hashing for memoization
(reference: graph.cc:1356), and graphviz export.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from heapq import heapify, heappop, heappush
from typing import Callable, Dict, FrozenSet, Iterable, List, NamedTuple, Optional, Sequence, Set, Tuple


class Edge(NamedTuple):
    """Tensor edge: output ``src_idx`` of ``src`` feeds input ``dst_idx`` of ``dst``.

    A NamedTuple, not a dataclass: substitution candidate generation
    constructs hundreds of thousands per search, and the frozen-
    dataclass ``object.__setattr__`` init was a measured hotspot."""

    src: int  # node guid
    dst: int  # node guid
    src_idx: int = 0
    dst_idx: int = 0


class Node:
    """A PCG node: guid + operator descriptor.

    ``op`` is any object exposing ``op_type``, ``name``,
    ``output_shapes`` and a stable ``signature()`` used for structural
    hashing (operators from flexflow_tpu.ops satisfy this).
    """

    __slots__ = ("guid", "op")

    def __init__(self, guid: int, op):
        self.guid = guid
        self.op = op

    def __repr__(self) -> str:
        return f"Node({self.guid}, {getattr(self.op, 'name', self.op)})"


class Graph:
    """Directed multigraph of operator nodes (the PCG)."""

    def __init__(self):
        self.nodes: Dict[int, Node] = {}
        self.in_edges: Dict[int, List[Edge]] = {}
        self.out_edges: Dict[int, List[Edge]] = {}
        self._next_guid = 1
        self._topo_cache: Optional[List[Node]] = None
        self._hash_cache: Optional[int] = None
        self._node_hash_cache: Optional[Dict[int, int]] = None
        self._anc_hash_cache: Optional[Dict[int, int]] = None
        # process-stable digests (persistent DP memo keys) — computed
        # lazily by stable_node_digests / cost_cache.stable_graph_digest
        self._stable_nh_cache: Optional[Dict[int, str]] = None
        self._stable_gd_cache: Optional[str] = None

    # ---- construction ----------------------------------------------------
    def new_node(self, op) -> Node:
        node = Node(self._next_guid, op)
        self._next_guid += 1
        self.add_node(node)
        return node

    def _invalidate(self) -> None:
        self._topo_cache = None
        self._hash_cache = None
        self._node_hash_cache = None
        self._anc_hash_cache = None
        self._stable_nh_cache = None
        self._stable_gd_cache = None

    def add_node(self, node: Node) -> None:
        if node.guid in self.nodes:
            return
        self._invalidate()
        self.nodes[node.guid] = node
        self.in_edges.setdefault(node.guid, [])
        self.out_edges.setdefault(node.guid, [])
        self._next_guid = max(self._next_guid, node.guid + 1)

    def add_edge(self, src: Node, dst: Node, src_idx: int = 0, dst_idx: int = 0) -> None:
        self.add_node(src)
        self.add_node(dst)
        self._invalidate()
        e = Edge(src.guid, dst.guid, src_idx, dst_idx)
        self.out_edges[src.guid].append(e)
        self.in_edges[dst.guid].append(e)

    def remove_node(self, guid: int) -> None:
        self._invalidate()
        for e in list(self.in_edges.get(guid, [])):
            self.out_edges[e.src].remove(e)
        for e in list(self.out_edges.get(guid, [])):
            self.in_edges[e.dst].remove(e)
        self.in_edges.pop(guid, None)
        self.out_edges.pop(guid, None)
        self.nodes.pop(guid, None)

    def __getstate__(self):
        # pickle structure only: derived caches rebuild on demand, and
        # delta annotations (_changed_vs parent weakref, touched sets)
        # are meaningless outside the process that made them — the
        # persistent search-result cache pickles rewritten graphs
        return {
            "nodes": self.nodes,
            "in_edges": self.in_edges,
            "out_edges": self.out_edges,
            "_next_guid": self._next_guid,
        }

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._topo_cache = None
        self._hash_cache = None
        self._node_hash_cache = None
        self._anc_hash_cache = None
        self._stable_nh_cache = None
        self._stable_gd_cache = None

    def copy(self) -> "Graph":
        g = Graph()
        g._next_guid = self._next_guid
        # nodes are immutable (op descriptors shared); C-level copies —
        # candidate generation clones the graph once per substitution
        g.nodes = dict(self.nodes)
        g.in_edges = {k: list(v) for k, v in self.in_edges.items()}
        g.out_edges = {k: list(v) for k, v in self.out_edges.items()}
        return g

    def copy_cow(self) -> "Graph":
        """Copy-on-write clone: edge LISTS are shared with the parent.
        Callers must REPLACE a node's edge list to change it, never
        mutate one in place (substitution._insert_before/_insert_after
        follow this; remove_node does NOT — rewrites that delete nodes
        take a full copy()).  Candidate generation applies thousands of
        single-splice rewrites per search; sharing the untouched lists
        is most of a copy's cost back, and lets delta consumers detect
        unchanged nodes by list identity."""
        g = Graph()
        g._next_guid = self._next_guid
        g.nodes = dict(self.nodes)
        g.in_edges = dict(self.in_edges)
        g.out_edges = dict(self.out_edges)
        return g

    # ---- queries ---------------------------------------------------------
    @property
    def num_nodes(self) -> int:
        return len(self.nodes)

    @property
    def num_edges(self) -> int:
        return sum(len(v) for v in self.out_edges.values())

    def sources(self) -> List[Node]:
        return [self.nodes[g] for g in self.nodes if not self.in_edges[g]]

    def sinks(self) -> List[Node]:
        return [self.nodes[g] for g in self.nodes if not self.out_edges[g]]

    def predecessors(self, guid: int) -> List[int]:
        seen, out = set(), []
        for e in self.in_edges[guid]:
            if e.src not in seen:
                seen.add(e.src)
                out.append(e.src)
        return out

    def successors(self, guid: int) -> List[int]:
        seen, out = set(), []
        for e in self.out_edges[guid]:
            if e.dst not in seen:
                seen.add(e.dst)
                out.append(e.dst)
        return out

    def topo_order(self) -> List[Node]:
        """Deterministic Kahn topological order (ties by guid); cached —
        the search costs one graph thousands of times."""
        if self._topo_cache is not None:
            return self._topo_cache
        indeg = {g: len(self.in_edges[g]) for g in self.nodes}
        ready = [g for g, d in indeg.items() if d == 0]
        order: List[Node] = []
        heapify(ready)
        while ready:
            g = heappop(ready)
            order.append(self.nodes[g])
            for e in self.out_edges[g]:
                indeg[e.dst] -= 1
                if indeg[e.dst] == 0:
                    heappush(ready, e.dst)
        if len(order) != len(self.nodes):
            raise ValueError("graph has a cycle")
        self._topo_cache = order
        return order

    # ---- structural hash (memoization key) -------------------------------
    def _sig_repr(self, node: Node) -> str:
        op = node.op
        sig = getattr(op, "_sig_repr_cache", None)
        if sig is None:
            sig = repr(op.signature()) if hasattr(op, "signature") else repr(op)
            try:
                op._sig_repr_cache = sig  # ops are immutable; see base.py
            except AttributeError:
                pass
        return sig

    def hash(self) -> int:
        """Structure-and-op hash, stable across guid renumbering.

        Iteratively refines per-node hashes from op signatures and
        predecessor hashes — same role as the reference's graph hash
        used to memoize DP states (reference: src/runtime/graph.cc:1356).
        """
        if self._hash_cache is not None:
            return self._hash_cache
        h = self._anc_hash_cache or self._anc_hash_map()
        out = hash(tuple(sorted(h[n.guid] for n in self.sinks())))
        self._hash_cache = out
        return out

    def _anc_hash_map(self) -> Dict[int, int]:
        """Ancestor-refined per-node hashes (the forward half of
        ``node_hashes``) — in-process tuple hashing: every consumer
        (DP memo, driver segment cache, best-first seen-set) lives in
        this process, and the search hashes tens of thousands of
        rewritten graphs (blake2b-over-strings here was a measured 6s
        of the Inception search).

        Delta path: a substituted graph carries the changed-guid sets
        its rewrite touched (substitution._finish_rewrite); when the
        parent has PRIMED hashes (``prime_delta_hashes``, called on
        best-first pop), the clean cone copies the parent's values —
        the per-node hash is a pure function of sig + pred hashes, so
        the copy is exact — and only the dirty cone pays the tuple
        building.  The map is NOT cached here: storing a per-node dict
        on all ~10^4 candidate graphs of a search was measured as 2s of
        pure GC pressure on Inception."""
        h: Dict[int, int] = {}
        in_edges = self.in_edges
        ph = None
        cv = getattr(self, "_changed_vs", None)
        if cv is not None:
            parent = cv[0]()
            if parent is not None:
                ph = parent._anc_hash_cache
        if ph is not None:
            dirty = cv[1]
            # start from the parent's map (C-level copy; stale entries
            # for removed nodes are never read) and rewrite only the
            # cone whose hash actually moved — `diff` tracks it
            h = dict(ph)
            diff: Set[int] = set()
            for node in self.topo_order():
                g = node.guid
                el = in_edges[g]
                if g not in dirty:
                    for e in el:
                        if e.src in diff:
                            break
                    else:
                        continue  # parent's value stands
                if len(el) == 1:  # the common case: skip the sort
                    e = el[0]
                    ins = ((h[e.src], e.src_idx, e.dst_idx),)
                else:
                    ins = tuple(sorted(
                        (h[e.src], e.src_idx, e.dst_idx) for e in el))
                v = hash((self._sig_repr(node), ins))
                if v != h.get(g):
                    diff.add(g)
                    h[g] = v
        else:
            for node in self.topo_order():
                el = in_edges[node.guid]
                if len(el) == 1:
                    e = el[0]
                    ins = ((h[e.src], e.src_idx, e.dst_idx),)
                else:
                    ins = tuple(sorted(
                        (h[e.src], e.src_idx, e.dst_idx) for e in el))
                h[node.guid] = hash((self._sig_repr(node), ins))
        return h

    def prime_delta_hashes(self) -> Dict[int, int]:
        """Retain this graph's ancestor-hash map so derived rewrites
        hash incrementally.  Called for graphs that become substitution
        PARENTS (best-first pops) — a bounded set, unlike the candidate
        stream."""
        if self._anc_hash_cache is None:
            self._anc_hash_cache = self._anc_hash_map()
        return self._anc_hash_cache

    def node_hashes(self) -> Dict[int, int]:
        """Bidirectional per-node structural hashes: combines each
        node's ancestor-refined and descendant-refined hash, so two
        nodes get equal hashes only when their full structural contexts
        match.  Nodes with equal hashes are interchangeable under graph
        isomorphism — the basis for guid-independent DP memoization
        (reference memoizes by the same kind of structural hash,
        graph.cc:1356; here per-node so cached *strategies* can be
        remapped onto isomorphic segments, e.g. repeated transformer
        layers)."""
        if self._node_hash_cache is not None:
            return self._node_hash_cache
        topo = self.topo_order()
        anc: Dict[int, int] = {}
        for node in topo:
            ins = sorted(
                (anc[e.src], e.src_idx, e.dst_idx)
                for e in self.in_edges[node.guid]
            )
            anc[node.guid] = hash((self._sig_repr(node), tuple(ins)))
        desc: Dict[int, int] = {}
        for node in reversed(topo):
            outs = sorted(
                (desc[e.dst], e.src_idx, e.dst_idx)
                for e in self.out_edges[node.guid]
            )
            desc[node.guid] = hash((self._sig_repr(node), tuple(outs)))
        combined = {g: hash((anc[g], desc[g])) for g in self.nodes}
        self._node_hash_cache = combined
        return combined

    def stable_sig_reprs(self) -> Dict[int, str]:
        """Per-node signature strings for PROCESS-STABLE digesting —
        the ONE input-handling rule shared by ``stable_node_digests``
        and ``cost_cache.stable_graph_digest`` (they key the same
        persisted memo rows and must stay in lock-step): InputOp
        signatures embed the frontend's GLOBAL tensor_guid counter
        (process-lifetime, build-order dependent), so the input's rank
        of appearance in topo order is substituted — it carries the
        same distinctness without the counter, letting graphs/segments
        containing model inputs digest identically across builds."""
        input_rank: Dict[object, int] = {}
        sigs: Dict[int, str] = {}
        for node in self.topo_order():
            op = node.op
            if op.op_type.value == "input":
                shape = op.output_shapes[0]
                sigs[node.guid] = repr((
                    "input", shape.sizes, shape.dtype.value,
                    input_rank.setdefault(
                        op.attrs.get("tensor_guid"), len(input_rank)),
                ))
            else:
                sigs[node.guid] = self._sig_repr(node)
        return sigs

    def stable_node_digests(self) -> Dict[int, str]:
        """Process-stable analogue of ``node_hashes``: per-node
        structural digests combining the ancestor- and descendant-
        refined context, as blake2b hex over signature strings instead
        of python tuple hashes (PYTHONHASHSEED randomizes those across
        processes).  Nodes with equal digests are interchangeable under
        graph isomorphism — the pairing rule the persistent DP memo
        (search/cost_cache.py dp-row layer) stores strategies under, so
        a COLD process can remap a row solved by any prior run.  Cached
        per graph; only consumers that persist/serve rows compute it."""
        if self._stable_nh_cache is not None:
            return self._stable_nh_cache
        from hashlib import blake2b

        def h(payload: str) -> str:
            return blake2b(payload.encode(), digest_size=12).hexdigest()

        topo = self.topo_order()
        sigs = self.stable_sig_reprs()
        anc: Dict[int, str] = {}
        for node in topo:
            ins = sorted(
                (anc[e.src], e.src_idx, e.dst_idx)
                for e in self.in_edges[node.guid]
            )
            anc[node.guid] = h(sigs[node.guid] + repr(ins))
        desc: Dict[int, str] = {}
        for node in reversed(topo):
            outs = sorted(
                (desc[e.dst], e.src_idx, e.dst_idx)
                for e in self.out_edges[node.guid]
            )
            desc[node.guid] = h(sigs[node.guid] + repr(outs))
        combined = {g: h(anc[g] + desc[g]) for g in self.nodes}
        self._stable_nh_cache = combined
        return combined

    def remap(self, mapping: Dict[int, int], fresh_start: Optional[int] = None) -> Tuple["Graph", Dict[int, int]]:
        """New graph with guids renamed through ``mapping``; nodes not in
        the mapping get fresh guids from ``fresh_start`` (default: after
        every mapped guid).  Returns (graph, full mapping incl. fresh
        assignments).  Used to transplant a cached optimized segment onto
        an isomorphic segment with different guids."""
        full = dict(mapping)
        nxt = fresh_start if fresh_start is not None else (
            max(list(mapping.values()) + [self._next_guid]) + 1
        )
        for guid in sorted(self.nodes):
            if guid not in full:
                full[guid] = nxt
                nxt += 1
        g = Graph()
        g._next_guid = nxt
        for guid in self.nodes:
            ng = full[guid]
            n = self.nodes[guid]
            g.nodes[ng] = n if ng == guid else Node(ng, n.op)
            g.in_edges[ng] = []
            g.out_edges[ng] = []
        for guid in self.nodes:
            for e in self.out_edges[guid]:
                ne = Edge(full[e.src], full[e.dst], e.src_idx, e.dst_idx)
                g.out_edges[ne.src].append(ne)
                g.in_edges[ne.dst].append(ne)
        return g, full

    # ---- dominators & bottlenecks ----------------------------------------
    def dominators(self) -> Dict[int, Set[int]]:
        """dom(v) = set of nodes on every path from any source to v
        (multi-source DAG variant, reference: include/flexflow/dominators.h)."""
        dom: Dict[int, Set[int]] = {}
        for node in self.topo_order():
            preds = self.predecessors(node.guid)
            if not preds:
                dom[node.guid] = {node.guid}
            else:
                inter = set(dom[preds[0]])
                for p in preds[1:]:
                    inter &= dom[p]
                inter.add(node.guid)
                dom[node.guid] = inter
        return dom

    def post_dominators(self) -> Dict[int, Set[int]]:
        return self.reversed().dominators()

    def reversed(self) -> "Graph":
        g = Graph()
        g._next_guid = self._next_guid
        for guid, n in self.nodes.items():
            g.nodes[guid] = n
            g.in_edges[guid] = [Edge(e.dst, e.src, e.src_idx, e.dst_idx) for e in self.out_edges[guid]]
            g.out_edges[guid] = [Edge(e.dst, e.src, e.src_idx, e.dst_idx) for e in self.in_edges[guid]]
        return g

    def bottlenecks(self) -> List[Node]:
        """Nodes through which *every* source→sink path passes, in topo
        order, excluding sources/sinks — the sequence-split candidates
        (reference: src/runtime/graph.cc:580 find_bottleneck_node).
        Runs on the native bitset engine when available
        (native/src/graph_algos.cpp ffn_graph_bottlenecks)."""
        if not self.nodes:
            return []
        native = self._native_call("graph_bottlenecks")
        if native is not None:
            idx_to_guid, result = native
            return [self.nodes[idx_to_guid[i]] for i in result]
        sink_guids = [n.guid for n in self.sinks()]
        src_guids = {n.guid for n in self.sources()}
        dom = self.dominators()
        pdom = self.post_dominators()
        common_dom = None
        for s in sink_guids:
            common_dom = set(dom[s]) if common_dom is None else common_dom & dom[s]
        common_pdom = None
        for s in src_guids:
            common_pdom = set(pdom[s]) if common_pdom is None else common_pdom & pdom[s]
        cands = (common_dom or set()) & (common_pdom or set())
        cands -= src_guids
        cands -= set(sink_guids)
        order = {n.guid: i for i, n in enumerate(self.topo_order())}
        return [self.nodes[g] for g in sorted(cands, key=lambda g: order[g])]

    # ---- splits (used by DP search) --------------------------------------
    def split_at_node(self, node: Node) -> Tuple["Graph", "Graph"]:
        """Sequence split: (prefix including ``node``, suffix with ``node``
        as its source) — reference: src/runtime/graph.cc:96-159."""
        order = self.topo_order()
        idx = {n.guid: i for i, n in enumerate(order)}
        pivot = idx[node.guid]
        first, second = Graph(), Graph()
        first._next_guid = second._next_guid = self._next_guid
        pre_guids = {n.guid for n in order[: pivot + 1]}
        for guid, n in self.nodes.items():
            if guid in pre_guids:
                first.add_node(n)
            if guid not in pre_guids or guid == node.guid:
                second.add_node(n)
        for guid in self.nodes:
            for e in self.out_edges[guid]:
                s_pre, d_pre = e.src in pre_guids, e.dst in pre_guids
                if s_pre and d_pre:
                    first.out_edges[e.src].append(e)
                    first.in_edges[e.dst].append(e)
                elif not s_pre and not d_pre:
                    second.out_edges[e.src].append(e)
                    second.in_edges[e.dst].append(e)
                elif e.src == node.guid and not d_pre:
                    second.out_edges[e.src].append(e)
                    second.in_edges[e.dst].append(e)
                else:
                    # crossing edge not through the bottleneck: caller must
                    # only split at true bottlenecks
                    raise ValueError(f"split_at_node: edge {e} crosses the split")
        return first, second

    def split_horizontal(self) -> Optional[Tuple["Graph", "Graph"]]:
        """Partition into two independent (vertex-disjoint, no crossing
        edges) subgraphs if the PCG is disconnected between them —
        reference: src/runtime/graph.cc:161-295 nonsequence split."""
        comps = self.weakly_connected_components()
        if len(comps) < 2:
            return None
        half = len(comps) // 2
        a_guids = set().union(*comps[:half])
        return self._subgraph(a_guids), self._subgraph(
            set(self.nodes) - a_guids
        )

    def _native_call(self, fn_name: str):
        """Run a native graph algorithm over dense indices (sorted-guid
        order, matching the Python tie-breaks). None = lib unavailable."""
        try:
            from flexflow_tpu import native
        except ImportError:
            return None
        fn = getattr(native, fn_name)
        guids = sorted(self.nodes)
        index = {g: i for i, g in enumerate(guids)}
        edges = [
            (index[e.src], index[e.dst])
            for g in self.nodes
            for e in self.out_edges[g]
        ]
        result = fn(len(guids), edges)
        if result is None:
            return None
        return guids, result

    def weakly_connected_components(self) -> List[Set[int]]:
        native = self._native_call("graph_components")
        if native is not None:
            guids, labels = native
            comps: Dict[int, Set[int]] = {}
            for g, lbl in zip(guids, labels):
                comps.setdefault(lbl, set()).add(g)
            # native labels are assigned in smallest-member order already
            return [comps[k] for k in sorted(comps)]
        parent = {g: g for g in self.nodes}

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        for guid in self.nodes:
            for e in self.out_edges[guid]:
                ra, rb = find(e.src), find(e.dst)
                if ra != rb:
                    parent[ra] = rb
        comps: Dict[int, Set[int]] = {}
        for g in self.nodes:
            comps.setdefault(find(g), set()).add(g)
        # deterministic order (and native-path parity): by smallest member
        return sorted(comps.values(), key=min)

    def _subgraph(self, guids: Set[int]) -> "Graph":
        g = Graph()
        g._next_guid = self._next_guid
        for guid in guids:
            g.add_node(self.nodes[guid])
        for guid in guids:
            for e in self.out_edges[guid]:
                if e.dst in guids:
                    g.out_edges[e.src].append(e)
                    g.in_edges[e.dst].append(e)
        return g

    # ---- verification ----------------------------------------------------
    def check(self, strict_shapes: bool = True) -> list:
        """Well-formedness findings for this PCG ([] = sound) — the
        static-analysis invariant pass (flexflow_tpu/analysis,
        PCG0xx codes) as an instance method for interactive debugging.
        Lazy import: the graph core stays dependency-free."""
        from flexflow_tpu.analysis.invariants import check_graph

        return check_graph(self, strict_shapes=strict_shapes)

    # ---- export ----------------------------------------------------------
    def to_dot(self, strategy: Optional[Dict[int, object]] = None) -> str:
        """Graphviz export (reference: substitution.cc:1790
        export_strategy_computation_graph_file)."""
        lines = ["digraph PCG {", "  rankdir=TB;"]
        for guid, n in sorted(self.nodes.items()):
            label = getattr(n.op, "name", str(n.op))
            if strategy and guid in strategy:
                label += f"\\n{strategy[guid]}"
            lines.append(f'  n{guid} [label="{label}" shape=box];')
        for guid in sorted(self.nodes):
            for e in self.out_edges[guid]:
                lines.append(f"  n{e.src} -> n{e.dst};")
        lines.append("}")
        return "\n".join(lines)

    def write_dot(self, path: str, strategy=None) -> None:
        with open(path, "w") as f:
            f.write(self.to_dot(strategy))
