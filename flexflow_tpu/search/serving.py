"""Serving objective: KV-cache-aware latency/SLO search currency.

Training search optimizes MEAN step time (throughput).  A decode-step
serving deployment wants **p99 latency under an HBM budget** — a
different Pareto point, because the decode step's dominant term is the
RAGGED paged-KV stream (ops/decode_attention.py) whose per-device load
depends on how the strategy shards sequences:

* a **batch split** of degree d partitions the frame's sequence slots
  over d device groups — each step's latency is gated by the group
  holding the most live tokens, and with ragged lengths the max-shard
  load concentrates: fewer sequences per shard = less averaging = a
  fatter p99 tail;
* a **head split** (decode TP, the replica slot) divides EVERY
  sequence's KV stream evenly — no imbalance term, at the price of the
  output projection's partial-sum allreduce.

``ServingSpec`` is the arrival model that makes this priceable: a
deterministic (seeded) population of ragged decode frames, reduced to
``load_factor(batch_degree)`` — the p-quantile max-shard token load
relative to full occupancy.  The decode op's ``sharded_bytes_accessed``
hook scales its cache stream by exactly this factor when a
``CostModel.serving`` spec is armed, so under ``FFConfig.
objective="serve"`` the ENTIRE search — both DP engines, substitution
estimates, delta sim, the champion-vs-DP floor — natively ranks in the
p99-latency currency with zero search-machinery changes; with
``objective="train"`` (default) nothing here runs and every priced
number is bit-identical to history (tests/test_serving.py inertness
gate).  The HBM budget needs no separate mechanism: per-device KV
residency at FULL page-pool occupancy enters ``CostModel.op_memory``
(``kv_cache_bytes``), so a strategy that cannot hold the pool is
rejected during search, not at OOM.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple

import numpy as np

# arrival-model defaults: enough samples for a stable p99 of a
# max-of-shards statistic, pinned seed so searches are reproducible
DEFAULT_SAMPLES = 256
DEFAULT_QUANTILE = 0.99


@dataclass(frozen=True)
class ServingSpec:
    """The decode deployment the serve objective prices against.

    ``max_seqs``/``page_size``/``pages_per_seq`` mirror the decode
    graph's own frame geometry (``serving_spec_for`` derives them from
    its DecodeAttentionOps); ``p99_budget_ms`` is the declared SLO —
    recorded + linted (SHD163 warns when the predicted p99 exceeds
    it), never silently enforced by clamping."""

    max_seqs: int
    page_size: int
    pages_per_seq: int
    p99_budget_ms: float = 0.0
    quantile: float = DEFAULT_QUANTILE
    samples: int = DEFAULT_SAMPLES
    seed: int = 0
    # phase-split arrival model (prefill/decode disaggregation,
    # search/disaggregation.py): steady-state prompt traffic the
    # PREFILL phase must absorb, separate from the decode p99 load.
    # 0 derives defaults from the cache geometry.  Deliberately NOT
    # part of ``signature()``: these fields price only the
    # disaggregation proposal pass, never the per-(op, view) cost rows,
    # so train/serve search paths stay bit-identical to history.
    prompt_tokens_mean: int = 0  # 0 = max_seq_len // 2
    decode_tokens_mean: int = 0  # 0 = max(1, max_seq_len // 4)
    # fleet arrival share (search/fleet.py): a replica routed a
    # fraction of the fleet's traffic runs PARTIAL frames — only
    # ``occupancy_slots`` of its sequence slots are live, the rest
    # stream nothing.  0 = full frame (every non-fleet path).  Folded
    # into ``signature()`` ONLY when set, so no-fleet cost rows stay
    # byte-identical to history while occupancy-priced rows can never
    # cross-serve full-frame ones.
    occupancy_slots: int = 0
    # radix prefix sharing (runtime/decode.py PageAllocator): pages per
    # sequence expected to be CLAIMED from the shared prefix trie
    # rather than privately allocated (FFConfig.
    # serve_shared_prefix_pages — e.g. a fleet-wide system prompt of
    # N*page_size tokens).  Enters ``shared_residency_factor`` so
    # RESIDENCY pricing (kv_cache_bytes, SHD161) counts the shared
    # pages ONCE across the frame; the decode STREAM is deliberately
    # unaffected — every sequence still reads its own prefix.  Folded
    # into ``signature()`` ONLY when set (extension-only, like
    # occupancy_slots).  0 = no sharing assumed.
    shared_prefix_pages: int = 0
    _factors: Dict[int, float] = field(default_factory=dict, compare=False,
                                       repr=False, hash=False)

    @property
    def max_seq_len(self) -> int:
        return self.page_size * self.pages_per_seq

    def signature(self) -> Tuple:
        """The fields a priced cost row depends on — the extension-only
        component ``cost_cache.cost_signature`` folds in under the
        serve objective (serve rows must never cross-serve train
        runs)."""
        sig: Tuple = ("serve", self.max_seqs, self.page_size,
                      self.pages_per_seq, self.quantile, self.samples,
                      self.seed)
        if self.occupancy_slots:
            # extension-only: absent ⇒ bytes identical to pre-fleet
            sig = sig + ("occ", self.occupancy_slots)
        if self.shared_prefix_pages:
            # extension-only: absent ⇒ bytes identical to pre-sharing
            sig = sig + ("shared", self.shared_prefix_pages)
        return sig

    def shared_residency_factor(self) -> float:
        """SHARED/private residency ratio of the page pool: with
        ``shared_prefix_pages`` pages per sequence claimed from one
        trie-resident prefix, the frame holds
        ``max_seqs * (pps - shared) + shared`` distinct pages instead
        of ``max_seqs * pps``.  Multiplies kv_cache_bytes (residency/
        SHD161) only — never the decode stream."""
        s = max(0, min(self.shared_prefix_pages, self.pages_per_seq - 1))
        if s == 0 or self.max_seqs <= 0 or self.pages_per_seq <= 0:
            return 1.0
        total = self.max_seqs * self.pages_per_seq
        distinct = self.max_seqs * (self.pages_per_seq - s) + s
        return float(distinct) / float(total)

    # ---- arrival model ---------------------------------------------------
    def sample_lengths(self) -> np.ndarray:
        """[samples, max_seqs] int32 live-token counts: the ragged
        decode-frame population.  Deterministic under the seed.  The
        mixture is the continuous-batching steady state: most slots
        mid-generation (uniform over the cache), a short-prompt mode
        (fresh admissions), and a near-full mode (about to evict) —
        enough spread that max-shard concentration is a real
        phenomenon, not a degenerate constant."""
        rng = np.random.default_rng(self.seed)
        L = self.max_seq_len
        shape = (self.samples, self.max_seqs)
        mode = rng.random(shape)
        uniform = rng.integers(1, L + 1, size=shape)
        fresh = rng.integers(1, max(2, L // 8) + 1, size=shape)
        full = rng.integers(max(1, (7 * L) // 8), L + 1, size=shape)
        lens = np.where(mode < 0.2, fresh, np.where(mode < 0.9, uniform,
                                                    full))
        lens = lens.astype(np.int64)
        if 0 < self.occupancy_slots < self.max_seqs:
            # partial frame: the trailing slots are EMPTY, not short —
            # the draws for the live slots stay bit-identical to the
            # full frame's (same rng stream), so occupancy only removes
            # load, never reshuffles it
            lens[:, self.occupancy_slots:] = 0
        return lens

    def load_factor(self, batch_degree: int) -> float:
        """p-quantile of the max-shard live-token load under a batch
        split of ``batch_degree``, relative to full occupancy — the
        multiplier on the decode op's cache-stream bytes.  degree 1
        averages over every slot (factor well below 1); degree ==
        max_seqs is gated by the single longest sequence (factor near
        1): the imbalance amplification batch splits pay and head
        splits don't."""
        d = max(1, int(batch_degree))
        hit = self._factors.get(d)
        if hit is not None:
            return hit
        if self.max_seqs % d != 0:
            # propagation rejects such views anyway; price pessimally
            self._factors[d] = 1.0
            return 1.0
        lens = self.sample_lengths()  # [S, B]
        shards = lens.reshape(self.samples, d, self.max_seqs // d)
        max_shard = shards.sum(axis=2).max(axis=1)  # [S]
        q = float(np.quantile(max_shard, self.quantile))
        full = (self.max_seqs // d) * self.max_seq_len
        f = min(1.0, q / float(full)) if full > 0 else 1.0
        self._factors[d] = f
        return f

    def with_quantile(self, q: float) -> "ServingSpec":
        return replace(self, quantile=float(q), _factors={})

    def with_occupancy(self, slots: int) -> "ServingSpec":
        """The same deployment at ``slots`` live sequence slots per
        frame (fleet pricing: a replica's arrival share in frame
        currency).  ``slots >= max_seqs`` is the full frame."""
        k = max(1, min(self.max_seqs, int(slots)))
        if k >= self.max_seqs:
            k = 0
        return replace(self, occupancy_slots=k, _factors={})

    # ---- phase-split arrival model (disaggregation pricing) -------------
    def prefill_tokens_per_frame(self) -> float:
        """Expected PROMPT tokens the prefill phase must absorb per
        decode frame, in steady state: every live slot generates one
        token per frame and turns over every ``decode_tokens_mean``
        frames; each turnover admits a fresh prompt of
        ``prompt_tokens_mean`` tokens.  This is the compute-bound
        arrival load the disaggregation search prices against the
        prefill block — colocated deployments pay it as phase
        interference on the decode devices, disaggregated ones overlap
        it on their own submesh and pay the KV handoff instead
        (search/disaggregation.py)."""
        g = self.decode_tokens_mean or max(1, self.max_seq_len // 4)
        p = self.prompt_tokens_mean or max(1, self.max_seq_len // 2)
        return self.max_seqs * (float(p) / float(g))


def decode_nodes(graph):
    """The graph's DecodeAttentionOp nodes, topo order."""
    from flexflow_tpu.core.optype import OperatorType

    return [n for n in graph.topo_order()
            if n.op.op_type == OperatorType.DECODE_ATTENTION]


def serving_spec_for(graph, config) -> Optional[ServingSpec]:
    """Derive the ServingSpec from the graph's own decode ops (frame
    geometry is a graph property, not a config guess), or None when the
    graph has no decode ops — the serve objective then degenerates to
    train pricing and the driver says so."""
    nodes = decode_nodes(graph)
    if not nodes:
        return None
    first = nodes[0].op
    geo = (first.max_seqs, first.attrs["page_size"],
           first.attrs["pages_per_seq"])
    for n in nodes[1:]:
        g = (n.op.max_seqs, n.op.attrs["page_size"],
             n.op.attrs["pages_per_seq"])
        if g != geo:
            raise ValueError(
                f"decode ops disagree on frame geometry: "
                f"{nodes[0].op.name} has {geo}, {n.op.name} has {g} — "
                f"one page allocator cannot serve both")
    return ServingSpec(
        max_seqs=geo[0], page_size=geo[1], pages_per_seq=geo[2],
        p99_budget_ms=float(getattr(config, "serve_p99_budget_ms", 0.0)
                            or 0.0),
        prompt_tokens_mean=int(getattr(
            config, "serve_prompt_tokens_mean", 0) or 0),
        decode_tokens_mean=int(getattr(
            config, "serve_decode_tokens_mean", 0) or 0),
        shared_prefix_pages=int(getattr(
            config, "serve_shared_prefix_pages", 0) or 0),
    )


def kv_residency_bytes(graph, strategy, num_devices: int,
                       serving: Optional[ServingSpec] = None) -> float:
    """Per-device resident KV bytes of ``(graph, strategy)``: the sum of
    every decode op's ``kv_cache_bytes`` under its view — the number
    SHD161 checks against the HBM capacity and the serve bench records
    per strategy.  ``serving`` threads the prefix-sharing residency
    discount (``shared_residency_factor``) into ops whose hook accepts
    it; a legacy hook without the keyword is priced unshared."""
    from flexflow_tpu.core.machine import MachineView

    total = 0.0
    for node in decode_nodes(graph):
        mv = strategy.get(node.guid)
        if mv is None:
            mv = node.op.fixed_machine_view() or MachineView.trivial(
                node.op.output_shapes[0].ndim)
        try:
            total += node.op.kv_cache_bytes(mv, serving=serving)
        except TypeError:
            total += node.op.kv_cache_bytes(mv)
    return total


def serve_latency_quantiles(graph, strategy, config, calibration=None,
                            quantiles=(0.5, 0.9, 0.99)) -> Dict[str, float]:
    """Simulated decode-step latency at several arrival quantiles for
    one (graph, strategy) — the bench's p50/p90/p99 columns.  Each
    quantile gets a FRESH simulator (per-(op, view) cost rows bake the
    serving load factor, so one simulator cannot serve two quantiles)
    with the persistent cost cache detached (quantile sweeps are
    bench-local probes, not the search's cost surface)."""
    from flexflow_tpu.search.simulator import Simulator

    spec = serving_spec_for(graph, config)
    out: Dict[str, float] = {}
    for q in quantiles:
        sim = Simulator(
            config.machine_spec, num_devices=config.search_devices,
            calibration=calibration, inference=True,
            serving=spec.with_quantile(q) if spec is not None else None,
        )
        t = sim.simulate(graph, strategy)
        out[f"p{int(round(q * 100))}"] = t
    return out
