"""Serving fleet searched as one N-block placement + routing question.

``search/disaggregation.py`` prices TWO blocks (prefill, decode) on
disjoint submeshes.  This pass generalizes the move to N REPLICA
blocks: partition the mesh into replica submeshes, give every block its
own full rewriting search at its width (and optionally its own
intra-replica prefill/decode split — the two-block machinery nested
one level down), and price the candidate fleet together with the
per-SLO-class ROUTING fractions that decide which classes land where.
"How many replicas × which strategy each × which classes route where"
is one searched question in one currency: per-class p99 seconds.

The currency extends the serve objective's ragged-arrival model
(search/serving.py) with two fleet-specific terms:

* **arrival shares** — a replica routed a fraction ``x`` of the
  fleet's traffic runs PARTIAL frames: only ``round(x·load·max_seqs)``
  sequence slots are live.  ``ServingSpec.with_occupancy`` prices
  exactly that frame (the decode op's cache stream scales, weights and
  collectives do not — which is why narrow replicas are not free);
* **queueing** — each replica is charged an M/M/1-style wait factor
  per class, ``Q = u/(1-u)`` with ``u`` the utilization its
  PRIORITY-ADMISSION lane sees (only traffic of equal-or-higher
  priority delays a class, mirroring the executor's admission order),
  so a dedicated low-utilization replica is exactly the mechanism that
  buys an interactive class its p99.

Per class the fleet's p99 is the worst replica it routes to:

    p99_c = max_{r: f_{c,r} > 0}  T_r · (1 + Q_{c,r})
    T_r   = T_dec(w_r, slots_r) + pre_r · T_pre(w_r) / L        (coloc)
          | max(T_dec(b, slots_r), pre_r · T_pre(a) / L) + T_handoff
    cost  = Σ_c a_c · p99_c

with ``a_c`` the per-class arrival weights (the normalized ``weight``
field of the SLO class table),
``pre_r`` the replica's share of the prompt-token arrival stream, and
the intra-replica (a, b) split searched per block exactly like the
top-level disaggregation.  The single-replica baseline is the SAME
formula at k = 1, so adoption compares like with like; the winner must
beat it by the search margin.  ``load_scale`` re-parameterizes the
offered load — the controller's elastic re-search feeds the measured
p99 drift ratio back through it, which is how a drift episode can
re-size N (runtime/controller.py observe_fleet).

Adopted fleets are always-on lint-gated (SHD166 N-block frame/overlap,
SHD167 routing coverage + pool-geometry coherence, flat SHD101-110 per
block) and persist as ``__meta__.fleet`` behind the digest gate with
import re-lint (model.compile) and a stdlib fflint check (STR212).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.core.machine import MachineView

Strategy = Dict[int, MachineView]

# utilization clamp: past this the M/M/1 wait is effectively "the lane
# is saturated" — an unbounded queue would make every comparison inf
U_CAP = 0.95
DEFAULT_CLASS = {"name": "standard", "priority": 0, "deadline_frames": 0,
                 "quantile": 0.99}


@dataclass
class FleetReplica:
    """One priced replica block: its submesh, its searched strategy,
    its optional intra-replica prefill/decode split, and its share of
    the arrival stream."""

    index: int
    devices: int
    start: int
    prefill_devices: int  # 0 = colocated inside the replica
    decode_devices: int
    share: float  # fraction of total arrival traffic routed here
    occupancy_slots: int  # live sequence slots the share fills
    step_s: float  # priced frame time at this share
    handoff_s: float
    spans_dcn: bool
    # runtime-only (not persisted): the searched block strategies and
    # the (possibly rewritten) block graphs they map
    strategy: Strategy = field(default_factory=dict, repr=False)
    graph: object = field(default=None, repr=False)
    prefill_strategy: Strategy = field(default_factory=dict, repr=False)
    prefill_graph: object = field(default=None, repr=False)

    def to_meta(self) -> dict:
        return {
            "replica": self.index,
            "devices": self.devices,
            "start": self.start,
            "prefill_devices": self.prefill_devices,
            "decode_devices": self.decode_devices,
            "share": round(self.share, 6),
            "occupancy_slots": self.occupancy_slots,
            "step_ms": round(self.step_s * 1e3, 6),
            "handoff_ms": round(self.handoff_s * 1e3, 6),
            "spans_dcn": self.spans_dcn,
            "strategy_ops": len(self.strategy),
        }


@dataclass
class FleetProposal:
    """One priced fleet decision: the replica blocks, the per-class
    routing fractions, and the fleet-vs-single per-class p99
    comparison.  ``adopted`` is the margin-gated verdict — a proposal
    is always returned (the bench records honest zeros), only adopted
    winners persist."""

    num_devices: int
    replicas: Tuple[FleetReplica, ...]
    routing: Dict[str, Tuple[float, ...]]  # class -> per-replica f
    routing_policy: str
    single_cost_s: float
    fleet_cost_s: float
    per_class_p99_s: Dict[str, float]
    single_per_class_p99_s: Dict[str, float]
    adopted: bool
    max_seqs: int
    page_size: int
    pages_per_seq: int
    offered_load: float
    load_scale: float
    slo_classes: Tuple[dict, ...] = ()

    def to_meta(self) -> dict:
        """The jsonable ``__meta__.fleet`` block (what fflint STR212
        re-checks stdlib-only).  Pool geometry rides along because
        every replica's page allocator must agree with the decode
        graph's own frame."""
        return {
            "num_devices": self.num_devices,
            "replicas": [r.to_meta() for r in self.replicas],
            "routing": {c: [round(f, 6) for f in fr]
                        for c, fr in sorted(self.routing.items())},
            "routing_policy": self.routing_policy,
            "single_step_ms": round(self.single_cost_s * 1e3, 6),
            "fleet_step_ms": round(self.fleet_cost_s * 1e3, 6),
            "per_class_p99_ms": {
                c: round(v * 1e3, 6)
                for c, v in sorted(self.per_class_p99_s.items())},
            "max_seqs": self.max_seqs,
            "page_size": self.page_size,
            "pages_per_seq": self.pages_per_seq,
            "offered_load": round(self.offered_load, 6),
            "load_scale": round(self.load_scale, 6),
            "slo_classes": [dict(c) for c in self.slo_classes],
        }


def _partitions(n: int, max_parts: int) -> List[Tuple[int, ...]]:
    """Mesh partitions into replica widths: non-increasing parts, each
    a divisor of ``n`` (submesh-aligned, the same rule the two-block
    budget pairs follow), at most ``max_parts`` parts, summing exactly
    to ``n``.  Deterministic order: widest-first lexicographic."""
    widths = [w for w in range(n, 0, -1) if n % w == 0]
    out: List[Tuple[int, ...]] = []

    def rec(remaining: int, cap: int, acc: List[int]) -> None:
        if remaining == 0:
            out.append(tuple(acc))
            return
        if len(acc) >= max_parts:
            return
        for w in widths:
            if w <= cap and w <= remaining:
                acc.append(w)
                rec(remaining - w, w, acc)
                acc.pop()

    rec(n, n, [])
    return out


def _routing_candidates(classes: Sequence[dict],
                        speeds: Sequence[float]) -> List[Tuple[str, Dict[str, List[float]]]]:
    """The deterministic routing-policy set priced per partition.  Each
    candidate maps class name -> per-replica fractions summing to 1.
    ``speeds`` are full-occupancy frame times per replica (pricing
    evaluates the EXACT fractions afterwards; speeds only order)."""
    k = len(speeds)
    names = [c["name"] for c in classes]
    uniform = {c: [1.0 / k] * k for c in names}
    out = [("uniform", uniform)]
    if k == 1:
        return out
    inv = [1.0 / s if s > 0 else 0.0 for s in speeds]
    tot = sum(inv) or 1.0
    out.append(("capacity", {c: [v / tot for v in inv] for c in names}))
    if len(names) > 1:
        # classes by priority desc then name; replicas fastest-first
        by_pri = sorted(classes,
                        key=lambda c: (-int(c.get("priority", 0)),
                                       c["name"]))
        order = sorted(range(k), key=lambda i: (speeds[i], i))
        fastest = order[0]
        rest = [i for i in range(k) if i != fastest]
        rtot = sum(inv[i] for i in rest) or 1.0
        dedicated = {}
        for c in by_pri:
            f = [0.0] * k
            if c is by_pri[0]:
                f[fastest] = 1.0
            else:
                for i in rest:
                    f[i] = inv[i] / rtot
            dedicated[c["name"]] = f
        out.append(("dedicated", dedicated))
        tiered = {}
        for j, c in enumerate(by_pri):
            f = [0.0] * k
            f[order[j % k]] = 1.0
            tiered[c["name"]] = f
        out.append(("tiered", tiered))
    return out


def propose_fleet(decode_graph, decode_strategy, config, *,
                  calibration=None, prefill_graph=None,
                  prefill_config=None, base_graph=None,
                  load_scale: float = 1.0) -> Optional[FleetProposal]:
    """Search the replica-fleet space for ``decode_graph`` under its
    searched ``decode_strategy`` and return the best N-block proposal
    (``adopted`` when a k > 1 fleet beats the single-replica baseline
    by the search margin), or None when the graph/machine cannot
    express one.  Always-on lint gate: an adopted fleet that fails
    SHD166/167 is a search bug and raises ``AnalysisError`` loudly.

    ``load_scale`` multiplies the configured offered load — the
    controller's elastic re-search passes the measured p99 drift ratio
    here, which is what lets a drift episode re-size N."""
    import dataclasses

    from flexflow_tpu.obs.events import BUS
    from flexflow_tpu.search.disaggregation import kv_handoff_bytes
    from flexflow_tpu.search.placement_search import _budget_pairs
    from flexflow_tpu.search.serving import serving_spec_for
    from flexflow_tpu.search.simulator import Simulator

    n = config.search_devices
    if n < 2:
        return None
    spec = serving_spec_for(decode_graph, config)
    if spec is None:
        return None
    load_pre = spec.prefill_tokens_per_frame()
    L = spec.prompt_tokens_mean or max(1, spec.max_seq_len // 2)
    offered = float(getattr(config, "serve_fleet_offered_load", 0.85))
    ls = offered * max(0.0, float(load_scale))
    max_k = max(1, int(getattr(config, "serve_fleet_max_replicas", 4)))
    classes = [dict(c) for c in
               (getattr(config, "serve_slo_classes", None) or ())]
    if not classes:
        classes = [dict(DEFAULT_CLASS)]
    # per-class arrival weights: the relative rates the SLO table
    # declares (config.parse_slo_classes), normalized to a distribution
    wsum = sum(float(c.get("weight", 1.0)) for c in classes)
    wt = {c["name"]: float(c.get("weight", 1.0)) / wsum for c in classes}

    if prefill_graph is None:
        from flexflow_tpu.models.decode import derive_prefill_model

        pre_model, prefill_config = derive_prefill_model(
            decode_graph, config, seq_len=L)
        prefill_graph = pre_model.graph
    elif prefill_config is None:
        prefill_config = config
    from flexflow_tpu.runtime.prefill import prefill_weight_bridge

    try:
        prefill_weight_bridge(prefill_graph, decode_graph)
    except ValueError:
        return None

    block_graph = base_graph if base_graph is not None else decode_graph
    machine = config.machine_spec
    dph = getattr(machine, "devices_per_host", 0) or n
    bpt = kv_handoff_bytes(decode_graph, 1.0)  # KV bytes per token

    # ---- per-width block solves (memoized, same discipline as the
    # two-block search: each block is a real deployment on its submesh
    # and earns whatever rewrites its mesh admits) -------------------------
    _solve_memo: Dict[Tuple, Tuple] = {}

    def _block_search(graph, cfg, devices, serving_armed):
        key = (id(graph), devices, serving_armed)
        if key in _solve_memo:
            return _solve_memo[key]
        from flexflow_tpu.search.driver import optimize_strategy

        cfg_blk = dataclasses.replace(
            cfg, num_devices=devices, search_num_devices=0,
            export_strategy_file=None, import_strategy_file=None,
            serve_disaggregation="off", serve_fleet="off")
        try:
            g_blk, s_blk = optimize_strategy(graph, cfg_blk,
                                             return_graph=True)
        except Exception:
            _solve_memo[key] = (math.inf, None, None)
            return _solve_memo[key]
        if not s_blk:
            _solve_memo[key] = (math.inf, None, None)
            return _solve_memo[key]
        sim_blk = Simulator.for_config(
            cfg_blk, calibration=calibration,
            serving=spec if serving_armed else None)
        _solve_memo[key] = (sim_blk.simulate(g_blk, s_blk), g_blk, s_blk)
        return _solve_memo[key]

    def _dec_block(devices):
        """(full-occupancy cost, graph, strategy) of a decode block at
        ``devices`` wide.  The full-mesh block reuses the model's own
        searched strategy — the same graph the colocated baseline
        prices, no redundant search."""
        if devices == n:
            key = ("dec-full", n)
            if key not in _solve_memo:
                sim = Simulator.for_config(config, calibration=calibration,
                                           serving=spec)
                _solve_memo[key] = (sim.simulate(decode_graph,
                                                 decode_strategy),
                                    decode_graph, decode_strategy)
            return _solve_memo[key]
        return _block_search(block_graph, config, devices,
                             serving_armed=True)

    # occupancy-priced decode frames: the SAME block (graph, strategy),
    # re-simulated with only ``slots`` live sequence slots — cache
    # stream scales with the share, weights/collectives do not.
    # Detached simulators (bench-local probes, not the search surface).
    _occ_memo: Dict[Tuple[int, int], float] = {}

    def _dec_at(devices: int, slots: int) -> float:
        key = (devices, slots)
        hit = _occ_memo.get(key)
        if hit is not None:
            return hit
        full, g_blk, s_blk = _dec_block(devices)
        if not math.isfinite(full):
            _occ_memo[key] = math.inf
            return math.inf
        if slots >= spec.max_seqs:
            _occ_memo[key] = full
            return full
        sim = Simulator(
            machine, num_devices=devices, calibration=calibration,
            inference=True, serving=spec.with_occupancy(slots))
        _occ_memo[key] = sim.simulate(g_blk, s_blk)
        return _occ_memo[key]

    def _pre_block(devices):
        return _block_search(prefill_graph, prefill_config, devices,
                             serving_armed=False)

    def _replica_price(width: int, start: int, share: float):
        """Best intra-replica phase placement for a block of ``width``
        devices at arrival ``share``: colocated, or the best
        (prefill a, decode b) split — the two-block search nested at
        replica scope.  Returns (step_s, pre_dev, dec_dev, handoff_s,
        spans_dcn, slots) or None."""
        occ = min(1.0, ls * share)
        slots = max(1, min(spec.max_seqs,
                           int(round(occ * spec.max_seqs))))
        pre_load = ls * share * load_pre
        t_dec = _dec_at(width, slots)
        t_pre_w, _, _ = _pre_block(width)
        if not (math.isfinite(t_dec) and math.isfinite(t_pre_w)):
            return None
        best = (t_dec + pre_load * (t_pre_w / L), 0, width, 0.0, False)
        for a, b in _budget_pairs(width):
            t_pre_a, _, _ = _pre_block(a)
            if not math.isfinite(t_pre_a):
                continue
            t_dec_b = _dec_at(b, slots)
            if not math.isfinite(t_dec_b):
                continue
            spans = ((start + a + b - 1) // dph
                     > (start + a - 1) // dph)
            bytes_pf = bpt * pre_load
            if spans:
                handoff = (bytes_pf / machine.dcn_bandwidth
                           + machine.dcn_latency)
            else:
                handoff = (bytes_pf / machine.ici_bandwidth
                           + machine.ici_latency)
            cand = max(t_dec_b, pre_load * (t_pre_a / L)) + handoff
            if cand < best[0]:
                best = (cand, a, b, handoff, spans)
        return best + (slots,)

    def _price(widths, fractions):
        """(cost_s, per_class_p99_s, replica details) for one
        (partition, routing) candidate, or None when any loaded block
        is infeasible."""
        k = len(widths)
        starts = [sum(widths[:i]) for i in range(k)]
        shares = [sum(wt[c["name"]] * fractions[c["name"]][r]
                      for c in classes)
                  for r in range(k)]
        details = []
        for r in range(k):
            priced = _replica_price(widths[r], starts[r], shares[r])
            if priced is None:
                return None
            details.append(priced)
        per_class: Dict[str, float] = {}
        for c in classes:
            pri = int(c.get("priority", 0))
            worst = 0.0
            for r in range(k):
                if fractions[c["name"]][r] <= 1e-12:
                    continue
                # priority admission: only equal-or-higher priority
                # traffic on this replica delays class c
                u = ls * sum(
                    wt[cc["name"]] * fractions[cc["name"]][r]
                    for cc in classes
                    if int(cc.get("priority", 0)) >= pri)
                u = min(U_CAP, u)
                lat = details[r][0] * (1.0 + u / (1.0 - u))
                worst = max(worst, lat)
            if worst == 0.0:
                return None  # class routed nowhere: illegal candidate
            per_class[c["name"]] = worst
        cost = sum(wt[c["name"]] * per_class[c["name"]] for c in classes)
        return cost, per_class, starts, shares, details

    # ---- enumerate partitions × routing policies -------------------------
    best_single = None
    best_fleet = None
    for widths in _partitions(n, max_k):
        k = len(widths)
        speeds = []
        feasible = True
        for w in widths:
            full, _, _ = _dec_block(w)
            if not math.isfinite(full):
                feasible = False
                break
            speeds.append(full)
        if not feasible:
            continue
        for policy, fractions in _routing_candidates(classes, speeds):
            priced = _price(widths, fractions)
            if priced is None:
                continue
            cand = (priced[0], k, widths, policy, fractions, priced)
            if k == 1:
                if best_single is None or cand[0] < best_single[0]:
                    best_single = cand
            elif best_fleet is None or cand[0] < best_fleet[0]:
                best_fleet = cand

    if best_single is None:
        return None
    if best_fleet is None:
        best_fleet = best_single
    margin = max(0.0, config.search_improvement_margin)
    adopted = (best_fleet[1] > 1
               and best_fleet[0] < best_single[0] * (1.0 - margin))
    chosen = best_fleet if adopted else best_single
    cost, k, widths, policy, fractions, priced = chosen
    _, per_class, starts, shares, details = priced

    replicas = []
    for r in range(k):
        step_s, a, b, handoff, spans, slots = details[r]
        _, g_dec, s_dec = _dec_block(b if a else widths[r])
        pre_s, g_pre, s_pre = (None, None, None)
        if a:
            _, g_pre, s_pre = _pre_block(a)
        replicas.append(FleetReplica(
            index=r, devices=widths[r], start=starts[r],
            prefill_devices=a, decode_devices=b if a else widths[r],
            share=shares[r], occupancy_slots=slots, step_s=step_s,
            handoff_s=handoff, spans_dcn=spans,
            strategy=s_dec or {}, graph=g_dec,
            prefill_strategy=s_pre or {}, prefill_graph=g_pre,
        ))
    routing = {c["name"]: tuple(fractions[c["name"]]) for c in classes}
    single_per_class = best_single[5][1]
    proposal = FleetProposal(
        num_devices=n, replicas=tuple(replicas), routing=routing,
        routing_policy=policy, single_cost_s=best_single[0],
        fleet_cost_s=best_fleet[0], per_class_p99_s=dict(per_class),
        single_per_class_p99_s=dict(single_per_class), adopted=adopted,
        max_seqs=spec.max_seqs, page_size=spec.page_size,
        pages_per_seq=spec.pages_per_seq, offered_load=offered,
        load_scale=float(load_scale),
        slo_classes=tuple(dict(c) for c in classes),
    )
    if adopted:
        # always-on legality gate (SHD166/167 + per-block flat lint):
        # an adopted fleet that fails is a search bug
        from flexflow_tpu.analysis import (
            AnalysisError,
            emit_findings,
            errors_only,
            lint_fleet,
        )

        blocks = [(rep.graph, rep.strategy, rep.decode_devices)
                  for rep in replicas]
        bad = errors_only(lint_fleet(decode_graph, proposal.to_meta(),
                                     config, replica_blocks=blocks))
        if bad:
            emit_findings(bad)
            raise AnalysisError(
                "fleet search produced an illegal N-block placement",
                bad)
    BUS.emit(
        "search.fleet", adopted=adopted, replicas=k,
        single_ms=round(best_single[0] * 1e3, 6),
        fleet_ms=round(best_fleet[0] * 1e3, 6),
        policy=policy, partition=list(widths),
        per_class_ms={c: round(v * 1e3, 6)
                      for c, v in sorted(per_class.items())},
        blocks=[rep.to_meta() for rep in replicas],
        routing={c: [round(f, 6) for f in fr]
                 for c, fr in sorted(routing.items())},
        load_scale=round(float(load_scale), 6),
    )
    from flexflow_tpu.utils.logging import SEARCH_LOG as log

    log.log(
        f"fleet search: {k} replica(s) {list(widths)} policy={policy} "
        f"modeled {cost * 1e3:.4f} ms weighted per-class p99 vs "
        f"single-replica {best_single[0] * 1e3:.4f} ms — "
        f"{'ADOPTED' if adopted else 'single replica stays optimal'}"
    )
    return proposal
