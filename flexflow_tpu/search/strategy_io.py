"""Strategy export/import (reference: src/runtime/strategy.cc:26-197,
--export-strategy/--import-strategy, config.h:140-143).

Format: JSON mapping op name -> {"dims": [...], "replica": r}.  Keyed
by op NAME (stable across runs with deterministic name generation)
rather than guid so strategies transfer between processes.

A reserved ``"__meta__"`` entry (never a legal op name key for
``import_strategy``, which only reads names present in the graph)
carries run provenance: the simulator's predicted step breakdown at
export time and — via ``attach_meta`` after training — the measured
DriftReport, so a strategy file records both what the search promised
and what execution delivered.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

from flexflow_tpu.core.graph import Graph
from flexflow_tpu.core.machine import MachineView

META_KEY = "__meta__"


def export_strategy(
    path: str,
    graph: Graph,
    strategy: Dict[int, MachineView],
    meta: Optional[dict] = None,
) -> None:
    out = {}
    for guid, mv in strategy.items():
        node = graph.nodes.get(guid)
        if node is None:
            continue
        if node.op.name in out:
            raise ValueError(
                f"duplicate op name {node.op.name!r}: strategies are keyed "
                "by name — give layers unique names to export"
            )
        out[node.op.name] = {
            "dims": list(mv.dim_degrees),
            "replica": mv.replica_degree,
            "start": mv.start_part,
        }
    if meta:
        out[META_KEY] = meta
    with open(path, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)


def import_strategy(path: str, graph: Graph) -> Dict[int, MachineView]:
    with open(path) as f:
        data = json.load(f)
    strategy: Dict[int, MachineView] = {}
    for node in graph.topo_order():
        if node.op.name in data:
            d = data[node.op.name]
            strategy[node.guid] = MachineView(
                dim_degrees=tuple(d["dims"]),
                replica_degree=d.get("replica", 1),
                start_part=d.get("start", 0),
            )
    return strategy


def read_meta(path: str) -> dict:
    """The ``__meta__`` provenance block of an exported strategy file
    ({} when absent)."""
    with open(path) as f:
        return json.load(f).get(META_KEY, {})


def attach_meta(path: str, **updates) -> dict:
    """Merge ``updates`` into the strategy file's ``__meta__`` block in
    place (model.fit persists the post-training DriftReport next to
    the strategy this way).  Returns the merged block."""
    with open(path) as f:
        data = json.load(f)
    meta = data.setdefault(META_KEY, {})
    meta.update(updates)
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
    return meta
