"""Strategy export/import (reference: src/runtime/strategy.cc:26-197,
--export-strategy/--import-strategy, config.h:140-143).

Format: JSON mapping op name -> {"dims": [...], "replica": r}.  Keyed
by op NAME (stable across runs with deterministic name generation)
rather than guid so strategies transfer between processes.
"""

from __future__ import annotations

import json
from typing import Dict

from flexflow_tpu.core.graph import Graph
from flexflow_tpu.core.machine import MachineView


def export_strategy(path: str, graph: Graph, strategy: Dict[int, MachineView]) -> None:
    out = {}
    for guid, mv in strategy.items():
        node = graph.nodes.get(guid)
        if node is None:
            continue
        if node.op.name in out:
            raise ValueError(
                f"duplicate op name {node.op.name!r}: strategies are keyed "
                "by name — give layers unique names to export"
            )
        out[node.op.name] = {
            "dims": list(mv.dim_degrees),
            "replica": mv.replica_degree,
            "start": mv.start_part,
        }
    with open(path, "w") as f:
        json.dump(out, f, indent=2, sort_keys=True)


def import_strategy(path: str, graph: Graph) -> Dict[int, MachineView]:
    with open(path) as f:
        data = json.load(f)
    strategy: Dict[int, MachineView] = {}
    for node in graph.topo_order():
        if node.op.name in data:
            d = data[node.op.name]
            strategy[node.guid] = MachineView(
                dim_degrees=tuple(d["dims"]),
                replica_degree=d.get("replica", 1),
                start_part=d.get("start", 0),
            )
    return strategy
