"""Series-parallel decomposition of the PCG — generalized graph cuts
for production-scale search.

PR 7's ``chain_optimize`` decomposes *chain-structured* graphs: it cuts
at single-node bottlenecks (articulation nodes every source→sink path
crosses).  A multi-branch MoE trunk, a persistent-skip stack, or a
disaggregated prefill/decode placement graph has NO such bottleneck —
every interior node is bypassed by some path — and used to fall back to
the binary recursion, which degenerates to a whole-graph brute
force/greedy past the native DP ceiling (the mystery thousand-node
slowdown ROADMAP item 4 names).

This module generalizes the cut: a **frontier cut** at topo position
``p`` is the set of prefix nodes (``topo[0..p]``) that still feed the
suffix.  Its *width* is the number of such nodes.  A width-1 frontier
cut whose crossing node sits at ``p`` is exactly a bottleneck — the
chain decomposition is the degenerate case — and a width-k cut
(``k <= MAX_CUT_WIDTH``, the same bounded-boundary discipline as the
placed executor's MAX_CROSSING_TENSORS) cuts the shapes bottleneck
finding cannot: the segment DP then pins a *tuple* of boundary views,
one per crossing node, instead of a single view.

The scan is one O(nodes + edges) sweep (``frontier_widths``); cut
selection (``find_series_cuts``) first applies the EXACT bottleneck
spacing rule of PR 7's chain path — so chain-shaped graphs produce
bit-identical cuts, pins, and therefore solves (test-enforced against
the retained ``chain_optimize`` oracle) — and only reaches for wider
frontiers when the chain rule finds no usable chain.  Parallel
composition (disconnected components) is handled by the driver/DP
layers as before; segments the cuts produce re-enter the driver's
recursion, so a still-large segment decomposes again — the recursive
SP-tree build, expressed through the existing memoized recursion
instead of an explicit tree datatype.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from flexflow_tpu.core.graph import Edge, Graph, Node

# bounded-width cut ceiling: the widest boundary-view tuple the segment
# DP will pin.  Mirrors the placed executor's MAX_CROSSING_TENSORS
# discipline (compiler/placement_lowering.py) — a cut wider than this
# costs more in boundary enumeration than the split saves.
MAX_CUT_WIDTH = 8

# boundary-view tuples enumerated per cut: the full per-node
# boundary_views product when it fits, else index-aligned "profiles"
# (pure-DP across the cut, pure-TP across the cut, ...) — the product
# of k 4-view sets is 4^k, and the DP is states^2 per segment.
MAX_CUT_TUPLES = 16

# minimum usable cuts for the generalized path (the chain rule keeps
# PR 7's own >= 4 floor; two wide cuts already bound every segment to
# ~a third of the graph, which the recursion decomposes further)
MIN_SP_CUTS = 2


@dataclass(frozen=True)
class SeriesCut:
    """A frontier cut AFTER topo position ``pos``: ``crossing`` is the
    sorted tuple of prefix guids with >=1 edge into the suffix."""

    pos: int
    crossing: Tuple[int, ...]

    @property
    def width(self) -> int:
        return len(self.crossing)


def frontier_widths(graph: Graph) -> Tuple[List[Node], List[int]]:
    """(topo order, per-position frontier width): ``widths[i]`` is the
    number of distinct nodes in ``topo[0..i]`` that still feed
    ``topo[i+1..]``.  One O(nodes + edges) sweep — the per-node pending
    out-edge count drops as consumers enter the prefix."""
    topo = graph.topo_order()
    pending = {g: len(graph.out_edges[g]) for g in graph.nodes}
    live = 0
    widths: List[int] = []
    for node in topo:
        g = node.guid
        for e in graph.in_edges[g]:
            pending[e.src] -= 1
            if pending[e.src] == 0:
                live -= 1
        if pending[g] > 0:
            live += 1
        widths.append(live)
    return topo, widths


def _crossing_at(graph: Graph, topo: List[Node],
                 positions: Sequence[int]) -> Dict[int, Tuple[int, ...]]:
    """The crossing sets for selected cut ``positions`` — a second
    incremental sweep that snapshots the live frontier only where a cut
    was chosen."""
    want = set(positions)
    pending = {g: len(graph.out_edges[g]) for g in graph.nodes}
    frontier: set = set()
    out: Dict[int, Tuple[int, ...]] = {}
    for i, node in enumerate(topo):
        g = node.guid
        for e in graph.in_edges[g]:
            pending[e.src] -= 1
            if pending[e.src] == 0:
                frontier.discard(e.src)
        if pending[g] > 0:
            frontier.add(g)
        if i in want:
            out[i] = tuple(sorted(frontier))
    return out


def chain_cuts(graph: Graph, fixed, threshold: int,
               ) -> Optional[List[SeriesCut]]:
    """PR 7's bottleneck spacing rule, verbatim, expressed as width-1
    SeriesCuts: >= 8 un-pinned bottlenecks, cuts at every
    ``threshold``-spaced bottleneck topo position (never the last
    node), >= 4 cuts or None.  ``find_series_cuts`` tries this FIRST so
    chain-shaped graphs keep bit-identical cuts to the chain path."""
    bottlenecks = [b for b in graph.bottlenecks() if b.guid not in fixed]
    if len(bottlenecks) < 8:
        return None
    order = {n.guid: i for i, n in enumerate(graph.topo_order())}
    cuts: List[SeriesCut] = []
    last = 0
    for bn in bottlenecks:
        at = order[bn.guid]
        if at - last >= threshold and at < len(order) - 1:
            cuts.append(SeriesCut(pos=at, crossing=(bn.guid,)))
            last = at
    if len(cuts) < 4:
        return None
    return cuts


def find_series_cuts(graph: Graph, fixed, threshold: int,
                     max_width: int = MAX_CUT_WIDTH,
                     ) -> Tuple[Optional[List[SeriesCut]], str]:
    """(cuts, mode) for ``graph``: mode ``"chain"`` when the PR 7
    bottleneck rule applies (width-1 cuts, bit-identical to
    chain_optimize), ``"sp"`` for bounded-width frontier cuts, and
    ``(None, reason)`` when neither yields a usable series
    decomposition (the caller falls back to binary recursion and emits
    the reason on the ``search.decompose`` obs event)."""
    got = chain_cuts(graph, fixed, threshold)
    if got is not None:
        return got, "chain"
    topo, widths = frontier_widths(graph)
    n = len(topo)
    # windowed min-width selection: inside each [last+threshold,
    # last+2*threshold) window take the narrowest eligible frontier —
    # narrow cuts mean small boundary-view tuples, so prefer them even
    # a few positions later
    positions: List[int] = []
    last = 0
    i = 0
    while i < n - 1:
        if i - last < threshold:
            i += 1
            continue
        best_pos, best_w = None, max_width + 1
        j = i
        while j < n - 1 and j - last < 2 * threshold:
            if 1 <= widths[j] < best_w:
                best_pos, best_w = j, widths[j]
            j += 1
        if best_pos is None:
            # no bounded frontier in this window: slide forward
            i = j
            last = j - threshold
            continue
        positions.append(best_pos)
        last = best_pos
        i = best_pos + 1
    if len(positions) < MIN_SP_CUTS:
        return None, "no_bounded_cuts"
    crossing = _crossing_at(graph, topo, positions)
    cuts = [SeriesCut(pos=p, crossing=crossing[p]) for p in positions]
    cuts = [c for c in cuts
            if c.crossing and not any(g in fixed for g in c.crossing)]
    if len(cuts) < MIN_SP_CUTS:
        return None, "cuts_pinned"
    return cuts, "sp"


def split_series(graph: Graph, cuts: List[SeriesCut],
                 ) -> Optional[List[Tuple[Graph, Tuple[int, ...],
                                          Tuple[int, ...]]]]:
    """Split ``graph`` into len(cuts)+1 segments: segment ``i`` holds
    the topo interval between cut ``i-1`` (exclusive) and cut ``i``
    (inclusive), PLUS cut ``i-1``'s crossing nodes replayed as sources
    carrying only their into-segment edges — the multi-node analogue of
    ``split_at_node`` keeping the bottleneck on both sides.  Returns
    ``[(segment, in_crossing, out_crossing)]`` with ``()`` at the chain
    ends, or None when an edge skips over a cut entirely (a crossing
    node must catch every prefix→suffix edge by construction, so None
    here means the cut list is stale for this graph)."""
    topo = graph.topo_order()
    pos = {n.guid: i for i, n in enumerate(topo)}
    bounds = [-1] + [c.pos for c in cuts] + [len(topo) - 1]
    crossings = [()] + [c.crossing for c in cuts] + [()]
    segments = []
    for i in range(len(bounds) - 1):
        lo, hi = bounds[i], bounds[i + 1]
        in_cross = crossings[i]
        interior = {n.guid for n in topo[lo + 1: hi + 1]}
        seg_nodes = set(interior)
        seg_nodes.update(in_cross)
        seg = Graph()
        seg._next_guid = graph._next_guid
        # sorted insertion: segment node/edge dict order must be
        # deterministic (and match the ascending-guid order the chain
        # path's iterative split_at_node preserves) — downstream float
        # accumulation orders depend on it, and the chain bit-identity
        # gate compares exact floats
        for g in sorted(seg_nodes):
            seg.add_node(graph.nodes[g])
        for g in sorted(seg_nodes):
            for e in graph.out_edges[g]:
                if e.dst in interior:
                    seg.out_edges[e.src].append(e)
                    seg.in_edges[e.dst].append(e)
        # sanity: every interior in-edge must originate inside the
        # segment (interior or the in-crossing) — otherwise an edge
        # skipped the cut and the decomposition is unsound
        for g in interior:
            for e in graph.in_edges[g]:
                if e.src not in seg_nodes:
                    return None
        segments.append((seg, in_cross, crossings[i + 1]))
    return segments


def boundary_tuples(views_per_guid: Dict[int, list],
                    crossing: Tuple[int, ...],
                    carry: Optional[Dict[int, object]] = None,
                    max_tuples: int = MAX_CUT_TUPLES) -> List[tuple]:
    """Boundary-view tuples for one cut, aligned with ``crossing``
    order.  ``carry`` pins guids shared with the previous cut to their
    already-chosen view (a persistent-skip node crossing many cuts must
    keep ONE view, or consecutive segment solves would disagree about
    it).  Full cartesian product when it fits ``max_tuples`` —
    degenerating to exactly the per-node boundary_views list at width
    1 — else index-aligned profiles (all-DP, all-TP, ..., all-trivial
    across the cut)."""
    lists = []
    for g in crossing:
        if carry is not None and g in carry:
            lists.append([carry[g]])
        else:
            lists.append(list(views_per_guid[g]))
    total = 1
    for lst in lists:
        total *= max(1, len(lst))
    if total <= max_tuples:
        import itertools

        return [tuple(t) for t in itertools.product(*lists)]
    depth = max(len(lst) for lst in lists)
    out = []
    seen = set()
    for k in range(depth):
        t = tuple(lst[min(k, len(lst) - 1)] for lst in lists)
        if t not in seen:
            seen.add(t)
            out.append(t)
    return out[:max_tuples]


def merge_segment_into(acc_g: Graph, acc_s, post_g: Graph, post_s,
                       shared) -> None:
    """Append one solved segment into the merge accumulator — the
    multi-node generalization of the driver's ``_merge_split``
    (original nodes are disjoint apart from the shared crossing;
    rewrite-inserted guids may collide between segments and are
    renumbered on the post side).  In place: the repeated-copy merge
    was O(n^2) over a 660-segment 10k-node replay.  ``acc_g`` must be
    OWNED by the caller (never a cached segment object), and node/edge
    insertion order matches the chain path's iterative merge —
    downstream float accumulation orders, and therefore the chain
    bit-identity gate, depend on it."""
    if post_g._next_guid > acc_g._next_guid:
        acc_g._next_guid = post_g._next_guid
    remap: Dict[int, int] = {}
    for guid in post_g.nodes:
        if guid in acc_g.nodes and guid not in shared:
            remap[guid] = acc_g._next_guid
            acc_g._next_guid += 1
    for guid, n in post_g.nodes.items():
        ng = remap.get(guid, guid)
        if ng not in acc_g.nodes:
            acc_g.nodes[ng] = n if ng == guid else Node(ng, n.op)
            acc_g.in_edges.setdefault(ng, [])
            acc_g.out_edges.setdefault(ng, [])
    for guid in post_g.nodes:
        for e in post_g.out_edges[guid]:
            ne = Edge(
                remap.get(e.src, e.src),
                remap.get(e.dst, e.dst),
                e.src_idx,
                e.dst_idx,
            )
            acc_g.out_edges[ne.src].append(ne)
            acc_g.in_edges[ne.dst].append(ne)
    for guid, v in post_s.items():
        acc_s[remap.get(guid, guid)] = v
    acc_g._invalidate()
