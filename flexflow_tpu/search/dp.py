"""DP over graph splits — Unity's inner loop.

Re-implements the algorithm of SearchHelper::graph_cost
(reference: src/runtime/graph.cc:79-295, 1276-1526): given a *fixed*
PCG, find the min-cost MachineView assignment by

* sequence-splitting at bottleneck nodes and enumerating the split
  node's views (graph.cc:96-159) — several bottleneck candidates are
  tried and memoization makes the overlap cheap,
* nonsequence-splitting independent components over SEQUENTIAL /
  VERTICAL resource partitions with real device-block offsets
  (graph.cc:161-295 execute_nonsequence_split; MachineResource
  start_gpu_id becomes MachineView.start_part),
* brute-forcing small leaves against the event-driven simulator,
* memoizing by (graph hash, fixed-view constraints, device budget,
  placement offset) (graph.cc:1356 dp_state hash).

One deliberate difference: the reference's views place ops on physical
device boxes; here views are degree vectors plus a contiguous-block
offset, and XLA/GSPMD realizes placement (degrees only — offsets are a
simulator-level planning notion, see MachineView docstring).
"""

from __future__ import annotations

import dataclasses
import itertools
import math
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.core.graph import Graph, Node
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.obs.events import BUS
from flexflow_tpu.obs.metrics import METRICS
from flexflow_tpu.search.simulator import Simulator
from flexflow_tpu.search.views import boundary_views, candidate_views

# cached metric handles (registry objects are stable across reset())
_MEMO_HITS = METRICS.counter("dp.memo_hits")
_MEMO_MISSES = METRICS.counter("dp.memo_misses")
_NATIVE_HITS = METRICS.counter("dp.native_hits")
_CTX_PATCHES = METRICS.counter("dp.ctx_patch_hits")
_CTX_REBUILDS = METRICS.counter("dp.ctx_rebuilds")
_DP_ROWS_SERVED = METRICS.counter("dp.rows_served")

# persistent DP memo: rows below this node count are not worth the
# stable-digest hashing (tiny leaves re-solve in microseconds, and the
# small-segment storm would bloat COST_CACHE.json for nothing)
DP_PERSIST_MIN_NODES = 6


def _ctx_check_enabled() -> bool:
    """FLEXFLOW_TPU_DELTA_CHECK=1 also arms the ctx-patch oracle: every
    PATCHED native-DP ctx is re-derived by the full build and asserted
    identical (same topo order, same packed view/candidate arrays) —
    the incremental-assembly contract as a runtime check, mirroring the
    delta-simulation oracle in search/simulator.py."""
    import os

    return os.environ.get("FLEXFLOW_TPU_DELTA_CHECK", "") not in ("", "0")


CTX_CHECK = _ctx_check_enabled()


def _same_stamp(a, b) -> bool:
    """Element-wise stamp comparison: numbers by value, everything else
    by identity (id() of a freed CostModel can be reallocated — holding
    the references in the stamp prevents reuse, `is` detects swaps)."""
    return len(a) == len(b) and all(
        x is y or x == y if isinstance(x, (int, bool, float)) else x is y
        for x, y in zip(a, b)
    )


def _assert_ctx_equal(patched, rebuilt) -> None:
    """The ctx-patch oracle: a PATCHED native-DP ctx must be
    indistinguishable from a full rebuild — same topo order, same
    budgets, same packed per-view cost/candidate arrays, same edge
    matrices.  The C engine is a deterministic function of these
    inputs, so array equality is the whole contract."""
    import numpy as _np

    assert [n.guid for n in patched["topo"]] == \
        [n.guid for n in rebuilt["topo"]], "ctx patch: topo order diverged"
    assert patched["budgets"] == rebuilt["budgets"], \
        "ctx patch: budget set diverged"
    a, b = patched["pack"], rebuilt["pack"]
    assert set(a) == set(b), "ctx patch: pack keys diverged"
    for k in a:
        va, vb = a[k], b[k]
        if isinstance(va, _np.ndarray):
            assert va.shape == vb.shape and bool((va == vb).all()), (
                f"ctx patch: packed array {k!r} diverged")
        else:
            assert va == vb, f"ctx patch: pack entry {k!r} diverged"
    ea, eb = patched["edges"], rebuilt["edges"]
    assert len(ea) == len(eb), "ctx patch: edge count diverged"
    for (sa, da, ga, ma), (sb, db, gb, mb) in zip(ea, eb):
        assert (sa, da, ga) == (sb, db, gb), "ctx patch: edge diverged"
        assert ma is mb or bool((ma == mb).all()), \
            "ctx patch: edge matrix diverged"


Strategy = Dict[int, MachineView]

# canonical strategy: ((node_structural_hash, view), ...) ordered by
# (hash, guid) at store time — guid-free, remappable onto isomorphic
# graphs (see Graph.node_hashes)
CanonStrategy = Tuple[Tuple[int, MachineView], ...]


def canon_fixed_views(graph: Graph, fixed: Strategy) -> Tuple:
    """Guid-free canonical form of pinned boundary views — the shared
    memo-key component for the DP memo and the driver's segment cache
    (must stay in lock-step; both import this)."""
    nh = graph.node_hashes()
    return tuple(
        sorted(
            (nh[g], v.dim_degrees, v.replica_degree, v.start_part)
            for g, v in fixed.items()
            if g in graph.nodes
        )
    )


def canonicalize_strategy(graph: Graph, strategy: Strategy) -> CanonStrategy:
    nh = graph.node_hashes()
    order = sorted(
        (g for g in strategy if g in graph.nodes), key=lambda g: (nh[g], g)
    )
    return tuple((nh[g], strategy[g]) for g in order)


def reconstruct_strategy(
    graph: Graph, canon: CanonStrategy, fixed: Optional[Strategy] = None
) -> Optional[Strategy]:
    """Map a canonical strategy onto ``graph``'s guids.  Nodes sharing a
    structural hash are interchangeable; ``fixed`` guids are pinned to
    their required views first (a group sibling takes the other view).
    Returns (strategy, ambiguous): ``ambiguous`` is True when any hash
    group holds >1 node — the in-group guid-order pairing is then not
    guaranteed to follow a single isomorphism across groups, so the
    caller must re-simulate rather than trust the cached cost.  Strategy
    is None when the canonical form does not fit at all (hash
    collision — caller recomputes)."""
    return _pair_views(graph, graph.node_hashes(), canon, fixed)


def _pair_views(graph: Graph, nh, canon, fixed: Optional[Strategy]):
    """The ONE guid-pairing rule shared by the in-process memo
    (``reconstruct_strategy``, int node hashes) and the persistent DP
    memo (stable hex digests): group guids by structural key, honor
    ``fixed`` pins first, pair the rest in sorted-guid order.  Both
    layers MUST pair identically or a warm serve could diverge from the
    in-process replay of the same row."""
    groups: Dict[int, List[int]] = {}
    for g in sorted(graph.nodes):
        groups.setdefault(nh[g], []).append(g)
    views: Dict[int, List[MachineView]] = {}
    for h, v in canon:
        views.setdefault(h, []).append(v)
    strategy: Strategy = {}
    fixed = fixed or {}
    ambiguous = False
    for h, guids in groups.items():
        vs = views.get(h)
        if vs is None or len(vs) != len(guids):
            return None, False
        if len(guids) > 1:
            ambiguous = True
        vs = list(vs)
        rest = []
        for g in guids:
            want = fixed.get(g)
            if want is not None:
                try:
                    vs.remove(want)
                except ValueError:
                    return None, False
                strategy[g] = want
            else:
                rest.append(g)
        for g, v in zip(rest, vs):
            strategy[g] = v
    return strategy, ambiguous


def encode_strategy_rows(graph: Graph, strategy: Strategy):
    """The persisted memo-row strategy encoding shared by the dp-row
    and sp-row layers: ``[[stable node digest, degrees, replica,
    start], ...]`` sorted by (digest, guid).  Returns None when
    ``strategy`` does not cover the graph exactly (a partial strategy
    is not a persistable result).  MUST stay the decode's inverse —
    fflint's _lint_digest_row_layer lints the same shape."""
    snh = graph.stable_node_digests()
    rows = [
        [snh[g], list(strategy[g].dim_degrees),
         int(strategy[g].replica_degree), int(strategy[g].start_part)]
        for g in sorted(strategy, key=lambda g: (snh.get(g, ""), g))
        if g in graph.nodes
    ]
    if len(rows) != graph.num_nodes:
        return None
    return rows


def decode_strategy_rows(row: dict):
    """(cost, canonical digest-keyed strategy) from a persisted memo
    row, or None on any malformation — the reader side of
    ``encode_strategy_rows``, shared by the dp-row and sp-row serves
    (a corrupt row is a miss, never a crash or a wrong serve)."""
    try:
        cost = float(row["cost"])
        canon = tuple(
            (h, MachineView(tuple(int(x) for x in dims), int(rep),
                            int(st)))
            for h, dims, rep, st in row["strategy"]
        )
    except (KeyError, TypeError, ValueError):
        return None
    return cost, canon


class SearchHelper:
    def __init__(
        self,
        simulator: Simulator,
        num_devices: int,
        leaf_threshold: int = 4,
        max_views_per_op: int = 16,
        max_bottleneck_tries: int = 2,
    ):
        self.sim = simulator
        self.num_devices = num_devices
        self.leaf_threshold = leaf_threshold
        self.max_views_per_op = max_views_per_op
        self.max_bottleneck_tries = max_bottleneck_tries
        self.memo: Dict[Tuple, Tuple[float, Strategy]] = {}
        self._views_cache: Dict[Tuple, List[MachineView]] = {}
        # native-DP digests shared across every graph this helper
        # searches (rewritten variants repeat the same op signatures);
        # cleared when the calibration table's version moves on
        # (_node_digest), so stale generations never accumulate
        self._node_digest_cache: Dict[Tuple, dict] = {}
        self._node_digest_version: object = None
        self._edge_matrix_cache: Dict[Tuple, object] = {}
        # diagnostic: how often the greedy fallback decided a subgraph —
        # zero on the model zoo (tests assert this; VERDICT r1 weak #2)
        self.greedy_hits = 0
        # memo-cache effectiveness (mirrored into the global obs
        # metrics registry; the driver emits them as dp.summary)
        self.memo_hits = 0
        self.memo_misses = 0
        self.native_hits = 0
        # incremental ctx assembly + persistent DP memo + segment
        # stamping effectiveness (search.perf: ctx_patch_hits/
        # ctx_rebuilds/dp_rows_served/segments_stamped — the driver's
        # _UnityOptimizer increments segments_stamped on cache remaps)
        self.ctx_patch_hits = 0
        self.ctx_rebuilds = 0
        self.dp_rows_served = 0
        self.segments_stamped = 0
        # persisted sp-segment memo rows served (driver._serve_sp_row:
        # whole SP-segment solves — substitution search included —
        # answered from the cost cache's sp-row layer)
        self.sp_rows_served = 0
        # joint strategy x comm-plan co-search (search/comm_plan.py):
        # when the driver binds a JointPricer here, every cost this
        # helper GROUNDS (the _finish re-validation, its DP floor, the
        # ambiguous-pairing re-simulations, the native engine's
        # winners) is priced in the joint exposed-comm currency — the
        # enumeration interiors (split bounds, leaf brute force,
        # native DP) keep ranking in the fast legacy scalar currency
        # and the joint gate re-prices their winners.  None (default)
        # keeps every path bit-identical to the sequential pipeline.
        self.joint = None
        # depth gate mirroring the driver's sequence_optimize gate: the
        # joint currency grounds only the TOP-level graph_cost query
        # (the whole candidate graph) — an interior split segment
        # priced jointly in isolation is charged the full exposed sync
        # tail the merged graph hides under the other segments'
        # backward, so joint-priced segments compose into provably
        # worse merges (and every novel segment signature would pay an
        # unmemoized plan sweep).  Interior recursion suspends the
        # pricer; the top-level _finish re-prices the composed winner
        # jointly.
        self._joint_depth = 0

    def _price(self, graph, strategy) -> float:
        """Ground-truth pricing of one (graph, strategy): the joint
        exposed-comm currency under co-search, the legacy scalar
        simulation otherwise."""
        if self.joint is not None:
            return self.joint.price(self.sim, graph, strategy)
        return self.sim.simulate(graph, strategy)

    @contextmanager
    def joint_scope(self, top: bool):
        """THE depth-gate rule, shared by every gated recursion
        (``graph_cost``/``graph_cost_only`` here, the driver's
        ``sequence_optimize``): interior levels suspend the joint
        pricer — a segment priced jointly in isolation is charged the
        exposed sync tail the merged graph hides — and the top level
        keeps it, so composed winners ground jointly exactly once."""
        saved = self.joint
        if not top:
            self.joint = None
        try:
            yield
        finally:
            self.joint = saved

    # ------------------------------------------------------------------
    def _views(self, node: Node, budget: int, start: int = 0) -> List[MachineView]:
        key = (node.op.signature(), budget, start)
        if key not in self._views_cache:
            views = candidate_views(
                node.op, budget, max_views=self.max_views_per_op
            )
            if start:
                views = [dataclasses.replace(v, start_part=start) for v in views]
            self._views_cache[key] = views
        return self._views_cache[key]

    def _bviews(self, node: Node, budget: int, start: int = 0) -> List[MachineView]:
        """Compact diverse view set for split-boundary pinning — the DP
        state count is intervals x boundary-view products, so this stays
        at the reference's ~4-view scale (graph.cc:1778 registers only
        1-D divisor views)."""
        key = ("b", node.op.signature(), budget, start)
        if key not in self._views_cache:
            views = boundary_views(node.op, budget)
            if start:
                views = [dataclasses.replace(v, start_part=start) for v in views]
            self._views_cache[key] = views
        return self._views_cache[key]

    def _fixed_view(self, node: Node, start: int) -> Optional[MachineView]:
        fv = node.op.fixed_machine_view()
        if fv is not None and start:
            fv = dataclasses.replace(fv, start_part=start)
        return fv

    # ------------------------------------------------------------------
    # native DP engine (native/src/dp_engine.cpp): the ENTIRE graph_cost
    # recursion in C++ for the default cost currency — the reference
    # keeps this loop in C++ for the same reason (graph.cc:79-295).
    # Eligibility: no placement-overlap credit (starts are cost-inert in
    # the default currency — the planning mode stays Python) and <=256
    # nodes; every pinned view must exist in the exported view sets.
    # Fusion-cluster ratios are per-(member, own-view) quantities
    # (simulate()'s cluster_scale note) and bake into the exported rows
    # — a cluster-bearing table no longer forces the python path.
    def _native_dp_ctx(self, graph: Graph):
        if self.sim.placement_overlap:
            return None
        if graph.num_nodes > 256 or graph.num_nodes == 0:
            return None
        # staleness stamp: the digest bakes in the graph's structure and
        # THIS helper's costing surface — a mutated graph (graph.hash()
        # changes; Graph._invalidate clears its cache on mutation) or a
        # different machine/device configuration must re-digest
        # strong refs in the stamp compared with `is`: id() of a freed
        # CostModel can be reallocated to a new one and validate a
        # stale digest; holding the reference prevents address reuse
        # outright
        cal = self.sim.cost.calibration
        stamp = (
            graph.hash(), self.num_devices, self.sim.machine,
            self.sim.cost, cal,
            # content fingerprint: the same table OBJECT mutated in
            # place (driver's in-place recalibration pattern, or a
            # same-key re-measurement) must invalidate the ctx, or
            # baked rows keep pre-mutation costs while the python
            # engine sees the new records.  version bumps on EVERY put.
            getattr(cal, "version", -1) if cal is not None else -1,
            self.sim.inference,
            self.leaf_threshold, self.max_bottleneck_tries,
        )

        cached = getattr(graph, "_ndp_ctx", None)
        if cached == "ineligible":
            return None  # hard override (tests force the Python path)
        if cached is not None and _same_stamp(cached[0], stamp):
            return cached[1]  # may be None (= ineligible)
        from flexflow_tpu import native as _native

        if _native.get_lib() is None:
            graph._ndp_ctx = (stamp, None)
            return None
        # incremental assembly: a substitution candidate patches its
        # parent's ctx from the changed-guid seed sets instead of
        # re-deriving every per-node block (the per-pop tier-2 rebuild
        # ROADMAP item 3 names); a failed patch falls back to the full
        # build, and FLEXFLOW_TPU_DELTA_CHECK asserts patched == rebuilt
        ctx = None
        try:
            ctx = self._patch_native_dp(graph, stamp)
        except Exception:
            ctx = None
        if ctx is not None:
            self.ctx_patch_hits += 1
            _CTX_PATCHES.inc()
            if CTX_CHECK:
                _assert_ctx_equal(ctx, self._build_native_dp(graph))
        else:
            try:
                ctx = self._build_native_dp(graph)
            except Exception:
                ctx = None
            self.ctx_rebuilds += 1
            _CTX_REBUILDS.inc()
        graph._ndp_ctx = (stamp, ctx)
        return ctx

    def _node_digest(self, node: Node, budgets: List[int]):
        """Per-op-signature digest shared across every graph this
        helper searches (rewritten variants repeat the same ops): the
        union candidate-view list, per-view (cost row, propagated
        sharding), per-budget candidate/boundary/default index lists,
        and the trivial/fixed view indices."""
        cal = self.sim.cost.calibration
        # digest rows bake per-(op, view) calibration lookups, so an
        # in-place recalibration must re-bake them.  The cache is
        # CLEARED on a version change rather than keyed by it — a
        # version-widened key retains every superseded generation of
        # rows and grows without bound across calibration rounds
        ver = getattr(cal, "version", None) if cal is not None else None
        if self._node_digest_version != ver:
            self._node_digest_cache.clear()
            self._node_digest_version = ver
        sig = node.op.signature()
        hit = self._node_digest_cache.get(sig)
        if hit is not None:
            return hit
        import numpy as _np

        sim = self.sim
        views: List[MachineView] = []
        view_key: Dict[Tuple, int] = {}

        def intern(mv: MachineView) -> int:
            key = (mv.dim_degrees, mv.replica_degree)
            got = view_key.get(key)
            if got is None:
                got = len(views)
                view_key[key] = got
                views.append(
                    dataclasses.replace(mv, start_part=0)
                    if mv.start_part else mv
                )
            return got

        nd = node.op.output_shapes[0].ndim
        shape = node.op.output_shapes[0]
        trivial = intern(MachineView.trivial(nd))
        fv = node.op.fixed_machine_view()
        fixed = intern(fv) if fv is not None else -1
        cand_lists, bview_lists, defaults = [], [], []
        for b in budgets:
            cand_lists.append([intern(v) for v in self._views(node, b)])
            bview_lists.append([intern(v) for v in self._bviews(node, b)])
            # _default_strategy's per-node dp view for this budget
            mv = None
            if nd and 0 in node.op.splittable_output_dims():
                d = b
                while d > 1 and shape.sizes[0] % d != 0:
                    d //= 2
                if d > 1:
                    mv = MachineView.data_parallel(nd, d)
            defaults.append(intern(mv) if mv is not None else trivial)
        nv = len(views)
        rows = _np.zeros((nv, 4), dtype=_np.float64)  # fwd full sync mem
        parts = _np.ones(nv, dtype=_np.int32)
        valid = _np.zeros(nv, dtype=_np.uint8)
        annots: List[Optional[object]] = []
        for vi, mv in enumerate(views):
            osh = sim._propagate(node, mv)
            annots.append(osh)
            if osh is None:
                continue
            rows[vi] = sim._node_costs(node, mv)
            parts[vi] = mv.num_parts
            valid[vi] = 1
        digest = {
            "views": views, "view_key": view_key, "rows": rows,
            "parts": parts, "valid": valid, "annots": annots,
            "cand": cand_lists, "bview": bview_lists,
            "default": defaults, "trivial": trivial, "fixed": fixed,
            # flat per-signature arrays (node-major, budget-minor once
            # concatenated): _pack_native_dp assembles a ctx by
            # concatenating these per node instead of re-flattening
            # python lists per (node, budget) on every build
            "cand_counts": _np.asarray(
                [len(c) for c in cand_lists], dtype=_np.int32),
            "cand_flat": _np.asarray(
                [i for lst in cand_lists for i in lst], dtype=_np.int32),
            "bview_counts": _np.asarray(
                [len(b) for b in bview_lists], dtype=_np.int32),
            "bview_flat": _np.asarray(
                [i for lst in bview_lists for i in lst], dtype=_np.int32),
            "default_arr": _np.asarray(defaults, dtype=_np.int32),
        }
        self._node_digest_cache[sig] = digest
        return digest

    def _edge_matrix(self, src: Node, dst: Node, src_idx: int,
                     dst_idx: int, budgets: List[int]):
        """Baked xfer matrix over the two ops' union view lists —
        a pure function of the endpoint signatures (+ this helper's
        budgets), so isomorphic edges across all searched graphs share
        one bake."""
        key = (src.op.signature(), dst.op.signature(), src_idx, dst_idx)
        hit = self._edge_matrix_cache.get(key)
        if hit is not None:
            return hit
        import numpy as _np

        sim = self.sim
        ds, dd = self._node_digest(src, budgets), self._node_digest(
            dst, budgets)
        shape = src.op.output_shapes[src_idx]
        mat = _np.empty((len(ds["views"]), len(dd["views"])),
                        dtype=_np.float64)
        for svi, s_osh in enumerate(ds["annots"]):
            for dvi, d_osh in enumerate(dd["annots"]):
                if s_osh is None or d_osh is None:
                    mat[svi, dvi] = math.inf
                    continue
                src_annot = (
                    s_osh.outputs[src_idx]
                    if src_idx < len(s_osh.outputs) else None
                )
                dst_annot = (
                    d_osh.inputs[dst_idx]
                    if dst_idx < len(d_osh.inputs) else None
                )
                mat[svi, dvi] = sim.cost.xfer_cost(
                    shape, src_annot, dst_annot)
        self._edge_matrix_cache[key] = mat
        return mat

    def _dp_budgets(self) -> Tuple[List[int], List[int]]:
        cands = sorted(self._budget_cands())
        return sorted(set(cands) | {self.num_devices}), cands

    def _node_block(self, node: Node, budgets: List[int], membership):
        """Per-node assembly unit of the native-DP ctx: the shared
        per-signature digest plus this GRAPH's cluster-scaled cost rows
        (scaling is chain-contextual, so it adjusts a per-graph copy,
        never the digest cache).  ``cm_key`` fingerprints the chain
        context the rows were scaled under — the patch path may reuse a
        block only while it matches."""
        d = self._node_digest(node, budgets)
        rows = d["rows"]
        cm_key = None
        cm = membership.get(node.guid) if membership else None
        if cm is not None:
            cm_key = (tuple(m.guid for m in cm[0]), cm[1])
            rows = rows.copy()
            for vi, mv in enumerate(d["views"]):
                if not d["valid"][vi]:
                    continue
                rows[vi] = self.sim.cluster_scaled_costs(
                    node, mv, tuple(rows[vi]), membership)
        return {"digest": d, "rows": rows, "cm_key": cm_key}

    def _assemble_native_dp(self, graph: Graph, blocks: Dict[int, dict],
                            budgets: List[int], cands: List[int]):
        """Concatenate per-node blocks (topo order) into the packed
        arrays the native engine consumes, upload, and return the ctx.
        The per-(node, budget) candidate/boundary lists ride the
        digests' pre-flattened arrays (``cand_flat``/``bview_flat``), so
        assembly is numpy concatenation instead of the O(nodes x
        budgets) python loops the per-pop rebuild used to pay."""
        import numpy as _np

        from flexflow_tpu import native as _native

        sim = self.sim
        topo = graph.topo_order()
        n = len(topo)
        index = {node.guid: i for i, node in enumerate(topo)}
        guid_rank = {g: r for r, g in enumerate(sorted(graph.nodes))}

        digests = [blocks[node.guid]["digest"] for node in topo]
        rows_list = [blocks[node.guid]["rows"] for node in topo]
        ndp = _native.NativeDPGraph(
            n, self.num_devices, sim.machine.hbm_capacity,
            include_update=not sim.inference,
            leaf_threshold=self.leaf_threshold,
            max_tries=self.max_bottleneck_tries,
        )
        node_off = _np.zeros(n + 1, dtype=_np.int32)
        _np.cumsum([len(d["views"]) for d in digests], out=node_off[1:])
        pack = {
            "node_off": node_off,
            "fwd": _np.concatenate([r[:, 0] for r in rows_list]),
            "full": _np.concatenate([r[:, 1] for r in rows_list]),
            "sync": _np.concatenate([r[:, 2] for r in rows_list]),
            "mem": _np.concatenate([r[:, 3] for r in rows_list]),
            "parts": _np.concatenate([d["parts"] for d in digests]),
            "valid": _np.concatenate([d["valid"] for d in digests]),
            "fixed": _np.asarray([d["fixed"] for d in digests],
                                 dtype=_np.int32),
            "trivial": _np.asarray([d["trivial"] for d in digests],
                                   dtype=_np.int32),
            "guid_rank": _np.asarray(
                [guid_rank[node.guid] for node in topo], dtype=_np.int32),
        }
        ndp.set_views(node_off, pack["fwd"], pack["full"], pack["sync"],
                      pack["mem"], pack["parts"], pack["valid"])
        ndp.set_node_meta(pack["fixed"], pack["trivial"], pack["guid_rank"])
        ndp.set_budgets(budgets, cands)
        nb = len(budgets)
        cand_counts = _np.concatenate([d["cand_counts"] for d in digests])
        bview_counts = _np.concatenate([d["bview_counts"] for d in digests])
        cand_off = _np.zeros(n * nb + 1, dtype=_np.int64)
        bview_off = _np.zeros(n * nb + 1, dtype=_np.int64)
        _np.cumsum(cand_counts, out=cand_off[1:])
        _np.cumsum(bview_counts, out=bview_off[1:])
        pack["cand_off"] = cand_off
        pack["bview_off"] = bview_off
        pack["cand_idx"] = _np.concatenate([d["cand_flat"] for d in digests])
        pack["bview_idx"] = _np.concatenate(
            [d["bview_flat"] for d in digests])
        pack["default_idx"] = _np.concatenate(
            [d["default_arr"] for d in digests])
        ndp.set_lists(cand_off, pack["cand_idx"], bview_off,
                      pack["bview_idx"], pack["default_idx"])

        edges = []
        for guid in graph.nodes:
            for e in graph.out_edges[guid]:
                mat = self._edge_matrix(
                    graph.nodes[e.src], graph.nodes[e.dst],
                    e.src_idx, e.dst_idx, budgets)
                has_grad = not graph.nodes[e.src].op.is_gradient_free
                ndp.add_edge(index[e.src], index[e.dst], has_grad, mat)
                if CTX_CHECK:
                    edges.append((index[e.src], index[e.dst], has_grad, mat))
        ctx = {"ndp": ndp, "index": index,
               "views": [d["views"] for d in digests],
               "view_key": [d["view_key"] for d in digests],
               "topo": topo, "budgets": set(budgets), "blocks": blocks}
        if CTX_CHECK:
            # pack/edges duplicate what blocks + the native graph already
            # hold; only the patched-vs-rebuilt oracle reads them.
            ctx["pack"] = pack
            ctx["edges"] = edges
        return ctx

    def _build_native_dp(self, graph: Graph):
        budgets, cands = self._dp_budgets()
        membership = self.sim.cluster_membership(graph)
        blocks = {
            node.guid: self._node_block(node, budgets, membership)
            for node in graph.topo_order()
        }
        return self._assemble_native_dp(graph, blocks, budgets, cands)

    def _patch_native_dp(self, graph: Graph, stamp):
        """Incremental ctx assembly: a substitution candidate reuses its
        parent ctx's per-node blocks outside the changed-guid seed sets
        (the same sets that drive delta simulation and delta matching)
        and re-derives blocks only for the dirty cone.  A block is a
        pure function of (op signature, budgets, chain membership, cost
        surface); the stamp tail proves the surface matches and
        ``cm_key`` proves the chain context does.  Returns None when
        ineligible — the caller falls back to the full build — and the
        FLEXFLOW_TPU_DELTA_CHECK oracle asserts patched == rebuilt."""
        cv = getattr(graph, "_changed_vs", None)
        if cv is None:
            return None
        parent = cv[0]()
        if parent is None:
            return None
        pcached = getattr(parent, "_ndp_ctx", None)
        if pcached in (None, "ineligible") or pcached[1] is None:
            return None
        if not _same_stamp(pcached[0][1:], stamp[1:]):
            return None  # costing surface moved under the parent ctx
        pblocks = pcached[1].get("blocks")
        if pblocks is None:
            return None
        dirty = set(cv[1]) | set(cv[2])
        budgets, cands = self._dp_budgets()
        membership = self.sim.cluster_membership(graph)
        blocks: Dict[int, dict] = {}
        for node in graph.topo_order():
            g = node.guid
            pb = None if g in dirty else pblocks.get(g)
            if pb is not None:
                cm = membership.get(g) if membership else None
                cm_key = (
                    (tuple(m.guid for m in cm[0]), cm[1])
                    if cm is not None else None
                )
                if cm_key != pb["cm_key"]:
                    pb = None  # chain context shifted under a clean guid
            blocks[g] = pb if pb is not None else self._node_block(
                node, budgets, membership)
        return self._assemble_native_dp(graph, blocks, budgets, cands)

    def _budget_cands(self) -> List[int]:
        """_sub_budgets' candidate sizes (shared with the native DP)."""
        divs = [d for d in range(1, self.num_devices + 1)
                if self.num_devices % d == 0]
        cands = set(divs)
        dph = getattr(self.sim.machine, "devices_per_host", 0)
        if 1 < dph < self.num_devices:
            cands.update(
                k * dph for k in range(1, self.num_devices // dph + 1)
            )
        return sorted(cands)

    def _native_graph_cost(self, graph: Graph, fixed: Strategy,
                           budget: int) -> Optional[Tuple[float, Strategy]]:
        ctx = self._native_dp_ctx(graph)
        if ctx is None or budget not in ctx["budgets"]:
            return None
        index, view_key = ctx["index"], ctx["view_key"]
        fixed_native: Dict[int, int] = {}
        for g, v in fixed.items():
            if g not in index:
                continue
            vi = view_key[index[g]].get((v.dim_degrees, v.replica_degree))
            if vi is None:
                return None  # pinned view outside the exported sets
            fixed_native[index[g]] = vi
        ndp = ctx["ndp"]
        before = ndp.greedy_hits()
        cost, assign = ndp.graph_cost(
            list(index.values()), fixed_native, budget)
        self.greedy_hits += ndp.greedy_hits() - before
        strategy: Strategy = {}
        for node in ctx["topo"]:
            vi = int(assign[index[node.guid]])
            if vi >= 0:
                strategy[node.guid] = ctx["views"][index[node.guid]][vi]
        # keep the caller's pinned views object-identical (start offsets
        # on fixed boundary views are preserved even though they are
        # cost-inert in this currency)
        for g, v in fixed.items():
            if g in strategy:
                strategy[g] = v
        # mirror the result into the Python memo: isomorphic graphs with
        # different guids (repeated blocks seen through other Graph
        # objects) then reuse it via canonical remapping exactly as the
        # Python path would.  Under co-search the caller routes this
        # winner through _finish (joint re-pricing + floor), which owns
        # the memo write there — mirroring the native scalar cost would
        # poison the joint-currency memo.
        if self.joint is None:
            key = (graph.hash(), canon_fixed_views(graph, fixed), budget, 0)
            if key not in self.memo:
                self.memo[key] = (
                    float(cost), canonicalize_strategy(graph, strategy))
                self._persist_dp_row(graph, fixed, budget, 0, float(cost),
                                     strategy)
        return float(cost), strategy

    # ------------------------------------------------------------------
    # persistent DP memo (cost_cache.py dp-row layer): tier-2 segment
    # results keyed by PROCESS-STABLE digests, so a cold process skips
    # DP on any segment a prior run has solved.  Serving is restricted
    # to rows LOADED from disk — within one run the in-process memo is
    # a superset of anything this run wrote, so the layer is inert on a
    # cold cache and the bit-identical regression gate holds.

    def _dp_cache_warm(self) -> bool:
        cc = self.sim.cost_cache
        return cc is not None and getattr(cc, "dp_loaded", False) \
            and not cc.stale

    def _dp_persist_key(self, graph: Graph, fixed: Strategy, budget: int,
                        start: int) -> str:
        """Guid-free persistent key: stable graph digest + stable
        canonical pinned views + every knob that changes the DP's
        answer and is not already in the cache's cost-surface signature
        (budget/start plus this helper's search shape)."""
        from hashlib import blake2b

        from flexflow_tpu.search.cost_cache import stable_graph_digest

        snh = graph.stable_node_digests()
        pins = tuple(sorted(
            (snh[g], tuple(v.dim_degrees), int(v.replica_degree),
             int(v.start_part))
            for g, v in fixed.items() if g in graph.nodes
        ))
        knobs = (budget, start, self.num_devices, self.leaf_threshold,
                 self.max_views_per_op, self.max_bottleneck_tries,
                 bool(self.sim.placement_overlap))
        if self.joint is not None:
            # joint-currency rows live under their own key family so a
            # sequential-pipeline run never serves a co-searched cost
            # (extension-only: off-mode keys stay byte-identical)
            knobs = knobs + ("co_search",)
        tail = blake2b(repr((pins, knobs)).encode(),
                       digest_size=10).hexdigest()
        return stable_graph_digest(graph) + ":" + tail

    def _serve_persistent_dp(self, graph, fixed, budget, start):
        """(cost, strategy) from a persisted DP memo row remapped onto
        this graph's guids, or None.  The remap uses the SAME pairing
        rule as the in-process memo (_pair_views) over stable digests;
        ambiguous pairings are re-simulated for an honest cost, and the
        stamped strategy must still pass the SHD1xx legality lint — a
        corrupt row costs one recompute, never a wrong serve."""
        if graph.num_nodes < DP_PERSIST_MIN_NODES:
            return None
        cc = self.sim.cost_cache
        row = cc.get_dp_row(
            self._dp_persist_key(graph, fixed, budget, start))
        if row is None:
            return None
        decoded = decode_strategy_rows(row)
        if decoded is None:
            return None
        cost, canon = decoded
        strategy, ambiguous = _pair_views(
            graph, graph.stable_node_digests(), canon, fixed)
        if strategy is None or len(strategy) != graph.num_nodes:
            return None
        if ambiguous:
            cost = self._price(graph, strategy)
        from flexflow_tpu.analysis import errors_only, lint_strategy

        if errors_only(lint_strategy(graph, strategy, self.num_devices)):
            return None
        key = self._memo_key(graph, fixed, budget, start)
        if key not in self.memo:
            self.memo[key] = (cost, canonicalize_strategy(graph, strategy))
        self.dp_rows_served += 1
        _DP_ROWS_SERVED.inc()
        return cost, strategy

    def _persist_dp_row(self, graph, fixed, budget, start, cost,
                        strategy) -> None:
        cc = self.sim.cost_cache
        if (cc is None or cc.stale or not math.isfinite(cost)
                or graph.num_nodes < DP_PERSIST_MIN_NODES or not strategy):
            return
        rows = encode_strategy_rows(graph, strategy)
        if rows is None:
            return  # partial coverage is not a DP result
        cc.put_dp_row(self._dp_persist_key(graph, fixed, budget, start),
                      float(cost), rows)

    def _memo_lookup(self, graph, key, fixed):
        """The in-process structural memo hit path (reconstruction +
        ambiguity grounding) shared by graph_cost and the warm-serve
        prelude."""
        hit = self.memo.get(key)
        if hit is None:
            return None
        cost, canon = hit
        strategy, ambiguous = reconstruct_strategy(graph, canon, fixed)
        if strategy is None:
            return None
        if ambiguous:
            # multi-member hash groups: the in-group pairing may not
            # follow one isomorphism, so the cached cost may not match
            # this strategy — ground it in the sim
            cost = self._price(graph, strategy)
        return cost, strategy

    # ------------------------------------------------------------------
    def _memo_key(self, graph, fixed, budget: int, start: int) -> Tuple:
        """In-process memo key.  Joint-priced rows (top-level queries
        under co-search) live under their own key family so a
        scalar-currency lookup can never serve an exposed-comm cost
        into a bound comparison (and vice versa) — the same
        extension-only marker the persistent dp layer carries."""
        key = (graph.hash(), canon_fixed_views(graph, fixed), budget, start)
        if self.joint is not None:
            key = key + ("co_search",)
        return key

    def graph_cost(
        self,
        graph: Graph,
        fixed: Optional[Strategy] = None,
        budget: Optional[int] = None,
        start: int = 0,
    ) -> Tuple[float, Strategy]:
        """Depth-gated wrapper (see ``joint_scope``): interior split
        recursion suspends the joint pricer, the top level keeps it."""
        top = self._joint_depth == 0
        self._joint_depth += 1
        try:
            with self.joint_scope(top):
                return self._graph_cost_gated(graph, fixed, budget, start)
        finally:
            self._joint_depth -= 1

    def _graph_cost_gated(
        self,
        graph: Graph,
        fixed: Optional[Strategy] = None,
        budget: Optional[int] = None,
        start: int = 0,
    ) -> Tuple[float, Strategy]:
        """Min cost + argmin strategy for ``graph`` with some nodes' views
        pinned by ``fixed`` (split-boundary nodes), using ``budget``
        devices beginning at device ``start``."""
        fixed = fixed or {}
        budget = budget or self.num_devices
        if self._dp_cache_warm() or self.joint is not None:
            # warm prelude: the in-process memo first (repeat queries
            # must not re-lint a served row), then the persisted rows —
            # BEFORE the native engine, which is the work being skipped.
            # Co-search also takes this prelude: _finish's joint
            # re-pricing is the expensive step there, so repeat queries
            # must serve the memoized joint cost instead of re-pricing
            key = self._memo_key(graph, fixed, budget, start)
            got = self._memo_lookup(graph, key, fixed)
            if got is not None:
                self.memo_hits += 1
                _MEMO_HITS.inc()
                return got
            if self._dp_cache_warm():
                served = self._serve_persistent_dp(graph, fixed, budget,
                                                   start)
                if served is not None:
                    return served
        if start == 0:
            native = self._native_graph_cost(graph, fixed, budget)
            if native is not None:
                self.native_hits += 1
                _NATIVE_HITS.inc()
                if self.joint is not None:
                    # the native engine enumerated in the legacy scalar
                    # currency; its winner still passes the joint gate
                    # (re-price + DP floor + memo) like every other
                    # DP result
                    key = self._memo_key(graph, fixed, budget, start)
                    return self._finish(graph, key, native[0], native[1],
                                        fixed, budget, start)
                return native
        # structural memo: keyed by graph hash + guid-free canonical
        # fixed views, so isomorphic segments with different guids
        # (repeated transformer layers, Inception blocks) share work.
        # Cached strategies are canonical and remapped onto the caller's
        # guids (reconstruct_strategy); round 2's guid-set key blocked
        # exactly this sharing and made 12-layer search intractable.
        key = self._memo_key(graph, fixed, budget, start)
        got = self._memo_lookup(graph, key, fixed)
        if got is not None:
            self.memo_hits += 1
            _MEMO_HITS.inc()
            return got

        self.memo_misses += 1
        _MEMO_MISSES.inc()
        cost, strategy = self._graph_cost_uncached(graph, fixed, budget, start)
        return self._finish(graph, key, cost, strategy, fixed, budget, start)

    def graph_cost_only(
        self,
        graph: Graph,
        fixed: Optional[Strategy] = None,
        budget: Optional[int] = None,
        start: int = 0,
    ) -> float:
        """Depth-gated like ``graph_cost`` (see ``joint_scope``)."""
        top = self._joint_depth == 0
        self._joint_depth += 1
        try:
            with self.joint_scope(top):
                return self._graph_cost_only_gated(graph, fixed, budget,
                                                   start)
        finally:
            self._joint_depth -= 1

    def _graph_cost_only_gated(
        self,
        graph: Graph,
        fixed: Optional[Strategy] = None,
        budget: Optional[int] = None,
        start: int = 0,
    ) -> float:
        """Cost without strategy materialization — memo hits skip the
        canonical-strategy reconstruction, which dominates enumeration
        loops (the reference's templated float-only graph_cost,
        graph.cc:1456-1526, exists for exactly this reason)."""
        fixed = fixed or {}
        budget = budget or self.num_devices
        if self._dp_cache_warm() or self.joint is not None:
            key = self._memo_key(graph, fixed, budget, start)
            hit = self.memo.get(key)
            if hit is not None:
                self.memo_hits += 1
                _MEMO_HITS.inc()
                return hit[0]
            if self._dp_cache_warm():
                served = self._serve_persistent_dp(graph, fixed, budget,
                                                   start)
                if served is not None:
                    return served[0]
        if start == 0:
            native = self._native_graph_cost(graph, fixed, budget)
            if native is not None:
                self.native_hits += 1
                _NATIVE_HITS.inc()
                if self.joint is not None:
                    key = self._memo_key(graph, fixed, budget, start)
                    return self._finish(graph, key, native[0], native[1],
                                        fixed, budget, start)[0]
                return native[0]
        key = self._memo_key(graph, fixed, budget, start)
        hit = self.memo.get(key)
        if hit is not None:
            # the cached cost is achievable on any isomorphic graph, so
            # no reconstruction is needed for cost-only queries
            self.memo_hits += 1
            _MEMO_HITS.inc()
            return hit[0]
        self.memo_misses += 1
        _MEMO_MISSES.inc()
        cost, strategy = self._graph_cost_uncached(graph, fixed, budget, start)
        return self._finish(graph, key, cost, strategy, fixed, budget, start)[0]

    def _finish(self, graph, key, cost, strategy, fixed, budget, start):
        # Re-validate against the simulator: split-based composition
        # over-counts boundary nodes and assumes realizable overlap; the
        # event-driven sim of the full (sub)graph is ground truth.
        # Under co-search this is THE DP re-validation the co-search
        # prices jointly: the composed strategy and the DP floor both
        # carry their best comm plan into the comparison.
        if strategy:
            cost = self._price(graph, strategy)
        # Floor: the batch-parallel default is always in the search
        # space, so the result must never be worse than it (the split
        # composition optimizes a bound, not the true cost, and can
        # otherwise steer to a worse re-validated strategy).
        dp = self._default_strategy(graph, fixed, budget, start)
        c_dp = self._price(graph, dp)
        if c_dp < cost:
            cost, strategy = c_dp, dp
        self.memo[key] = (cost, canonicalize_strategy(graph, strategy))
        self._persist_dp_row(graph, fixed, budget, start, cost, strategy)
        return cost, strategy

    def _default_strategy(self, graph, fixed, budget, start) -> Strategy:
        """Batch-parallel-where-possible assignment honoring ``fixed``
        (the reference's --only-data-parallel construction,
        graph.cc:1572-1597, restricted to the segment's resources)."""
        out: Strategy = {}
        for guid, node in graph.nodes.items():
            if guid in fixed:
                out[guid] = fixed[guid]
                continue
            fv = self._fixed_view(node, start)
            if fv is not None:
                out[guid] = fv
                continue
            shape = node.op.output_shapes[0]
            nd = shape.ndim
            mv = None
            if nd and 0 in node.op.splittable_output_dims():
                d = budget
                while d > 1 and shape.sizes[0] % d != 0:
                    d //= 2
                if d > 1:
                    mv = MachineView.data_parallel(nd, d)
            if mv is None:
                mv = MachineView.trivial(nd)
            if start:
                mv = dataclasses.replace(mv, start_part=start)
            out[guid] = mv
        return out

    def _graph_cost_uncached(self, graph, fixed, budget, start):
        n_free = sum(1 for g in graph.nodes if g not in fixed)
        if graph.num_nodes <= self.leaf_threshold or n_free <= 2:
            return self._leaf_cost(graph, fixed, budget, start)

        # nonsequence split: independent components (graph.cc:161-295)
        comps = graph.weakly_connected_components()
        if len(comps) > 1:
            return self._component_cost(graph, fixed, budget, start, comps)

        # sequence split at a bottleneck (graph.cc:96-159).  Several
        # candidates are tried (first/middle/last of the bottleneck
        # chain); the memo makes revisited intervals cheap, and chains
        # reach the same optimum from any split point.  Large graphs try
        # a single balanced split and fewer boundary views — the state
        # count is intervals x boundary-view-pairs, and the reference
        # keeps the same product small via 1-D views + its outer-loop
        # threshold (graph.cc:1778, substitution.cc:2007).
        bottlenecks = [b for b in graph.bottlenecks() if b.guid not in fixed]
        large = graph.num_nodes > 6 * self.leaf_threshold
        tries = (
            [bottlenecks[len(bottlenecks) // 2]]
            if (large and bottlenecks)
            else self._pick_bottlenecks(bottlenecks)
        )
        # enumerate with cost-only DP; materialize the winner's strategy
        # once at the end (memo hits make it two reconstructions)
        best_c, best_plan = math.inf, None
        for bn in tries:
            try:
                pre, post = graph.split_at_node(bn)
            except ValueError:
                continue
            for v in self._bviews(bn, budget, start):
                f2 = dict(fixed)
                f2[bn.guid] = v
                c_pre = self.graph_cost_only(pre, f2, budget, start)
                if c_pre >= best_c:
                    continue
                c_post = self.graph_cost_only(post, f2, budget, start)
                total = c_pre + c_post
                if total < best_c:
                    best_c, best_plan = total, (pre, post, f2, bn.guid, v)
        if best_plan is not None:
            pre, post, f2, bn_guid, v = best_plan
            if BUS.enabled:
                BUS.emit(
                    "dp.split", op=graph.nodes[bn_guid].op.name,
                    pre_nodes=pre.num_nodes, post_nodes=post.num_nodes,
                    cost_s=best_c, budget=budget,
                )
            _, s_pre = self.graph_cost(pre, f2, budget, start)
            _, s_post = self.graph_cost(post, f2, budget, start)
            s = dict(s_pre)
            s.update(s_post)
            s[bn_guid] = v
            return best_c, s

        # no usable bottleneck: nonsequence split BETWEEN the boundary
        # nodes — drop sources/sinks, partition the interior's parallel
        # branches (reference: find_optimal_nonsequence_graph_time,
        # graph.cc:241-295, where source/sink carry NodeAssignments).
        # This is the Inception shape: branches diverging from one node
        # and reconverging at a concat.
        interior = self._interior_split(graph, fixed, budget, start)
        if interior is not None:
            return interior
        # leaf brute force (compact-view fallback inside) before the
        # per-node greedy — mid-size branch interiors land here
        return self._leaf_cost(graph, fixed, budget, start)

    def _interior_split(self, graph, fixed, budget, start):
        srcs = {g for g in graph.nodes if not graph.in_edges[g]}
        sinks = {g for g in graph.nodes if not graph.out_edges[g]}
        bounds = srcs | sinks
        interior = set(graph.nodes) - bounds
        if not interior or not bounds:
            return None
        inner = graph._subgraph(interior)
        comps = inner.weakly_connected_components()
        if len(comps) < 2:
            return None
        unfixed = sorted(b for b in bounds if b not in fixed)
        choice_lists = [
            self._bviews(graph.nodes[b], budget, start) for b in unfixed
        ]
        n_combos = 1
        for c in choice_lists:
            n_combos *= max(1, len(c))
        if n_combos > 256:
            # too many boundary choices: pin them to the batch-parallel
            # default and let the components search freely
            choice_lists = [c[:1] for c in choice_lists]
        best = (math.inf, {})
        for combo in itertools.product(*choice_lists):
            f2 = dict(fixed)
            for b, v in zip(unfixed, combo):
                f2[b] = v
            c_in, _ = self._component_cost(
                inner, f2, budget, start, comps, cost_only=True
            )
            if c_in >= best[0]:
                continue
            _, s_in = self._component_cost(inner, f2, budget, start, comps)
            strategy = {g: v for g, v in f2.items() if g in graph.nodes}
            strategy.update(s_in)
            c = self.sim.simulate(graph, strategy)
            if c < best[0]:
                best = (c, strategy)
        if best[0] < math.inf:
            return best
        return None

    def _pick_bottlenecks(self, bottlenecks: List[Node]) -> List[Node]:
        k = self.max_bottleneck_tries
        if len(bottlenecks) <= k:
            return bottlenecks
        # evenly spaced sample including the middle (the reference
        # tie-breaks toward balanced splits, substitution.cc:1980-1999)
        idxs = sorted({
            round(i * (len(bottlenecks) - 1) / (k - 1)) for i in range(k)
        } | {len(bottlenecks) // 2})
        return [bottlenecks[i] for i in idxs][:k + 1]

    # ------------------------------------------------------------------
    def _sub_budgets(self, budget: int) -> List[Tuple[int, int]]:
        """(first, rest) device-count pairs for a VERTICAL or
        HORIZONTAL resource split (reference: graph.cc:161-295 tries
        gpu-dim and node-dim resource partitions).  VERTICAL budgets
        are divisors of the machine size (view degrees must factor
        onto the global mesh); HORIZONTAL adds whole-host multiples —
        node-granular partitions that need not divide the device count
        (e.g. 16 of 24 devices = 2 of 3 hosts).  Each side's views are
        still divisor-constrained; the budget only bounds them."""
        divs = [d for d in range(1, self.num_devices + 1)
                if self.num_devices % d == 0]
        cands = set(divs)
        dph = getattr(self.sim.machine, "devices_per_host", 0)
        if 1 < dph < self.num_devices:
            cands.update(
                k * dph for k in range(1, self.num_devices // dph + 1)
            )
        pairs = []
        for a in sorted(cands):
            if a >= budget:
                continue
            rest = budget - a
            b = max((d for d in sorted(cands) if d <= rest), default=0)
            if b >= 1:
                pairs.append((a, b))
        return pairs

    def _component_cost(self, graph, fixed, budget, start, comps, cost_only=False):
        """Independent subgraphs, reference-style first-vs-rest
        recursion (graph.cc:161-295): SEQUENTIAL (both use the full
        budget, costs add) vs VERTICAL (disjoint device blocks, costs
        max) over every valid budget split, both orientations.
        Enumerates with cost-only DP; the winner's strategies are
        materialized once at the end."""
        comps = sorted(comps, key=lambda c: (-len(c), min(c)))
        first = graph._subgraph(comps[0])
        rest_guids = set(graph.nodes) - comps[0]
        rest = graph._subgraph(rest_guids)

        # SEQUENTIAL: full budget for both, run one after the other
        c_seq = self.graph_cost_only(first, fixed, budget, start) + \
            self.graph_cost_only(rest, fixed, budget, start)
        # plan: (ga, a_budget, a_start, gb, b_budget, b_start)
        best_c = c_seq
        best_plan = (first, budget, start, rest, budget, start)

        # VERTICAL: disjoint contiguous blocks, run concurrently
        for a, b in self._sub_budgets(budget):
            for first_a in (True, False):  # flip_graphs (graph.cc:172)
                ga, gb = (first, rest) if first_a else (rest, first)
                ca = self.graph_cost_only(ga, fixed, a, start)
                if ca >= best_c:
                    continue
                cb = self.graph_cost_only(gb, fixed, b, start + a)
                par = max(ca, cb)
                if par < best_c:
                    best_c = par
                    best_plan = (ga, a, start, gb, b, start + a)
        if cost_only:
            return best_c, None
        ga, ba, sa, gb, bb, sb = best_plan
        _, s_a = self.graph_cost(ga, fixed, ba, sa)
        _, s_b = self.graph_cost(gb, fixed, bb, sb)
        s = dict(s_a)
        s.update(s_b)
        return best_c, s

    # ------------------------------------------------------------------
    def _leaf_cost(self, graph, fixed, budget, start):
        """Brute force over candidate-view products for free nodes —
        runs on the native engine when available (native/src/
        sim_engine.cpp ffn_sim_brute_force), falling back to the
        equivalent Python loop."""
        free = [graph.nodes[g] for g in sorted(graph.nodes) if g not in fixed]
        if not free:
            strategy = {g: v for g, v in fixed.items() if g in graph.nodes}
            return self.sim.simulate(graph, strategy), strategy
        choices = [self._views(n, budget, start) for n in free]
        total_combos = 1
        for c in choices:
            total_combos *= len(c)
        if total_combos > 262144:
            # rich view products too big: fall back to the compact
            # boundary sets (still covers DP/TP/hybrid/contraction) —
            # vastly better than the per-node greedy for mid-size
            # multi-branch interiors (attention blocks)
            choices = [self._bviews(n, budget, start) for n in free]
            total_combos = 1
            for c in choices:
                total_combos *= len(c)
        base = {g: v for g, v in fixed.items() if g in graph.nodes}
        if 0 < total_combos <= 262144:
            # the native engine enumerates big products cheaply
            # (native/src/sim_engine.cpp ffn_sim_brute_force)
            native = self._native_leaf(graph, base, free, choices)
            if native is not None:
                return native
        if total_combos > 4096:
            return self._greedy_cost(graph, fixed, budget, start)
        best = (math.inf, {})
        for combo in itertools.product(*choices):
            strategy = dict(base)
            for node, v in zip(free, combo):
                strategy[node.guid] = v
            c = self.sim.simulate(graph, strategy)
            if c < best[0]:
                best = (c, strategy)
        return best

    def _native_leaf(self, graph, base, free, choices):
        node_views = {g: [v] for g, v in base.items()}
        for node, views in zip(free, choices):
            node_views[node.guid] = list(views)
        built = self.sim.build_native(graph, node_views)
        if built is None:
            return None
        ns, index = built
        assign = [0] * ns.num_nodes
        free_idx = [index[n.guid] for n in free]
        cost, best = ns.brute_force(
            free_idx, assign, include_update=not self.sim.inference
        )
        if not math.isfinite(cost):
            return (math.inf, {})
        strategy = {
            guid: node_views[guid][best[i]] for guid, i in index.items()
        }
        return cost, strategy

    # ------------------------------------------------------------------
    def _greedy_cost(self, graph, fixed, budget, start):
        """Fallback for odd topologies: assign views in topo order,
        choosing each node's view to minimize the simulated cost of the
        prefix assigned so far (keeps the xfer terms local).  Native
        when available (ffn_sim_greedy)."""
        self.greedy_hits += 1
        base = {g: v for g, v in fixed.items() if g in graph.nodes}
        native = self._native_greedy(graph, base, budget, start)
        if native is not None:
            return native
        strategy: Strategy = dict(base)
        for node in graph.topo_order():
            if node.guid in strategy:
                continue
            best_v, best_c = None, math.inf
            for v in self._views(node, budget, start):
                strategy[node.guid] = v
                c = self.sim.simulate(graph, strategy)
                if c < best_c:
                    best_v, best_c = v, c
            strategy[node.guid] = best_v
        return self.sim.simulate(graph, strategy), strategy

    def _native_greedy(self, graph, base, budget, start):
        node_views = {}
        enum_counts = {}
        for guid, node in graph.nodes.items():
            if guid in base:
                node_views[guid] = [base[guid]]
                enum_counts[guid] = 0
            else:
                cands = list(self._views(node, budget, start))
                default = self._fixed_view(node, start) or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
                node_views[guid] = cands + [default]
                enum_counts[guid] = len(cands)
        built = self.sim.build_native(graph, node_views)
        if built is None:
            return None
        ns, index = built
        n = ns.num_nodes
        assign = [0] * n
        is_free = [False] * n
        counts = [0] * n
        for guid, i in index.items():
            counts[i] = enum_counts[guid]
            if guid in base:
                assign[i] = 0
            else:
                is_free[i] = True
                assign[i] = len(node_views[guid]) - 1  # default view
        cost, best = ns.greedy(
            is_free, counts, assign, include_update=not self.sim.inference
        )
        strategy = {
            guid: node_views[guid][best[i]] for guid, i in index.items()
        }
        return cost, strategy
