"""DP over graph splits — Unity's inner loop.

Re-implements the algorithm of SearchHelper::graph_cost
(reference: src/runtime/graph.cc:79-295, 1276-1526): given a *fixed*
PCG, find the min-cost MachineView assignment by

* sequence-splitting at a bottleneck node and enumerating that node's
  views (graph.cc:96-159),
* nonsequence-splitting independent components over SEQUENTIAL /
  VERTICAL(-ish) resource partitions (graph.cc:161-295),
* brute-forcing small leaves against the event-driven simulator,
* memoizing by (graph hash, fixed-view constraints, device budget)
  (graph.cc:1356 dp_state hash).

One deliberate difference: the reference's views place ops on physical
device boxes; here views are degree vectors canonically mapped to mesh
axes, so the "resources" being split are abstract device counts
(mirroring MachineResource), and XLA/GSPMD realizes placement.
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.core.graph import Graph, Node
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.search.simulator import Simulator
from flexflow_tpu.search.views import candidate_views

Strategy = Dict[int, MachineView]


class SearchHelper:
    def __init__(
        self,
        simulator: Simulator,
        num_devices: int,
        leaf_threshold: int = 4,
        max_views_per_op: int = 16,
    ):
        self.sim = simulator
        self.num_devices = num_devices
        self.leaf_threshold = leaf_threshold
        self.max_views_per_op = max_views_per_op
        self.memo: Dict[Tuple, Tuple[float, Strategy]] = {}
        self._views_cache: Dict[Tuple, List[MachineView]] = {}

    # ------------------------------------------------------------------
    def _views(self, node: Node, budget: int) -> List[MachineView]:
        key = (node.op.signature(), budget)
        if key not in self._views_cache:
            self._views_cache[key] = candidate_views(
                node.op, budget, max_views=self.max_views_per_op
            )
        return self._views_cache[key]

    # ------------------------------------------------------------------
    def graph_cost(
        self,
        graph: Graph,
        fixed: Optional[Strategy] = None,
        budget: Optional[int] = None,
    ) -> Tuple[float, Strategy]:
        """Min cost + argmin strategy for ``graph`` with some nodes' views
        pinned by ``fixed`` (split-boundary nodes)."""
        fixed = fixed or {}
        budget = budget or self.num_devices
        key = (
            graph.hash(),
            tuple(sorted((g, v) for g, v in fixed.items() if g in graph.nodes)),
            budget,
        )
        if key in self.memo:
            return self.memo[key]

        cost, strategy = self._graph_cost_uncached(graph, fixed, budget)
        # Re-validate against the simulator: split-based composition
        # over-counts boundary nodes and assumes realizable overlap; the
        # event-driven sim of the full (sub)graph is ground truth.
        if strategy:
            cost = self.sim.simulate(graph, strategy)
        result = (cost, strategy)
        self.memo[key] = result
        return result

    def _graph_cost_uncached(self, graph, fixed, budget):
        n_free = sum(1 for g in graph.nodes if g not in fixed)
        if graph.num_nodes <= self.leaf_threshold or n_free <= 2:
            return self._leaf_cost(graph, fixed, budget)

        # nonsequence split: independent components (graph.cc:161-295)
        comps = graph.weakly_connected_components()
        if len(comps) > 1:
            return self._component_cost(graph, fixed, budget, comps)

        # sequence split at a bottleneck (graph.cc:96-159)
        bottlenecks = [
            b for b in graph.bottlenecks() if b.guid not in fixed
        ]
        if bottlenecks:
            mid = bottlenecks[len(bottlenecks) // 2]
            try:
                pre, post = graph.split_at_node(mid)
            except ValueError:
                return self._greedy_cost(graph, fixed, budget)
            best = (math.inf, {})
            for v in self._views(mid, budget):
                f2 = dict(fixed)
                f2[mid.guid] = v
                c_pre, s_pre = self.graph_cost(pre, f2, budget)
                if c_pre >= best[0]:
                    continue
                c_post, s_post = self.graph_cost(post, f2, budget)
                total = c_pre + c_post
                if total < best[0]:
                    s = dict(s_pre)
                    s.update(s_post)
                    s[mid.guid] = v
                    best = (total, s)
            if best[0] < math.inf:
                return best
        return self._greedy_cost(graph, fixed, budget)

    # ------------------------------------------------------------------
    def _component_cost(self, graph, fixed, budget, comps):
        """Independent subgraphs: best of running them SEQUENTIALly on the
        full budget vs in parallel (VERTICAL) on split budgets."""
        subs = [graph._subgraph(c) for c in comps]
        results_full = [self.graph_cost(s, fixed, budget) for s in subs]
        seq_cost = sum(c for c, _ in results_full)
        seq_strategy: Strategy = {}
        for _, s in results_full:
            seq_strategy.update(s)
        best = (seq_cost, seq_strategy)
        if budget >= 2 and len(subs) == 2:
            half = budget // 2
            r1 = self.graph_cost(subs[0], fixed, half)
            r2 = self.graph_cost(subs[1], fixed, budget - half)
            par_cost = max(r1[0], r2[0])
            if par_cost < best[0]:
                s = dict(r1[1])
                s.update(r2[1])
                best = (par_cost, s)
        return best

    # ------------------------------------------------------------------
    def _leaf_cost(self, graph, fixed, budget):
        """Brute force over candidate-view products for free nodes —
        runs on the native engine when available (native/src/
        sim_engine.cpp ffn_sim_brute_force), falling back to the
        equivalent Python loop."""
        free = [graph.nodes[g] for g in sorted(graph.nodes) if g not in fixed]
        if not free:
            strategy = {g: v for g, v in fixed.items() if g in graph.nodes}
            return self.sim.simulate(graph, strategy), strategy
        choices = [self._views(n, budget) for n in free]
        total_combos = 1
        for c in choices:
            total_combos *= len(c)
        if total_combos > 4096:
            return self._greedy_cost(graph, fixed, budget)
        base = {g: v for g, v in fixed.items() if g in graph.nodes}
        if total_combos > 0:
            native = self._native_leaf(graph, base, free, choices)
            if native is not None:
                return native
        best = (math.inf, {})
        for combo in itertools.product(*choices):
            strategy = dict(base)
            for node, v in zip(free, combo):
                strategy[node.guid] = v
            c = self.sim.simulate(graph, strategy)
            if c < best[0]:
                best = (c, strategy)
        return best

    def _native_leaf(self, graph, base, free, choices):
        node_views = {g: [v] for g, v in base.items()}
        for node, views in zip(free, choices):
            node_views[node.guid] = list(views)
        built = self.sim.build_native(graph, node_views)
        if built is None:
            return None
        ns, index = built
        assign = [0] * ns.num_nodes
        free_idx = [index[n.guid] for n in free]
        cost, best = ns.brute_force(free_idx, assign)
        if not math.isfinite(cost):
            return (math.inf, {})
        strategy = {
            guid: node_views[guid][best[i]] for guid, i in index.items()
        }
        return cost, strategy

    # ------------------------------------------------------------------
    def _greedy_cost(self, graph, fixed, budget):
        """Fallback for odd topologies: assign views in topo order,
        choosing each node's view to minimize the simulated cost of the
        prefix assigned so far (keeps the xfer terms local).  Native
        when available (ffn_sim_greedy)."""
        base = {g: v for g, v in fixed.items() if g in graph.nodes}
        native = self._native_greedy(graph, base, budget)
        if native is not None:
            return native
        strategy: Strategy = dict(base)
        for node in graph.topo_order():
            if node.guid in strategy:
                continue
            best_v, best_c = None, math.inf
            for v in self._views(node, budget):
                strategy[node.guid] = v
                c = self.sim.simulate(graph, strategy)
                if c < best_c:
                    best_v, best_c = v, c
            strategy[node.guid] = best_v
        return self.sim.simulate(graph, strategy), strategy

    def _native_greedy(self, graph, base, budget):
        node_views = {}
        enum_counts = {}
        for guid, node in graph.nodes.items():
            if guid in base:
                node_views[guid] = [base[guid]]
                enum_counts[guid] = 0
            else:
                cands = list(self._views(node, budget))
                default = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
                node_views[guid] = cands + [default]
                enum_counts[guid] = len(cands)
        built = self.sim.build_native(graph, node_views)
        if built is None:
            return None
        ns, index = built
        n = ns.num_nodes
        assign = [0] * n
        is_free = [False] * n
        counts = [0] * n
        for guid, i in index.items():
            counts[i] = enum_counts[guid]
            if guid in base:
                assign[i] = 0
            else:
                is_free[i] = True
                assign[i] = len(node_views[guid]) - 1  # default view
        cost, best = ns.greedy(is_free, counts, assign)
        strategy = {
            guid: node_views[guid][best[i]] for guid, i in index.items()
        }
        return cost, strategy
