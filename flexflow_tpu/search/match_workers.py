"""Opt-in process-parallel substitution matching.

At 10k-node scale the full-scan match sweeps that SEED the search —
the driver's one-time ``_score_edges`` pass and every popped
candidate's first (parent-less) match collection — are embarrassingly
parallel across xfers: each ``find_matches`` is a pure function of
(graph, xfer).  This module fans those sweeps out to a small process
pool when ``FLEXFLOW_TPU_MATCH_WORKERS=N`` (N >= 2) is set; the
default (unset/0/1) keeps the exact serial path, so the pool is
strictly opt-in and the zoo bit-identity gates hold by construction.

Workers rebuild the xfer registry themselves from ``(num_devices,
substitution_json)`` — xfer closures do not pickle — which is sound
because ``generate_all_pcg_xfers`` + the JSON loader are deterministic
in those inputs, so worker index ``i`` is the parent's ``xfers[i]``.
Matches return as guids (GraphXfer) or binding dicts
(BatchEmbeddingsXfer / PatternRule) and are re-bound to the parent's
Node objects.  Under ``FLEXFLOW_TPU_DELTA_CHECK=1`` every pooled sweep
is recomputed serially and asserted identical — the same oracle
discipline as delta simulation and the seed index.

Any pool failure (spawn, pickle, worker crash) degrades to the serial
path and disables the pool for the rest of the process — matching can
never be less available than before.
"""

from __future__ import annotations

import os
import pickle
from typing import List, Optional

from flexflow_tpu.obs.metrics import METRICS

BATCHES = METRICS.counter("substitution.match_worker_batches")

# graphs below this size never dispatch: the graph pickle + IPC costs
# more than the serial sweep saves
MIN_POOL_NODES = 384

_POOL = None  # (pool object, key) once armed
_DISABLED = False  # sticky off-switch after any pool failure

_W_XFERS: Optional[list] = None  # worker-process registry


def worker_count() -> int:
    v = os.environ.get("FLEXFLOW_TPU_MATCH_WORKERS", "")
    try:
        n = int(v)
    except ValueError:
        return 0
    return n if n >= 2 else 0


def _init_worker(num_devices: int, substitution_json: Optional[str]):
    global _W_XFERS
    from flexflow_tpu.search.substitution import generate_all_pcg_xfers

    xfers = list(generate_all_pcg_xfers(num_devices))
    if substitution_json:
        from flexflow_tpu.search.substitution_loader import (
            load_substitution_json,
        )

        xfers += load_substitution_json(substitution_json)
    _W_XFERS = xfers


def _match_task(args):
    graph_bytes, indices = args
    g = pickle.loads(graph_bytes)
    out = {}
    for xi in indices:
        ms = _W_XFERS[xi].find_matches(g)
        out[xi] = [m.guid if hasattr(m, "guid") else m for m in ms]
    return out


def _get_pool(num_devices: int, substitution_json: Optional[str]):
    global _POOL, _DISABLED
    if _DISABLED:
        return None
    n = worker_count()
    if n == 0:
        return None
    key = (n, num_devices, substitution_json or "")
    if _POOL is not None:
        if _POOL[1] == key:
            return _POOL[0]
        _POOL[0].terminate()
        _POOL = None
    import atexit
    import multiprocessing as mp

    try:
        # fork: workers inherit the imported registry modules without
        # re-importing jax; matching itself is pure python
        ctx = mp.get_context("fork")
        pool = ctx.Pool(
            n, initializer=_init_worker,
            initargs=(num_devices, substitution_json))
    except (ValueError, OSError):
        _DISABLED = True
        return None
    _POOL = (pool, key)
    atexit.register(shutdown)
    return pool


def shutdown() -> None:
    global _POOL
    if _POOL is not None:
        _POOL[0].terminate()
        _POOL = None


def find_all_matches(xfers: list, graph, config,
                     num_devices: int) -> Optional[List[list]]:
    """All xfers' matches of ``graph`` via the worker pool — a list
    aligned with ``xfers`` — or None when the pool is off/ineligible
    (caller runs the serial sweep).  Serial-identity is asserted under
    FLEXFLOW_TPU_DELTA_CHECK."""
    global _DISABLED
    if graph.num_nodes < MIN_POOL_NODES:
        return None
    pool = _get_pool(num_devices,
                     getattr(config, "substitution_json", None))
    if pool is None:
        return None
    try:
        blob = pickle.dumps(graph, protocol=4)
    except Exception:
        return None
    n = worker_count()
    chunks: List[List[int]] = [[] for _ in range(min(n * 2, len(xfers)))]
    for xi in range(len(xfers)):
        chunks[xi % len(chunks)].append(xi)
    try:
        results = pool.map(_match_task, [(blob, ch) for ch in chunks])
    except Exception:
        # a dead pool must not kill the search — degrade to serial
        shutdown()
        _DISABLED = True
        return None
    BATCHES.inc()
    merged = {}
    for r in results:
        merged.update(r)
    nodes = graph.nodes
    out: List[list] = []
    for xi in range(len(xfers)):
        ms = [nodes[m] if isinstance(m, int) else m
              for m in merged.get(xi, [])]
        out.append(ms)
    from flexflow_tpu.search.substitution import DELTA_MATCH_CHECK

    if DELTA_MATCH_CHECK:
        for xi, xf in enumerate(xfers):
            serial = xf.find_matches(graph)
            a = [m.guid if hasattr(m, "guid") else m for m in out[xi]]
            b = [m.guid if hasattr(m, "guid") else m for m in serial]
            assert a == b, (
                f"match worker pool diverged from serial for "
                f"{getattr(xf, 'name', xf)}: {a} != {b}"
            )
    return out
