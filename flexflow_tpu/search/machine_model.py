"""Analytic TPU cost model.

Replaces the reference's measured-kernel + bandwidth-table stack
(reference: src/runtime/machine_model.cc:57-68 SimpleMachineModel,
src/runtime/simulator.cc:515-787 measure_operator_cost /
estimate_xfer_cost) with a roofline model parameterized by MachineSpec:

* compute: max(FLOPs/MXU-peak, bytes/HBM-bw) per shard — correct
  first-order model for XLA-fused TPU programs, where the reference's
  per-op cuda-event timing has no analogue (ops fuse; SURVEY.md §7
  hard part (a)).  An optional on-device probe refines hot ops.
* collectives: ring formulas over ICI (bandwidth-optimal on a torus):
  allreduce 2(n-1)/n, allgather/reducescatter (n-1)/n, all_to_all
  (n-1)/n² per direction; DCN terms added when a collective spans
  ICI domains (hosts on CPU machines, slices on multislice TPU).

Whether a collective crosses DCN depends on WHICH mesh axes it rides,
not just its size: the lowering's deterministic axis assignment
(parallel/mesh.py view_slot_axes) gives the first (outermost, strided)
pool axes to the first view slots, and jax device ordering keeps an
ICI domain's devices contiguous — so an outer-axis group of size 2 on
a 2-slice machine crosses DCN while an inner-axis group of size
devices_per_host does not.  The cost model replays that assignment
(``_slot_axes``) so DP-across-slices weight syncs are priced at DCN
bandwidth and within-slice TP collectives at ICI bandwidth — the
scaling-book multislice recipe.  Callers without slot context fall
back to the size heuristic (n > devices_per_host).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from flexflow_tpu.core.machine import MachineSpec, MachineView
from flexflow_tpu.core.ptensor import ParallelTensorShape
from flexflow_tpu.ops.base import REPLICA_SLOT, Operator, ShardAnnot
from flexflow_tpu.parallel.mesh import (
    assign_slot_axes,
    place_zero_factors,
    prime_factors,
)

# fixed per-op dispatch overhead inside one XLA program (fusion makes
# this tiny compared to the reference's per-task launch overhead)
OP_OVERHEAD_S = 2e-6


def _merge_levels(acc: Dict[str, float], split: Dict[str, float]) -> None:
    """Accumulate a per-link-level seconds split into ``acc``."""
    for name, t in split.items():
        acc[name] = acc.get(name, 0.0) + t


def _min_compress_elems() -> int:
    """comm.quantized.MIN_COMPRESS_ELEMS, imported lazily: the comm
    module pulls in jax, which this pure-python cost model otherwise
    never needs."""
    from flexflow_tpu.comm.quantized import MIN_COMPRESS_ELEMS

    return MIN_COMPRESS_ELEMS


@dataclass
class CostModel:
    machine: MachineSpec
    # optional NetworkedMachineModel: collectives are then routed over
    # the ICI torus with per-link contention (search/network.py) instead
    # of the flat ring formulas
    network: Optional[object] = None
    # optional CalibrationTable of MEASURED per-(op, view) forward
    # seconds from the real chip — consulted before the roofline
    # (reference: ProfilingRecord cache, simulator.cc:515-554)
    calibration: Optional[object] = None
    # device count the search runs against (--search-num-nodes style
    # overrides make this differ from machine.num_devices); the mesh
    # the strategies lower onto has THIS many devices, so slot→axis
    # assignment must factor it, not the spec's chip count
    num_devices: Optional[int] = None
    # execution shards optimizer state of replicated weights over their
    # replication axes (config.zero_dp_shard) — memory feasibility must
    # credit the 1/replica optimizer share or the search rejects
    # strategies that actually fit
    zero_dp_shard: bool = False
    # inference compile (reference COMP_MODE_INFERENCE): no grads, no
    # optimizer state — op_memory counts weights + activations only
    inference: bool = False
    # gradient-sync wire precision (FFConfig.sync_precision): fp32 |
    # bf16 | int8 price every weight sync at that precision (safety
    # heuristic permitting); "search" makes it a per-weight-group
    # choice — sync_cost() returns the cheapest admissible precision's
    # cost, so the DP trades e.g. TP (no sync) against DP + compressed
    # sync with honest numbers (EQuARX, arXiv:2506.17615)
    sync_precision: str = "fp32"
    # error feedback on int8 sync (FFConfig.sync_ef="auto"): int8
    # choices upgrade to "int8_ef" — same wire, plus the residual
    # add/store passes priced in _quant_overhead.  A fidelity POLICY,
    # not a cost comparison: EF costs strictly more seconds than plain
    # int8 and the currency cannot see the error it removes, so the
    # upgrade is gated here instead of argmin'd
    sync_ef: bool = False
    # serving arrival model (search/serving.py ServingSpec,
    # FFConfig.objective="serve"): ops with a `sharded_bytes_accessed`
    # hook (the paged-KV decode attention) then price their ragged
    # cache stream at the spec's p-quantile max-shard load instead of
    # full occupancy, which puts the WHOLE search — both DP engines,
    # estimates, delta sim, the floor — in the p99-latency currency.
    # None (the default) changes nothing: every existing op's pricing
    # is byte-identical
    serving: Optional[object] = None

    # ---- slice topology --------------------------------------------------
    def levels(self):
        """The link hierarchy this cost model prices against
        (``MachineSpec.topology_levels``), clamped to the SEARCH device
        count: a level whose aligned group already contains every
        searched device adds no crossing class (an 8-device search of a
        16-chip 2-slice spec runs inside one slice).  Finest first;
        a flat machine is the single-level degenerate case."""
        if not hasattr(self, "_levels_cache"):
            import dataclasses

            from flexflow_tpu.core.machine import LinkLevel

            ndev = self.num_devices or self.machine.num_devices
            lv = list(self.machine.topology_levels())
            out = [lv[0]]
            for lvl in lv[1:]:
                if ndev > out[-1].span:
                    out.append(lvl)
            if ndev > out[-1].span:
                # a --search-num-nodes-style override spans more devices
                # than the spec names: the extra reach is one more DCN
                # hop class (widen the coarsest configured level, or add
                # the classic machine-wide DCN level to a flat spec)
                if len(out) == 1:
                    out.append(LinkLevel(
                        "dcn", ndev, self.machine.dcn_bandwidth,
                        self.machine.dcn_latency))
                else:
                    out[-1] = dataclasses.replace(out[-1], span=ndev)
            self._levels_cache = tuple(out)
        return self._levels_cache

    def _axis_level(self, span: int) -> int:
        """The finest level whose aligned group contains an axis group
        of aligned ``span`` (stride * size): groups along an axis live
        in ALIGNED blocks, so the group stays inside one level-i block
        iff the span both fits and DIVIDES the level's group size —
        span 3 with slice 8 crosses at the [6,9) block even though
        3 < 8.  Returns 0 for within-slice, k for a group only the
        level-k links connect."""
        levels = self.levels()
        for i, lvl in enumerate(levels):
            if span <= lvl.span and lvl.span % span == 0:
                return i
        return len(levels) - 1

    def _slot_axes(self, slot_degrees: Tuple[int, ...]):
        """Per-slot (stride, size) mesh axes under the lowering's
        canonical take-first assignment (parallel/mesh.py
        assign_slot_axes over the prime-factor pool, devices in jax
        order: axis i has stride = product of later factor sizes).
        Returns None when a degree does not factor into the pool
        (invalid view — callers fall back to the size heuristic)."""
        if not hasattr(self, "_slot_axes_cache"):
            self._slot_axes_cache = {}
        if slot_degrees in self._slot_axes_cache:
            return self._slot_axes_cache[slot_degrees]
        pool = prime_factors(self.num_devices or self.machine.num_devices)
        strides = [1] * len(pool) if pool else []
        for i in range(len(pool) - 2, -1, -1):
            strides[i] = strides[i + 1] * pool[i + 1]
        try:
            idx = assign_slot_axes(slot_degrees, pool)
            result = tuple(
                tuple((strides[j], pool[j]) for j in taken) for taken in idx
            )
        except ValueError:
            result = None
        self._slot_axes_cache[slot_degrees] = result
        return result

    @staticmethod
    def _vanished_axes(slot_axes, retained_degree: int):
        """Axes of one slot that a resharding actually moves.  The dst
        annot replays the same take-first rule, so its retained factors
        consume the first SIZE-MATCHING axes of the slot (not simply
        the first k — with mixed primes, e.g. slot degree 6 = axes
        (2, 3), a retained degree 3 keeps the size-3 axis); whatever
        is left over is what the collective rides."""
        remaining = list(slot_axes)
        for p in prime_factors(retained_degree):
            for k, (_, size) in enumerate(remaining):
                if size == p:
                    del remaining[k]
                    break
        return remaining

    def _spans_dcn(
        self, slot_degrees: Tuple[int, ...], active_slots, retained=None
    ) -> Optional[int]:
        """The deepest link LEVEL a collective riding ``active_slots``
        of a view with ``slot_degrees`` crosses (0 = stays within one
        ICI domain/slice; k = the coarsest DCN class it must traverse —
        for the classic two-level machine the truthiness matches the
        historical crosses-DCN bool).  Groups along an axis of stride s
        and size f always live in ALIGNED blocks of span s*f (inner
        axes contribute < s to the base, outer axes multiples of the
        span), so the per-axis level is ``_axis_level(s*f)`` and the
        collective pays the worst axis.  ``retained[slot]`` is the
        degree the destination keeps on that slot — its size-matched
        axes are excluded (only the vanished axes move).  None =
        assignment failed."""
        dph = self.machine.devices_per_host
        if (self.num_devices or self.machine.num_devices) <= dph:
            return 0
        axes = self._slot_axes(tuple(slot_degrees))
        if axes is None:
            return None
        retained = retained or {}
        level = 0
        for slot in active_slots:
            ax = axes[slot]
            if slot in retained:
                ax = self._vanished_axes(ax, retained[slot])
            for stride, size in ax:
                level = max(level, self._axis_level(stride * size))
        return level

    def _net_groups(self, n: int) -> Optional[list]:
        """Candidate device groups for an n-way collective on the torus.
        The cost model only knows the group SIZE, not which mesh axis it
        rides: an inner-axis group is contiguous (0..n-1), an outer-axis
        group is strided (0, N/n, 2N/n, ...) and crosses more links.  We
        cost both and take the worst — underpricing outer-axis
        communication would bias the search toward strategies whose
        collectives are not actually cheap."""
        if self.network is None or n > self.network.topology.num_nodes:
            return None
        groups = [list(range(n))]
        stride = self.network.topology.num_nodes // n
        if stride > 1:
            groups.append(list(range(0, stride * n, stride)))
        return groups

    def _net_cached(self, kind: str, n: int, nbytes: float, fn) -> float:
        """Route expansion is O(n²) for all_to_all and runs in the
        search's innermost loop — memoize by (kind, n, nbytes): with the
        canonical groups these are pure functions of the key."""
        if not hasattr(self, "_net_cache"):
            self._net_cache = {}
        key = (kind, n, nbytes)
        hit = self._net_cache.get(key)
        if hit is None:
            hit = fn()
            self._net_cache[key] = hit
        return hit

    # ---- compute ---------------------------------------------------------
    def op_cost(self, op: Operator, mv: MachineView, backward: bool = True) -> float:
        """Per-iteration compute seconds for one shard of ``op`` under
        ``mv`` (all shards run concurrently on distinct devices).
        A calibration measurement for (op, view) overrides the
        roofline forward estimate when available."""
        # ops with a per-shard bytes hook (the paged-KV decode
        # attention) own their HBM-stream sharding rule: a head split
        # genuinely divides the cache read, and an armed serving spec
        # scales it to the ragged p-quantile load.  Such ops skip the
        # calibration override when a serving spec is armed — a lone-
        # chip probe measured full occupancy, which is exactly the
        # shape the serve currency must NOT price.
        sharded_bytes = getattr(op, "sharded_bytes_accessed", None)
        fwd = None
        if self.calibration is not None and not (
                sharded_bytes is not None and self.serving is not None):
            fwd = self.calibration.get(op, mv)
        if fwd is None:
            # replica groups do REDUNDANT work: only the partition count
            # shrinks each device's share.  Dividing by num_parts (which
            # includes replica_degree) priced an R8-replicated op at 1/8
            # of its true per-device cost and made the search replicate
            # compute that execution pays in full.
            parts = max(1, mv.num_parts // max(1, mv.replica_degree))
            flops = op.flops() / parts
            if sharded_bytes is not None:
                bytes_ = sharded_bytes(mv, serving=self.serving)
            else:
                bytes_ = op.bytes_accessed() / parts
            fwd = max(
                flops / self.machine.peak_flops,
                bytes_ / self.machine.hbm_bandwidth,
            )
        t = fwd + OP_OVERHEAD_S
        if backward:
            # bwd ≈ 2x fwd FLOPs for matmul-family, ~1x for elementwise
            bwd_factor = 2.0 if op.flops() > 4 * op.output_shapes[0].num_elements else 1.0
            t += bwd_factor * fwd + OP_OVERHEAD_S
            # training also pays the optimizer's elementwise update over
            # the local weight shard (measured on the host mesh: the
            # REPLICATED lm_head update dominated DP's real loss — a
            # weight-sharded view divides this term by its shard count)
            t += self.update_cost(op, mv)
        # ops whose sharded execution runs an internal collective (ring
        # attention over a split seq dim) declare the wire bytes — a
        # calibration measurement can't see them (probes run one chip).
        # Priced via allgather(): identical neighbor-ring pattern
        # ((n-1) hops of one shard), so the NetworkedMachineModel's
        # contention routing applies when configured.
        ring = getattr(op, "ring_comm_bytes", None)
        if ring is not None:
            nbytes, n, slot = ring(mv)
            if nbytes > 0.0:
                per_hop = nbytes / max(n - 1, 1)
                spans = self._spans_dcn(
                    tuple(mv.dim_degrees) + (mv.replica_degree,), [slot]
                )
                t += (2 if backward else 1) * self.allgather(
                    per_hop, n, spans
                )
        return t

    # ---- compressed collectives (EQuARX, arXiv:2506.17615) ---------------
    # elements per int8 scale block (comm/quantized.py DEFAULT_CHUNK);
    # each chunk ships one fp32 scale alongside its int8 payload
    QUANT_CHUNK = 256
    # HBM passes per quantize/dequantize endpoint (read fp32, write
    # int8+scales, read back ≈ 3 streaming passes over the buffer)
    QUANT_PASSES = 3.0

    # extra HBM passes the error-feedback residual costs per collective:
    # read the carried residual into the addend, write the new residual
    # back — two streaming passes over the full local fp32 buffer
    EF_PASSES = 2.0

    def _wire_scale(self, precision: Optional[str]) -> float:
        """Wire bytes per fp32 byte under the sync precision
        (``int8_ef`` rides the identical int8 wire — EF changes what is
        quantized, not the payload format)."""
        if precision == "bf16":
            return 0.5
        if precision in ("int8", "int8_ef"):
            return (1.0 + 4.0 / self.QUANT_CHUNK) / 4.0
        return 1.0

    def _quant_overhead(
        self, nbytes: float, n: int, precision: Optional[str]
    ) -> float:
        """Per-device quantize/dequant seconds for one compressed
        collective: the entry quantize runs over the full local buffer,
        the mid requant (between reduce-scatter and all-gather) over
        the 1/n reduced shard.  bf16 conversion is the same streaming
        pattern at the same pass count (the VPU cast is free; the
        traffic isn't).  ``int8_ef`` additionally pays the residual
        read + write (EF_PASSES over the full buffer) — the honest
        price of threading the error-feedback state."""
        if precision in (None, "fp32") or n <= 1:
            return 0.0
        t = (
            self.QUANT_PASSES * (nbytes + nbytes / n)
            / self.machine.hbm_bandwidth
        )
        if precision == "int8_ef":
            t += self.EF_PASSES * nbytes / self.machine.hbm_bandwidth
        return t

    # ---- collectives -----------------------------------------------------
    def _crosses(self, n: int, spans_dcn: Optional[int]) -> int:
        """The deepest link level an n-way collective rides (0 = pure
        ICI).  Axis-aware when the caller resolved it (``spans_dcn``,
        the level from ``_spans_dcn`` — legacy bool True maps to the
        deepest level), size heuristic otherwise."""
        if spans_dcn is not None:
            if spans_dcn is True:  # legacy callers/tests pass a bool
                return len(self.levels()) - 1
            return int(spans_dcn)
        if n > self.machine.devices_per_host:
            return len(self.levels()) - 1
        return 0

    def _link_time(
        self, bytes_per_device: float, n: int, spans_dcn: Optional[int] = None
    ) -> Tuple[float, float]:
        """(ici seconds, cross-level seconds) for moving bytes once
        around a ring of n devices; a ring crossing level k adds one
        term per traversed DCN class 1..k (the classic two-level
        machine keeps its single historical DCN term bit-identically)."""
        ici = bytes_per_device / self.machine.ici_bandwidth
        dcn = 0.0
        crossed = self._crosses(n, spans_dcn)
        if crossed:
            levels = self.levels()
            for i in range(1, crossed + 1):
                dcn += bytes_per_device / levels[i].bandwidth
        return ici, dcn

    def _cross_time(
        self, nbytes: float, n: int, spans_dcn: Optional[int]
    ) -> float:
        """Seconds per byte-unit across the traversed DCN classes (one
        term per level 1..crossed; 0 when the collective stays on ICI).
        The DCN add-on of the network-routed collective paths."""
        crossed = self._crosses(n, spans_dcn)
        if not crossed:
            return 0.0
        t = 0.0
        levels = self.levels()
        for i in range(1, crossed + 1):
            t += nbytes / levels[i].bandwidth
        return t

    def allreduce(
        self, nbytes: float, n: int, spans_dcn: Optional[bool] = None,
        precision: Optional[str] = None,
    ) -> float:
        """``precision`` (fp32|bf16|int8, default fp32) compresses the
        wire bytes by _wire_scale and adds the per-device quantize
        overhead — the EQuARX pricing the search uses to trade sync
        precision against everything else."""
        if n <= 1:
            return 0.0
        wire = nbytes * self._wire_scale(precision)
        extra = self._quant_overhead(nbytes, n, precision)
        groups = self._net_groups(n)
        if groups is not None:
            t = self._net_cached(
                "ar", n, wire,
                lambda: max(self.network.ring_allreduce_time(g, wire)
                            for g in groups))
            t += 2.0 * (n - 1) / n * self._cross_time(wire, n, spans_dcn)
            return t + extra
        ici, dcn = self._link_time(2.0 * (n - 1) / n * wire, n, spans_dcn)
        return ici + dcn + 2 * (n - 1) * self.machine.ici_latency + extra

    def allgather(
        self, nbytes_shard: float, n: int, spans_dcn: Optional[bool] = None,
        precision: Optional[str] = None,
    ) -> float:
        if n <= 1:
            return 0.0
        wire = nbytes_shard * self._wire_scale(precision)
        groups = self._net_groups(n)
        if groups is not None:
            t = self._net_cached(
                "ag", n, wire,
                lambda: max(self.network.allgather_time(g, wire)
                            for g in groups))
            t += (n - 1) * self._cross_time(wire, n, spans_dcn)
            return t
        ici, dcn = self._link_time((n - 1) * wire, n, spans_dcn)
        return ici + dcn + (n - 1) * self.machine.ici_latency

    def reducescatter(
        self, nbytes: float, n: int, spans_dcn: Optional[bool] = None,
        precision: Optional[str] = None,
    ) -> float:
        """One compressed phase plus the quantize passes (entry over
        the full buffer, shard-side dequant) — the ZeRO-1 grad path;
        the update's all-gather is priced separately."""
        return (
            self.allgather(nbytes / max(n, 1), n, spans_dcn, precision)
            + self._quant_overhead(nbytes, n, precision)
        )

    def all_to_all(
        self, nbytes_shard: float, n: int, spans_dcn: Optional[bool] = None
    ) -> float:
        if n <= 1:
            return 0.0
        groups = self._net_groups(n)
        if groups is not None:
            t = self._net_cached(
                "a2a", n, nbytes_shard,
                lambda: max(self.network.all_to_all_time(g, nbytes_shard)
                            for g in groups))
            t += (n - 1) / n * self._cross_time(nbytes_shard, n, spans_dcn)
            return t
        # each device exchanges (n-1)/n of its shard; ICI torus is
        # dimension-ordered so add a hop-count factor ~sqrt(n)/2
        hops = max(1.0, math.sqrt(n) / 2.0)
        ici, dcn = self._link_time(nbytes_shard * (n - 1) / n * hops, n, spans_dcn)
        return ici + dcn + (n - 1) * self.machine.ici_latency

    # ---- resharding (parallel-op) cost ----------------------------------
    def xfer_cost(
        self,
        shape: ParallelTensorShape,
        src: Optional[ShardAnnot],
        dst: Optional[ShardAnnot],
    ) -> float:
        """Edge cost when producer/consumer shardings differ — the role
        of estimate_xfer_cost (reference: simulator.cc:556-731), but
        classified into the collective GSPMD will emit.  Memoized — the
        search evaluates the same (shape, src, dst) triple millions of
        times (reference caches the same way, simulator.cc:515-554)."""
        if src is None or dst is None:
            return 0.0
        if not hasattr(self, "_xfer_cache"):
            self._xfer_cache = {}
        key = (shape.num_bytes, src, dst)
        hit = self._xfer_cache.get(key)
        if hit is None:
            hit = self._xfer_cost_uncached(shape, src, dst)
            self._xfer_cache[key] = hit
        return hit

    def _xfer_cost_uncached(
        self,
        shape: ParallelTensorShape,
        src: ShardAnnot,
        dst: ShardAnnot,
    ) -> float:
        if src.degrees == dst.degrees and src.partial == dst.partial:
            # NOTE: replica-degree differences are deliberately free — in
            # GSPMD a tensor is implicitly replicated over every mesh axis
            # its spec does not use, so "replicate to r" moves no bytes
            # (the producer's unused-axis devices already hold the value);
            # redundant compute is parallel in wall-time.  All-gather cost
            # appears only on sharded->unsharded dim changes (below).
            return 0.0
        n_src = max(1, src.num_parts)
        n_dst = max(1, dst.num_parts)
        total = shape.num_bytes
        # slot degrees in the producer view's assignment order,
        # approximated by the tensor's own dim order (exact when the
        # annot's parallel_idx is the identity — the common case)
        src_slots = tuple(src.degrees) + (src.replica,)
        if src.partial:
            # partial-sum producer: reduction (+ possible reshard).
            # The psum rides the replica/contraction slot.
            spans = self._spans_dcn(src_slots, [len(src.degrees)])
            return self.allreduce(
                total / max(n_dst // src.replica, 1), src.replica, spans
            )
        shard_src = total / max(n_src // max(src.replica, 1), 1)
        shard_dst = total / max(n_dst // max(dst.replica, 1), 1)
        # every emitted reshard op materializes its result through HBM
        # (write + read) and breaks XLA producer->consumer fusion —
        # charged on top of the link bytes below.  Without this term the
        # search trades noise-level compute wins for real boundary
        # copies (measured on the host mesh: a 1.4% predicted win
        # executed 7-12% slower).
        mat = (2.0 * shard_dst / self.machine.hbm_bandwidth
               + self.machine.reshard_overhead_s)
        n = max(n_src, n_dst)
        src_deg = 1
        for d in src.degrees:
            src_deg *= d
        dst_deg = 1
        for d in dst.degrees:
            dst_deg *= d
        if dst_deg > src_deg and all(
            dd % sd == 0 for sd, dd in zip(src.degrees, dst.degrees)
        ):
            # pure refinement (repartition): slicing is local when the
            # finer sharding nests in the coarser one
            return mat + OP_OVERHEAD_S
        if dst_deg < src_deg and all(
            sd % dd == 0 for sd, dd in zip(src.degrees, dst.degrees)
        ):
            # combine: all-gather over the vanished degree — only the
            # TAIL axes of each shrinking slot move (the retained dst
            # degree keeps the slot's first-assigned axes)
            shrink = [
                i for i, (sd, dd) in enumerate(zip(src.degrees, dst.degrees))
                if sd > dd
            ]
            spans = self._spans_dcn(
                src_slots, shrink, {i: dst.degrees[i] for i in shrink},
            )
            return (
                self.allgather(shard_src, src_deg // max(dst_deg, 1), spans)
                + mat + OP_OVERHEAD_S
            )
        if src_deg == dst_deg and src.replica == dst.replica:
            # pure dim-to-dim migration at constant total degree (e.g.
            # [B/8, S] -> [B, S/8]): GSPMD emits a true all-to-all over
            # the axes each shrinking slot releases
            moved = [
                i for i, (sd, dd) in enumerate(zip(src.degrees, dst.degrees))
                if sd > dd
            ]
            spans = self._spans_dcn(
                src_slots, moved,
                {i: math.gcd(src.degrees[i], dst.degrees[i]) for i in moved},
            )
            return self.all_to_all(shard_src, n, spans) + mat + OP_OVERHEAD_S
        # mixed transition (degrees change AND migrate across dims, or
        # the replica factor changes): the SPMD partitioner's fallback
        # is "involuntary full rematerialization" — all-gather to
        # replicated, then slice locally (observed XLA warning
        # spmd_partitioner.cc:652).  Charging only an all-to-all here
        # made the search pick reshardings that execution pays full
        # gather for.
        spans = self._spans_dcn(
            src_slots, [i for i, d in enumerate(src.degrees) if d > 1]
        )
        # full remat: the replicated intermediate (the WHOLE tensor) is
        # written and re-read on every device before the local re-slice
        return (self.allgather(shard_src, src_deg, spans)
                + 2.0 * total / self.machine.hbm_bandwidth
                + self.machine.reshard_overhead_s + OP_OVERHEAD_S)

    def placement_move_cost(
        self, shape: ParallelTensorShape, src: Optional[ShardAnnot],
        spans_dcn: bool = False,
    ) -> float:
        """Cost of relocating a tensor between disjoint device blocks
        (views with different start_part): each shard crosses ICI once —
        or DCN when the blocks live on different hosts/slices."""
        parts = max(1, src.num_parts) if src is not None else 1
        shard = shape.num_bytes / parts
        if spans_dcn:
            return shard / self.machine.dcn_bandwidth + self.machine.dcn_latency
        return shard / self.machine.ici_bandwidth + self.machine.ici_latency

    # ---- gradient synchronization ---------------------------------------
    # optimizer-update memory passes per weight element: Adam reads
    # (w, g, m, v) and writes (w, m, v) — ~7 sequential streams.  The
    # constant matters less than the SCALING: each device updates its
    # own weight SHARD, so sharding a weight divides its update traffic
    # while replication repeats it on every holder (the host_cpu
    # per-device bandwidth already encodes that holders share the core).
    OPT_UPDATE_PASSES = 7.0

    def weight_sync_parts(
        self, op: Operator, mv: MachineView
    ) -> Optional[list]:
        """The per-weight sync terms of one (op, view): a list of
        ``(shard_bytes, replica, spans_dcn, total_elems)`` tuples, one
        per weight whose propagated annot is replicated (replica > 1) —
        the shared decomposition ``weight_sync_cost`` sums and the
        gradient-sync SCHEDULE coalesces into fused buckets
        (search/sync_schedule.py, Simulator's per-bucket lanes).
        Returns None when propagation rejects the view."""
        try:
            osh = op.propagate(mv)
        except AssertionError:
            return None
        # view slot degrees in the lowering's assignment order
        # (output dims, then the replica/contraction slot)
        nslots = len(mv.dim_degrees)
        slot_degrees = tuple(mv.dim_degrees) + (mv.replica_degree,)
        parts = []
        for ws, annot in zip(op._weight_specs, osh.weights):
            if annot is None or annot.replica <= 1:
                continue
            n = 1
            for d in ws.shape:
                n *= d
            shard_elems = n
            for d in annot.degrees:
                shard_elems //= max(d, 1)
            # the grad psum rides every view slot the weight itself
            # does NOT consume (the weight is replicated across them)
            weight_slots = {
                s for s, d in zip(annot.parallel_idx(), annot.degrees)
                if d > 1 and s != -1
            }
            active = [
                i for i in range(nslots)
                if slot_degrees[i] > 1 and i not in weight_slots
            ]
            if mv.replica_degree > 1 and REPLICA_SLOT not in weight_slots:
                active.append(nslots)
            spans = self._spans_dcn(slot_degrees, active)
            # group key: the (slot degrees, active slots) signature —
            # under the lowering's canonical slot→axis assignment, two
            # weights share their replication MESH AXES (and so can ride
            # one fused collective, comm/bucketed.py groups by the axes)
            # only when this signature matches; bucket_sync_cost fuses
            # per key so mixed-sharding buckets are never under-priced
            # with fewer latency floors than execution pays
            parts.append(
                (shard_elems * ws.dtype.itemsize, annot.replica, spans, n,
                 (slot_degrees, tuple(active)))
            )
        return parts

    def weight_sync_cost(
        self, op: Operator, mv: MachineView, precision: str = "fp32"
    ) -> float:
        """Per-iteration grad-allreduce for weights replicated across
        ``mv`` (reference: NCCL allreduce in optimizer, optimizer.cc:155-193;
        here XLA's psum over the batch axes of the mesh), at the given
        wire ``precision``.  The optimizer's elementwise update is
        priced separately (``update_cost``) on the compute timeline."""
        parts = self.weight_sync_parts(op, mv)
        if parts is None:
            return math.inf
        total = 0.0
        for nbytes, replica, spans, n, _key in parts:
            # sub-floor weights (bias/scale vectors) sync at fp32 even
            # inside a compressed group — mirrors quantized_grad_sync's
            # per-weight MIN_COMPRESS_ELEMS skip exactly
            p = precision
            if p != "fp32" and n < _min_compress_elems():
                p = "fp32"
            total += self.allreduce(nbytes, replica, spans, precision=p)
        return total

    def bucket_sync_cost(self, parts: list, precision: str = "fp32",
                         plan=None, level_acc: Optional[dict] = None,
                         ) -> float:
        """Seconds for ONE coalesced sync bucket: every weight part
        sharing a replication-axes signature (the group key from
        ``weight_sync_parts``) and effective wire precision rides a
        single fused collective over the summed bytes — one latency
        term where ``weight_sync_cost`` pays one per weight.  That
        amortization is what the schedule search trades against
        exposure: XLA's all-reduce combiner batches small same-group
        all-reduces the same way, and the bucketed execution path
        (comm/bucketed.py) flattens each replication group's payload
        into one wire buffer for real — the key keeps the priced fusion
        granularity matched to the executed one, so mixed-sharding
        buckets never get credited fewer latency floors than execution
        pays.  Sub-floor weights inside a compressed bucket keep fp32,
        exactly as ``weight_sync_cost``/``quantized_grad_sync`` do.

        ``plan`` — a staged reduction plan (search/reduction_plan.py):
        groups whose replication spans a link-level boundary are then
        priced as the staged hierarchy (``staged_sync_cost``) at the
        plan's per-level wire precisions instead of one flat ring; a
        sub-floor (fp32-forced) group stays fp32 at every level.  With
        ``plan=None`` the pricing is unchanged — the flat bit-identical
        baseline.  ``level_acc`` accumulates per-link-level seconds
        (the ICI-vs-DCN lanes of the simulator breakdown)."""
        groups: Dict[Tuple, float] = {}
        for nbytes, replica, spans, n, key in parts:
            if replica <= 1:
                continue
            p = precision
            if p != "fp32" and n < _min_compress_elems():
                p = "fp32"
            gk = (replica, spans, p, key)
            groups[gk] = groups.get(gk, 0.0) + nbytes
        total = 0.0
        for (replica, spans, p, key), nbytes in groups.items():
            if plan is not None and spans:
                factors = self.replica_level_split(key, replica)
                deepest = 0 if factors is None else max(
                    (i for i, f in enumerate(factors) if f > 1), default=0)
                # stage only when the plan reaches EXACTLY the deepest
                # level this group spans (the SHD131 legality rule);
                # a mismatched plan would otherwise be priced with
                # compressed RS/AG stages or a flat-rated cross stage —
                # a shape the executor never runs
                if deepest > 0 and plan.cross_level == deepest:
                    precs = tuple(
                        sp if p != "fp32" else "fp32"
                        for sp in plan.level_precisions)
                    total += self.staged_sync_cost(
                        nbytes, factors, precs, level_acc)
                    continue
            t = self.allreduce(nbytes, replica, spans, precision=p)
            total += t
            if level_acc is not None:
                _merge_levels(level_acc, self.allreduce_level_split(
                    nbytes, replica, spans, p, total=t))
        return total

    # ---- hierarchical (staged) reduction pricing -------------------------
    def replica_level_split(self, key, replica: int):
        """Per-level group factors of one fused sync group: how the
        replica-allreduce of a weight part (the group key from
        ``weight_sync_parts``) decomposes over the link hierarchy —
        ``factors[0]`` devices within a slice x ``factors[1]`` slice
        groups at DCN level 1 x ...; the product equals ``replica``.
        None when the slot→axis assignment fails or does not reproduce
        the replica factor (callers fall back to flat pricing)."""
        slot_degrees, active = key
        axes = self._slot_axes(tuple(slot_degrees))
        if axes is None:
            return None
        factors = [1] * len(self.levels())
        for slot in active:
            for stride, size in axes[slot]:
                factors[self._axis_level(stride * size)] *= size
        p = 1
        for f in factors:
            p *= f
        if p != replica:
            return None
        return tuple(factors)

    def staged_sync_cost(self, nbytes: float, factors: Tuple[int, ...],
                         precisions: Tuple[str, ...],
                         level_acc: Optional[dict] = None) -> float:
        """Hierarchical allreduce over the level split ``factors``:
        reduce-scatter within each level-0 group, recursively allreduce
        the 1/f0 shard across the coarser levels, then all-gather
        within the group (the staged shape of arXiv:2110.10548; XLA's
        own multislice allreduce).  The cross-level traffic shrinks by
        the within-level factor — THE hierarchical win the flat ring
        never earns.  ``precisions[i]`` is the wire precision of the
        level-i stage (the RS/AG pair below the deepest level, the
        middle allreduce at it); per-level precision is how int8-over-
        DCN composes with fp32-over-ICI."""
        levels = self.levels()

        def go(nb: float, li: int) -> float:
            k = factors[li]
            deeper = any(f > 1 for f in factors[li + 1:])
            prec = precisions[li] if li < len(precisions) else "fp32"
            if not deeper:
                t = self.allreduce(nb, k, li, precision=prec)
                if level_acc is not None and k > 1:
                    _merge_levels(level_acc, self.allreduce_level_split(
                        nb, k, li, prec, total=t))
                return t
            t = 0.0
            if k > 1:
                rs = self.reducescatter(nb, k, li, prec)
                ag = self.allgather(nb / k, k, li, prec)
                t += rs + ag
                if level_acc is not None:
                    _merge_levels(
                        level_acc, {levels[li].name: rs + ag})
                nb = nb / k
            return t + go(nb, li + 1)

        return go(nbytes, 0)

    def allreduce_level_split(
        self, nbytes: float, n: int, spans_dcn: Optional[int] = None,
        precision: Optional[str] = None, total: Optional[float] = None,
    ) -> Dict[str, float]:
        """``allreduce(...)`` decomposed per link level (the predicted
        ICI-vs-DCN lanes): each traversed DCN class gets its ring-bytes
        term, level 0 the remainder (ici wire + latency + quantize
        overhead) — the split sums exactly to the scalar cost."""
        if total is None:
            total = self.allreduce(nbytes, n, spans_dcn, precision)
        if n <= 1 or not math.isfinite(total):
            return {}
        levels = self.levels()
        crossed = self._crosses(n, spans_dcn)
        wire = nbytes * self._wire_scale(precision)
        split: Dict[str, float] = {}
        acc = 0.0
        for i in range(1, crossed + 1):
            t = 2.0 * (n - 1) / n * wire / levels[i].bandwidth
            split[levels[i].name] = split.get(levels[i].name, 0.0) + t
            acc += t
        split[levels[0].name] = max(0.0, total - acc)
        return split

    def sync_levels(self, op: Operator, mv: MachineView) -> Dict[str, float]:
        """Per-link-level seconds of one (op, view)'s weight sync at the
        mode-selected wire precision — the per-level predicted comm rows
        the DriftReport renders (drift on the slow DCN class visible
        separately from intra-slice drift)."""
        parts = self.weight_sync_parts(op, mv)
        if not parts:
            return {}
        prec = self.sync_precision_choice(op, mv)[0]
        out: Dict[str, float] = {}
        for nbytes, replica, spans, n, _key in parts:
            p = prec
            if p != "fp32" and n < _min_compress_elems():
                p = "fp32"
            _merge_levels(out, self.allreduce_level_split(
                nbytes, replica, spans, p))
        return out

    # the search compresses a group's sync only where the allreduce
    # actually DOMINATES: fp32 sync must exceed this fraction of the
    # op's own compute+update time.  Where compute dominates, the sync
    # hides behind it (async collectives — simulate()'s comm timeline),
    # so quantization would trade gradient fidelity for nothing.
    SYNC_DOMINANCE = 0.5

    def sync_precision_choice(
        self, op: Operator, mv: MachineView
    ) -> Tuple[str, float]:
        """(wire precision, sync seconds) this cost model prices for
        one (op, view) — THE shared rule between the DP search (via
        ``sync_cost``), the simulator, and the execution-side map
        builder (search/sync_precision.py), so simulated strategies
        price compressed sync exactly as the lowering will run it."""
        base = self.weight_sync_cost(op, mv)
        mode = self.sync_precision or "fp32"
        if mode == "fp32" or base <= 0.0 or not math.isfinite(base):
            return "fp32", base
        from flexflow_tpu.search.sync_precision import grad_safe_to_compress

        if not grad_safe_to_compress(op):
            return "fp32", base
        if mode == "search":
            comp = self.op_cost(op, mv, backward=not self.inference)
            if not math.isfinite(comp) or base < self.SYNC_DOMINANCE * comp:
                return "fp32", base
            candidates = ("bf16", "int8")
        else:
            candidates = (mode,)
        best = ("fp32", base)
        for p in candidates:
            c = self.weight_sync_cost(op, mv, precision=p)
            if c < best[1]:
                best = (p, c)
        if best[0] == "int8" and self.sync_ef:
            # EF upgrade (FFConfig.sync_ef="auto"): same int8 wire plus
            # the residual passes, returned at its honest (slightly
            # higher) price — chosen for fidelity the currency cannot
            # see, never by the argmin above.  Unless the EF passes eat
            # the whole compression win: fp32 is then both exact AND
            # cheaper, so the upgrade falls back instead of picking a
            # strictly dominated wire.
            c_ef = self.weight_sync_cost(op, mv, precision="int8_ef")
            best = ("int8_ef", c_ef) if c_ef < base else ("fp32", base)
        return best

    def sync_cost(self, op: Operator, mv: MachineView) -> float:
        """weight_sync_cost at the precision the model's mode selects —
        what the simulator and both DP engines put on the comm
        timeline."""
        return self.sync_precision_choice(op, mv)[1]

    def update_cost(self, op: Operator, mv: MachineView) -> float:
        """Optimizer elementwise update over the local weight shard —
        serial compute at the tail of the step (it needs the final
        grads), so it belongs on the device timeline, unlike the
        overlappable grad allreduce."""
        if not op._weight_specs:
            return 0.0
        try:
            osh = op.propagate(mv)
        except AssertionError:
            return math.inf
        total = 0.0
        for ws, annot in zip(op._weight_specs, osh.weights):
            shard_elems = 1
            for d in ws.shape:
                shard_elems *= d
            if annot is not None:
                for d in annot.degrees:
                    shard_elems //= max(d, 1)
            total += (
                self.OPT_UPDATE_PASSES * shard_elems * ws.dtype.itemsize
                / self.machine.hbm_bandwidth
            )
        return total

    # ---- memory ----------------------------------------------------------
    def op_memory(self, op: Operator, mv: MachineView) -> float:
        """Per-device bytes: weights + activations for one shard."""
        try:
            osh = op.propagate(mv)
        except AssertionError:
            return math.inf
        mem = 0.0
        for ws, annot in zip(op._weight_specs, osh.weights):
            n = 1
            for d in ws.shape:
                n *= d
            for d in annot.degrees:
                n //= max(d, 1)
            w = n * ws.dtype.itemsize
            if self.inference:
                mem += w  # weights only: no grad, no optimizer state
                continue
            opt = w  # one optimizer-state share (weight + grad + opt)
            if self.zero_dp_shard:
                # mirror execution exactly (lowering._zero_augmented):
                # state shards over the mesh axes the weight does NOT
                # consume — implicit replication included — but only
                # onto evenly-divisible dims (place_zero_factors is THE
                # shared rule); unplaceable factors stay replicated, so
                # an indivisible weight is NOT credited savings it
                # won't get at runtime
                nd = self.num_devices or self.machine.num_devices
                sharded = 1
                for d in annot.degrees:
                    sharded *= max(d, 1)
                if sharded >= 1 and nd % sharded == 0 and nd > sharded:
                    extents = [
                        s // max(d, 1) if d and s % max(d, 1) == 0 else 1
                        for s, d in zip(ws.shape, annot.degrees)
                    ]
                    free = prime_factors(nd // sharded)
                    placed = place_zero_factors(extents, free)
                    achieved = 1
                    for _, fi in placed:
                        achieved *= free[fi]
                    opt = w / achieved
            mem += w * 2 + opt
        for shape, annot in zip(op.output_shapes, osh.outputs):
            n = shape.num_elements
            for d in annot.degrees:
                n //= max(d, 1)
            mem += n * shape.dtype.itemsize * (1 if self.inference else 2)
            # fwd activation (+ its grad when training)
        kv = getattr(op, "kv_cache_bytes", None)
        if kv is not None:
            # per-device KV residency at FULL page-pool occupancy (the
            # paged decode cache, ops/decode_attention.py): strategies
            # that cannot hold the pool are rejected inside the search's
            # memory check, not at runtime OOM.  Full occupancy, not the
            # arrival model's ragged load — HBM must fit the worst frame
            # the executor is allowed to admit.  Prefix sharing shrinks
            # that worst frame (shared pages are resident once across
            # the pool, ServingSpec.shared_residency_factor) — thread
            # the armed spec into hooks that accept it; legacy hooks
            # without the keyword price unshared.
            try:
                mem += kv(mv, serving=self.serving)
            except TypeError:
                mem += kv(mv)
        return mem
