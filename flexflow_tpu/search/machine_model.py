"""Analytic TPU cost model.

Replaces the reference's measured-kernel + bandwidth-table stack
(reference: src/runtime/machine_model.cc:57-68 SimpleMachineModel,
src/runtime/simulator.cc:515-787 measure_operator_cost /
estimate_xfer_cost) with a roofline model parameterized by MachineSpec:

* compute: max(FLOPs/MXU-peak, bytes/HBM-bw) per shard — correct
  first-order model for XLA-fused TPU programs, where the reference's
  per-op cuda-event timing has no analogue (ops fuse; SURVEY.md §7
  hard part (a)).  An optional on-device probe refines hot ops.
* collectives: ring formulas over ICI (bandwidth-optimal on a torus):
  allreduce 2(n-1)/n, allgather/reducescatter (n-1)/n, all_to_all
  (n-1)/n² per direction; DCN terms added when a collective spans
  hosts.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from flexflow_tpu.core.machine import MachineSpec, MachineView
from flexflow_tpu.core.ptensor import ParallelTensorShape
from flexflow_tpu.ops.base import Operator, ShardAnnot

# fixed per-op dispatch overhead inside one XLA program (fusion makes
# this tiny compared to the reference's per-task launch overhead)
OP_OVERHEAD_S = 2e-6


@dataclass
class CostModel:
    machine: MachineSpec
    # optional NetworkedMachineModel: collectives are then routed over
    # the ICI torus with per-link contention (search/network.py) instead
    # of the flat ring formulas
    network: Optional[object] = None
    # optional CalibrationTable of MEASURED per-(op, view) forward
    # seconds from the real chip — consulted before the roofline
    # (reference: ProfilingRecord cache, simulator.cc:515-554)
    calibration: Optional[object] = None

    def _net_groups(self, n: int) -> Optional[list]:
        """Candidate device groups for an n-way collective on the torus.
        The cost model only knows the group SIZE, not which mesh axis it
        rides: an inner-axis group is contiguous (0..n-1), an outer-axis
        group is strided (0, N/n, 2N/n, ...) and crosses more links.  We
        cost both and take the worst — underpricing outer-axis
        communication would bias the search toward strategies whose
        collectives are not actually cheap."""
        if self.network is None or n > self.network.topology.num_nodes:
            return None
        groups = [list(range(n))]
        stride = self.network.topology.num_nodes // n
        if stride > 1:
            groups.append(list(range(0, stride * n, stride)))
        return groups

    def _net_cached(self, kind: str, n: int, nbytes: float, fn) -> float:
        """Route expansion is O(n²) for all_to_all and runs in the
        search's innermost loop — memoize by (kind, n, nbytes): with the
        canonical groups these are pure functions of the key."""
        if not hasattr(self, "_net_cache"):
            self._net_cache = {}
        key = (kind, n, nbytes)
        hit = self._net_cache.get(key)
        if hit is None:
            hit = fn()
            self._net_cache[key] = hit
        return hit

    # ---- compute ---------------------------------------------------------
    def op_cost(self, op: Operator, mv: MachineView, backward: bool = True) -> float:
        """Per-iteration compute seconds for one shard of ``op`` under
        ``mv`` (all shards run concurrently on distinct devices).
        A calibration measurement for (op, view) overrides the
        roofline forward estimate when available."""
        fwd = None
        if self.calibration is not None:
            fwd = self.calibration.get(op, mv)
        if fwd is None:
            parts = max(1, mv.num_parts)
            flops = op.flops() / parts
            bytes_ = op.bytes_accessed() / parts
            fwd = max(
                flops / self.machine.peak_flops,
                bytes_ / self.machine.hbm_bandwidth,
            )
        t = fwd + OP_OVERHEAD_S
        if backward:
            # bwd ≈ 2x fwd FLOPs for matmul-family, ~1x for elementwise
            bwd_factor = 2.0 if op.flops() > 4 * op.output_shapes[0].num_elements else 1.0
            t += bwd_factor * fwd + OP_OVERHEAD_S
        return t

    # ---- collectives -----------------------------------------------------
    def _link_time(self, bytes_per_device: float, n: int) -> Tuple[float, float]:
        """(ici seconds, dcn seconds) for moving bytes once around a ring
        of n devices; adds a DCN term when the ring spans hosts."""
        ici = bytes_per_device / self.machine.ici_bandwidth
        dcn = 0.0
        if n > self.machine.devices_per_host:
            dcn = bytes_per_device / self.machine.dcn_bandwidth
        return ici, dcn

    def allreduce(self, nbytes: float, n: int) -> float:
        if n <= 1:
            return 0.0
        groups = self._net_groups(n)
        if groups is not None:
            t = self._net_cached(
                "ar", n, nbytes,
                lambda: max(self.network.ring_allreduce_time(g, nbytes)
                            for g in groups))
            if n > self.machine.devices_per_host:
                t += 2.0 * (n - 1) / n * nbytes / self.machine.dcn_bandwidth
            return t
        ici, dcn = self._link_time(2.0 * (n - 1) / n * nbytes, n)
        return ici + dcn + 2 * (n - 1) * self.machine.ici_latency

    def allgather(self, nbytes_shard: float, n: int) -> float:
        if n <= 1:
            return 0.0
        groups = self._net_groups(n)
        if groups is not None:
            t = self._net_cached(
                "ag", n, nbytes_shard,
                lambda: max(self.network.allgather_time(g, nbytes_shard)
                            for g in groups))
            if n > self.machine.devices_per_host:
                t += (n - 1) * nbytes_shard / self.machine.dcn_bandwidth
            return t
        ici, dcn = self._link_time((n - 1) * nbytes_shard, n)
        return ici + dcn + (n - 1) * self.machine.ici_latency

    def reducescatter(self, nbytes: float, n: int) -> float:
        return self.allgather(nbytes / max(n, 1), n)

    def all_to_all(self, nbytes_shard: float, n: int) -> float:
        if n <= 1:
            return 0.0
        groups = self._net_groups(n)
        if groups is not None:
            t = self._net_cached(
                "a2a", n, nbytes_shard,
                lambda: max(self.network.all_to_all_time(g, nbytes_shard)
                            for g in groups))
            if n > self.machine.devices_per_host:
                t += nbytes_shard * (n - 1) / n / self.machine.dcn_bandwidth
            return t
        # each device exchanges (n-1)/n of its shard; ICI torus is
        # dimension-ordered so add a hop-count factor ~sqrt(n)/2
        hops = max(1.0, math.sqrt(n) / 2.0)
        ici, dcn = self._link_time(nbytes_shard * (n - 1) / n * hops, n)
        return ici + dcn + (n - 1) * self.machine.ici_latency

    # ---- resharding (parallel-op) cost ----------------------------------
    def xfer_cost(
        self,
        shape: ParallelTensorShape,
        src: Optional[ShardAnnot],
        dst: Optional[ShardAnnot],
    ) -> float:
        """Edge cost when producer/consumer shardings differ — the role
        of estimate_xfer_cost (reference: simulator.cc:556-731), but
        classified into the collective GSPMD will emit.  Memoized — the
        search evaluates the same (shape, src, dst) triple millions of
        times (reference caches the same way, simulator.cc:515-554)."""
        if src is None or dst is None:
            return 0.0
        if not hasattr(self, "_xfer_cache"):
            self._xfer_cache = {}
        key = (shape.num_bytes, src, dst)
        hit = self._xfer_cache.get(key)
        if hit is None:
            hit = self._xfer_cost_uncached(shape, src, dst)
            self._xfer_cache[key] = hit
        return hit

    def _xfer_cost_uncached(
        self,
        shape: ParallelTensorShape,
        src: ShardAnnot,
        dst: ShardAnnot,
    ) -> float:
        if src.degrees == dst.degrees and src.partial == dst.partial:
            # NOTE: replica-degree differences are deliberately free — in
            # GSPMD a tensor is implicitly replicated over every mesh axis
            # its spec does not use, so "replicate to r" moves no bytes
            # (the producer's unused-axis devices already hold the value);
            # redundant compute is parallel in wall-time.  All-gather cost
            # appears only on sharded->unsharded dim changes (below).
            return 0.0
        n_src = max(1, src.num_parts)
        n_dst = max(1, dst.num_parts)
        total = shape.num_bytes
        if src.partial:
            # partial-sum producer: reduction (+ possible reshard)
            return self.allreduce(total / max(n_dst // src.replica, 1), src.replica)
        shard_src = total / max(n_src // max(src.replica, 1), 1)
        n = max(n_src, n_dst)
        src_deg = 1
        for d in src.degrees:
            src_deg *= d
        dst_deg = 1
        for d in dst.degrees:
            dst_deg *= d
        if dst_deg > src_deg and all(
            dd % sd == 0 for sd, dd in zip(src.degrees, dst.degrees)
        ):
            # pure refinement (repartition): slicing is local when the
            # finer sharding nests in the coarser one
            return OP_OVERHEAD_S
        if dst_deg < src_deg and all(
            sd % dd == 0 for sd, dd in zip(src.degrees, dst.degrees)
        ):
            # combine: all-gather over the vanished degree
            return self.allgather(shard_src, src_deg // max(dst_deg, 1))
        if src_deg == dst_deg and src.replica == dst.replica:
            # pure dim-to-dim migration at constant total degree (e.g.
            # [B/8, S] -> [B, S/8]): GSPMD emits a true all-to-all
            return self.all_to_all(shard_src, n)
        # mixed transition (degrees change AND migrate across dims, or
        # the replica factor changes): the SPMD partitioner's fallback
        # is "involuntary full rematerialization" — all-gather to
        # replicated, then slice locally (observed XLA warning
        # spmd_partitioner.cc:652).  Charging only an all-to-all here
        # made the search pick reshardings that execution pays full
        # gather for.
        return self.allgather(shard_src, src_deg) + OP_OVERHEAD_S

    def placement_move_cost(
        self, shape: ParallelTensorShape, src: Optional[ShardAnnot]
    ) -> float:
        """Cost of relocating a tensor between disjoint device blocks
        (views with different start_part): each shard crosses ICI once."""
        parts = max(1, src.num_parts) if src is not None else 1
        shard = shape.num_bytes / parts
        return shard / self.machine.ici_bandwidth + self.machine.ici_latency

    # ---- gradient synchronization ---------------------------------------
    def weight_sync_cost(self, op: Operator, mv: MachineView) -> float:
        """Per-iteration grad-allreduce for weights replicated across
        ``mv`` (reference: NCCL allreduce in optimizer, optimizer.cc:155-193;
        here XLA's psum over the batch axes of the mesh)."""
        try:
            osh = op.propagate(mv)
        except AssertionError:
            return math.inf
        total = 0.0
        for ws, annot in zip(op._weight_specs, osh.weights):
            if annot is None or annot.replica <= 1:
                continue
            n = 1
            for d in ws.shape:
                n *= d
            shard_elems = n
            for d in annot.degrees:
                shard_elems //= max(d, 1)
            total += self.allreduce(shard_elems * ws.dtype.itemsize, annot.replica)
        return total

    # ---- memory ----------------------------------------------------------
    def op_memory(self, op: Operator, mv: MachineView) -> float:
        """Per-device bytes: weights + activations for one shard."""
        try:
            osh = op.propagate(mv)
        except AssertionError:
            return math.inf
        mem = 0.0
        for ws, annot in zip(op._weight_specs, osh.weights):
            n = 1
            for d in ws.shape:
                n *= d
            for d in annot.degrees:
                n //= max(d, 1)
            mem += n * ws.dtype.itemsize * 3  # weight + grad + opt state
        for shape, annot in zip(op.output_shapes, osh.outputs):
            n = shape.num_elements
            for d in annot.degrees:
                n //= max(d, 1)
            mem += n * shape.dtype.itemsize * 2  # fwd + grad
        return mem
