"""Gradient-sync schedule: bucketed, issue-ordered, searched.

The simulator has always CREDITED async-collective overlap — a weight
group's allreduce rides the comm timeline and hides under later compute
— but the executed step fires ONE monolithic post-backward sync
(compiler/lowering.py ``_sync_grads``), so the predicted and real
timelines systematically disagreed on exactly the term the
sync-precision search made searchable.  GSPMD (arXiv:2105.04663) hides
reduction latency by issuing collectives asynchronously under the
remaining backward; the cross-replica weight-update sharding work
(arXiv:2004.13336) shows the sync/update tail is where data-parallel
steps lose their time.  This module closes the loop: the sync becomes a
first-class, searched, persisted, linted ARTIFACT —

* a ``SyncSchedule`` partitions the strategy's synced weight groups
  into issue-ordered buckets, reverse-topological so a bucket's fused
  collective issues as soon as the backward has produced its members'
  grads, overlapping the rest of the backward;
* small groups coalesce to amortize per-collective latency (the cost
  model prices one latency term per fused bucket,
  ``CostModel.bucket_sync_cost``); per-bucket precision composes with
  the sync-precision map (search/sync_precision.py);
* ``choose_sync_schedule`` sweeps coalescing thresholds under
  ``FFConfig.sync_schedule="search"``, prices every candidate with the
  simulator's exposed-comm semantics (``simulate(sync_schedule=...)``)
  and returns a schedule only when it beats the monolithic baseline;
* the result embeds in the strategy file's ``__meta__`` (strategy_io)
  behind the existing graph-digest gate, is linted always-on
  (``analysis.lint_sync_schedule``, SHD12x) wherever it is produced or
  imported, and is executed for real by ``comm/bucketed.py``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

SCHEDULE_SCHEMA = 1

# wire precisions a bucket may carry — mirrors comm.quantized
# SYNC_PRECISIONS without importing jax (this module must stay loadable
# by the stdlib-only lint path).  "int8_ef" is the error-feedback
# variant of int8 (comm.quantized_allreduce_ef): identical wire format,
# the device re-injects its local quantization error next step via a
# residual carried as training-loop state (FFConfig.sync_ef)
BUCKET_PRECISIONS = ("fp32", "bf16", "int8", "int8_ef")


def wire_base(precision: Optional[str]) -> Optional[str]:
    """The on-wire format of a bucket precision: ``int8_ef`` rides the
    plain int8 wire (EF changes WHAT is quantized, not the payload) —
    the normalization every consumer of the raw collective applies
    (staged cross-slice stages, the execution dispatch, SHD133)."""
    return "int8" if precision == "int8_ef" else precision

# default coalescing floors swept by the search when FFConfig does not
# pin one (sync_bucket_bytes): fused-bucket fp32 payload bytes below
# which the next group keeps joining the open bucket.  Small floors
# maximize overlap (more, earlier issue points), large floors maximize
# latency amortization — the simulator arbitrates.
DEFAULT_BUCKET_BYTES = (1 << 20, 4 << 20, 16 << 20)


@dataclass(frozen=True)
class SyncBucket:
    """One fused gradient-sync collective: the named weight groups'
    grads flatten into a single wire payload at ``precision``.
    ``plan`` — an optional staged reduction plan for hierarchical
    topologies (search/reduction_plan.py): the bucket's cross-slice
    traffic then rides the staged RS/AR/AG shape at per-level wire
    precision instead of one flat ring; None keeps the flat collective
    (always the case on single-level machines)."""

    name: str
    ops: Tuple[str, ...]
    precision: str = "fp32"
    plan: Optional[object] = None  # reduction_plan.ReductionPlan


@dataclass
class SyncSchedule:
    """Issue-ordered bucket list (bucket 0 = the deepest layers, whose
    grads the backward produces FIRST) plus provenance metadata."""

    buckets: List[SyncBucket]
    meta: Dict = field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.buckets)

    def covered_ops(self) -> List[str]:
        out: List[str] = []
        for b in self.buckets:
            out.extend(b.ops)
        return out

    def to_jsonable(self) -> dict:
        out = []
        for b in self.buckets:
            d = {"name": b.name, "ops": list(b.ops),
                 "precision": b.precision}
            if b.plan is not None:
                d["plan"] = b.plan.to_jsonable()
            out.append(d)
        return {
            "schema": SCHEDULE_SCHEMA,
            "buckets": out,
            **({"meta": dict(self.meta)} if self.meta else {}),
        }

    @staticmethod
    def from_jsonable(data) -> "SyncSchedule":
        """Parse a persisted schedule (strategy-file ``__meta__`` entry).
        Raises ``ValueError`` on structural malformation — semantic
        legality against a (graph, strategy) is the lint's job
        (``analysis.lint_sync_schedule``)."""
        if not isinstance(data, dict):
            raise ValueError("sync_schedule is not an object")
        if data.get("schema") != SCHEDULE_SCHEMA:
            raise ValueError(
                f"unknown sync_schedule schema {data.get('schema')!r} "
                f"(known: {SCHEDULE_SCHEMA})")
        raw = data.get("buckets")
        if not isinstance(raw, list) or not raw:
            raise ValueError("sync_schedule has no buckets")
        buckets = []
        for i, b in enumerate(raw):
            if not isinstance(b, dict):
                raise ValueError(f"buckets[{i}] is not an object")
            ops = b.get("ops")
            if (not isinstance(ops, list) or not ops
                    or any(not isinstance(o, str) for o in ops)):
                raise ValueError(f"buckets[{i}] has malformed ops {ops!r}")
            prec = b.get("precision", "fp32")
            if prec not in BUCKET_PRECISIONS:
                raise ValueError(
                    f"buckets[{i}] precision {prec!r} not in "
                    f"{BUCKET_PRECISIONS}")
            name = b.get("name")
            if not isinstance(name, str) or not name:
                raise ValueError(f"buckets[{i}] has no name")
            plan = None
            if b.get("plan") is not None:
                from flexflow_tpu.search.reduction_plan import ReductionPlan

                try:
                    plan = ReductionPlan.from_jsonable(b["plan"])
                except ValueError as e:
                    raise ValueError(
                        f"buckets[{i}] carries a malformed reduction "
                        f"plan: {e}") from e
            buckets.append(SyncBucket(name=name, ops=tuple(ops),
                                      precision=prec, plan=plan))
        meta = data.get("meta")
        return SyncSchedule(buckets, dict(meta) if isinstance(meta, dict)
                            else {})


def synced_weight_groups(graph, strategy, cost_model) -> List[Tuple]:
    """Topo-ordered ``(node, view, parts)`` for every op whose weights
    actually sync under ``strategy`` (some propagated weight annot has
    replica > 1) — THE membership rule the schedule builder, the
    simulator's coverage fallback, and the legality lint all share."""
    from flexflow_tpu.core.machine import MachineView

    out = []
    for node in graph.topo_order():
        if not node.op._weight_specs:
            continue
        mv = strategy.get(node.guid)
        if mv is None:
            mv = node.op.fixed_machine_view() or MachineView.trivial(
                node.op.output_shapes[0].ndim
            )
        parts = cost_model.weight_sync_parts(node.op, mv)
        if parts:
            out.append((node, mv, parts))
    return out


def build_bucketed_schedule(
    synced: List[Tuple],
    precision_map: Optional[Dict[str, str]] = None,
    min_bucket_bytes: float = math.inf,
) -> Optional[SyncSchedule]:
    """Greedy reverse-topological coalescing: walk the synced groups in
    backward-readiness order (last topo position first — its grads are
    produced first), open a new bucket whenever the wire precision
    changes or the open bucket's fp32 payload has reached
    ``min_bucket_bytes``.  ``math.inf`` yields the per-precision
    MONOLITHIC schedule — the executed status quo, priced in the same
    currency so the search's comparison is apples to apples."""
    if not synced:
        return None
    pmap = precision_map or {}
    buckets: List[SyncBucket] = []
    cur_ops: List[str] = []
    cur_prec: Optional[str] = None
    cur_bytes = 0.0

    def close():
        nonlocal cur_ops, cur_bytes
        if cur_ops:
            buckets.append(SyncBucket(
                name=f"b{len(buckets)}", ops=tuple(cur_ops),
                precision=cur_prec or "fp32"))
        cur_ops, cur_bytes = [], 0.0

    for node, _mv, parts in reversed(synced):
        prec = pmap.get(node.op.name, "fp32")
        if cur_ops and (prec != cur_prec or cur_bytes >= min_bucket_bytes):
            close()
        cur_prec = prec
        cur_ops.append(node.op.name)
        cur_bytes += sum(p[0] for p in parts)
    close()
    return SyncSchedule(buckets)


def lint_gate(graph, strategy, schedule, precision_map=None,
              cost_model=None) -> None:
    """Always-on legality gate on a schedule THIS tree produced: an
    error finding here is a builder bug, not a user error — fail loudly
    before the artifact is persisted or executed (same discipline as
    ``optimize_strategy``'s strategy gate).  With a ``cost_model`` the
    per-bucket reduction plans are gated too (SHD13x — level coverage,
    group/slice coherence, precision-per-level validity)."""
    from flexflow_tpu.analysis import (
        AnalysisError,
        emit_findings,
        errors_only,
        lint_sync_schedule,
    )

    findings = lint_sync_schedule(graph, strategy, schedule, precision_map)
    if cost_model is not None:
        from flexflow_tpu.analysis import lint_reduction_plan

        findings = findings + lint_reduction_plan(
            graph, strategy, schedule, cost_model)
    bad = errors_only(findings)
    if bad:
        emit_findings(bad)
        raise AnalysisError(
            "sync-schedule builder produced an illegal schedule", bad)


def choose_sync_schedule(
    graph,
    strategy,
    sim,
    precision_map: Optional[Dict[str, str]] = None,
    config=None,
) -> Tuple[Optional[SyncSchedule], Dict]:
    """Pick bucket composition + issue order for ``(graph, strategy)``
    under the simulator's exposed-comm pricing.  Returns
    ``(schedule, info)`` — ``schedule`` is None when no bucketing beats
    the monolithic baseline (the bit-exact status quo then stands);
    ``info`` records the comparison for telemetry/bench.  ``sim`` must
    be the Simulator the search ranked with, so the schedule is chosen
    in the same cost currency the strategy was.  The returned schedule
    has passed the always-on legality gate (``lint_gate``).

    On a hierarchical machine (MachineSpec.topology_levels > 1) the
    search gains the REDUCTION-PLAN dimension: every candidate (the
    monolithic baseline included) is also priced with per-bucket
    staged plans (search/reduction_plan.py — RS within slice, small
    cross-slice exchange at per-level wire precision, AG within slice)
    and the staged variant is adopted only when it beats the flat
    plan.  Flat single-level machines enumerate no plans, so their
    choice is bit-identical to the plan-free search."""
    info: Dict = {"monolithic_s": None, "scheduled_s": None, "buckets": 0,
                  "staged_buckets": 0}
    synced = synced_weight_groups(graph, strategy, sim.cost)
    multi_level = len(sim.cost.levels()) > 1
    if not synced or (len(synced) < 2 and not multi_level):
        return None, info  # nothing to order, coalesce, or stage
    names = [node.op.name for node, _mv, _p in synced]
    if len(names) != len(set(names)):
        # stamped production graphs (PR 7 segment stamping) can repeat
        # op names; buckets are keyed by name, so a schedule cannot
        # address such groups individually — the monolithic status quo
        # stands (SHD121's exact-once coverage would reject any
        # schedule built here)
        return None, info
    pmap = dict(precision_map or {})
    mono = build_bucketed_schedule(synced, pmap, math.inf)
    base = sim.simulate(graph, strategy, sync_schedule=mono)
    info["monolithic_s"] = base
    if not math.isfinite(base):
        return None, info
    thresholds: List[float] = []
    pinned = getattr(config, "sync_bucket_bytes", 0) if config else 0
    if pinned:
        thresholds = [float(pinned)]
    else:
        total = sum(p[0] for _n, _mv, parts in synced for p in parts)
        thresholds = sorted(
            {float(t) for t in DEFAULT_BUCKET_BYTES}
            # adaptive points so small models still split into a few
            # buckets instead of collapsing to the monolithic shape
            | {max(1.0, total / 8.0), max(1.0, total / 4.0)}
        )
    best: Tuple[Optional[SyncSchedule], float] = (None, base)
    priced = set()  # adjacent thresholds often coalesce identically —
    # don't pay a full simulate per duplicate composition
    for th in thresholds:
        cand = build_bucketed_schedule(synced, pmap, th)
        if cand is None or len(cand.buckets) <= len(mono.buckets):
            continue
        key = tuple(b.ops for b in cand.buckets)
        if key in priced:
            continue
        priced.add(key)
        c = sim.simulate(graph, strategy, sync_schedule=cand)
        if c < best[1]:
            cand.meta = {"bucket_bytes": th}
            best = (cand, c)

    # ---- reduction-plan dimension (hierarchical topologies only) ----
    # the flat-winner AND the monolithic baseline both get a staged
    # variant priced; a staged plan is adopted only when its simulated
    # step beats everything flat (single-level machines enumerate no
    # plans, so this is a no-op there — bit-identical flat behavior)
    if multi_level:
        from flexflow_tpu.search.reduction_plan import (
            assign_reduction_plans,
        )

        plan_candidates = [mono]
        if best[0] is not None:
            plan_candidates.append(best[0])
        for cand in plan_candidates:
            aug, ainfo = assign_reduction_plans(cand, synced, sim.cost)
            if aug is None:
                continue
            c = sim.simulate(graph, strategy, sync_schedule=aug)
            if c < best[1]:
                aug.meta.update(cand.meta)
                aug.meta["reduction_plans"] = {
                    b.name: b.plan.name for b in aug.buckets
                    if b.plan is not None}
                best = (aug, c)
                info["staged_buckets"] = ainfo["staged_buckets"]
                info["flat_sync_s"] = ainfo["flat_sync_s"]
                info["planned_sync_s"] = ainfo["planned_sync_s"]

    schedule, cost = best
    if schedule is None:
        return None, info  # scheduled_s stays None: monolithic stands
    info["scheduled_s"] = cost
    info["buckets"] = len(schedule.buckets)
    schedule.meta.update(
        predicted_monolithic_s=base, predicted_scheduled_s=cost)
    lint_gate(graph, strategy, schedule, pmap, cost_model=sim.cost)
    return schedule, info
