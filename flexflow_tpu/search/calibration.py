"""Measured per-(op, view) cost calibration.

The reference ranks strategies with MEASURED kernel times, cached per
(op params, machine view) and collected on a real GPU inside the search
(reference: src/runtime/simulator.cc:515-554 ProfilingRecord cache;
src/runtime/model.cu:38-74 warmup+repeat cuda-event timing).  The TPU
analogue measures one jitted forward of the op at its per-shard shapes
on the real chip (runtime/profiler.measure_operator_cost) and persists
the result in a ``CalibrationTable`` that ``CostModel.op_cost`` consults
before its analytic roofline fallback.

Because XLA fuses aggressively, a lone-op probe is an upper bound on
the op's in-graph cost (SURVEY.md §7 hard part (a)); it still captures
the shard-size nonlinearities (MXU tiling, small-matmul inefficiency)
the roofline cannot, which is what strategy *ranking* needs.
"""

from __future__ import annotations

import json
import math
import time
from typing import Dict, Optional, Tuple

from flexflow_tpu.core.graph import Graph
from flexflow_tpu.core.machine import MachineView

Key = Tuple[str, Tuple[int, ...], int]


class CalibrationTable:
    """Persisted measured-forward-seconds per (op signature, view) —
    the reference's ProfilingRecord hash cache (simulator.cc:515-554),
    with a JSON file standing in for the in-memory lifetime of the
    reference's single search task."""

    def __init__(self):
        self._t: Dict[Key, float] = {}
        self.backend: Optional[str] = None  # platform the probes ran on

    @staticmethod
    def key(op, mv: MachineView) -> Key:
        return (
            repr(op.signature()),
            tuple(mv.dim_degrees),
            int(mv.replica_degree),
        )

    def get(self, op, mv: MachineView) -> Optional[float]:
        return self._t.get(self.key(op, mv))

    def put(self, op, mv: MachineView, seconds: float) -> None:
        self._t[self.key(op, mv)] = float(seconds)

    def __len__(self) -> int:
        return len(self._t)

    def save(self, path: str) -> None:
        if self.backend is None:
            try:
                import jax

                self.backend = jax.devices()[0].platform
            except Exception:  # pragma: no cover
                pass
        rows = [
            {"sig": k[0], "degrees": list(k[1]), "replica": k[2], "seconds": v}
            for k, v in sorted(self._t.items())
        ]
        with open(path, "w") as f:
            json.dump(
                {"version": 1, "backend": self.backend, "records": rows},
                f, indent=1,
            )

    @staticmethod
    def load(path: str) -> "CalibrationTable":
        table = CalibrationTable()
        with open(path) as f:
            data = json.load(f)
        table.backend = data.get("backend")
        for r in data.get("records", []):
            table._t[(r["sig"], tuple(r["degrees"]), int(r["replica"]))] = float(
                r["seconds"]
            )
        return table


def _shard_sizes(sizes, annot) -> Tuple[int, ...]:
    if annot is None:
        return tuple(sizes)
    out = []
    for i, s in enumerate(sizes):
        d = annot.degrees[i] if i < len(annot.degrees) else 1
        out.append(max(1, s // max(d, 1)))
    return tuple(out)


def measure_op_view(
    op, mv: MachineView, warmup: int = 1, repeats: int = 3
) -> Optional[float]:
    """Median seconds of one jitted forward of ``op`` at the per-shard
    shapes ``mv`` induces (via the op's own degree propagation), on the
    live jax backend.  None when the op cannot be probed standalone
    (shape-monomorphic forward, invalid view) — callers keep the
    roofline for those."""
    import jax.numpy as jnp

    from flexflow_tpu.runtime.profiler import measure_operator_cost

    try:
        osh = op.propagate(mv)
    except AssertionError:
        return None
    try:
        inputs = [
            jnp.zeros(_shard_sizes(s.sizes, a), s.dtype.to_numpy())
            for s, a in zip(op.input_shapes, osh.inputs)
        ]
        weight_shapes = {
            ws.name: _shard_sizes(ws.shape, a)
            for ws, a in zip(getattr(op, "_weight_specs", ()), osh.weights)
        }
        return measure_operator_cost(
            op,
            batch_inputs=inputs,
            warmup=warmup,
            repeats=repeats,
            weight_shapes=weight_shapes,
        )
    except Exception:
        # ops whose forward bakes in logical sizes (reshape etc.) can't
        # be probed at shard shapes; the analytic model covers them
        return None


def calibrate_graph(
    graph: Graph,
    num_devices: int,
    table: Optional[CalibrationTable] = None,
    time_budget_s: float = 120.0,
    repeats: int = 3,
) -> CalibrationTable:
    """Fill ``table`` with measurements for every distinct
    (op signature, candidate view) in ``graph`` — the probe set the
    search will actually query (reference measures lazily mid-search,
    simulator.cc:515; measuring up front keeps the search itself pure).
    Budget-bounded: stops adding new probes when the wall budget is
    spent (existing entries are never re-measured)."""
    from flexflow_tpu.search.views import boundary_views, candidate_views

    # NOT `table or ...`: an empty CalibrationTable is falsy (__len__ == 0),
    # and the caller's table must be filled in place
    table = table if table is not None else CalibrationTable()
    deadline = time.monotonic() + time_budget_s
    for node in graph.topo_order():
        op = node.op
        views = list(candidate_views(op, num_devices))
        for bv in boundary_views(op, num_devices):
            if bv not in views:
                views.append(bv)
        for mv in views:
            if table.get(op, mv) is not None:
                continue
            if time.monotonic() > deadline:
                from flexflow_tpu.utils.logging import SEARCH_LOG as log

                log.log(
                    f"calibration budget ({time_budget_s:.0f}s) spent at "
                    f"node {node.op.name!r}: later (op, view) probes keep "
                    f"the analytic roofline"
                )
                return table
            t = measure_op_view(op, mv, repeats=repeats)
            if t is not None and math.isfinite(t) and t > 0:
                table.put(op, mv, t)
    return table
