"""Search-proposed inter-op placement over two disjoint device blocks.

The Unity search's VERTICAL resource splits assign subgraphs to
disjoint device boxes and the mapper executes that placement
(reference: src/runtime/graph.cc:161-295 execute_nonsequence_split;
src/mapper/mapper.cc:371-475).  This framework's flat search costs
every strategy with ``placement_overlap=False`` because its default
execution is ONE SPMD program (small-degree views replicate, offsets
are inert).  This pass closes the loop the other way: it enumerates
2-block cut candidates of the PCG, intra-op-searches each side on its
own device block with the overlap-aware simulator, prices the placed
executor's actual schedule (compiler/placement_lowering.py):

    T_placed = T_A(full step on block A) + T_B(full step on block B)
             + 2 x sum(crossing-tensor moves)        (fwd + cotangent)

and returns the best start_part-carrying strategy that passes
``placeable()`` and beats the flat strategy by the search margin.

The honest win regime is a DCN-spanning machine: each block's weight
syncs stay inside one ICI domain and only the crossing activations pay
DCN — the same mechanism that makes the pipeline proposal win
(search/pipeline_search.py).  On a single ICI domain the flat SPMD
program can spread every op over all devices, so placement rarely wins
there and this pass returns None.
"""

from __future__ import annotations

import math
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from flexflow_tpu.core.graph import Graph
from flexflow_tpu.core.machine import MachineView

Strategy = Dict[int, MachineView]


def _ancestors(graph: Graph, guid: int) -> Set[int]:
    out: Set[int] = set()
    stack = [e.src for e in graph.in_edges[guid]]
    while stack:
        g = stack.pop()
        if g in out:
            continue
        out.add(g)
        stack.extend(e.src for e in graph.in_edges[g])
    return out


def _cut_candidates(graph: Graph, max_candidates: int = 6,
                    max_crossing: int = 16) -> List[FrozenSet[int]]:
    """Predecessor-closed node sets A (block-0 side of a cut), ranked by
    forward-FLOP balance.  Closure under predecessors guarantees no
    back edges; candidates come from ``A = ancestors(x)`` and
    ``A = ancestors(x) + {x}`` for every interior node x — this covers
    both sequence cuts (x a bottleneck) and join cuts (x a concat whose
    towers land in A), the two shapes the reference's VERTICAL splits
    produce."""
    sinks = graph.sinks()
    if not sinks:
        return []
    sink_guid = sinks[-1].guid
    flops = {g: n.op.flops() for g, n in graph.nodes.items()}
    total = sum(flops.values()) or 1.0
    seen: Set[FrozenSet[int]] = set()
    scored: List[Tuple[float, FrozenSet[int]]] = []
    for guid in graph.nodes:
        if guid == sink_guid:
            continue
        anc = _ancestors(graph, guid)
        for a_set in (frozenset(anc), frozenset(anc | {guid})):
            if not a_set or sink_guid in a_set:
                continue
            if len(a_set) >= graph.num_nodes:
                continue
            if a_set in seen:
                continue
            seen.add(a_set)
            crossing = {
                (e.src, e.src_idx)
                for g in a_set
                for e in graph.out_edges[g]
                if e.dst not in a_set
            }
            if not 0 < len(crossing) <= max_crossing:
                continue
            frac = sum(flops[g] for g in a_set) / total
            # prefer balanced cuts with few crossing tensors
            scored.append((abs(frac - 0.5) + 0.02 * len(crossing), a_set))
    scored.sort(key=lambda t: t[0])
    return [a for _, a in scored[:max_candidates]]


def _budget_pairs(n: int) -> List[Tuple[int, int]]:
    cands = {n // 2, n // 4, n - n // 4}
    return sorted(
        (a, n - a) for a in cands if 0 < a < n
    )


def propose_placement(graph: Graph, config, flat_cost: float,
                      calibration=None) -> Optional[Strategy]:
    """Best 2-block placed strategy whose modeled step time beats
    ``flat_cost`` by more than the search margin, or None."""
    import jax

    from flexflow_tpu.compiler.placement_lowering import (
        MAX_CROSSING_TENSORS,
        placeable,
    )
    from flexflow_tpu.search.dp import SearchHelper
    from flexflow_tpu.search.simulator import Simulator

    n = config.search_devices
    if n < 2 or jax.process_count() > 1:
        return None
    if getattr(config, "grad_accum_steps", 1) > 1:
        return None
    if getattr(config, "zero_dp_shard", False):
        return None
    if graph.num_nodes > config.placement_search_max_nodes:
        return None

    sim = Simulator.for_config(
        config, calibration=calibration, placement_overlap=True
    )
    helper = SearchHelper(sim, n)
    best: Optional[Tuple[float, Strategy]] = None
    for a_set in _cut_candidates(
            graph, max_crossing=MAX_CROSSING_TENSORS):
        b_set = set(graph.nodes) - a_set
        graph_a = graph._subgraph(set(a_set))
        graph_b = graph._subgraph(b_set)
        # distinct crossing TENSORS: the placed executor transfers each
        # (src, src_idx) exactly once however many B-side consumers it
        # has (placement_lowering boundary_srcs is the same set)
        crossing = sorted({
            (e.src, e.src_idx)
            for g in a_set
            for e in graph.out_edges[g]
            if e.dst not in a_set
        })
        dph = getattr(sim.machine, "devices_per_host", 0) or n
        for a, b in _budget_pairs(n):
            ca, sa = helper.graph_cost(graph_a, budget=a, start=0)
            if not math.isfinite(ca):
                continue
            cb, sb = helper.graph_cost(graph_b, budget=b, start=a)
            if not math.isfinite(cb):
                continue
            # the boundary crosses DCN when block B extends beyond block
            # A's hosts — exactly the regime this pass targets, so the
            # move must be priced at DCN speed there, not ICI
            spans_dcn = (a + b - 1) // dph > (a - 1) // dph
            moves = 0.0
            for src, idx in crossing:
                node = graph.nodes[src]
                mv = sa.get(src)
                osh = sim._propagate(node, mv) if mv is not None else None
                annot = (
                    osh.outputs[idx]
                    if osh is not None and idx < len(osh.outputs)
                    else None
                )
                shape = node.op.output_shapes[idx]
                # activation forward + cotangent back, each one
                # cross-block move
                moves += 2.0 * sim.cost.placement_move_cost(
                    shape, annot, spans_dcn=spans_dcn)
            total = ca + cb + moves
            if best is None or total < best[0]:
                merged = dict(sa)
                merged.update(sb)
                best = (total, merged)

    if best is None:
        return None
    margin = max(0.0, config.search_improvement_margin)
    # flat_cost == inf (flat strategy HBM-infeasible): any finite placed
    # candidate wins outright
    if math.isfinite(flat_cost) and best[0] >= flat_cost * (1.0 - margin):
        return None
    strategy = best[1]
    if not placeable(graph, strategy, config):
        return None
    # always-on legality gate (analysis/placement.py, SHD153-155 +
    # per-segment SHD101-110) — the same discipline optimize_strategy
    # applies to flat results: a proposal that fails is a SEARCH bug
    # and must fail loudly here, not inside XLA or, worse, never
    from flexflow_tpu.analysis import (
        AnalysisError,
        emit_findings,
        errors_only,
        lint_placement,
    )

    bad = errors_only(lint_placement(graph, strategy, config))
    if bad:
        emit_findings(bad)
        raise AnalysisError(
            "placement search produced an illegal 2-block placed "
            "strategy", bad)
    from flexflow_tpu.utils.logging import SEARCH_LOG as log

    log.log(
        f"placement search: 2-block placed strategy modeled "
        f"{best[0] * 1e3:.3f} ms/iter beats flat "
        f"{flat_cost * 1e3:.3f} ms/iter"
    )
    return strategy
