"""Prefill/decode disaggregation searched as a two-block placement.

A serving deployment runs two phases with opposite cost shapes: the
compute-bound PREFILL of arriving prompts and the HBM-bound DECODE of
live sequences.  Colocated (the single-lane PR 10 shape) the prompt
work rides the decode devices, so every decode frame pays the
interleaved prefill chunks as PHASE INTERFERENCE on top of its p99
cache stream.  Disaggregated — the placement-synthesis thesis of
arXiv:2110.10548 applied to the ragged-paged serving model of
arXiv:2604.15464 — prefill and decode run on DISJOINT device blocks:
the phases overlap instead of interleaving, at the price of moving
each admitted prompt's KV pages across the block boundary once.

This pass makes that trade a SEARCHED decision in the serve currency
(seconds per decode frame, steady state):

    T_coloc  = T_dec(all n) + load_pre * T_pre(all n) / L
    T_disagg = max(T_dec(block B), load_pre * T_pre(block A) / L)
             + T_handoff(KV bytes of load_pre tokens across the cut)

where ``load_pre = ServingSpec.prefill_tokens_per_frame()`` is the
steady-state prompt-token arrival per decode frame (the phase-split
load factor: prefill = compute-bound arrivals, decode = the p99 token
load the serve objective already prices), ``T_pre``/``T_dec`` are
intra-op-searched per block with the PR 9 two-block machinery
(``SearchHelper.graph_cost(budget=, start=)`` — block B's views carry
``start_part`` like every placed strategy), and the handoff is priced
at the boundary link's speed (DCN when the cut spans hosts, the same
rule the placed executor's move cost applies).  The prompt graph is
DERIVED from the deployment's own decode graph
(models/decode.py ``derive_prefill_model``) and must share one
parameter set with it (``prefill_weight_bridge`` — gated by SHD165).

The winner is adopted only past the search margin (honest zero when
colocation stays optimal — small configs usually do), always-on
lint-gated (``analysis.lint_disaggregation``, SHD164/165 + the flat
SHD101-110 lint per block), and persists as ``__meta__.disaggregation``
behind the digest gate with import re-lint (model.compile) and a
stdlib fflint check (STR211).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from flexflow_tpu.core.machine import MachineView

Strategy = Dict[int, MachineView]


@dataclass
class DisaggregationProposal:
    """One priced disaggregation decision: the two-block frame, both
    phase strategies, and the colocated-vs-disaggregated serve-currency
    comparison.  ``adopted`` is the margin-gated verdict — a proposal
    is always returned (the bench records honest zeros), only adopted
    winners persist."""

    num_devices: int
    prefill_devices: int
    decode_devices: int
    chunk: int
    prefill_seq_len: int
    max_seqs: int
    page_size: int
    pages_per_seq: int
    colocated_step_s: float
    disagg_step_s: float
    handoff_s: float
    prefill_tokens_per_frame: float
    spans_dcn: bool
    adopted: bool
    slo_classes: Tuple[dict, ...] = ()
    # runtime-only (not persisted): the searched per-phase strategies
    prefill_strategy: Strategy = field(default_factory=dict, repr=False)
    decode_strategy: Strategy = field(default_factory=dict, repr=False)

    def to_meta(self) -> dict:
        """The jsonable ``__meta__.disaggregation`` block (what fflint
        STR211 re-checks stdlib-only).  Pool geometry rides along
        because it must AGREE across the handoff — the prefill writer
        scatters into pages the decode block's allocator owns."""
        return {
            "num_devices": self.num_devices,
            "prefill_devices": self.prefill_devices,
            "decode_devices": self.decode_devices,
            "chunk": self.chunk,
            "prefill_seq_len": self.prefill_seq_len,
            "max_seqs": self.max_seqs,
            "page_size": self.page_size,
            "pages_per_seq": self.pages_per_seq,
            "colocated_step_ms": round(self.colocated_step_s * 1e3, 6),
            "disagg_step_ms": round(self.disagg_step_s * 1e3, 6),
            "handoff_ms": round(self.handoff_s * 1e3, 6),
            "prefill_tokens_per_frame": round(
                self.prefill_tokens_per_frame, 3),
            "spans_dcn": self.spans_dcn,
            "slo_classes": [dict(c) for c in self.slo_classes],
        }


def _budget_pairs(n: int):
    from flexflow_tpu.search.placement_search import _budget_pairs as bp

    return bp(n)


def kv_handoff_bytes(decode_graph, tokens: float) -> float:
    """KV bytes ``tokens`` prompt tokens occupy across every decode
    layer — what one decode frame's worth of admissions moves over the
    block boundary."""
    from flexflow_tpu.search.serving import decode_nodes

    return tokens * sum(n.op.kv_bytes_per_token()
                        for n in decode_nodes(decode_graph))


def propose_disaggregation(decode_graph, decode_strategy, config, *,
                           calibration=None, prefill_graph=None,
                           prefill_config=None, base_graph=None,
                           ) -> Optional[DisaggregationProposal]:
    """Price colocated vs disaggregated serving for ``decode_graph``
    under its searched ``decode_strategy`` and return the best
    two-block proposal (``adopted`` when it beats colocation by the
    search margin), or None when the graph/machine cannot express one
    (no decode ops, fewer than 2 devices).  Always-on lint gate: an
    adopted proposal that fails SHD164/165 is a search bug and raises
    ``AnalysisError`` loudly.

    ``base_graph`` is the UN-REWRITTEN decode graph when the search
    rewrote ``decode_graph``: substitution rewrites bake repartition
    views sized for the FULL mesh, so the narrow-block solves start
    from the base graph and run their OWN full search (rewrites
    included) at their block width — each block is a real deployment
    on its submesh, so both sides of the comparison carry whatever
    rewrites their mesh admits."""
    import dataclasses

    from flexflow_tpu.obs.events import BUS
    from flexflow_tpu.search.serving import serving_spec_for
    from flexflow_tpu.search.simulator import Simulator

    n = config.search_devices
    if n < 2:
        return None
    spec = serving_spec_for(decode_graph, config)
    if spec is None:
        return None
    load_pre = spec.prefill_tokens_per_frame()
    L = spec.prompt_tokens_mean or max(1, spec.max_seq_len // 2)

    if prefill_graph is None:
        from flexflow_tpu.models.decode import derive_prefill_model

        pre_model, prefill_config = derive_prefill_model(
            decode_graph, config, seq_len=L)
        prefill_graph = pre_model.graph
    elif prefill_config is None:
        prefill_config = config
    # one parameter set or no proposal: the bridge failing here is a
    # family mismatch, not a search bug — decline, the lint repeats
    # the check with findings for persisted artifacts
    from flexflow_tpu.runtime.prefill import prefill_weight_bridge

    try:
        prefill_weight_bridge(prefill_graph, decode_graph)
    except ValueError:
        return None

    block_graph = base_graph if base_graph is not None else decode_graph
    serve_sim = Simulator.for_config(config, calibration=calibration,
                                     serving=spec)

    _solve_memo = {}

    def _block_search(graph, cfg, devices, serving_armed):
        """One phase placed on a ``devices``-wide block: the FULL
        search (substitution rewrites included) at that width — each
        block is a real deployment on its submesh, so it earns
        whatever rewrites its mesh admits, exactly like the colocated
        baseline earned its own.  Returns (cost_s, block_graph,
        strategy) — the possibly-rewritten block graph the strategy
        maps — or (inf, None, None)."""
        key = (id(graph), devices, serving_armed)
        if key in _solve_memo:
            return _solve_memo[key]
        from flexflow_tpu.search.driver import optimize_strategy

        cfg_blk = dataclasses.replace(
            cfg, num_devices=devices, search_num_devices=0,
            export_strategy_file=None, import_strategy_file=None,
            serve_disaggregation="off")
        try:
            g_blk, s_blk = optimize_strategy(graph, cfg_blk,
                                             return_graph=True)
        except Exception:
            _solve_memo[key] = (math.inf, None, None)
            return _solve_memo[key]
        if not s_blk:
            _solve_memo[key] = (math.inf, None, None)
            return _solve_memo[key]
        sim_blk = Simulator.for_config(
            cfg_blk, calibration=calibration,
            serving=spec if serving_armed else None)
        _solve_memo[key] = (sim_blk.simulate(g_blk, s_blk), g_blk,
                            s_blk)
        return _solve_memo[key]

    # colocated: the searched decode strategy on the full mesh, plus
    # the arriving prompts' share of a full-mesh prefill pass per frame
    t_dec_full = serve_sim.simulate(decode_graph, decode_strategy)
    t_pre_full, _, _ = _block_search(prefill_graph, prefill_config, n,
                                     serving_armed=False)
    if not (math.isfinite(t_dec_full) and math.isfinite(t_pre_full)):
        return None
    colocated = t_dec_full + load_pre * (t_pre_full / L)

    bytes_pf = kv_handoff_bytes(decode_graph, load_pre)
    machine = serve_sim.machine
    dph = getattr(machine, "devices_per_host", 0) or n
    best = None
    for a, b in _budget_pairs(n):
        t_pre, g_pre, s_pre = _block_search(
            prefill_graph, prefill_config, a, serving_armed=False)
        if not math.isfinite(t_pre):
            continue
        t_dec, g_dec, s_dec = _block_search(
            block_graph, config, b, serving_armed=True)
        if not math.isfinite(t_dec):
            continue
        # the handoff crosses DCN when block B extends past block A's
        # hosts — the same spans rule the placed executor's move cost
        # applies.  The whole frame's admission payload is priced as
        # one serial boundary transfer: conservative for sharded
        # receivers, honest for the single-link worst case.
        spans_dcn = (a + b - 1) // dph > (a - 1) // dph
        if spans_dcn:
            handoff = (bytes_pf / machine.dcn_bandwidth
                       + machine.dcn_latency)
        else:
            handoff = (bytes_pf / machine.ici_bandwidth
                       + machine.ici_latency)
        # disaggregated phases OVERLAP (disjoint devices): the frame
        # rate is gated by the slower phase, plus the handoff wire
        disagg = max(t_dec, load_pre * (t_pre / L)) + handoff
        if best is None or disagg < best[0]:
            best = (disagg, a, b, g_pre, s_pre, g_dec, s_dec, handoff,
                    spans_dcn)

    if best is None:
        return None
    (disagg, a, b, g_pre, s_pre, g_dec, s_dec, handoff,
     spans_dcn) = best
    margin = max(0.0, config.search_improvement_margin)
    adopted = disagg < colocated * (1.0 - margin)
    proposal = DisaggregationProposal(
        num_devices=n, prefill_devices=a, decode_devices=b,
        chunk=int(getattr(config, "prefill_chunk", 32)),
        prefill_seq_len=L, max_seqs=spec.max_seqs,
        page_size=spec.page_size, pages_per_seq=spec.pages_per_seq,
        colocated_step_s=colocated, disagg_step_s=disagg,
        handoff_s=handoff, prefill_tokens_per_frame=load_pre,
        spans_dcn=spans_dcn, adopted=adopted,
        slo_classes=tuple(getattr(config, "serve_slo_classes", None)
                          or ()),
        prefill_strategy=s_pre, decode_strategy=s_dec,
    )
    if adopted:
        # always-on legality gate, the same discipline as every other
        # proposal class the search emits (SHD164/165 + per-block flat
        # lint): an adopted winner that fails is a search bug
        from flexflow_tpu.analysis import (
            AnalysisError,
            emit_findings,
            errors_only,
            lint_disaggregation,
        )

        bad = errors_only(lint_disaggregation(
            g_dec, proposal.to_meta(), config,
            prefill_graph=g_pre,
            prefill_strategy=s_pre, decode_strategy=s_dec))
        if bad:
            emit_findings(bad)
            raise AnalysisError(
                "disaggregation search produced an illegal two-block "
                "placement", bad)
    BUS.emit(
        "search.disagg", adopted=adopted,
        colocated_ms=round(colocated * 1e3, 6),
        disagg_ms=round(disagg * 1e3, 6),
        handoff_ms=round(handoff * 1e3, 6),
        prefill_devices=a, decode_devices=b, spans_dcn=spans_dcn,
        prefill_tokens_per_frame=round(load_pre, 3),
    )
    from flexflow_tpu.utils.logging import SEARCH_LOG as log

    log.log(
        f"disaggregation search: prefill[0:{a}) + decode[{a}:{a + b}) "
        f"modeled {disagg * 1e3:.4f} ms/frame vs colocated "
        f"{colocated * 1e3:.4f} ms/frame (handoff "
        f"{handoff * 1e3:.4f} ms) — "
        f"{'ADOPTED' if adopted else 'colocated stays optimal'}"
    )
    return proposal
