"""Event-driven strategy simulator.

Predicts the per-iteration runtime of (PCG, strategy) on the machine —
the role of Simulator::simulate_runtime (reference:
src/runtime/simulator.cc:796-1186): per-device timelines, compute tasks
placed on the devices their shards map to, xfer tasks on edges whose
shardings mismatch, and a post-pass adding weight-gradient allreduce
under device-availability constraints (reference: :1062-1186).

Device identity comes from the same canonical axis assignment the
lowering uses (parallel.mesh), so ops sharing axes serialize on the
same timeline while ops on disjoint sub-meshes overlap — which is what
makes VERTICAL/HORIZONTAL resource splits (inter-op parallelism) win
when they should.
"""

from __future__ import annotations

import math
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from flexflow_tpu.core.graph import Graph
from flexflow_tpu.core.machine import MachineSpec, MachineView
from flexflow_tpu.obs.metrics import METRICS
from flexflow_tpu.search.machine_model import CostModel

# module-cached metric handles (objects stay valid across METRICS.reset)
_FULL_SIMS = METRICS.counter("sim.full")
_DELTA_SIMS = METRICS.counter("sim.delta")
_DELTA_BAILS = METRICS.counter("sim.delta_bails")


def _delta_check_enabled() -> bool:
    """FLEXFLOW_TPU_DELTA_CHECK=1: every delta-served simulate() result
    is re-derived by the full path and asserted bit-identical — the
    exact-equivalence contract as a runtime oracle (tests and debug
    sessions flip it; the hot path reads a module flag)."""
    import os

    return os.environ.get("FLEXFLOW_TPU_DELTA_CHECK", "") not in ("", "0")


DELTA_CHECK = _delta_check_enabled()

# lazily built OperatorType sets mirroring calibration.find_clusters
# membership (heads / fusable followers) — the hot _local_chain and
# cluster-dirty paths must not pay per-call imports or string compares
_HEAD_TYPES: Optional[frozenset] = None
_FUSABLE_TYPES: Optional[frozenset] = None


def _init_chain_types() -> None:
    global _HEAD_TYPES, _FUSABLE_TYPES
    if _HEAD_TYPES is not None:
        return
    from flexflow_tpu.core.optype import OperatorType
    from flexflow_tpu.search.calibration import _CLUSTER_HEADS, _fusable

    class _Shim:
        __slots__ = ("op_type",)

        def __init__(self, t):
            self.op_type = t

    _FUSABLE_TYPES = frozenset(
        t for t in OperatorType if _fusable(_Shim(t)))
    _HEAD_TYPES = frozenset(
        t for t in OperatorType if t.value in _CLUSTER_HEADS)


class SimSnapshot:
    """Baseline schedule of one ``(graph, strategy)`` simulation in the
    default (scalar) cost currency — everything ``simulate`` derived
    per node, stored so a *substituted* graph can be re-costed by
    recomputing only the dirty cone (reference: simulator.h
    ``SIMULATE_DELTA``, which re-simulates only the tasks a
    substitution perturbed).

    Per node (by guid): resolved view, propagated sharding, the
    mode-selected cluster-scaled duration, sync/memory costs, the
    per-in-edge xfer seconds (training doubling baked in), and the
    baseline finish time.  Per topo position: the running scan state
    (device avail, memory prefix sum, compute/comm horizons, per-device
    comm timelines) so a delta walk can resume mid-schedule with
    bit-identical floats."""

    __slots__ = (
        "graph", "include_update", "cal_version", "order", "views",
        "ops", "annots", "in_list", "out_list", "rec", "finish",
        "chain", "pre_avail", "pre_mem", "pre_end_time", "pre_end_comm",
        "pre_comm", "total",
    )


class Simulator:
    def __init__(self, machine: MachineSpec, num_devices: Optional[int] = None,
                 use_network_model: bool = True, calibration=None,
                 placement_overlap: bool = False, zero_dp_shard: bool = False,
                 inference: bool = False, sync_precision: str = "fp32",
                 sync_ef: bool = False, cost_cache=None, serving=None):
        self.machine = machine
        self.num_devices = num_devices or machine.num_devices
        # placement_overlap=True credits inter-op COMPUTE overlap for
        # views on disjoint device blocks (start_part offsets — the
        # reference's mapper really places subgraphs on disjoint GPUs,
        # mapper.cc:371-475).  Since round 4 such strategies EXECUTE:
        # two-block start_part strategies lower to per-submesh programs
        # (compiler/placement_lowering.py) whose async dispatch overlaps
        # segments across consecutive steps.  The default stays False
        # because the DEFAULT lowering is one SPMD program where a view
        # with fewer parts than devices is replicated, not placed —
        # simulate with placement_overlap=True only when the strategy
        # will go down the placed lowering.  Comm-group overlap (weight
        # syncs over distinct device groups) IS real and stays on
        # view-level device sets in both modes.
        self.placement_overlap = placement_overlap
        # inference=True: simulate() defaults to forward-only costs with
        # no weight sync (the reference's COMP_MODE_INFERENCE,
        # config.h:47-50 / FFModel::compile comp_mode arg) — the search
        # then ranks strategies by inference latency
        self.inference = inference
        self._all_devices = frozenset(range(self.num_devices))
        network = None
        if use_network_model:
            from flexflow_tpu.search.network import ici_network

            try:
                network = ici_network(machine, num_devices=self.num_devices)
            except (AssertionError, ValueError):
                network = None
        # serving: a search/serving.py ServingSpec — arms the serve
        # objective's ragged-load pricing (MUST be set at construction,
        # before the persistent cost cache computes its signature, so
        # serve-currency rows never cross-serve train runs)
        self.cost = CostModel(machine, network=network, calibration=calibration,
                              num_devices=self.num_devices,
                              zero_dp_shard=zero_dp_shard,
                              inference=inference,
                              sync_precision=sync_precision,
                              sync_ef=sync_ef,
                              serving=serving)
        self._device_sets: Dict[Tuple, FrozenSet[int]] = {}
        # propagate()/op_cost results per (op signature, view): structural
        # keys stay valid across graph copies and op lifetimes (an id()
        # key could be recycled after GC during a long search)
        self._prop_cache: Dict[Tuple, object] = {}
        self._cost_cache: Dict[Tuple, Tuple[float, float, float]] = {}
        # optional persistent CostCache (search/cost_cache.py): misses
        # of the in-memory row cache consult it before recomputing, so
        # repeated searches across processes start warm
        self.cost_cache = cost_cache
        # delta-simulation baseline (SimSnapshot) + counters.  full_sims
        # counts every full O(nodes+edges) schedule derivation (snapshot
        # builds included); delta_sims the incremental re-costs.
        self._baseline: Optional[SimSnapshot] = None
        self.full_sims = 0
        self.delta_sims = 0
        self.delta_bails = 0

    # ------------------------------------------------------------------
    def view_device_set(self, mv: MachineView, use_start: bool = True) -> FrozenSet[int]:
        """Device ids covered by a view: the contiguous block
        [start_part, start_part + num_parts) — the reference's stride-1
        MachineView box (machine_view.h:14-87).  Ops whose blocks are
        disjoint can overlap in time (inter-op parallelism from
        VERTICAL/HORIZONTAL resource splits); nested blocks (divisor
        degrees at the same start) serialize, like same-device ops.
        With use_start=False the offset is ignored (default executable
        mode, where GSPMD has no placement offsets)."""
        start = (mv.start_part % self.num_devices) if use_start else 0
        key = (mv.num_parts, start)
        hit = self._device_sets.get(key)
        if hit is None:
            n = min(max(1, mv.num_parts), self.num_devices)
            hit = frozenset((start + i) % self.num_devices for i in range(n))
            self._device_sets[key] = hit
        return hit

    @classmethod
    def for_config(cls, config, calibration=None, **kw):
        """Simulator matching an FFConfig's search settings — the ONE
        place every config-derived flag is threaded, so a new flag
        cannot miss a construction site (driver search, MCMC, strategy
        task-graph export).  Attaches the persistent cost cache when
        the config enables one (cost_cache_file / env)."""
        sim = cls(
            config.machine_spec,
            num_devices=config.search_devices,
            calibration=calibration,
            zero_dp_shard=config.zero_dp_shard,
            inference=config.comp_mode == "inference",
            sync_precision=getattr(config, "sync_precision", "fp32"),
            sync_ef=getattr(config, "sync_ef", "off") == "auto",
            **kw,
        )
        if sim.cost_cache is None:
            from flexflow_tpu.search.cost_cache import load_for_simulator

            load_for_simulator(config, sim)
        return sim

    # ------------------------------------------------------------------
    def _node_costs(self, node, mv) -> Tuple[float, float, float, float]:
        """(fwd_cost, full_cost, weight_sync, mem_bytes) cached per
        (op, view)."""
        key = (node.op.signature(), (mv.dim_degrees, mv.replica_degree))
        hit = self._cost_cache.get(key)
        if hit is None:
            cc = self.cost_cache
            if cc is not None:
                hit = cc.get(node.op, mv)
            if hit is None:
                fwd = self.cost.op_cost(node.op, mv, backward=False)
                full = self.cost.op_cost(node.op, mv, backward=True)
                # sync at the precision the cost model's mode selects
                # (per weight group under "search") — both DP engines
                # consume this row, so compressed sync is priced
                # consistently
                sync = self.cost.sync_cost(node.op, mv)
                mem = self.cost.op_memory(node.op, mv)
                hit = (fwd, full, sync, mem)
                if cc is not None:
                    cc.put(node.op, mv, hit)
            self._cost_cache[key] = hit
        return hit

    def _propagate(self, node, mv):
        key = (node.op.signature(), (mv.dim_degrees, mv.replica_degree))
        hit = self._prop_cache.get(key)
        if hit is None:
            try:
                hit = node.op.propagate(mv)
            except AssertionError:
                hit = "invalid"
            self._prop_cache[key] = hit
        return None if hit == "invalid" else hit

    def simulate(
        self,
        graph: Graph,
        strategy: Dict[int, MachineView],
        include_update: Optional[bool] = None,
        schedule: Optional[list] = None,
        breakdown: Optional[dict] = None,
        comm_schedule: Optional[list] = None,
        sync_schedule=None,
    ) -> float:
        """Seconds per training iteration under the strategy (or per
        inference when the simulator was built with inference=True —
        ``include_update`` defaults to the simulator's mode).  Pass a
        list as ``schedule`` to receive per-task placement records
        ``(op_name, start_s, finish_s, device_ids)`` — the simulated
        task graph (reference: simulator.cc:1008-1058 dot export) —
        and as ``comm_schedule`` the weight-sync collective records in
        the same shape (the comm rows of the predicted timeline).
        Pass a dict as ``breakdown`` to receive the predicted phase
        split (compute/comm critical paths, total xfer/sync seconds,
        peak memory) — the predicted side of the obs DriftReport.

        ``sync_schedule`` — a gradient-sync schedule
        (search/sync_schedule.py): weight-gradient sync is then priced
        per BUCKET under exposed-comm semantics — a bucket's collective
        issues when the backward has produced all its members' grads
        and only costs what is not hidden under the backward compute
        still to run at that point (GSPMD async collectives,
        arXiv:2105.04663) — instead of the legacy per-node issuance.
        Per-bucket lanes land in ``comm_schedule`` and ``breakdown``
        gains ``sync_exposed_s`` + ``sync_buckets``.

        When a delta baseline is armed (``set_baseline``), calls in the
        default scalar currency are served incrementally: only the
        substituted nodes plus the downstream cone whose ready-times
        shift are recomputed, with a bit-identical-to-full contract
        (``_simulate_delta``; reference: simulator.h SIMULATE_DELTA)."""
        if include_update is None:
            include_update = not self.inference
        snap = self._baseline
        if (snap is not None and schedule is None and breakdown is None
                and comm_schedule is None and sync_schedule is None
                and not self.placement_overlap
                and include_update == snap.include_update
                and snap.cal_version == getattr(
                    self.cost.calibration, "version", None)):
            got = self._simulate_delta(snap, graph, strategy)
            if got is not None:
                self.delta_sims += 1
                _DELTA_SIMS.inc()
                if DELTA_CHECK:
                    full = self._simulate_full(
                        graph, strategy, include_update)
                    assert got == full or (
                        math.isnan(got) and math.isnan(full)
                    ), (
                        f"delta simulation diverged from full: "
                        f"{got!r} != {full!r}"
                    )
                return got
            self.delta_bails += 1
            _DELTA_BAILS.inc()
        return self._simulate_full(graph, strategy, include_update,
                                   schedule, breakdown, comm_schedule,
                                   sync_schedule)

    def _simulate_full(
        self,
        graph: Graph,
        strategy: Dict[int, MachineView],
        include_update: bool,
        schedule: Optional[list] = None,
        breakdown: Optional[dict] = None,
        comm_schedule: Optional[list] = None,
        sync_schedule=None,
    ) -> float:
        self.full_sims += 1
        _FULL_SIMS.inc()
        ready: Dict[Tuple[int, int], float] = {}  # (guid, out_idx) -> time
        device_avail: Dict[int, float] = {d: 0.0 for d in range(self.num_devices)}
        # per-device COMM timelines for weight-grad allreduces
        # (reference: simulator.cc:1062-1186 schedules NCCL allreduces
        # under device availability): same-device syncs serialize on the
        # shared ICI links, disjoint-device syncs overlap, and comm
        # overlaps later compute (async collectives).
        comm_avail: Dict[int, float] = {d: 0.0 for d in range(self.num_devices)}
        # per-device memory accounting: strategies that overflow HBM are
        # infeasible (the reference's simulator rejects strategies that
        # exhaust its device memory arena, simulator.h:688 allocate;
        # this is what forces big embedding tables to be SHARDED rather
        # than redundantly replicated)
        mem: Dict[int, float] = {d: 0.0 for d in range(self.num_devices)}
        topo = graph.topo_order()
        shardings = {}
        for node in topo:
            mv = strategy.get(node.guid)
            if mv is None:
                mv = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            osh = self._propagate(node, mv)
            if osh is None:
                return math.inf
            shardings[node.guid] = (mv, osh)

        # measured fusion-cluster overrides: when a producer+followers
        # chain member's view has a fused measurement, scale the
        # member's compute by the measured fused-over-lone ratio (lone
        # probes are upper bounds; the cluster record is what XLA
        # actually runs).  The ratio is keyed on EACH MEMBER'S OWN view
        # — a pure per-(node, view) quantity both engines can bake,
        # keeping native/python parity exact.  For the dominant case (a
        # chain sharing one view, which resharding-inside-an-elementwise
        # -chain xfer costs enforce) this equals the chain-uniform
        # semantics; a member resharded away from its head keeps its
        # own-view ratio even though XLA would break the fusion there —
        # an accepted under-charge on strategies the xfer penalty
        # already rules out.  The optimizer update term is NOT scaled —
        # fusion doesn't shrink it.
        cluster_scale: Dict[int, Tuple[float, float]] = {}
        cal = self.cost.calibration
        if cal is not None and getattr(cal, "num_clusters", 0) > 0:
            for members in self._cluster_chains(graph):
                if any(m.guid not in shardings for m in members):
                    continue
                for pos, m in enumerate(members):
                    got = self._cluster_ratio(members, shardings[m.guid][0])
                    if got is None:
                        continue
                    r, upds = got
                    cluster_scale[m.guid] = (r, upds[pos])

        end_time = 0.0
        end_comm = 0.0
        track = breakdown is not None
        xfer_total = 0.0
        sync_total = 0.0
        compute_total = 0.0
        overlap = self.placement_overlap
        # a gradient-sync schedule replaces the legacy per-node sync
        # issuance with per-bucket exposed-comm pricing (below the loop)
        sched = sync_schedule if include_update else None
        node_rows: Optional[list] = [] if sched is not None else None
        # fast path: in the default (overlap=False) currency every op
        # occupies ALL device timelines, so device availability is ONE
        # scalar and per-device memory is the plain sum — identical math
        # to the full per-device form (and to the native engines), at a
        # fraction of the dict traffic.  The search calls this tens of
        # thousands of times per compile.
        scalar = not overlap and schedule is None
        avail = 0.0
        mem_total = 0.0
        for node in topo:
            mv, osh = shardings[node.guid]
            start = avail if scalar else 0.0
            # input readiness + edge xfer costs
            for e in graph.in_edges[node.guid]:
                src_mv, src_osh = shardings[e.src]
                src_annot = (
                    src_osh.outputs[e.src_idx]
                    if e.src_idx < len(src_osh.outputs)
                    else None
                )
                dst_annot = (
                    osh.inputs[e.dst_idx] if e.dst_idx < len(osh.inputs) else None
                )
                shape = graph.nodes[e.src].op.output_shapes[e.src_idx]
                xfer = self.cost.xfer_cost(shape, src_annot, dst_annot)
                if overlap and src_mv.start_part != mv.start_part:
                    # producer and consumer live on different device
                    # blocks: every shard moves at least one hop even
                    # when shardings agree (reference charges this via
                    # per-pair xfers, simulator.cc:599-731)
                    xfer += self.cost.placement_move_cost(shape, src_annot)
                if include_update and not graph.nodes[e.src].op.is_gradient_free:
                    # training pays every boundary twice: the activation
                    # reshards/moves forward AND its gradient pays the
                    # inverse transfer flowing back (GSPMD emits the
                    # transposed collective in the backward program).
                    # Applied AFTER the placement move so both engines
                    # double the identical baked quantity.  Edges sourced
                    # at inputs/constants carry no cotangent back, so
                    # they pay the forward reshard only.
                    xfer *= 2.0
                if track:
                    xfer_total += xfer
                t = ready.get((e.src, e.src_idx), 0.0) + xfer
                if t > start:
                    start = t
            fwd, full, sync, m_bytes = self._node_costs(node, mv)
            scale = cluster_scale.get(node.guid)
            if scale is not None:
                r, upd = scale
                fwd = fwd * r
                full = (full - upd) * r + upd
            dur = full if include_update else fwd
            if track:
                compute_total += dur
            if scalar:
                mem_total += m_bytes
                finish = start + dur
                avail = finish
            else:
                comm_devs = self.view_device_set(mv, use_start=overlap)
                devs = comm_devs if overlap else self._all_devices
                for d in devs:
                    start = max(start, device_avail[d])
                for d in devs:
                    mem[d] += m_bytes
                finish = start + dur
                for d in devs:
                    device_avail[d] = finish
                if schedule is not None:
                    schedule.append(
                        (node.op.name, start, finish, tuple(sorted(devs))))
            for i in range(len(node.op.output_shapes)):
                ready[(node.guid, i)] = finish
            if finish > end_time:
                end_time = finish
            if node_rows is not None:
                node_rows.append((node, mv, fwd, dur, sync))
            elif include_update and sync > 0:
                if scalar:
                    comm_devs = self.view_device_set(mv, use_start=False)
                s = finish
                for d in comm_devs:
                    s = max(s, comm_avail[d])
                f = s + sync
                for d in comm_devs:
                    comm_avail[d] = f
                end_comm = max(end_comm, f)
                if track:
                    sync_total += sync
                if comm_schedule is not None:
                    comm_schedule.append(
                        (f"{node.op.name}:sync", s, f,
                         tuple(sorted(comm_devs))))

        sync_buckets: Optional[list] = None
        sync_levels: Optional[dict] = None
        if sched is not None:
            end_comm, sync_total, sync_buckets, sync_levels = \
                self._scheduled_sync(
                    sched, node_rows, end_time, comm_avail, comm_schedule)

        peak = mem_total if scalar else max(mem.values())
        total = max(end_time, end_comm)
        oom = peak > self.machine.hbm_capacity
        if track:
            breakdown.update(
                total_s=math.inf if oom else total,
                compute_end_s=end_time,
                comm_end_s=end_comm,
                compute_total_s=compute_total,
                xfer_total_s=xfer_total,
                sync_total_s=sync_total,
                # the EXPOSED sync tail: comm past the last compute —
                # what the step actually pays for gradient sync after
                # overlap credit (0 when fully hidden)
                sync_exposed_s=max(0.0, end_comm - end_time),
                peak_mem_bytes=peak,
                num_devices=self.num_devices,
                include_update=include_update,
                # per-collective records exist in this currency (the
                # pooled-traffic LogicalTaskGraphSimulator sets True
                # and leaves comm_schedule empty by design)
                pooled_comm=False,
            )
            if sync_buckets is not None:
                breakdown["sync_buckets"] = sync_buckets
            # per-link-level sync seconds (ICI vs DCN lanes) — from the
            # scheduled buckets when a schedule priced them, otherwise
            # re-derived per synced node (track mode only: the split is
            # not on the search's hot path)
            if sync_levels is None:
                sync_levels = {}
                for node in topo:
                    mv, _osh = shardings[node.guid]
                    if include_update:
                        for name, t in self.cost.sync_levels(
                                node.op, mv).items():
                            sync_levels[name] = sync_levels.get(
                                name, 0.0) + t
            if sync_levels:
                breakdown["sync_levels_s"] = sync_levels
        if oom:
            return math.inf
        return total

    def _scheduled_sync(self, sync_schedule, node_rows, end_time,
                        comm_avail, comm_schedule):
        """Exposed-comm pricing of a gradient-sync schedule over the
        scan just finished.  Backward model: the backward sweeps the
        graph in REVERSE topo order, so a bucket whose earliest member
        sits at topo position p has all its grads ready once only the
        backward shares of nodes 0..p-1 remain — its fused collective
        issues at ``end_time - bwd_prefix[p]`` and hides under exactly
        that remaining compute (GSPMD async collectives; the legacy
        per-node issuance credits overlap in FORWARD order, which the
        executed post-backward sync never earns).  Buckets serialize on
        their device groups' comm lanes in schedule order; synced
        groups the schedule does not cover issue after the full
        backward (the monolithic behavior execution gives them).
        Returns (end_comm, sync_total, per-bucket breakdown rows,
        per-link-level seconds aggregate)."""
        pos = {node.guid: i for i, (node, *_r) in enumerate(node_rows)}
        bwd_prefix = [0.0] * (len(node_rows) + 1)
        for i, (_n, _mv, fwd, dur, _s) in enumerate(node_rows):
            bwd_prefix[i + 1] = bwd_prefix[i] + max(0.0, dur - fwd)
        by_name = {node.op.name: (node, mv, sync)
                   for node, mv, _f, _d, sync in node_rows}
        end_comm = 0.0
        sync_total = 0.0
        rows = []
        covered = set()
        level_tot: dict = {}
        for bucket in getattr(sync_schedule, "buckets", sync_schedule):
            members = [by_name[nm] for nm in bucket.ops if nm in by_name]
            if not members:
                continue
            covered.update(nm for nm in bucket.ops)
            parts = []
            devs = set()
            min_pos = len(node_rows)
            for node, mv, _sync in members:
                got = self.cost.weight_sync_parts(node.op, mv)
                if got:
                    parts.extend(got)
                    devs |= self.view_device_set(mv, use_start=False)
                    min_pos = min(min_pos, pos[node.guid])
            levels: dict = {}
            cost = self.cost.bucket_sync_cost(
                parts, getattr(bucket, "precision", "fp32"),
                plan=getattr(bucket, "plan", None), level_acc=levels)
            if cost <= 0.0 or not devs:
                continue
            ready = end_time - bwd_prefix[min_pos]
            s = ready
            for d in devs:
                if comm_avail[d] > s:
                    s = comm_avail[d]
            f = s + cost
            for d in devs:
                comm_avail[d] = f
            if f > end_comm:
                end_comm = f
            sync_total += cost
            if comm_schedule is not None:
                comm_schedule.append(
                    (f"bucket:{bucket.name}:sync", s, f,
                     tuple(sorted(devs))))
            plan = getattr(bucket, "plan", None)
            rows.append({
                "name": bucket.name,
                # stable lane id — IDENTICAL to this bucket's
                # comm_schedule record name and to the annotation tag
                # the executed step stamps (obs/annotate.py), so a
                # device-trace capture matches by tag equality
                "lane": f"bucket:{bucket.name}:sync",
                "ops": list(bucket.ops),
                "precision": getattr(bucket, "precision", "fp32"),
                "plan": plan.name if plan is not None else None,
                "ready_s": ready,
                "start_s": s,
                "finish_s": f,
                "sync_s": cost,
                # per-link-level lanes (ICI vs DCN classes): drift on
                # the slow cross-slice links visible separately
                "levels": levels,
            })
            for name, t in levels.items():
                level_tot[name] = level_tot.get(name, 0.0) + t
        # uncovered synced groups: the executed _sync_grads leaves them
        # on the post-backward monolithic path — price them there (the
        # legality lint flags the coverage hole; pricing must not hide
        # it as free communication)
        for node, mv, _f, _d, sync in node_rows:
            if sync <= 0 or node.op.name in covered:
                continue
            devs = self.view_device_set(mv, use_start=False)
            s = end_time
            for d in devs:
                if comm_avail[d] > s:
                    s = comm_avail[d]
            f = s + sync
            for d in devs:
                comm_avail[d] = f
            if f > end_comm:
                end_comm = f
            sync_total += sync
            for name, t in self.cost.sync_levels(node.op, mv).items():
                level_tot[name] = level_tot.get(name, 0.0) + t
            if comm_schedule is not None:
                comm_schedule.append(
                    (f"{node.op.name}:sync", s, f, tuple(sorted(devs))))
        # the exposed share of each bucket's lane: the part of
        # [start, finish] past the end of compute (what the step pays)
        for r in rows:
            r["exposed_s"] = max(0.0, r["finish_s"]
                                 - max(r["start_s"], end_time))
        return end_comm, sync_total, rows, level_tot

    # ---- delta simulation (reference: simulator.h SIMULATE_DELTA) ----
    def set_baseline(self, graph: Graph,
                     strategy: Dict[int, MachineView],
                     include_update: Optional[bool] = None) -> Optional[SimSnapshot]:
        """Arm delta simulation: snapshot the baseline schedule of
        ``(graph, strategy)`` so subsequent ``simulate`` calls on
        substituted variants (or re-viewed strategies) are served
        incrementally.  Returns the snapshot, or None (and disarms)
        when the baseline is infeasible (invalid view / OOM)."""
        snap = self._snapshot(graph, strategy, include_update)
        self._baseline = snap
        return snap

    def clear_baseline(self) -> None:
        self._baseline = None

    def _resolve_view(self, node) -> MachineView:
        mv = node.op.fixed_machine_view()
        if mv is None:
            mv = MachineView.trivial(node.op.output_shapes[0].ndim)
        return mv

    def _snapshot(self, graph: Graph, strategy: Dict[int, MachineView],
                  include_update: Optional[bool] = None) -> Optional[SimSnapshot]:
        """One full scalar-currency simulation, recording every derived
        per-node quantity plus the per-position scan state.  The loop
        MUST stay arithmetic-identical to ``_simulate_full``'s scalar
        path — the delta contract (tests/test_search_delta.py) asserts
        equality to the float."""
        if include_update is None:
            include_update = not self.inference
        self.full_sims += 1
        _FULL_SIMS.inc()
        topo = graph.topo_order()
        snap = SimSnapshot()
        snap.graph = graph
        snap.include_update = include_update
        cal = self.cost.calibration
        snap.cal_version = getattr(cal, "version", None)
        views: Dict[int, MachineView] = {}
        annots: Dict[int, object] = {}
        shardings = {}
        for node in topo:
            mv = strategy.get(node.guid)
            if mv is None:
                mv = self._resolve_view(node)
            osh = self._propagate(node, mv)
            if osh is None:
                return None
            views[node.guid] = mv
            annots[node.guid] = osh
            shardings[node.guid] = (mv, osh)

        cluster_scale: Dict[int, Tuple[float, float]] = {}
        chain: Dict[int, Tuple[int, ...]] = {}
        if cal is not None and getattr(cal, "num_clusters", 0) > 0:
            for members in self._cluster_chains(graph):
                mg = tuple(m.guid for m in members)
                for pos, m in enumerate(members):
                    chain[m.guid] = mg
                    got = self._cluster_ratio(members, views[m.guid])
                    if got is None:
                        continue
                    r, upds = got
                    cluster_scale[m.guid] = (r, upds[pos])

        n = len(topo)
        order = [nd.guid for nd in topo]
        # per-node record: (duration, sync_s, mem_bytes, comm_devs,
        # ((src_guid, xfer_s), ...)) — ONE dict hit per clean node in
        # the delta walk
        rec: Dict[int, Tuple] = {}
        finish_d: Dict[int, float] = {}
        pre_avail: List[float] = [0.0] * (n + 1)
        pre_mem: List[float] = [0.0] * (n + 1)
        pre_end_time: List[float] = [0.0] * (n + 1)
        pre_end_comm: List[float] = [0.0] * (n + 1)
        pre_comm: List[Tuple[float, ...]] = [()] * (n + 1)

        comm_avail = [0.0] * self.num_devices
        comm_state = tuple(comm_avail)
        avail = 0.0
        mem_total = 0.0
        end_time = 0.0
        end_comm = 0.0
        ready: Dict[int, float] = {}
        for i, node in enumerate(topo):
            guid = node.guid
            pre_avail[i] = avail
            pre_mem[i] = mem_total
            pre_end_time[i] = end_time
            pre_end_comm[i] = end_comm
            pre_comm[i] = comm_state
            mv = views[guid]
            osh = annots[guid]
            start = avail
            edges = []
            for e in graph.in_edges[guid]:
                src_osh = annots[e.src]
                src_annot = (
                    src_osh.outputs[e.src_idx]
                    if e.src_idx < len(src_osh.outputs) else None
                )
                dst_annot = (
                    osh.inputs[e.dst_idx] if e.dst_idx < len(osh.inputs)
                    else None
                )
                shape = graph.nodes[e.src].op.output_shapes[e.src_idx]
                xfer = self.cost.xfer_cost(shape, src_annot, dst_annot)
                if include_update and not graph.nodes[e.src].op.is_gradient_free:
                    xfer *= 2.0
                edges.append((e.src, xfer))
                t = ready.get(e.src, 0.0) + xfer
                if t > start:
                    start = t
            fwd, full, sync, m_bytes = self._node_costs(node, mv)
            scale = cluster_scale.get(guid)
            if scale is not None:
                r, upd = scale
                fwd = fwd * r
                full = (full - upd) * r + upd
            d = full if include_update else fwd
            mem_total += m_bytes
            finish = start + d
            avail = finish
            ready[guid] = finish
            finish_d[guid] = finish
            if finish > end_time:
                end_time = finish
            cd = None
            if include_update and sync > 0:
                cd = self.view_device_set(mv, use_start=False)
                s = finish
                for dev in cd:
                    if comm_avail[dev] > s:
                        s = comm_avail[dev]
                f = s + sync
                for dev in cd:
                    comm_avail[dev] = f
                comm_state = tuple(comm_avail)
                if f > end_comm:
                    end_comm = f
            rec[guid] = (d, sync, m_bytes, cd, tuple(edges))
        pre_avail[n] = avail
        pre_mem[n] = mem_total
        pre_end_time[n] = end_time
        pre_end_comm[n] = end_comm
        pre_comm[n] = comm_state

        if mem_total > self.machine.hbm_capacity:
            return None
        snap.order = order
        snap.views = views
        snap.ops = {g: graph.nodes[g].op for g in order}
        snap.annots = annots
        snap.in_list = {g: graph.in_edges[g] for g in order}
        snap.out_list = {g: graph.out_edges[g] for g in order}
        snap.rec = rec
        snap.finish = finish_d
        snap.chain = chain
        snap.pre_avail = pre_avail
        snap.pre_mem = pre_mem
        snap.pre_end_time = pre_end_time
        snap.pre_end_comm = pre_end_comm
        snap.pre_comm = pre_comm
        snap.total = max(end_time, end_comm)
        return snap

    def _local_chain(self, graph: Graph, guid: int):
        """The fusion-cluster chain of ``graph`` containing ``guid``
        (same membership rule as calibration.find_clusters, derived
        locally), or None.  Used by the delta path to detect chain
        membership changes around substituted nodes without re-scanning
        the whole graph."""
        _init_chain_types()
        node = graph.nodes.get(guid)
        if node is None:
            return None
        cur = node
        while cur.op.op_type not in _HEAD_TYPES:
            if cur.op.op_type not in _FUSABLE_TYPES:
                return None
            ins = graph.in_edges[cur.guid]
            if len(ins) != 1:
                return None
            pred = graph.nodes[ins[0].src]
            if len(graph.out_edges[pred.guid]) != 1:
                return None
            cur = pred
        members = [cur]
        while True:
            edges = graph.out_edges[members[-1].guid]
            if len(edges) != 1:
                break
            nxt = graph.nodes[edges[0].dst]
            if len(graph.in_edges[nxt.guid]) != 1:
                break
            if nxt.op.op_type not in _FUSABLE_TYPES:
                break
            members.append(nxt)
        if len(members) < 2:
            return None
        return members if any(m.guid == guid for m in members) else None

    def _mark_cluster_dirty(self, snap: SimSnapshot, graph: Graph,
                            changed: set, cluster_seed) -> None:
        """Fusion-cluster membership can shift around edge rewires even
        for nodes whose own edges/views are untouched — mark every
        member of any OLD or NEW chain through the perturbed region.
        Only chain-typed seeds pay the local walk (substitution-inserted
        parallel ops never form chains)."""
        _init_chain_types()
        chain = snap.chain
        nodes = graph.nodes
        for guid in list(changed | set(cluster_seed)):
            old = chain.get(guid)
            if old is not None:
                changed.update(g for g in old if g in nodes)
            node = nodes.get(guid)
            if node is None:
                continue
            ot = node.op.op_type
            if ot not in _HEAD_TYPES and ot not in _FUSABLE_TYPES:
                continue
            new = self._local_chain(graph, guid)
            if new is not None:
                changed.update(m.guid for m in new)

    def _clusters_active(self) -> bool:
        cal = self.cost.calibration
        return cal is not None and getattr(cal, "num_clusters", 0) > 0

    def simulate_rewrite(self, graph: Graph, resolve_view) -> Optional[float]:
        """Tier-1 candidate estimate: delta re-cost of a substitution
        candidate whose parent is the armed baseline, under the
        caller's CONTRACT that every surviving node resolves to the
        baseline's view (the estimate rule — driver._estimate_strategy)
        and ``resolve_view(node)`` supplies the views of the touched
        nodes.  Skips the per-node strategy dict and view diff the
        generic ``simulate`` routing would pay.  None when no delta
        applies (caller falls back to ``simulate``)."""
        snap = self._baseline
        if snap is None or self.placement_overlap:
            return None
        if snap.include_update != (not self.inference):
            return None
        cv = getattr(graph, "_changed_vs", None)
        if cv is None or cv[0]() is not snap.graph:
            return None
        if snap.cal_version != getattr(self.cost.calibration, "version",
                                       None):
            return None
        nodes = graph.nodes
        changed = {g for g in cv[1] if g in nodes}
        if self._clusters_active():
            self._mark_cluster_dirty(snap, graph, changed, cv[2])
        if len(changed) > max(8, len(nodes) // 2):
            self.delta_bails += 1
            _DELTA_BAILS.inc()
            return None
        got = self._delta_walk(snap, graph, changed, resolve_view)
        self.delta_sims += 1
        _DELTA_SIMS.inc()
        if DELTA_CHECK:
            strategy = {
                guid: (resolve_view(node) if guid in changed
                       else snap.views[guid])
                for guid, node in nodes.items()
            }
            full = self._simulate_full(graph, strategy, snap.include_update)
            assert got == full or (math.isnan(got) and math.isnan(full)), (
                f"delta rewrite estimate diverged from full: "
                f"{got!r} != {full!r}"
            )
        return got

    def _delta_changed(self, snap: SimSnapshot, graph: Graph,
                       strategy: Dict[int, MachineView]):
        """Dirty-node set of ``graph`` vs the snapshot, or None when the
        graphs diverge too much for a delta to pay (the caller then
        full-simulates).  Seeded by the changed-guid sets GraphXfer
        application attaches (``graph._changed_vs``); falls back to a
        structural diff for graphs from other producers."""
        nodes = graph.nodes
        limit = max(8, len(nodes) // 4)
        changed = set()
        view_dirty = set()
        cluster_seed = set()
        if graph is not snap.graph:
            cv = getattr(graph, "_changed_vs", None)
            if cv is not None and cv[0]() is snap.graph:
                changed.update(g for g in cv[1] if g in nodes)
                cluster_seed.update(g for g in cv[2] if g in nodes)
            else:
                if abs(len(nodes) - len(snap.order)) > limit:
                    return None
                in_list = snap.in_list
                out_list = snap.out_list
                ops = snap.ops
                for guid, node in nodes.items():
                    base_in = in_list.get(guid)
                    if base_in is None or node.op is not ops[guid]:
                        changed.add(guid)
                        view_dirty.add(guid)
                        if len(changed) > limit:
                            return None
                        continue
                    cur = graph.in_edges[guid]
                    if cur is not base_in and cur != base_in:
                        changed.add(guid)
                        if len(changed) > limit:
                            return None
                    cur_out = graph.out_edges[guid]
                    base_out = out_list[guid]
                    if cur_out is not base_out and cur_out != base_out:
                        cluster_seed.add(guid)
        # view changes (re-viewed strategies on the same structure)
        views = snap.views
        for guid, node in nodes.items():
            if guid in changed:
                continue
            mv = strategy.get(guid)
            if mv is None:
                mv = self._resolve_view(node)
            base = views.get(guid)
            if mv is not base and mv != base:
                changed.add(guid)
                view_dirty.add(guid)
                if len(changed) > limit:
                    return None
        if not changed and not cluster_seed:
            return changed
        # a view-changed producer changes its consumers' edge xfers —
        # one hop.  Pure edge rewires don't: a surviving node's output
        # annot depends only on (op, view).
        for guid in view_dirty:
            for e in graph.out_edges.get(guid, ()):
                changed.add(e.dst)
        if self._clusters_active():
            self._mark_cluster_dirty(snap, graph, changed, cluster_seed)
        if len(changed) > limit:
            return None
        return changed

    def _simulate_delta(self, snap: SimSnapshot, graph: Graph,
                        strategy: Dict[int, MachineView]) -> Optional[float]:
        """Incremental re-cost against the armed baseline: resume the
        scalar scan at the first dirty topo position, reusing every
        clean node's cached durations/xfers.  Returns None when a delta
        does not apply (caller falls back to the full path).  The
        result is bit-identical to ``_simulate_full`` on the same
        inputs — same values, same arithmetic, same order."""
        changed = self._delta_changed(snap, graph, strategy)
        if changed is None:
            return None

        def resolve_view(node):
            mv = strategy.get(node.guid)
            if mv is None:
                mv = self._resolve_view(node)
            return mv

        return self._delta_walk(snap, graph, changed, resolve_view)

    def _delta_walk(self, snap: SimSnapshot, graph: Graph, changed,
                    resolve_view) -> float:
        """The scalar scan over ``graph`` with every clean node served
        from the snapshot record — same values, same arithmetic, same
        order as ``_simulate_full``, so the result is bit-identical."""
        order = graph.topo_order()
        base_order = snap.order
        n = len(order)
        # longest clean common prefix → resume state from the snapshot
        k = 0
        lim = min(n, len(base_order))
        while k < lim:
            g = order[k].guid
            if g != base_order[k] or g in changed:
                break
            k += 1
        if k == n and n == len(base_order):
            return snap.total  # nothing dirty: the baseline cost stands
        avail = snap.pre_avail[k]
        mem_total = snap.pre_mem[k]
        end_time = snap.pre_end_time[k]
        end_comm = snap.pre_end_comm[k]
        comm_avail = list(snap.pre_comm[k]) if k else [0.0] * self.num_devices
        ready: Dict[int, float] = {}
        ready_get = ready.get
        base_finish = snap.finish
        base_rec = snap.rec
        new_annots: Dict[int, object] = {}
        include_update = snap.include_update
        clusters = self._clusters_active()
        for i in range(k, n):
            node = order[i]
            guid = node.guid
            if guid not in changed:
                start = avail
                dur, sync, m_bytes, comm_devs, edges = base_rec[guid]
                for src, xfer in edges:
                    t = ready_get(src)
                    if t is None:
                        t = base_finish.get(src, 0.0)
                    t += xfer
                    if t > start:
                        start = t
            else:
                mv = resolve_view(node)
                osh = self._propagate(node, mv)
                if osh is None:
                    return math.inf
                new_annots[guid] = osh
                start = avail
                for e in graph.in_edges[guid]:
                    src = e.src
                    s_osh = new_annots.get(src)
                    if s_osh is None:
                        s_osh = snap.annots[src]
                    src_annot = (
                        s_osh.outputs[e.src_idx]
                        if e.src_idx < len(s_osh.outputs) else None
                    )
                    dst_annot = (
                        osh.inputs[e.dst_idx] if e.dst_idx < len(osh.inputs)
                        else None
                    )
                    src_op = graph.nodes[src].op
                    xfer = self.cost.xfer_cost(
                        src_op.output_shapes[e.src_idx], src_annot, dst_annot)
                    if include_update and not src_op.is_gradient_free:
                        xfer *= 2.0
                    t = ready_get(src)
                    if t is None:
                        t = base_finish.get(src, 0.0)
                    t += xfer
                    if t > start:
                        start = t
                fwd, full, sync, m_bytes = self._node_costs(node, mv)
                if clusters:
                    members = self._local_chain(graph, guid)
                    if members is not None:
                        got = self._cluster_ratio(members, mv)
                        if got is not None:
                            r, upds = got
                            pos = next(
                                j for j, m in enumerate(members)
                                if m.guid == guid)
                            upd = upds[pos]
                            fwd = fwd * r
                            full = (full - upd) * r + upd
                dur = full if include_update else fwd
                comm_devs = (self.view_device_set(mv, use_start=False)
                             if include_update and sync > 0 else None)
            mem_total += m_bytes
            finish = start + dur
            avail = finish
            ready[guid] = finish
            if finish > end_time:
                end_time = finish
            if comm_devs is not None:
                s = finish
                for dev in comm_devs:
                    if comm_avail[dev] > s:
                        s = comm_avail[dev]
                f = s + sync
                for dev in comm_devs:
                    comm_avail[dev] = f
                if f > end_comm:
                    end_comm = f
        if mem_total > self.machine.hbm_capacity:
            return math.inf
        return max(end_time, end_comm)

    # ------------------------------------------------------------------
    def _cluster_chains(self, graph: Graph):
        """find_clusters(graph) as flat member lists, weakly cached —
        simulate() runs thousands of times per search on the same
        graphs."""
        if not hasattr(self, "_cluster_graph_cache"):
            import weakref

            self._cluster_graph_cache = weakref.WeakKeyDictionary()
            self._cluster_ratio_cache: Dict = {}
        chains = self._cluster_graph_cache.get(graph)
        if chains is None:
            from flexflow_tpu.search.calibration import find_clusters

            chains = [
                [producer] + list(chain)
                for producer, chain in find_clusters(graph)
            ]
            self._cluster_graph_cache[graph] = chains
        return chains

    def _cluster_ratio(self, members, mv):
        """(fused/lone ratio, per-member update costs) for one chain at
        one view, or None — cached per (chain signature, view).  The
        cache drops wholesale when the table mutates (version bump):
        a budget-bounded calibration RESUMED in place would otherwise
        leave permanently-cached None results shadowing the new
        records in both engines."""
        cal = self.cost.calibration
        ver = getattr(cal, "version", None)
        if getattr(self, "_cluster_cache_version", None) != ver:
            self._cluster_ratio_cache = {}
            self._cluster_cache_version = ver
        key = cal.cluster_key([m.op for m in members], mv)
        hit = self._cluster_ratio_cache.get(key, "miss")
        if hit != "miss":
            return hit
        t = cal.get_cluster([m.op for m in members], mv)
        result = None
        if t is not None:
            lone = sum(
                self.cost.op_cost(m.op, mv, backward=False) for m in members
            )
            if lone > 0 and math.isfinite(lone):
                result = (
                    min(1.0, t / lone),
                    tuple(self.cost.update_cost(m.op, mv) for m in members),
                )
        self._cluster_ratio_cache[key] = result
        return result

    def cluster_membership(self, graph: Graph):
        """guid -> (chain members, position) for every fusion-cluster
        member of ``graph``, or an empty dict without cluster records.
        Nodes belong to at most one chain (heads are matmul-family,
        followers elementwise — disjoint sets; followers extend down
        sole-consumer links)."""
        out: Dict[int, Tuple[list, int]] = {}
        cal = self.cost.calibration
        if cal is not None and getattr(cal, "num_clusters", 0) > 0:
            for members in self._cluster_chains(graph):
                for pos, m in enumerate(members):
                    out[m.guid] = (members, pos)
        return out

    def cluster_scaled_costs(self, node, mv, costs, membership):
        """Apply the per-member-own-view fusion-cluster ratio to one
        (node, view) cost row ``(fwd, full, sync, mem)`` — the SAME
        formula simulate() applies, so baked native rows stay parity-
        exact with the python engine."""
        cm = membership.get(node.guid)
        if cm is None:
            return costs
        got = self._cluster_ratio(cm[0], mv)
        if got is None:
            return costs
        r, upds = got
        fwd, full, sync, m_bytes = costs
        upd = upds[cm[1]]
        return (fwd * r, (full - upd) * r + upd, sync, m_bytes)

    # ------------------------------------------------------------------
    def build_native(self, graph: Graph, node_views: Dict[int, list]):
        """Digest (graph, candidate views) onto the native C++ engine
        (native/src/sim_engine.cpp).  Returns (NativeSimGraph,
        guid->index map) or None when the library is unavailable.

        ``node_views[guid]`` lists each node's registrable views in
        order; view indices in native assignments refer to these lists.
        Semantics match ``simulate`` exactly (tests assert equality);
        fusion-cluster ratios are keyed per (member, own view) — a pure
        per-(node, view) quantity — so they bake into the exported cost
        rows (see simulate()'s cluster_scale note).
        """
        from flexflow_tpu import native

        if native.get_lib() is None:
            return None
        topo = graph.topo_order()
        index = {n.guid: i for i, n in enumerate(topo)}
        membership = self.cluster_membership(graph)
        ns = native.NativeSimGraph(len(topo), self.num_devices)
        ns.set_mem_cap(self.machine.hbm_capacity)
        annots = {}  # (node_index, view_index) -> OpSharding | None
        for i, node in enumerate(topo):
            for vi, mv in enumerate(node_views[node.guid]):
                osh = self._propagate(node, mv)
                annots[(i, vi)] = osh
                if osh is None:
                    ns.add_view(i, 0.0, 0.0, 0.0, [], [], valid=False)
                    continue
                fwd, full, sync, m_bytes = self.cluster_scaled_costs(
                    node, mv, self._node_costs(node, mv), membership)
                comm_devs = sorted(
                    self.view_device_set(mv, use_start=self.placement_overlap)
                )
                devs = (comm_devs if self.placement_overlap
                        else list(range(self.num_devices)))
                ns.add_view(i, fwd, full, sync, devs, comm_devs,
                            mem=m_bytes, valid=True)
        for guid in graph.nodes:
            for e in graph.out_edges[guid]:
                si, di = index[e.src], index[e.dst]
                src_views = node_views[e.src]
                dst_views = node_views[e.dst]
                shape = graph.nodes[e.src].op.output_shapes[e.src_idx]
                mat = []
                for svi in range(len(src_views)):
                    s_osh = annots[(si, svi)]
                    for dvi in range(len(dst_views)):
                        d_osh = annots[(di, dvi)]
                        if s_osh is None or d_osh is None:
                            mat.append(math.inf)
                            continue
                        src_annot = (
                            s_osh.outputs[e.src_idx]
                            if e.src_idx < len(s_osh.outputs) else None
                        )
                        dst_annot = (
                            d_osh.inputs[e.dst_idx]
                            if e.dst_idx < len(d_osh.inputs) else None
                        )
                        x = self.cost.xfer_cost(shape, src_annot, dst_annot)
                        # baked at 1x: both engines apply the 2x
                        # training factor at simulate time, keyed on
                        # include_update
                        if self.placement_overlap and (
                            src_views[svi].start_part
                            != dst_views[dvi].start_part
                        ):
                            # keep exact parity with simulate()'s
                            # cross-block movement charge
                            x += self.cost.placement_move_cost(shape, src_annot)
                        mat.append(x)
                ns.add_edge(
                    si, di,
                    np.asarray(mat, dtype=np.float64).reshape(
                        len(src_views), len(dst_views)),
                    has_grad=not graph.nodes[e.src].op.is_gradient_free,
                )
        return ns, index

    def node_cost_row(self, node, mv) -> Tuple[float, float, float, float]:
        """Public per-(op, view) cost row ``(fwd_s, full_s, sync_s,
        mem_bytes)`` — the strategy-explanation table (obs telemetry)
        reads predicted costs through this."""
        return self._node_costs(node, mv)

    # ------------------------------------------------------------------
    def export_chrome_trace(
        self,
        graph: Graph,
        strategy: Dict[int, MachineView],
        path: str,
        include_update: Optional[bool] = None,
        label: Optional[str] = None,
        schedule: Optional[list] = None,
        comm_schedule: Optional[list] = None,
        total_s: Optional[float] = None,
    ) -> float:
        """Write the simulated schedule as Chrome-trace JSON loadable
        in Perfetto/chrome://tracing — the PREDICTED timeline, viewable
        next to the real ``runtime.profiler.device_trace`` capture.
        Returns the simulated iteration seconds.  Callers that already
        simulated (e.g. for a breakdown) pass their ``schedule``/
        ``comm_schedule``/``total_s`` to skip the re-simulation."""
        from flexflow_tpu.obs.trace import write_chrome_trace

        if schedule is None:
            schedule, comm_schedule = [], []
            total_s = self.simulate(
                graph, strategy, include_update=include_update,
                schedule=schedule, comm_schedule=comm_schedule,
            )
        write_chrome_trace(
            path, schedule, comm_schedule or [],
            label=label or f"predicted ({type(self).__name__})",
            meta={"simulated_step_s": total_s,
                  "num_devices": self.num_devices,
                  "machine": self.machine.name},
        )
        return total_s

    # ------------------------------------------------------------------
    def export_task_graph_dot(self, graph: Graph,
                              strategy: Dict[int, MachineView],
                              path: str) -> float:
        """Write the simulated schedule as graphviz (reference:
        export_strategy_task_graph_file, simulator.cc:1008-1058).
        Returns the simulated iteration seconds."""
        schedule: list = []
        cost = self.simulate(graph, strategy, schedule=schedule)
        lines = ["digraph taskgraph {", "  rankdir=LR;"]
        for op_name, start, finish, devs in schedule:
            label = (f"{op_name}\\n[{start*1e3:.3f}, {finish*1e3:.3f}] ms"
                     f"\\ndevs={list(devs)}")
            lines.append(f'  "{op_name}" [shape=record, label="{label}"];')
        for g in graph.nodes:
            for e in graph.out_edges[g]:
                a = graph.nodes[e.src].op.name
                b = graph.nodes[e.dst].op.name
                lines.append(f'  "{a}" -> "{b}";')
        lines.append("}")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return cost

    # ------------------------------------------------------------------
    def strategy_table_rows(
        self,
        graph: Graph,
        strategy: Dict[int, MachineView],
        sync_precision_map: Optional[Dict[str, str]] = None,
    ) -> list:
        """Per-node strategy-explanation rows — op, chosen view, and
        the predicted compute/sync/memory breakdown the search ranked
        it by (plus the chosen gradient-sync wire precision for weight
        groups).  Emitted as the ``strategy.table`` obs event and
        rendered by ``tools/ffobs.py report``."""
        rows = []
        for node in graph.topo_order():
            mv = strategy.get(node.guid)
            if mv is None:
                mv = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            try:
                fwd, full, sync, mem_b = self._node_costs(node, mv)
            except Exception:  # never let telemetry break a compile
                fwd = full = sync = mem_b = math.nan
            row = {
                "op": node.op.name,
                "type": node.op.op_type.value,
                "view": {
                    "dims": list(mv.dim_degrees),
                    "replica": mv.replica_degree,
                    "start": mv.start_part,
                },
                "fwd_s": fwd,
                "full_s": full,
                "sync_s": sync,
                "mem_bytes": mem_b,
            }
            if getattr(node.op, "_weight_specs", ()):
                row["sync_precision"] = (sync_precision_map or {}).get(
                    node.op.name, "fp32")
            rows.append(row)
        return rows

    # ------------------------------------------------------------------
    def peak_memory(self, graph: Graph, strategy: Dict[int, MachineView]) -> float:
        """Sum of per-device op memory (upper bound; the reference uses a
        scratch arena the same way, simulator.h:688)."""
        total = 0.0
        for node in graph.topo_order():
            mv = strategy.get(node.guid)
            if mv is None:
                mv = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            total += self.cost.op_memory(node.op, mv)
        return total
