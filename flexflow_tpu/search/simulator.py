"""Event-driven strategy simulator.

Predicts the per-iteration runtime of (PCG, strategy) on the machine —
the role of Simulator::simulate_runtime (reference:
src/runtime/simulator.cc:796-1186): per-device timelines, compute tasks
placed on the devices their shards map to, xfer tasks on edges whose
shardings mismatch, and a post-pass adding weight-gradient allreduce
under device-availability constraints (reference: :1062-1186).

Device identity comes from the same canonical axis assignment the
lowering uses (parallel.mesh), so ops sharing axes serialize on the
same timeline while ops on disjoint sub-meshes overlap — which is what
makes VERTICAL/HORIZONTAL resource splits (inter-op parallelism) win
when they should.
"""

from __future__ import annotations

import math
from typing import Dict, FrozenSet, Optional, Tuple

import numpy as np

from flexflow_tpu.core.graph import Graph
from flexflow_tpu.core.machine import MachineSpec, MachineView
from flexflow_tpu.search.machine_model import CostModel


class Simulator:
    def __init__(self, machine: MachineSpec, num_devices: Optional[int] = None,
                 use_network_model: bool = True, calibration=None,
                 placement_overlap: bool = False, zero_dp_shard: bool = False,
                 inference: bool = False, sync_precision: str = "fp32"):
        self.machine = machine
        self.num_devices = num_devices or machine.num_devices
        # placement_overlap=True credits inter-op COMPUTE overlap for
        # views on disjoint device blocks (start_part offsets — the
        # reference's mapper really places subgraphs on disjoint GPUs,
        # mapper.cc:371-475).  Since round 4 such strategies EXECUTE:
        # two-block start_part strategies lower to per-submesh programs
        # (compiler/placement_lowering.py) whose async dispatch overlaps
        # segments across consecutive steps.  The default stays False
        # because the DEFAULT lowering is one SPMD program where a view
        # with fewer parts than devices is replicated, not placed —
        # simulate with placement_overlap=True only when the strategy
        # will go down the placed lowering.  Comm-group overlap (weight
        # syncs over distinct device groups) IS real and stays on
        # view-level device sets in both modes.
        self.placement_overlap = placement_overlap
        # inference=True: simulate() defaults to forward-only costs with
        # no weight sync (the reference's COMP_MODE_INFERENCE,
        # config.h:47-50 / FFModel::compile comp_mode arg) — the search
        # then ranks strategies by inference latency
        self.inference = inference
        self._all_devices = frozenset(range(self.num_devices))
        network = None
        if use_network_model:
            from flexflow_tpu.search.network import ici_network

            try:
                network = ici_network(machine, num_devices=self.num_devices)
            except (AssertionError, ValueError):
                network = None
        self.cost = CostModel(machine, network=network, calibration=calibration,
                              num_devices=self.num_devices,
                              zero_dp_shard=zero_dp_shard,
                              inference=inference,
                              sync_precision=sync_precision)
        self._device_sets: Dict[Tuple, FrozenSet[int]] = {}
        # propagate()/op_cost results per (op signature, view): structural
        # keys stay valid across graph copies and op lifetimes (an id()
        # key could be recycled after GC during a long search)
        self._prop_cache: Dict[Tuple, object] = {}
        self._cost_cache: Dict[Tuple, Tuple[float, float, float]] = {}

    # ------------------------------------------------------------------
    def view_device_set(self, mv: MachineView, use_start: bool = True) -> FrozenSet[int]:
        """Device ids covered by a view: the contiguous block
        [start_part, start_part + num_parts) — the reference's stride-1
        MachineView box (machine_view.h:14-87).  Ops whose blocks are
        disjoint can overlap in time (inter-op parallelism from
        VERTICAL/HORIZONTAL resource splits); nested blocks (divisor
        degrees at the same start) serialize, like same-device ops.
        With use_start=False the offset is ignored (default executable
        mode, where GSPMD has no placement offsets)."""
        start = (mv.start_part % self.num_devices) if use_start else 0
        key = (mv.num_parts, start)
        hit = self._device_sets.get(key)
        if hit is None:
            n = min(max(1, mv.num_parts), self.num_devices)
            hit = frozenset((start + i) % self.num_devices for i in range(n))
            self._device_sets[key] = hit
        return hit

    @classmethod
    def for_config(cls, config, calibration=None, **kw):
        """Simulator matching an FFConfig's search settings — the ONE
        place every config-derived flag is threaded, so a new flag
        cannot miss a construction site (driver search, MCMC, strategy
        task-graph export)."""
        return cls(
            config.machine_spec,
            num_devices=config.search_devices,
            calibration=calibration,
            zero_dp_shard=config.zero_dp_shard,
            inference=config.comp_mode == "inference",
            sync_precision=getattr(config, "sync_precision", "fp32"),
            **kw,
        )

    # ------------------------------------------------------------------
    def _node_costs(self, node, mv) -> Tuple[float, float, float, float]:
        """(fwd_cost, full_cost, weight_sync, mem_bytes) cached per
        (op, view)."""
        key = (node.op.signature(), (mv.dim_degrees, mv.replica_degree))
        hit = self._cost_cache.get(key)
        if hit is None:
            fwd = self.cost.op_cost(node.op, mv, backward=False)
            full = self.cost.op_cost(node.op, mv, backward=True)
            # sync at the precision the cost model's mode selects (per
            # weight group under "search") — both DP engines consume
            # this row, so compressed sync is priced consistently
            sync = self.cost.sync_cost(node.op, mv)
            mem = self.cost.op_memory(node.op, mv)
            hit = (fwd, full, sync, mem)
            self._cost_cache[key] = hit
        return hit

    def _propagate(self, node, mv):
        key = (node.op.signature(), (mv.dim_degrees, mv.replica_degree))
        hit = self._prop_cache.get(key)
        if hit is None:
            try:
                hit = node.op.propagate(mv)
            except AssertionError:
                hit = "invalid"
            self._prop_cache[key] = hit
        return None if hit == "invalid" else hit

    def simulate(
        self,
        graph: Graph,
        strategy: Dict[int, MachineView],
        include_update: Optional[bool] = None,
        schedule: Optional[list] = None,
        breakdown: Optional[dict] = None,
        comm_schedule: Optional[list] = None,
    ) -> float:
        """Seconds per training iteration under the strategy (or per
        inference when the simulator was built with inference=True —
        ``include_update`` defaults to the simulator's mode).  Pass a
        list as ``schedule`` to receive per-task placement records
        ``(op_name, start_s, finish_s, device_ids)`` — the simulated
        task graph (reference: simulator.cc:1008-1058 dot export) —
        and as ``comm_schedule`` the weight-sync collective records in
        the same shape (the comm rows of the predicted timeline).
        Pass a dict as ``breakdown`` to receive the predicted phase
        split (compute/comm critical paths, total xfer/sync seconds,
        peak memory) — the predicted side of the obs DriftReport."""
        if include_update is None:
            include_update = not self.inference
        ready: Dict[Tuple[int, int], float] = {}  # (guid, out_idx) -> time
        device_avail: Dict[int, float] = {d: 0.0 for d in range(self.num_devices)}
        # per-device COMM timelines for weight-grad allreduces
        # (reference: simulator.cc:1062-1186 schedules NCCL allreduces
        # under device availability): same-device syncs serialize on the
        # shared ICI links, disjoint-device syncs overlap, and comm
        # overlaps later compute (async collectives).
        comm_avail: Dict[int, float] = {d: 0.0 for d in range(self.num_devices)}
        # per-device memory accounting: strategies that overflow HBM are
        # infeasible (the reference's simulator rejects strategies that
        # exhaust its device memory arena, simulator.h:688 allocate;
        # this is what forces big embedding tables to be SHARDED rather
        # than redundantly replicated)
        mem: Dict[int, float] = {d: 0.0 for d in range(self.num_devices)}
        topo = graph.topo_order()
        shardings = {}
        for node in topo:
            mv = strategy.get(node.guid)
            if mv is None:
                mv = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            osh = self._propagate(node, mv)
            if osh is None:
                return math.inf
            shardings[node.guid] = (mv, osh)

        # measured fusion-cluster overrides: when a producer+followers
        # chain member's view has a fused measurement, scale the
        # member's compute by the measured fused-over-lone ratio (lone
        # probes are upper bounds; the cluster record is what XLA
        # actually runs).  The ratio is keyed on EACH MEMBER'S OWN view
        # — a pure per-(node, view) quantity both engines can bake,
        # keeping native/python parity exact.  For the dominant case (a
        # chain sharing one view, which resharding-inside-an-elementwise
        # -chain xfer costs enforce) this equals the chain-uniform
        # semantics; a member resharded away from its head keeps its
        # own-view ratio even though XLA would break the fusion there —
        # an accepted under-charge on strategies the xfer penalty
        # already rules out.  The optimizer update term is NOT scaled —
        # fusion doesn't shrink it.
        cluster_scale: Dict[int, Tuple[float, float]] = {}
        cal = self.cost.calibration
        if cal is not None and getattr(cal, "num_clusters", 0) > 0:
            for members in self._cluster_chains(graph):
                if any(m.guid not in shardings for m in members):
                    continue
                for pos, m in enumerate(members):
                    got = self._cluster_ratio(members, shardings[m.guid][0])
                    if got is None:
                        continue
                    r, upds = got
                    cluster_scale[m.guid] = (r, upds[pos])

        end_time = 0.0
        end_comm = 0.0
        track = breakdown is not None
        xfer_total = 0.0
        sync_total = 0.0
        compute_total = 0.0
        overlap = self.placement_overlap
        # fast path: in the default (overlap=False) currency every op
        # occupies ALL device timelines, so device availability is ONE
        # scalar and per-device memory is the plain sum — identical math
        # to the full per-device form (and to the native engines), at a
        # fraction of the dict traffic.  The search calls this tens of
        # thousands of times per compile.
        scalar = not overlap and schedule is None
        avail = 0.0
        mem_total = 0.0
        for node in topo:
            mv, osh = shardings[node.guid]
            start = avail if scalar else 0.0
            # input readiness + edge xfer costs
            for e in graph.in_edges[node.guid]:
                src_mv, src_osh = shardings[e.src]
                src_annot = (
                    src_osh.outputs[e.src_idx]
                    if e.src_idx < len(src_osh.outputs)
                    else None
                )
                dst_annot = (
                    osh.inputs[e.dst_idx] if e.dst_idx < len(osh.inputs) else None
                )
                shape = graph.nodes[e.src].op.output_shapes[e.src_idx]
                xfer = self.cost.xfer_cost(shape, src_annot, dst_annot)
                if overlap and src_mv.start_part != mv.start_part:
                    # producer and consumer live on different device
                    # blocks: every shard moves at least one hop even
                    # when shardings agree (reference charges this via
                    # per-pair xfers, simulator.cc:599-731)
                    xfer += self.cost.placement_move_cost(shape, src_annot)
                if include_update and not graph.nodes[e.src].op.is_gradient_free:
                    # training pays every boundary twice: the activation
                    # reshards/moves forward AND its gradient pays the
                    # inverse transfer flowing back (GSPMD emits the
                    # transposed collective in the backward program).
                    # Applied AFTER the placement move so both engines
                    # double the identical baked quantity.  Edges sourced
                    # at inputs/constants carry no cotangent back, so
                    # they pay the forward reshard only.
                    xfer *= 2.0
                if track:
                    xfer_total += xfer
                t = ready.get((e.src, e.src_idx), 0.0) + xfer
                if t > start:
                    start = t
            fwd, full, sync, m_bytes = self._node_costs(node, mv)
            scale = cluster_scale.get(node.guid)
            if scale is not None:
                r, upd = scale
                fwd = fwd * r
                full = (full - upd) * r + upd
            dur = full if include_update else fwd
            if track:
                compute_total += dur
            if scalar:
                mem_total += m_bytes
                finish = start + dur
                avail = finish
            else:
                comm_devs = self.view_device_set(mv, use_start=overlap)
                devs = comm_devs if overlap else self._all_devices
                for d in devs:
                    start = max(start, device_avail[d])
                for d in devs:
                    mem[d] += m_bytes
                finish = start + dur
                for d in devs:
                    device_avail[d] = finish
                if schedule is not None:
                    schedule.append(
                        (node.op.name, start, finish, tuple(sorted(devs))))
            for i in range(len(node.op.output_shapes)):
                ready[(node.guid, i)] = finish
            if finish > end_time:
                end_time = finish
            if include_update and sync > 0:
                if scalar:
                    comm_devs = self.view_device_set(mv, use_start=False)
                s = finish
                for d in comm_devs:
                    s = max(s, comm_avail[d])
                f = s + sync
                for d in comm_devs:
                    comm_avail[d] = f
                end_comm = max(end_comm, f)
                if track:
                    sync_total += sync
                if comm_schedule is not None:
                    comm_schedule.append(
                        (f"{node.op.name}:sync", s, f,
                         tuple(sorted(comm_devs))))

        peak = mem_total if scalar else max(mem.values())
        total = max(end_time, end_comm)
        oom = peak > self.machine.hbm_capacity
        if track:
            breakdown.update(
                total_s=math.inf if oom else total,
                compute_end_s=end_time,
                comm_end_s=end_comm,
                compute_total_s=compute_total,
                xfer_total_s=xfer_total,
                sync_total_s=sync_total,
                peak_mem_bytes=peak,
                num_devices=self.num_devices,
                include_update=include_update,
            )
        if oom:
            return math.inf
        return total

    # ------------------------------------------------------------------
    def _cluster_chains(self, graph: Graph):
        """find_clusters(graph) as flat member lists, weakly cached —
        simulate() runs thousands of times per search on the same
        graphs."""
        if not hasattr(self, "_cluster_graph_cache"):
            import weakref

            self._cluster_graph_cache = weakref.WeakKeyDictionary()
            self._cluster_ratio_cache: Dict = {}
        chains = self._cluster_graph_cache.get(graph)
        if chains is None:
            from flexflow_tpu.search.calibration import find_clusters

            chains = [
                [producer] + list(chain)
                for producer, chain in find_clusters(graph)
            ]
            self._cluster_graph_cache[graph] = chains
        return chains

    def _cluster_ratio(self, members, mv):
        """(fused/lone ratio, per-member update costs) for one chain at
        one view, or None — cached per (chain signature, view).  The
        cache drops wholesale when the table mutates (version bump):
        a budget-bounded calibration RESUMED in place would otherwise
        leave permanently-cached None results shadowing the new
        records in both engines."""
        cal = self.cost.calibration
        ver = getattr(cal, "version", None)
        if getattr(self, "_cluster_cache_version", None) != ver:
            self._cluster_ratio_cache = {}
            self._cluster_cache_version = ver
        key = cal.cluster_key([m.op for m in members], mv)
        hit = self._cluster_ratio_cache.get(key, "miss")
        if hit != "miss":
            return hit
        t = cal.get_cluster([m.op for m in members], mv)
        result = None
        if t is not None:
            lone = sum(
                self.cost.op_cost(m.op, mv, backward=False) for m in members
            )
            if lone > 0 and math.isfinite(lone):
                result = (
                    min(1.0, t / lone),
                    tuple(self.cost.update_cost(m.op, mv) for m in members),
                )
        self._cluster_ratio_cache[key] = result
        return result

    def cluster_membership(self, graph: Graph):
        """guid -> (chain members, position) for every fusion-cluster
        member of ``graph``, or an empty dict without cluster records.
        Nodes belong to at most one chain (heads are matmul-family,
        followers elementwise — disjoint sets; followers extend down
        sole-consumer links)."""
        out: Dict[int, Tuple[list, int]] = {}
        cal = self.cost.calibration
        if cal is not None and getattr(cal, "num_clusters", 0) > 0:
            for members in self._cluster_chains(graph):
                for pos, m in enumerate(members):
                    out[m.guid] = (members, pos)
        return out

    def cluster_scaled_costs(self, node, mv, costs, membership):
        """Apply the per-member-own-view fusion-cluster ratio to one
        (node, view) cost row ``(fwd, full, sync, mem)`` — the SAME
        formula simulate() applies, so baked native rows stay parity-
        exact with the python engine."""
        cm = membership.get(node.guid)
        if cm is None:
            return costs
        got = self._cluster_ratio(cm[0], mv)
        if got is None:
            return costs
        r, upds = got
        fwd, full, sync, m_bytes = costs
        upd = upds[cm[1]]
        return (fwd * r, (full - upd) * r + upd, sync, m_bytes)

    # ------------------------------------------------------------------
    def build_native(self, graph: Graph, node_views: Dict[int, list]):
        """Digest (graph, candidate views) onto the native C++ engine
        (native/src/sim_engine.cpp).  Returns (NativeSimGraph,
        guid->index map) or None when the library is unavailable.

        ``node_views[guid]`` lists each node's registrable views in
        order; view indices in native assignments refer to these lists.
        Semantics match ``simulate`` exactly (tests assert equality);
        fusion-cluster ratios are keyed per (member, own view) — a pure
        per-(node, view) quantity — so they bake into the exported cost
        rows (see simulate()'s cluster_scale note).
        """
        from flexflow_tpu import native

        if native.get_lib() is None:
            return None
        topo = graph.topo_order()
        index = {n.guid: i for i, n in enumerate(topo)}
        membership = self.cluster_membership(graph)
        ns = native.NativeSimGraph(len(topo), self.num_devices)
        ns.set_mem_cap(self.machine.hbm_capacity)
        annots = {}  # (node_index, view_index) -> OpSharding | None
        for i, node in enumerate(topo):
            for vi, mv in enumerate(node_views[node.guid]):
                osh = self._propagate(node, mv)
                annots[(i, vi)] = osh
                if osh is None:
                    ns.add_view(i, 0.0, 0.0, 0.0, [], [], valid=False)
                    continue
                fwd, full, sync, m_bytes = self.cluster_scaled_costs(
                    node, mv, self._node_costs(node, mv), membership)
                comm_devs = sorted(
                    self.view_device_set(mv, use_start=self.placement_overlap)
                )
                devs = (comm_devs if self.placement_overlap
                        else list(range(self.num_devices)))
                ns.add_view(i, fwd, full, sync, devs, comm_devs,
                            mem=m_bytes, valid=True)
        for guid in graph.nodes:
            for e in graph.out_edges[guid]:
                si, di = index[e.src], index[e.dst]
                src_views = node_views[e.src]
                dst_views = node_views[e.dst]
                shape = graph.nodes[e.src].op.output_shapes[e.src_idx]
                mat = []
                for svi in range(len(src_views)):
                    s_osh = annots[(si, svi)]
                    for dvi in range(len(dst_views)):
                        d_osh = annots[(di, dvi)]
                        if s_osh is None or d_osh is None:
                            mat.append(math.inf)
                            continue
                        src_annot = (
                            s_osh.outputs[e.src_idx]
                            if e.src_idx < len(s_osh.outputs) else None
                        )
                        dst_annot = (
                            d_osh.inputs[e.dst_idx]
                            if e.dst_idx < len(d_osh.inputs) else None
                        )
                        x = self.cost.xfer_cost(shape, src_annot, dst_annot)
                        # baked at 1x: both engines apply the 2x
                        # training factor at simulate time, keyed on
                        # include_update
                        if self.placement_overlap and (
                            src_views[svi].start_part
                            != dst_views[dvi].start_part
                        ):
                            # keep exact parity with simulate()'s
                            # cross-block movement charge
                            x += self.cost.placement_move_cost(shape, src_annot)
                        mat.append(x)
                ns.add_edge(
                    si, di,
                    np.asarray(mat, dtype=np.float64).reshape(
                        len(src_views), len(dst_views)),
                    has_grad=not graph.nodes[e.src].op.is_gradient_free,
                )
        return ns, index

    def node_cost_row(self, node, mv) -> Tuple[float, float, float, float]:
        """Public per-(op, view) cost row ``(fwd_s, full_s, sync_s,
        mem_bytes)`` — the strategy-explanation table (obs telemetry)
        reads predicted costs through this."""
        return self._node_costs(node, mv)

    # ------------------------------------------------------------------
    def export_chrome_trace(
        self,
        graph: Graph,
        strategy: Dict[int, MachineView],
        path: str,
        include_update: Optional[bool] = None,
        label: Optional[str] = None,
        schedule: Optional[list] = None,
        comm_schedule: Optional[list] = None,
        total_s: Optional[float] = None,
    ) -> float:
        """Write the simulated schedule as Chrome-trace JSON loadable
        in Perfetto/chrome://tracing — the PREDICTED timeline, viewable
        next to the real ``runtime.profiler.device_trace`` capture.
        Returns the simulated iteration seconds.  Callers that already
        simulated (e.g. for a breakdown) pass their ``schedule``/
        ``comm_schedule``/``total_s`` to skip the re-simulation."""
        from flexflow_tpu.obs.trace import write_chrome_trace

        if schedule is None:
            schedule, comm_schedule = [], []
            total_s = self.simulate(
                graph, strategy, include_update=include_update,
                schedule=schedule, comm_schedule=comm_schedule,
            )
        write_chrome_trace(
            path, schedule, comm_schedule or [],
            label=label or f"predicted ({type(self).__name__})",
            meta={"simulated_step_s": total_s,
                  "num_devices": self.num_devices,
                  "machine": self.machine.name},
        )
        return total_s

    # ------------------------------------------------------------------
    def export_task_graph_dot(self, graph: Graph,
                              strategy: Dict[int, MachineView],
                              path: str) -> float:
        """Write the simulated schedule as graphviz (reference:
        export_strategy_task_graph_file, simulator.cc:1008-1058).
        Returns the simulated iteration seconds."""
        schedule: list = []
        cost = self.simulate(graph, strategy, schedule=schedule)
        lines = ["digraph taskgraph {", "  rankdir=LR;"]
        for op_name, start, finish, devs in schedule:
            label = (f"{op_name}\\n[{start*1e3:.3f}, {finish*1e3:.3f}] ms"
                     f"\\ndevs={list(devs)}")
            lines.append(f'  "{op_name}" [shape=record, label="{label}"];')
        for g in graph.nodes:
            for e in graph.out_edges[g]:
                a = graph.nodes[e.src].op.name
                b = graph.nodes[e.dst].op.name
                lines.append(f'  "{a}" -> "{b}";')
        lines.append("}")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return cost

    # ------------------------------------------------------------------
    def strategy_table_rows(
        self,
        graph: Graph,
        strategy: Dict[int, MachineView],
        sync_precision_map: Optional[Dict[str, str]] = None,
    ) -> list:
        """Per-node strategy-explanation rows — op, chosen view, and
        the predicted compute/sync/memory breakdown the search ranked
        it by (plus the chosen gradient-sync wire precision for weight
        groups).  Emitted as the ``strategy.table`` obs event and
        rendered by ``tools/ffobs.py report``."""
        rows = []
        for node in graph.topo_order():
            mv = strategy.get(node.guid)
            if mv is None:
                mv = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            try:
                fwd, full, sync, mem_b = self._node_costs(node, mv)
            except Exception:  # never let telemetry break a compile
                fwd = full = sync = mem_b = math.nan
            row = {
                "op": node.op.name,
                "type": node.op.op_type.value,
                "view": {
                    "dims": list(mv.dim_degrees),
                    "replica": mv.replica_degree,
                    "start": mv.start_part,
                },
                "fwd_s": fwd,
                "full_s": full,
                "sync_s": sync,
                "mem_bytes": mem_b,
            }
            if getattr(node.op, "_weight_specs", ()):
                row["sync_precision"] = (sync_precision_map or {}).get(
                    node.op.name, "fp32")
            rows.append(row)
        return rows

    # ------------------------------------------------------------------
    def peak_memory(self, graph: Graph, strategy: Dict[int, MachineView]) -> float:
        """Sum of per-device op memory (upper bound; the reference uses a
        scratch arena the same way, simulator.h:688)."""
        total = 0.0
        for node in graph.topo_order():
            mv = strategy.get(node.guid)
            if mv is None:
                mv = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            total += self.cost.op_memory(node.op, mv)
        return total
