"""Search-integrated pipeline parallelism.

The reference only *declares* OP_PIPELINE (ffconst.h:148) and its Unity
search approximates inter-op parallelism with disjoint device splits
(reference: src/runtime/graph.cc:161-295); the pipelined executor here
(parallel/pipeline.py) was previously reachable only by the user
passing ``compile(pipeline=PipelineConfig(...))``.  This module closes
the loop: for stacked-block graphs the compile-time search also costs
``pp ∈ {2, 4, 8}`` pipelined candidates in the SAME simulator currency
as dp/tp/sp strategies and compile() lowers the winner automatically.

Pipeline cost model (collective/looped GPipe over a pp × dp mesh):

  T = (M + S − 1)/M · Σ_block fwd+bwd(dp d)      compute incl. bubble
    + 2(M + S − 1) · t_hop                        per-tick ppermute (fwd
                                                  + reversed bwd pass)
    + T_prologue/epilogue(dp n)                   unpipelined ends
    + max_stage weight allreduce + update         dp-d groups, parallel
                                                  across stages

where d = n/S is the data-parallel width inside each stage.  The pp
axis is OUTERMOST in build_pipeline_mesh, so on a multi-host machine
stage boundaries cross DCN while each stage's dp sync group stays
inside one ICI domain — exactly the PipeDream/GPipe reason pipelining
wins at scale: DP's weight allreduce over DCN is replaced by one
activation hop per tick.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional, Tuple

from flexflow_tpu.core.graph import Graph
from flexflow_tpu.core.machine import MachineView


@dataclasses.dataclass
class StagedPipelineProposal:
    """A costed S-stage partition of an ARBITRARY PCG (reference: the
    inter-op device splits of graph.cc:161-295 are general over any
    graph cut).  ``executable`` is True when the stacked-block scan
    lowering can run it; the general heterogeneous shape executes via
    the staged wavefront executor
    (compiler/staged_pipeline_lowering.StagedPipelinedModel), which
    compile() adopts when every flat strategy is infeasible."""

    num_stages: int
    num_microbatches: int
    stage_guids: List[List[int]]  # topo-interval partition, stage order
    cost: float                   # modeled seconds/iteration
    executable: bool


def _pick_microbatches(batch: int, stages: int, dp: int = 1) -> Optional[int]:
    """Largest M <= 4*stages with M >= stages, batch % M == 0, and each
    microbatch still divisible by the stage's dp width — enough
    microbatches to amortize the (S-1)/(M+S-1) bubble without shrinking
    per-microbatch shards to nothing."""
    best = None
    for m in range(stages, 4 * stages + 1):
        if batch % m == 0 and (batch // m) % max(dp, 1) == 0:
            best = m
    return best


def _applicable(graph: Graph, stages: int):
    """Replicate PipelinedCompiledModel's gates (pipeline_lowering.py):
    stacked isomorphic blocks, single entry/exit, linear chain, equal
    entry/exit shapes, stateless block ops.  Returns (blocks, prologue,
    epilogue) or None."""
    from flexflow_tpu.compiler.pipeline_lowering import (
        _block_signature,
        detect_blocks,
    )

    try:
        blocks, prologue, epilogue = detect_blocks(graph)
    except ValueError:
        return None
    if len(blocks) % stages or len(blocks) < stages:
        return None
    members = [{n.guid for n in blk} for blk in blocks]
    sig0 = _block_signature(blocks[0], graph, members[0])
    for blk, member in zip(blocks[1:], members[1:]):
        if _block_signature(blk, graph, member) != sig0:
            return None
    entries, exits = [], []
    topo = graph.topo_order()
    for blk, member in zip(blocks, members):
        ext_in = set()
        for node in blk:
            for e in graph.in_edges[node.guid]:
                if e.src not in member:
                    ext_in.add((e.src, e.src_idx))
        ext_out = set()
        for node in topo:
            if node.guid in member:
                continue
            for e in graph.in_edges[node.guid]:
                if e.src in member:
                    ext_out.add((e.src, e.src_idx))
        if len(ext_in) != 1 or len(ext_out) != 1:
            return None
        entries.append(next(iter(ext_in)))
        exits.append(next(iter(ext_out)))
        for node in blk:
            if getattr(node.op, "state_specs", None) is not None:
                return None
    for i in range(1, len(blocks)):
        if entries[i] != exits[i - 1]:
            return None
    # the streamed activation must keep one shape across stages
    src, idx = entries[0]
    entry_shape = graph.nodes[src].op.output_shapes[idx]
    src, idx = exits[-1]
    exit_shape = graph.nodes[src].op.output_shapes[idx]
    if tuple(entry_shape.sizes) != tuple(exit_shape.sizes):
        return None
    return blocks, prologue, epilogue, entry_shape


def propose_pipeline(graph: Graph, config, sim, baseline_cost: float):
    """Best PipelineConfig whose simulated step time beats
    ``baseline_cost`` by more than the search uncertainty margin, or
    None.  ``sim`` is the same Simulator that scored the flat search."""
    from flexflow_tpu.parallel.pipeline import PipelineConfig

    n = config.search_devices
    batch = config.batch_size
    cost = sim.cost
    machine = cost.machine
    best: Optional[Tuple[PipelineConfig, float]] = None

    for stages in (2, 4, 8):
        if stages <= 1 or stages > n or n % stages:
            continue
        got = _applicable(graph, stages)
        if got is None:
            continue
        blocks, prologue, epilogue, entry_shape = got
        d = n // stages  # dp width inside each stage
        m = _pick_microbatches(batch, stages, d)
        if m is None:
            continue

        def dp_view(op, deg):
            ndim = op.output_shapes[0].ndim
            batch_dim = op.output_shapes[0].sizes[0]
            if deg > 1 and batch_dim % deg:
                return None
            return MachineView.data_parallel(ndim, deg)

        # compute: all block ops fwd+bwd at dp-d shards, scaled by the
        # bubble; update term excluded here (charged once, below)
        comp = 0.0
        sync_one_stage = 0.0
        upd_one_stage = 0.0
        mem_one_stage = 0.0
        per_stage = len(blocks) // stages
        feasible = True
        for bi, blk in enumerate(blocks):
            for node in blk:
                v = dp_view(node.op, d)
                if v is None:
                    feasible = False
                    break
                if bi < per_stage:
                    mem_one_stage += cost.op_memory(node.op, v)
                full = cost.op_cost(node.op, v, backward=True)
                upd = cost.update_cost(node.op, v)
                comp += full - upd
                if bi < per_stage:  # one representative stage
                    upd_one_stage += upd
                    # stage grads allreduce over the d-wide dp group;
                    # pp is the OUTER mesh axis so this group sits
                    # inside one ICI domain whenever d <= domain size
                    for ws, annot in zip(
                        node.op._weight_specs,
                        node.op.propagate(v).weights,
                    ):
                        if annot is None or annot.replica <= 1:
                            continue
                        nbytes = ws.dtype.itemsize
                        for s in ws.shape:
                            nbytes *= s
                        sync_one_stage += cost.allreduce(
                            nbytes, d,
                            spans_dcn=d > machine.devices_per_host,
                        )
            if not feasible:
                break
        if not feasible:
            continue
        # a stage device holds its own stage's weights/opt state only —
        # the memory win that makes pipelining viable where replication
        # is not — but that stage must still fit
        if mem_one_stage > machine.hbm_capacity:
            continue
        bubble = (m + stages - 1) / m
        t_compute = bubble * comp

        # per-tick activation hop: microbatch shard over the dp group,
        # one ICI/DCN hop; both the forward scan and its differentiated
        # reverse pay it every tick
        hop_bytes = entry_shape.num_bytes / m / max(d, 1)
        spans_dcn = n > machine.devices_per_host  # pp crosses hosts
        if spans_dcn:
            t_hop = hop_bytes / machine.dcn_bandwidth + machine.dcn_latency
        else:
            t_hop = hop_bytes / machine.ici_bandwidth + machine.ici_latency
        t_comm = 2.0 * (m + stages - 1) * t_hop

        # unpipelined prologue/epilogue at full-dp width
        t_ends = 0.0
        for node in prologue + epilogue:
            v = dp_view(node.op, d)
            if v is None:
                v = MachineView.trivial(node.op.output_shapes[0].ndim)
            t_ends += cost.op_cost(node.op, v, backward=True)
            t_ends += cost.weight_sync_cost(node.op, v)

        total = t_compute + t_comm + t_ends + sync_one_stage + upd_one_stage
        if best is None or total < best[1]:
            from flexflow_tpu.parallel.pipeline import PipelineConfig

            best = (PipelineConfig(num_stages=stages, num_microbatches=m),
                    total)

    if best is None:
        return None
    margin = max(0.0, config.search_improvement_margin)
    if not math.isfinite(baseline_cost) or (
            best[1] < baseline_cost * (1.0 - margin)):
        _gate_pipeline_proposal(
            graph, config, best[0].num_stages, best[0].num_microbatches)
        from flexflow_tpu.utils.logging import SEARCH_LOG as log

        log.log(
            f"pipeline search: pp={best[0].num_stages} M="
            f"{best[0].num_microbatches} simulated "
            f"{best[1] * 1e3:.3f} ms/iter beats flat "
            f"{baseline_cost * 1e3:.3f} ms/iter"
        )
        return best[0]
    return None


def stacked_stage_guids(graph: Graph, stages: int) -> Optional[List[List[int]]]:
    """The explicit stage partition a stacked-block PipelineConfig
    implies: blocks grouped ``len(blocks)/S`` per stage, prologue in
    stage 0, epilogue in the last — the cut the scan lowering will run,
    materialized so the legality lint (SHD150-152) can check it."""
    got = _applicable(graph, stages)
    if got is None:
        return None
    blocks, prologue, epilogue, _entry = got
    per = len(blocks) // stages
    out: List[List[int]] = []
    for si in range(stages):
        stage = [n.guid for n in prologue] if si == 0 else []
        for blk in blocks[si * per:(si + 1) * per]:
            stage += [n.guid for n in blk]
        if si == stages - 1:
            stage += [n.guid for n in epilogue]
        out.append(stage)
    return out


def _gate_pipeline_proposal(graph: Graph, config, stages: int,
                            microbatches: int,
                            stage_guids: Optional[List[List[int]]] = None,
                            ) -> None:
    """Always-on legality gate on every pipeline proposal the search
    returns (analysis/placement.py SHD150-152) — the same discipline
    optimize_strategy applies to flat strategies.  A failure is a
    SEARCH bug: fail loudly at the proposal, not in the lowering."""
    from flexflow_tpu.analysis import (
        AnalysisError,
        emit_findings,
        errors_only,
        lint_pipeline_stages,
    )

    if stage_guids is None:
        stage_guids = stacked_stage_guids(graph, stages)
    bad = errors_only(lint_pipeline_stages(
        graph, stage_guids, stages, microbatches, config))
    if bad:
        emit_findings(bad)
        raise AnalysisError(
            "pipeline search produced an illegal stage partition", bad)


def _balanced_intervals(costs: List[float], stages: int) -> List[int]:
    """Split ``costs`` into ``stages`` contiguous intervals minimizing
    the max interval sum (classic linear-partition DP) — stage balance
    decides the pipeline tick.  Returns the end index (exclusive) of
    each interval."""
    n = len(costs)
    prefix = [0.0]
    for c in costs:
        prefix.append(prefix[-1] + c)

    INF = math.inf
    # dp[s][i]: min over partitions of costs[:i] into s intervals of the
    # max interval sum; cut[s][i]: position of the last cut
    dp = [[INF] * (n + 1) for _ in range(stages + 1)]
    cut = [[0] * (n + 1) for _ in range(stages + 1)]
    dp[0][0] = 0.0
    for s in range(1, stages + 1):
        for i in range(s, n + 1):
            for j in range(s - 1, i):
                v = max(dp[s - 1][j], prefix[i] - prefix[j])
                if v < dp[s][i]:
                    dp[s][i] = v
                    cut[s][i] = j
    ends = []
    i = n
    for s in range(stages, 0, -1):
        ends.append(i)
        i = cut[s][i]
    return ends[::-1]


def propose_pipeline_general(graph: Graph, config, sim,
                             baseline_cost: float
                             ) -> Optional[StagedPipelineProposal]:
    """Costed S-stage pipeline candidate for an ARBITRARY graph
    (reference: inter-op splits are general over any cut,
    graph.cc:161-295; the enum-stub OP_PIPELINE has no such limit).

    The topo order is partitioned into S contiguous intervals balancing
    full-step compute (every edge then crosses forward); cost model
    mirrors propose_pipeline's collective-GPipe formula with the tick
    set by the SLOWEST stage and the per-tick hop priced on the widest
    adjacent-cut crossing:

      T = (M + S - 1)/M · max_s C_s · S̄ …  — see inline terms

    Returns the best finite-cost proposal (marked ``executable`` when
    the graph also passes the stacked-block gates), or None."""
    n = config.search_devices
    batch = config.batch_size
    cost = sim.cost
    machine = cost.machine
    topo = [node for node in graph.topo_order()]
    best: Optional[StagedPipelineProposal] = None

    for stages in (2, 4, 8):
        if stages <= 1 or stages > n or n % stages:
            continue
        if len(topo) < stages:
            continue
        d = n // stages
        m = _pick_microbatches(batch, stages, d)
        if m is None:
            continue

        def dp_view(op, deg):
            ndim = op.output_shapes[0].ndim
            if ndim == 0:
                return MachineView.trivial(0)
            batch_dim = op.output_shapes[0].sizes[0]
            if deg > 1 and batch_dim % deg:
                return None
            return MachineView.data_parallel(ndim, deg)

        node_cost = {}
        feasible = True
        for node in topo:
            v = dp_view(node.op, d)
            if v is None:
                feasible = False
                break
            node_cost[node.guid] = (
                cost.op_cost(node.op, v, backward=True), v)
        if not feasible:
            continue
        ends = _balanced_intervals(
            [node_cost[nd.guid][0] for nd in topo], stages)
        stage_of = {}
        stage_guids: List[List[int]] = []
        startp = 0
        for si, e in enumerate(ends):
            stage_guids.append([nd.guid for nd in topo[startp:e]])
            for nd in topo[startp:e]:
                stage_of[nd.guid] = si
            startp = e
        if any(not s for s in stage_guids):
            continue

        # per-stage compute/sync/update/memory
        stage_comp = [0.0] * stages
        stage_sync = [0.0] * stages
        stage_upd = [0.0] * stages
        stage_mem = [0.0] * stages
        for node in topo:
            si = stage_of[node.guid]
            full, v = node_cost[node.guid]
            upd = cost.update_cost(node.op, v)
            stage_comp[si] += full - upd
            stage_upd[si] += upd
            stage_mem[si] += cost.op_memory(node.op, v)
            for ws, annot in zip(node.op._weight_specs,
                                 node.op.propagate(v).weights):
                if annot is None or annot.replica <= 1:
                    continue
                nbytes = ws.dtype.itemsize
                for s_ in ws.shape:
                    nbytes *= s_
                stage_sync[si] += cost.allreduce(
                    nbytes, d, spans_dcn=d > machine.devices_per_host)
        if max(stage_mem) > machine.hbm_capacity:
            continue

        # per-tick hop: widest adjacent-cut crossing (edges may skip
        # stages; a k-stage skip pays k hops — charged as k unit hops)
        hop_bytes = 0.0
        for guid in graph.nodes:
            for e in graph.out_edges[guid]:
                span = stage_of[e.dst] - stage_of[e.src]
                if span > 0:
                    shape = graph.nodes[e.src].op.output_shapes[e.src_idx]
                    hop_bytes = max(
                        hop_bytes,
                        span * shape.num_bytes / m / max(d, 1))
        spans_dcn = n > machine.devices_per_host
        if spans_dcn:
            t_hop = hop_bytes / machine.dcn_bandwidth + machine.dcn_latency
        else:
            t_hop = hop_bytes / machine.ici_bandwidth + machine.ici_latency

        # collective-GPipe: every tick runs all stages on one microbatch
        # each; tick = slowest stage's per-microbatch time + hop; fwd
        # and reversed bwd both pay the hop every tick
        tick = max(stage_comp) / m
        t_compute = (m + stages - 1) * tick
        t_comm = 2.0 * (m + stages - 1) * t_hop
        total = t_compute + t_comm + max(
            s + u for s, u in zip(stage_sync, stage_upd))

        if math.isfinite(total) and (best is None or total < best.cost):
            executable = _applicable(graph, stages) is not None
            best = StagedPipelineProposal(
                num_stages=stages, num_microbatches=m,
                stage_guids=stage_guids, cost=total,
                executable=executable)

    if best is None:
        return None
    margin = max(0.0, config.search_improvement_margin)
    if math.isfinite(baseline_cost) and (
            best.cost >= baseline_cost * (1.0 - margin)):
        return None
    _gate_pipeline_proposal(
        graph, config, best.num_stages, best.num_microbatches,
        stage_guids=best.stage_guids)
    return best
