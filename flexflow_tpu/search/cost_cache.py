"""Persistent cost cache — warm starts for the strategy search.

The reference's measured-cost cache lives for one process
(ProfilingRecord hash map, simulator.cc:515-554); every bench sweep,
CI run, or repeat compile here used to re-derive identical per-node
cost rows and re-run identical searches from scratch.  This module
persists two layers, both keyed under ONE ``signature`` that
fingerprints the whole cost surface (machine spec, device count,
calibration-table content, precision/sharding mode flags, schema
version):

* **Row cache** — ``Simulator._node_costs`` rows ``(fwd_s, full_s,
  sync_s, mem_bytes)`` per (op structural digest, machine view).  The
  native DP digests (`search/dp.py _node_digest`) are baked from these
  rows, so serving them from disk warms both engines.
* **Search-result cache** — ``optimize_strategy``'s final
  ``(best_graph, strategy, cost)`` per (graph structural digest,
  search-knob tuple).  The search is a deterministic pure function of
  (graph, knobs, cost surface); repeated searches — bench sweeps
  across the model zoo, re-runs after unrelated code edits, CI —
  return the stored result instead of re-searching.  Graphs are
  pickled (operator descriptors are plain immutable python objects);
  anything unpicklable silently skips storing.

Invalidation is WHOLESALE on signature change: a recalibration, a
different machine model, or a bumped ``SCHEMA_VERSION`` abandons every
stored row.  A ``calibration_stale`` flag (set when a measured
DriftReport flags the calibration table, obs/drift.py) makes the cache
refuse to serve until the table is re-probed — a stale surface must
not keep seeding searches.

Knobs: ``FFConfig.cost_cache_file`` / ``--cost-cache-file`` /
``--no-cost-cache``; env ``FLEXFLOW_TPU_COST_CACHE`` (path, or ``0``
to disable) when the config leaves it unset.
"""

from __future__ import annotations

import hashlib
import json
import math
import os
import pickle
import sys
from typing import Dict, Optional, Tuple

from flexflow_tpu.obs.metrics import METRICS

SCHEMA_VERSION = 1
# sub-schema of the persisted DP-memo rows ("dp_rows"/"dp_schema" keys,
# additive to SCHEMA_VERSION so caches written before the layer existed
# stay valid).  An UNKNOWN dp_schema drops the dp layer loudly (stderr +
# fflint CCH405) and keeps the rest of the cache — corrupt memo rows
# must cost a recompute, never serve a wrong strategy.
# v2: stable_node_digests substitutes input tensor_guids by rank of
# appearance (matching stable_graph_digest), so input-bearing segments
# key consistently across builds — v1 rows for such segments were
# permanently dead keys that still counted against DP_MAX_ROWS.
DP_SCHEMA = 2
# sub-schema of the persisted comm-plan memo rows ("comm_plans"/
# "comm_schema" keys, search/comm_plan.py): the co-search's chosen
# sync schedules / precision maps / zero-sharding choices per
# synced-group signature.  Same additive discipline as the dp layer —
# an unknown comm_schema drops ONLY this layer, loudly (stderr +
# fflint CCH407), and a re-search rebuilds it.
COMM_SCHEMA = 1
# sub-schema of the persisted SP-SEGMENT memo rows ("sp_rows"/
# "sp_schema" keys): finished series-parallel segment SOLVES — the
# whole unity recursion over one segment, substitutions included — as
# guid-free strategy rows under stable digests (driver._persist_sp_row)
# keyed by segment digest + pinned boundary-view tuple + search knobs.
# Same additive fail-LOUD discipline: an unknown sp_schema drops only
# this layer (stderr + fflint CCH409) and segments re-solve.
SP_SCHEMA = 1

_ROW_HITS = METRICS.counter("cost_cache.row_hits")
_ROW_MISSES = METRICS.counter("cost_cache.row_misses")
_RESULT_HITS = METRICS.counter("cost_cache.result_hits")
_RESULT_MISSES = METRICS.counter("cost_cache.result_misses")
_DP_HITS = METRICS.counter("cost_cache.dp_row_hits")
_DP_MISSES = METRICS.counter("cost_cache.dp_row_misses")
_COMM_HITS = METRICS.counter("cost_cache.comm_plan_hits")
_COMM_MISSES = METRICS.counter("cost_cache.comm_plan_misses")
_SP_HITS = METRICS.counter("cost_cache.sp_row_hits")
_SP_MISSES = METRICS.counter("cost_cache.sp_row_misses")

RowKey = Tuple[str, Tuple[int, ...], int]


def resolve_cost_cache_path(config) -> Optional[str]:
    """The on-disk cache path for a config, or None when disabled.
    Explicit ``cost_cache_file`` wins; empty string disables; unset
    falls back to the FLEXFLOW_TPU_COST_CACHE environment variable
    (its value ``0``/empty likewise disables)."""
    path = getattr(config, "cost_cache_file", None)
    if path is None:
        path = os.environ.get("FLEXFLOW_TPU_COST_CACHE") or None
    if not path or path == "0":
        return None
    return path


def calibration_digest(calibration) -> Optional[str]:
    """Content fingerprint of a CalibrationTable — the cache must
    invalidate when any measured record changes, not merely when the
    file path does."""
    if calibration is None:
        return None
    h = hashlib.sha256()
    h.update(repr(getattr(calibration, "backend", None)).encode())
    for k, v in sorted(calibration._t.items()):
        h.update(repr((k, v)).encode())
    for k, v in sorted(calibration._clusters.items()):
        h.update(repr((k, v)).encode())
    return h.hexdigest()[:16]


def cost_signature(cost_model) -> str:
    """Fingerprint of everything a cost row / search result depends on
    besides the (op, view) key itself — the ``calibration_signature``
    axis of the cache key."""
    m = cost_model.machine
    parts = {
        "schema": SCHEMA_VERSION,
        "python_hash_stable": True,
        "machine": [
            m.num_devices, m.devices_per_host, m.peak_flops,
            m.hbm_bandwidth, m.hbm_capacity, m.ici_bandwidth,
            m.ici_latency, list(m.ici_torus), m.dcn_bandwidth,
            m.dcn_latency, m.reshard_overhead_s, m.name, m.platform,
            [list(lvl) for lvl in m.slice_levels],
        ],
        "num_devices": cost_model.num_devices,
        "zero_dp_shard": cost_model.zero_dp_shard,
        "inference": cost_model.inference,
        "sync_precision": cost_model.sync_precision,
        "network": cost_model.network is not None,
        "calibration": calibration_digest(cost_model.calibration),
    }
    if getattr(cost_model, "sync_ef", False):
        # EF changes the priced sync seconds (EF_PASSES in
        # _quant_overhead, the int8→int8_ef upgrade) so its rows must
        # not cross-serve plain-int8 runs — extension-only keying:
        # sync_ef=off signatures stay byte-identical to caches written
        # before the flag existed (same discipline as search_key's
        # co_search marker)
        parts["sync_ef"] = True
    serving = getattr(cost_model, "serving", None)
    if serving is not None:
        # serve-objective rows price the decode ops' cache stream at
        # the arrival model's ragged quantile load — a different cost
        # surface per ServingSpec.  Extension-only: objective="train"
        # signatures stay byte-identical to every cache written before
        # the serving dimension existed
        parts["serving"] = list(serving.signature())
    return hashlib.sha256(
        json.dumps(parts, sort_keys=True).encode()).hexdigest()[:16]


def stable_graph_digest(graph) -> str:
    """Process-stable structural digest of a PCG (graph.hash() uses
    python tuple hashing, which PYTHONHASHSEED randomizes across
    processes — unusable as a persistent key).  Hashes the topo-ordered
    op signatures plus position-indexed edges.  InputOp signatures
    embed the frontend's GLOBAL tensor_guid counter (process-lifetime,
    build-order dependent); the digest replaces it with the input's
    rank of appearance, which carries the same distinctness.  Cached on
    the graph object (cleared by Graph._invalidate on mutation) — the
    persistent DP memo keys every tier-2 segment query by it."""
    cached = getattr(graph, "_stable_gd_cache", None)
    if cached is not None:
        return cached
    order = graph.topo_order()
    pos = {n.guid: i for i, n in enumerate(order)}
    # input-rank substitution lives in ONE place (the same rule keys
    # the per-node digests the dp/sp memo rows pair under)
    sigs = graph.stable_sig_reprs()
    h = hashlib.blake2b(digest_size=16)
    for node in order:
        h.update(sigs[node.guid].encode())
        for e in sorted(
            (pos[e.src], e.src_idx, e.dst_idx)
            for e in graph.in_edges[node.guid]
        ):
            h.update(repr(e).encode())
        h.update(b";")
    out = h.hexdigest()
    graph._stable_gd_cache = out
    return out


class CostCache:
    """One on-disk cache file (JSON rows + pickled search results in a
    sidecar), bound to a single cost ``signature``.  Load once per
    search/bench process; ``save()`` persists atomically when dirty."""

    def __init__(self, path: str, signature: str):
        self.path = path
        self.signature = signature
        self.rows: Dict[RowKey, Tuple[float, float, float, float]] = {}
        self.results: Dict[str, tuple] = {}
        # persisted tier-2 DP memo rows (dp-row layer): key string ->
        # {"cost": float, "strategy": [[node_digest, dims, replica,
        # start], ...]} — guid-free, remappable onto isomorphic
        # segments in any process (search/dp.py serves them).
        # ``dp_loaded`` marks rows that arrived FROM DISK: only those
        # are served — within one run the in-process DP memo already
        # covers anything this run wrote, so a cold cache stays inert
        # and the bit-identical regression gate holds
        self.dp_rows: Dict[str, dict] = {}
        self.dp_loaded = False
        # persisted comm-plan memo rows (comm-plan layer,
        # search/comm_plan.py): signature digest -> jsonable
        # CommPlanEntry.  Only consulted under FFConfig.co_search, so
        # the layer is inert on every sequential-pipeline run and the
        # bit-identical regression gate holds by construction.
        self.comm_plans: Dict[str, dict] = {}
        # persisted SP-SEGMENT memo rows (sp-row layer): key string ->
        # {"cost": float, "strategy": [[node_digest, dims, replica,
        # start], ...]} — whole series-parallel segment solves
        # (driver.sp_optimize) under guid-free stable digests.
        # ``sp_loaded`` marks rows FROM DISK: only those are served —
        # within one run the in-process segment cache already covers
        # this run's writes, so a cold cache stays inert and the chain
        # bit-identity gate holds.
        self.sp_rows: Dict[str, dict] = {}
        self.sp_loaded = False
        self.stale = False
        self.invalidated = False  # file existed with another signature
        self._dirty = False
        self.row_hits = 0
        self.row_misses = 0
        self.result_hits = 0
        self.result_misses = 0
        self.dp_row_hits = 0
        self.dp_row_misses = 0
        self.comm_plan_hits = 0
        self.comm_plan_misses = 0
        self.sp_row_hits = 0
        self.sp_row_misses = 0
        self._load()

    # ------------------------------------------------------------------
    @property
    def result_path(self) -> str:
        return self.path + ".results.pkl"

    def _load(self) -> None:
        if not os.path.exists(self.path):
            return
        try:
            with open(self.path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            self.invalidated = True
            return
        if data.get("signature") != self.signature or \
                data.get("schema") != SCHEMA_VERSION:
            # wholesale invalidation: the cost surface moved (new
            # calibration, different machine/flags, or schema bump)
            self.invalidated = True
            return
        if data.get("calibration_stale"):
            # a measured DriftReport flagged the calibration this cache
            # was keyed by: refuse to serve anything derived from it
            self.stale = True
            print(
                "flexflow_tpu cost cache: calibration flagged STALE by a "
                "measured drift report — recalibrate (--calibrate / "
                "bench_search.py --calibrate) or pass --no-cost-cache; "
                "refusing to serve cached rows",
                file=sys.stderr,
            )
            return
        for r in data.get("rows", []):
            self.rows[(r["sig"], tuple(r["degrees"]), int(r["replica"]))] = (
                tuple(float(x) for x in r["row"])
            )
        dp = data.get("dp_rows")
        if dp:
            if data.get("dp_schema") != DP_SCHEMA:
                # fail LOUD, not wrong: an unknown/missing dp sub-schema
                # means these memo rows were written by a different
                # layout — drop the layer (one recompute), keep the
                # still-valid row/result layers
                print(
                    f"flexflow_tpu cost cache: persisted DP-memo rows "
                    f"carry unknown dp_schema "
                    f"{data.get('dp_schema')!r} (known: {DP_SCHEMA}) — "
                    f"dropping the dp-row layer; rows will be "
                    f"recomputed (run tools/fflint.py cache to "
                    f"inspect)",
                    file=sys.stderr,
                )
            elif isinstance(dp, dict):
                self.dp_rows = dp
                self.dp_loaded = True
        sp = data.get("sp_rows")
        if sp:
            if data.get("sp_schema") != SP_SCHEMA:
                # same fail-LOUD discipline as the dp layer: an unknown
                # sub-schema drops ONLY the sp-row layer (segments
                # re-solve, one recompute each), keeps the rest
                print(
                    f"flexflow_tpu cost cache: persisted sp-segment memo "
                    f"rows carry unknown sp_schema "
                    f"{data.get('sp_schema')!r} (known: {SP_SCHEMA}) — "
                    f"dropping the sp-row layer; segments will be "
                    f"re-solved (run tools/fflint.py cache to inspect)",
                    file=sys.stderr,
                )
            elif isinstance(sp, dict):
                self.sp_rows = sp
                self.sp_loaded = True
        cp = data.get("comm_plans")
        if cp:
            if data.get("comm_schema") != COMM_SCHEMA:
                # same fail-LOUD discipline as the dp layer: unknown
                # layout drops only the comm-plan layer (one re-search
                # per signature), keeps row/result/dp layers intact
                print(
                    f"flexflow_tpu cost cache: persisted comm-plan rows "
                    f"carry unknown comm_schema "
                    f"{data.get('comm_schema')!r} (known: {COMM_SCHEMA}) "
                    f"— dropping the comm-plan layer; plans will be "
                    f"re-searched (run tools/fflint.py cache to "
                    f"inspect)",
                    file=sys.stderr,
                )
            elif isinstance(cp, dict):
                self.comm_plans = cp
        if os.path.exists(self.result_path):
            try:
                with open(self.result_path, "rb") as f:
                    blob = pickle.load(f)
                if blob.get("signature") == self.signature:
                    self.results = blob.get("results", {})
            except Exception:
                # a corrupt/unreadable result sidecar only costs a
                # recompute, never a failure
                self.results = {}

    def save(self) -> None:
        if not self._dirty or self.stale:
            return
        # a drift check may have marked the ON-DISK file stale after we
        # loaded it (model.fit in this or another process): rewriting
        # would silently un-mark it and resurrect rows derived from a
        # flagged calibration table — honor the mark instead
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    if json.load(f).get("calibration_stale"):
                        self.stale = True
                        return
            except (OSError, ValueError):
                pass
        rows = [
            {"sig": k[0], "degrees": list(k[1]), "replica": k[2],
             "row": list(v)}
            for k, v in sorted(self.rows.items())
        ]
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"schema": SCHEMA_VERSION, "signature": self.signature,
                 "calibration_stale": False, "rows": rows,
                 "dp_schema": DP_SCHEMA, "dp_rows": self.dp_rows,
                 "comm_schema": COMM_SCHEMA,
                 "comm_plans": self.comm_plans,
                 "sp_schema": SP_SCHEMA, "sp_rows": self.sp_rows},
                f,
            )
        os.replace(tmp, self.path)
        try:
            tmp = self.result_path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump(
                    {"signature": self.signature, "results": self.results},
                    f, protocol=4,
                )
            os.replace(tmp, self.result_path)
        except Exception:
            # unpicklable payloads (exotic op attributes) degrade to a
            # row-only cache
            try:
                os.remove(tmp)
            except OSError:
                pass
        self._dirty = False

    # ---- row layer ----------------------------------------------------
    @staticmethod
    def row_key(op, mv) -> RowKey:
        return (
            repr(op.signature()),
            tuple(mv.dim_degrees),
            int(mv.replica_degree),
        )

    def get(self, op, mv) -> Optional[Tuple[float, float, float, float]]:
        if self.stale:
            return None
        hit = self.rows.get(self.row_key(op, mv))
        if hit is None:
            self.row_misses += 1
            _ROW_MISSES.inc()
            return None
        self.row_hits += 1
        _ROW_HITS.inc()
        return hit

    def put(self, op, mv, row: Tuple[float, float, float, float]) -> None:
        if self.stale:
            return
        if not all(isinstance(x, (int, float)) for x in row):
            return
        self.rows[self.row_key(op, mv)] = tuple(float(x) for x in row)
        self._dirty = True

    # ---- DP memo-row layer (tier-2 segment results) -------------------
    def get_dp_row(self, key: str) -> Optional[dict]:
        """The persisted tier-2 DP memo row for a (segment digest,
        fixed-view digest, budget, start) key, or None.  The payload is
        guid-free: ``strategy`` pairs process-stable node digests
        (Graph.stable_node_digests) with view tuples; search/dp.py
        remaps it onto the caller's guids."""
        if self.stale:
            return None
        hit = self.dp_rows.get(key)
        if hit is None:
            self.dp_row_misses += 1
            _DP_MISSES.inc()
            return None
        self.dp_row_hits += 1
        _DP_HITS.inc()
        return hit

    # ---- sp-segment memo-row layer (series-parallel segment solves) ---
    def get_sp_row(self, key: str) -> Optional[dict]:
        """The persisted sp-segment memo row for a (segment digest,
        boundary-pin digest, knobs) key, or None.  The payload is
        guid-free like the dp layer's; driver._serve_sp_row remaps it
        onto the caller's segment and re-lints before serving."""
        if self.stale:
            return None
        hit = self.sp_rows.get(key)
        if hit is None:
            self.sp_row_misses += 1
            _SP_MISSES.inc()
            return None
        self.sp_row_hits += 1
        _SP_HITS.inc()
        return hit

    # soft bound mirroring DP_MAX_ROWS — a 10k-node sweep over many
    # boundary tuples must not grow the file without limit
    SP_MAX_ROWS = 20000

    def put_sp_row(self, key: str, cost: float, strategy_rows) -> None:
        if self.stale or not math.isfinite(cost):
            return
        if key in self.sp_rows:
            return  # deterministic solve: first write wins
        if len(self.sp_rows) >= self.SP_MAX_ROWS:
            return
        self.sp_rows[key] = {"cost": float(cost),
                             "strategy": strategy_rows}
        self._dirty = True

    # ---- comm-plan memo layer (co-search, search/comm_plan.py) --------
    def get_comm_plan(self, key: str) -> Optional[dict]:
        """The persisted comm-plan row for a synced-group signature
        digest, or None.  The payload is the jsonable CommPlanEntry
        (schedule + precision map + zero map + credit); comm_plan.py
        validates it structurally and treats malformation as a miss."""
        if self.stale:
            return None
        hit = self.comm_plans.get(key)
        if hit is None:
            self.comm_plan_misses += 1
            _COMM_MISSES.inc()
            return None
        self.comm_plan_hits += 1
        _COMM_HITS.inc()
        return hit

    # soft bound mirroring DP_MAX_ROWS — a signature-rich sweep must
    # not grow the file without limit
    COMM_MAX_ROWS = 20000

    def put_comm_plan(self, key: str, payload: dict) -> None:
        if self.stale:
            return
        if key in self.comm_plans:
            return  # deterministic choice: first write wins
        if len(self.comm_plans) >= self.COMM_MAX_ROWS:
            return
        self.comm_plans[key] = payload
        self._dirty = True

    # soft bound on the persisted memo: a production sweep over many
    # large graphs must not grow COST_CACHE.json without limit — beyond
    # the cap new rows cost a recompute next run, nothing breaks
    DP_MAX_ROWS = 20000

    def put_dp_row(self, key: str, cost: float, strategy_rows) -> None:
        if self.stale or not math.isfinite(cost):
            return
        if key in self.dp_rows:
            return  # deterministic DP: first write wins, stays stable
        if len(self.dp_rows) >= self.DP_MAX_ROWS:
            return
        self.dp_rows[key] = {"cost": float(cost),
                             "strategy": strategy_rows}
        self._dirty = True

    # ---- search-result layer -----------------------------------------
    @staticmethod
    def search_key(graph, config) -> str:
        # custom substitution rules are part of the search function:
        # fingerprint the FILE CONTENT, not just its presence — edited
        # rules must not be shadowed by a result cached under old ones
        sub_digest = None
        if config.substitution_json:
            try:
                with open(config.substitution_json, "rb") as f:
                    sub_digest = hashlib.sha256(f.read()).hexdigest()[:12]
            except OSError:
                sub_digest = "unreadable"
        knobs = (
            config.search_devices, config.search_budget,
            config.search_alpha, config.base_optimize_threshold,
            config.search_improvement_margin,
            sub_digest,
        )
        if getattr(config, "co_search", False):
            # extension-only keying: a joint co-search result is a
            # different function value, but sequential-pipeline keys
            # must stay byte-identical to caches written before the
            # flag existed
            knobs = knobs + ("co_search",)
        if getattr(config, "objective", "train") == "serve":
            # the serve objective is a different search function (p99
            # currency + serving lint gate) — same extension-only rule
            knobs = knobs + (
                "serve",
                float(getattr(config, "serve_p99_budget_ms", 0.0) or 0.0),
            )
            if getattr(config, "serve_fleet", "off") == "search":
                # fleet searches price replica blocks at partial
                # occupancy (arrival shares) — a different search
                # function again.  Extension-only: serve_fleet=off
                # keys stay byte-identical to pre-fleet caches
                knobs = knobs + (
                    "fleet",
                    int(getattr(config, "serve_fleet_max_replicas", 4)),
                    float(getattr(config, "serve_fleet_offered_load",
                                  0.85)),
                )
            if getattr(config, "kv_precision", "off") != "off":
                # the KV-precision lane re-prices the decode cache
                # stream per pool dtype — a different search function.
                # Extension-only: kv_precision=off keys stay
                # byte-identical to pre-lane caches
                knobs = knobs + ("kv", config.kv_precision)
            if int(getattr(config, "serve_shared_prefix_pages", 0) or 0):
                # prefix sharing discounts KV residency (the memory
                # feasibility check), so results ranked under it must
                # not cross-serve unshared runs — same extension rule
                knobs = knobs + (
                    "kvshared",
                    int(config.serve_shared_prefix_pages),
                )
        return stable_graph_digest(graph) + ":" + hashlib.sha256(
            repr(knobs).encode()).hexdigest()[:12]

    def get_search_result(self, graph, config):
        """The stored search payload for (graph digest, knobs) under
        this cost surface, or None.  The payload shape is the driver's
        (orig_topo_guids, best_graph_or_None, strategy, cost)."""
        if self.stale:
            return None
        blob = self.results.get(self.search_key(graph, config))
        if blob is None:
            self.result_misses += 1
            _RESULT_MISSES.inc()
            return None
        try:
            payload = pickle.loads(blob)
        except Exception:
            self.result_misses += 1
            _RESULT_MISSES.inc()
            return None
        self.result_hits += 1
        _RESULT_HITS.inc()
        return payload

    def drop_search_result(self, graph, config) -> bool:
        """Evict the stored result for (graph, knobs) — the driver calls
        this when a served payload fails the static-analysis gate
        (corrupt pickle, illegal strategy), so a bad entry costs one
        recompute instead of being served forever.  Returns True when an
        entry was dropped."""
        key = self.search_key(graph, config)
        if key in self.results:
            del self.results[key]
            self._dirty = True
            return True
        return False

    def put_search_result(self, graph, config, payload,
                          cost: float) -> None:
        if self.stale or not math.isfinite(cost):
            return
        try:
            blob = pickle.dumps(payload, protocol=4)
        except Exception:
            return  # unpicklable op payloads: result layer declines
        self.results[self.search_key(graph, config)] = blob
        self._dirty = True


def load_for_simulator(config, sim) -> Optional[CostCache]:
    """Attach-or-None: resolve the configured path and bind a CostCache
    to the simulator's exact cost surface."""
    path = resolve_cost_cache_path(config)
    if path is None:
        return None
    cache = CostCache(path, cost_signature(sim.cost))
    sim.cost_cache = cache
    return cache


def mark_calibration_stale(path: str) -> bool:
    """Flip the on-disk ``calibration_stale`` flag — called when a
    measured DriftReport flags the calibration table (the PR-2
    follow-up: staleness must gate the cache, not just warn).  Returns
    True when a cache file was marked."""
    if not path or not os.path.exists(path):
        return False
    try:
        with open(path) as f:
            data = json.load(f)
        data["calibration_stale"] = True
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f)
        os.replace(tmp, path)
        return True
    except (OSError, ValueError):
        return False
