"""Joint strategy × comm-plan co-search (ROADMAP item 2).

Unity's core claim is that parallelization decisions must be searched
*jointly* — yet until this module the comm plan was chosen
sequentially: the substitution/DP search picked a strategy under the
legacy per-node overlap credit, and only afterwards were the sync wire
precision (search/sync_precision.py), the bucketed issue schedule
(search/sync_schedule.py) and the staged reduction plans
(search/reduction_plan.py) fitted to it.  The search could therefore
commit to a TP-vs-DP trade whose actual comm cost it never priced.

Under ``FFConfig.co_search`` every candidate strategy the search
grounds — substitution proposals, DP re-validations, chain-segment
solves, the champion-vs-DP floor — is priced with its BEST comm plan
through the simulator's exposed-comm semantics
(``Simulator.simulate(sync_schedule=...)``):

* ``JointPricer.price`` = one exposed-comm simulation under the
  strategy's chosen plan, minus the per-group optimizer-sharding
  (ZeRO-1) update credit;
* the plan itself — bucket composition, per-bucket wire precision,
  staged reduction plans, per-group optimizer-state sharding — is
  memoized under the strategy's SYNCED-GROUP SIGNATURE (the
  topo-ordered (op name, op signature, view) tuple of its weighted
  nodes).  Most substitutions insert weightless parallel ops and most
  DP re-validations revisit previously seen view combinations, so the
  plan is *served*, not re-searched; only a genuinely new signature
  pays the full ``choose_sync_schedule`` sweep (~10 simulations);
* served/searched counts land in ``search.perf``
  (``comm_plan_serves`` / ``comm_plan_searches``) and, when telemetry
  is on, every decision emits a ``search.comm_plan`` event (rendered
  by ``ffobs report``);
* plans persist across processes as a third ``COST_CACHE.json`` layer
  (``comm_plans`` under ``comm_schema``, search/cost_cache.py) keyed
  by a process-stable digest of the signature — a warm process serves
  plans the way it already serves cost rows and DP memo rows.

The per-group optimizer-state sharding dimension ("Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training",
arXiv:2004.13336): instead of the global ``FFConfig.zero_dp_shard``
flag, co-search picks, per synced weight group, whether its optimizer
state (and update compute) shards over the group's replication axes —
the update term shrinks by the achieved shard factor, which is the
credit the joint currency subtracts (the RS+AG pair moves the same
ring bytes as the flat allreduce, so the wire is a wash; the update
and memory are not).  The chosen map persists in the strategy file's
``__meta__.zero_groups`` behind the digest gate, is linted always-on
(``analysis.lint_zero_map``, SHD140/141) and stdlib-checked by
``fflint strategy`` (STR207), and executes through the lowering's
per-group ZeRO shardings.

With ``co_search=False`` (the default) nothing here runs and the
sequential strategy→plan pipeline is bit-identical to history — the
regression gate tests/test_co_search.py enforces.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from flexflow_tpu.obs.events import BUS
from flexflow_tpu.obs.metrics import METRICS

_PLAN_SERVES = METRICS.counter("comm_plan.serves")
_PLAN_SEARCHES = METRICS.counter("comm_plan.searches")


def synced_signature(graph, strategy) -> Tuple:
    """The strategy's synced-group signature: topo-ordered
    ``(op name, op signature, dim degrees, replica degree)`` for every
    WEIGHTED node.  Two (graph, strategy) pairs with equal signatures
    have identical synced-group sets, wire-precision choices, bucket
    memberships and zero-sharding trade-offs — the comm plan transfers
    verbatim (bucket membership is by op name, and names survive
    rewrites: substitutions insert weightless parallel ops).  Cheap by
    construction: no propagation, no cost model — the per-candidate
    hot-path key of the co-search memo."""
    from flexflow_tpu.core.machine import MachineView

    sig = []
    for node in graph.topo_order():
        if not getattr(node.op, "_weight_specs", ()):
            continue
        mv = strategy.get(node.guid)
        if mv is None:
            mv = node.op.fixed_machine_view() or MachineView.trivial(
                node.op.output_shapes[0].ndim
            )
        sig.append((node.op.name, node.op.signature(),
                    tuple(mv.dim_degrees), int(mv.replica_degree)))
    return tuple(sig)


def signature_digest(sig: Tuple, config) -> str:
    """Process-stable digest of a synced-group signature plus the comm
    knobs the cost-cache signature does not already pin — the key of
    the persistent comm-plan layer (op signatures repr-stable the same
    way the persisted cost rows are)."""
    from hashlib import blake2b

    knobs = (int(getattr(config, "sync_bucket_bytes", 0) or 0),)
    return blake2b(repr((sig, knobs)).encode(),
                   digest_size=12).hexdigest()


def zero_weight_shards(cost_model, op, mv):
    """Per-weight ``(update_seconds, shard_factor)`` rows for ``(op,
    mv)`` under per-group ZeRO-1 — the SAME evenly-divisible placement
    rule the lowering's ``_zero_augmented`` and ``CostModel.op_memory``
    apply (``place_zero_factors``, per WEIGHT over the axes that weight
    does not consume), so the priced credit matches what execution
    realizes: an armed op shards EVERY weight over its own free axes,
    each by its own achieved factor.  [] when propagation fails."""
    from flexflow_tpu.parallel.mesh import place_zero_factors, prime_factors

    try:
        osh = op.propagate(mv)
    except Exception:
        return []
    nd = cost_model.num_devices or cost_model.machine.num_devices
    hbm = cost_model.machine.hbm_bandwidth
    rows = []
    for ws, annot in zip(op._weight_specs, osh.weights):
        degrees = annot.degrees if annot is not None else ()
        shard_elems = 1
        for d in ws.shape:
            shard_elems *= d
        sharded = 1
        for d in degrees:
            shard_elems //= max(d, 1)
            sharded *= max(d, 1)
        upd = (cost_model.OPT_UPDATE_PASSES * shard_elems
               * ws.dtype.itemsize / hbm)
        achieved = 1
        if sharded >= 1 and nd % sharded == 0 and nd > sharded:
            extents = [
                s // max(d, 1) if d and s % max(d, 1) == 0 else 1
                for s, d in zip(ws.shape, degrees)
            ]
            free = prime_factors(nd // sharded)
            for _, fi in place_zero_factors(extents, free):
                achieved *= free[fi]
        rows.append((upd, float(achieved)))
    return rows


def zero_update_factor(cost_model, op, mv) -> float:
    """The EFFECTIVE optimizer-update shrink factor per-group ZeRO-1
    achieves for ``(op, mv)``: total update seconds over the sharded
    update seconds, from the per-weight rows above.  1.0 when nothing
    shards (no placeable factor on any weight)."""
    rows = zero_weight_shards(cost_model, op, mv)
    total = sum(u for u, _f in rows)
    sharded = sum(u / f for u, f in rows)
    if total <= 0.0 or sharded <= 0.0 or sharded >= total:
        return 1.0
    return total / sharded


def choose_zero_groups(graph, strategy, cost_model) -> Tuple[Tuple[str, ...],
                                                             float]:
    """Per-group optimizer-state sharding choice: the op names whose
    update term genuinely shrinks under ZeRO-1 sharding (achieved
    factor > 1), plus the total update-seconds credit — the RS+AG pair
    moves the same ring bytes as the flat allreduce it replaces, so
    the wire term is a wash and the priced win is the update compute
    (the memory win additionally relaxes feasibility, credited
    conservatively: never).  Returns ``((), 0.0)`` when nothing
    qualifies."""
    from flexflow_tpu.core.machine import MachineView

    # stamped production graphs (PR 7 segment stamping) can carry the
    # SAME op name on several weighted nodes — a name-keyed map cannot
    # address them individually, so ambiguous names are skipped (no
    # credit claimed, nothing executed for them)
    weighted = [n for n in graph.topo_order()
                if getattr(n.op, "_weight_specs", ())]
    counts: Dict[str, int] = {}
    for n in weighted:
        counts[n.op.name] = counts.get(n.op.name, 0) + 1
    names = []
    credit = 0.0
    for node in weighted:
        if counts[node.op.name] > 1:
            continue
        mv = strategy.get(node.guid)
        if mv is None:
            mv = node.op.fixed_machine_view() or MachineView.trivial(
                node.op.output_shapes[0].ndim
            )
        try:
            osh = node.op.propagate(mv)
        except Exception:
            continue
        # membership requires a SYNCED (replicated) weight — the wash
        # argument (RS+AG vs flat allreduce) only holds there, and the
        # SHD140 lint enforces it; the credit then sums PER WEIGHT,
        # because an armed op shards every weight over its own free
        # axes by its own factor (lowering._zero_augmented)
        if not any(a is not None and a.replica > 1 for a in osh.weights):
            continue
        rows = zero_weight_shards(cost_model, node.op, mv)
        saving = sum(u * (1.0 - 1.0 / f) for u, f in rows if f > 1.0)
        if not math.isfinite(saving) or saving <= 0.0:
            continue
        names.append(node.op.name)
        credit += saving
    return tuple(names), credit


@dataclass
class CommPlanEntry:
    """One memoized comm plan: the exposed-comm schedule the joint
    currency prices with (ALWAYS present — the monolithic bucket
    composition when nothing beat it), whether bucketing was adopted
    over monolithic, the per-group wire-precision map, and the
    per-group optimizer-sharding choice with its update credit."""

    schedule: object  # search.sync_schedule.SyncSchedule
    adopted: bool
    pmap: Dict[str, str] = field(default_factory=dict)
    zero: Tuple[str, ...] = ()
    zero_credit: float = 0.0

    def to_jsonable(self) -> dict:
        return {
            "schedule": self.schedule.to_jsonable(),
            "adopted": bool(self.adopted),
            "pmap": dict(self.pmap),
            "zero": list(self.zero),
            "credit": float(self.zero_credit),
        }

    @staticmethod
    def from_jsonable(data) -> "CommPlanEntry":
        from flexflow_tpu.search.sync_schedule import SyncSchedule

        if not isinstance(data, dict):
            raise ValueError("comm plan row is not an object")
        pmap = data.get("pmap", {})
        zero = data.get("zero", [])
        credit = data.get("credit", 0.0)
        if (not isinstance(pmap, dict)
                or not isinstance(zero, list)
                or any(not isinstance(z, str) for z in zero)
                or not isinstance(credit, (int, float))):
            raise ValueError("comm plan row carries malformed fields")
        return CommPlanEntry(
            schedule=SyncSchedule.from_jsonable(data.get("schedule")),
            adopted=bool(data.get("adopted")),
            pmap={str(k): str(v) for k, v in pmap.items()},
            zero=tuple(zero),
            zero_credit=float(credit),
        )


class JointPricer:
    """The co-search pricing engine one ``optimize_strategy`` run
    shares: a comm-plan memo (in-process dict + the persistent
    ``comm_plans`` cost-cache layer) and the joint ``price`` function
    every candidate-grounding site calls instead of the legacy
    ``Simulator.simulate``."""

    def __init__(self, config, cost_cache=None):
        self.config = config
        self.cost_cache = cost_cache
        self._memo: Dict[Tuple, Optional[CommPlanEntry]] = {}
        self.serves = 0
        self.searches = 0

    # ------------------------------------------------------------------
    def plan_for(self, graph, strategy, sim) -> Optional[CommPlanEntry]:
        """The best comm plan for ``(graph, strategy)`` — served from
        the signature memo (then the persistent layer) when the synced
        -group signature was seen before, searched fresh otherwise.
        None when the strategy syncs nothing (the comm plan dimension
        is empty and the legacy scalar currency is already exact)."""
        sig = synced_signature(graph, strategy)
        if not sig:
            return None
        if sig in self._memo:
            self.serves += 1
            _PLAN_SERVES.inc()
            if BUS.enabled:
                BUS.emit("search.comm_plan", served=True, source="memo",
                         groups=len(sig))
            return self._memo[sig]
        cc = self.cost_cache
        digest = None
        if cc is not None:
            digest = signature_digest(sig, self.config)
            row = cc.get_comm_plan(digest)
            if row is not None:
                try:
                    entry = CommPlanEntry.from_jsonable(row)
                except ValueError:
                    entry = None  # malformed row: one re-search, and
                    # fflint cache (CCH408) points at the corruption
                if entry is not None:
                    self._memo[sig] = entry
                    self.serves += 1
                    _PLAN_SERVES.inc()
                    if BUS.enabled:
                        BUS.emit("search.comm_plan", served=True,
                                 source="disk", groups=len(sig))
                    return entry
        entry = self._search_plan(graph, strategy, sim)
        self._memo[sig] = entry
        self.searches += 1
        _PLAN_SEARCHES.inc()
        if BUS.enabled:
            BUS.emit("search.comm_plan", served=False, source="search",
                     groups=len(sig),
                     adopted=bool(entry is not None and entry.adopted))
        if entry is not None and cc is not None and digest is not None:
            cc.put_comm_plan(digest, entry.to_jsonable())
        return entry

    def _search_plan(self, graph, strategy, sim) -> Optional[CommPlanEntry]:
        """The full comm-plan search for one signature: per-group wire
        precision, bucketed schedule sweep (+ staged reduction plans on
        hierarchical machines) through ``choose_sync_schedule``, and
        the per-group optimizer-sharding choice.  Falls back to the
        MONOLITHIC bucket composition when nothing beats it — the
        joint currency must price every candidate in the same
        exposed-comm semantics, never the legacy per-node credit."""
        import math as _math

        from flexflow_tpu.search.sync_schedule import (
            build_bucketed_schedule,
            choose_sync_schedule,
            synced_weight_groups,
        )

        synced_names = [
            n.op.name for n in graph.topo_order()
            if getattr(n.op, "_weight_specs", ())
        ]
        if len(synced_names) != len(set(synced_names)):
            # stamped production graphs can repeat op names (PR 7
            # segment stamping) — every comm-plan artifact is keyed by
            # op NAME, so the plan dimension is undefined there: the
            # candidate prices in the legacy scalar currency instead
            return None
        pmap: Dict[str, str] = {}
        if getattr(self.config, "sync_precision", "fp32") != "fp32":
            from flexflow_tpu.search.sync_precision import (
                choose_sync_precision,
            )

            pmap = choose_sync_precision(graph, strategy, sim.cost)
        schedule, _info = choose_sync_schedule(
            graph, strategy, sim, pmap, self.config)
        adopted = schedule is not None
        if schedule is None:
            synced = synced_weight_groups(graph, strategy, sim.cost)
            if not synced:
                return None
            schedule = build_bucketed_schedule(synced, pmap, _math.inf)
            if schedule is None:
                return None
        zero, credit = choose_zero_groups(graph, strategy, sim.cost)
        return CommPlanEntry(schedule=schedule, adopted=adopted,
                             pmap=dict(pmap), zero=zero,
                             zero_credit=credit)

    # ------------------------------------------------------------------
    def price(self, sim, graph, strategy) -> float:
        """The joint currency: the exposed-comm simulated step under
        the strategy's best comm plan, minus the per-group
        optimizer-sharding update credit.  Strategies that sync
        nothing price exactly as the legacy scalar simulation (the two
        currencies coincide there)."""
        entry = self.plan_for(graph, strategy, sim)
        if entry is None:
            return sim.simulate(graph, strategy)
        cost = sim.simulate(graph, strategy, sync_schedule=entry.schedule)
        if not math.isfinite(cost):
            return cost
        return max(0.0, cost - entry.zero_credit)
