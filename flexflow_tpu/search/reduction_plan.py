"""Staged per-group reduction plans for hierarchical topologies.

A flat ring allreduce on a multi-slice machine drags the FULL gradient
around the slow DCN links; the hierarchical shape — reduce-scatter
within each slice, a small cross-slice exchange of the 1/f0 shard,
all-gather within each slice — shrinks the DCN traffic by the
within-slice factor ("Synthesizing Optimal Parallelism Placement and
Reduction Strategies on Hierarchical Systems", arXiv:2110.10548; XLA's
own multislice allreduce has the same shape).  This module makes that
shape a SEARCHED, per-weight-group artifact:

* a ``ReductionPlan`` names the staged decomposition per sync bucket
  (search/sync_schedule.py ``SyncBucket.plan``): one stage per link
  level (``MachineSpec.topology_levels``), the RS/AG pairs below the
  deepest level at fp32 (value-identity on already-reduced grads, like
  the fp32 buckets of comm/bucketed.py) and the cross-level middle
  allreduce at a wire precision composing with the sync-precision map
  (int8 over DCN, fp32 over ICI — PR 1's map gates which groups may
  compress at all);
* ``enumerate_reduction_plans`` lists the candidates for a machine's
  level count (a flat single-level machine has NONE — the flat ring
  stands bit-identically); ``assign_reduction_plans`` prices each
  bucket's candidates in the cost model's bucket currency
  (``CostModel.bucket_sync_cost(plan=...)``) and attaches a staged
  plan only where it beats the flat ring;
* plans persist inside the strategy file's ``__meta__.sync_schedule``
  behind the digest gate, are linted always-on
  (``analysis.lint_reduction_plan``, SHD13x) and stdlib-only
  (``fflint strategy``, STR206), and execute via
  ``comm/hierarchical.py``'s staged shard_map collectives.

Deliberately jax-free (like sync_schedule): the stdlib lint path must
load it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

# stage kinds of the canonical staged shape: RS/AG pairs bracket the
# cross-level allreduce, levels ascending then descending
STAGE_KINDS = ("reduce_scatter", "allreduce", "all_gather")

# wire precisions a stage may carry — mirrors sync_schedule
# BUCKET_PRECISIONS without importing jax
STAGE_PRECISIONS = ("fp32", "bf16", "int8")


@dataclass(frozen=True)
class ReductionStage:
    kind: str  # one of STAGE_KINDS
    level: int  # link level the stage rides (0 = ICI within a slice)
    precision: str = "fp32"


@dataclass(frozen=True)
class ReductionPlan:
    """One staged reduction: stages in issue order.  The canonical
    shape for a plan reaching level L is::

        RS(0) RS(1) ... RS(L-1)  AR(L)  AG(L-1) ... AG(1) AG(0)

    (``canonical_stages``); ``validate_stages`` proves an arbitrary
    stage list has it.  ``level_precisions[i]`` is the wire precision
    of the level-i stage — what the cost model's ``staged_sync_cost``
    and the executor consume."""

    name: str
    stages: Tuple[ReductionStage, ...]

    @property
    def cross_level(self) -> int:
        """The level of the middle allreduce (the plan's reach)."""
        for s in self.stages:
            if s.kind == "allreduce":
                return s.level
        return 0

    @property
    def level_precisions(self) -> Tuple[str, ...]:
        precs: Dict[int, str] = {}
        for s in self.stages:
            precs[s.level] = s.precision
        top = max(precs) if precs else 0
        return tuple(precs.get(i, "fp32") for i in range(top + 1))

    def to_jsonable(self) -> dict:
        return {
            "name": self.name,
            "stages": [
                {"kind": s.kind, "level": s.level, "precision": s.precision}
                for s in self.stages
            ],
        }

    @staticmethod
    def from_jsonable(data) -> "ReductionPlan":
        """Parse a persisted plan (a ``__meta__.sync_schedule`` bucket's
        ``plan`` entry).  Raises ``ValueError`` on structural
        malformation — semantic legality against a (graph, strategy,
        machine) is ``analysis.lint_reduction_plan``'s job."""
        if not isinstance(data, dict):
            raise ValueError("reduction plan is not an object")
        name = data.get("name")
        if not isinstance(name, str) or not name:
            raise ValueError("reduction plan has no name")
        raw = data.get("stages")
        if not isinstance(raw, list) or not raw:
            raise ValueError("reduction plan has no stages")
        stages = []
        for i, s in enumerate(raw):
            if not isinstance(s, dict):
                raise ValueError(f"stages[{i}] is not an object")
            kind = s.get("kind")
            if kind not in STAGE_KINDS:
                raise ValueError(
                    f"stages[{i}] kind {kind!r} not in {STAGE_KINDS}")
            level = s.get("level")
            if not isinstance(level, int) or level < 0:
                raise ValueError(f"stages[{i}] has malformed level {level!r}")
            prec = s.get("precision", "fp32")
            if prec not in STAGE_PRECISIONS:
                raise ValueError(
                    f"stages[{i}] precision {prec!r} not in "
                    f"{STAGE_PRECISIONS}")
            stages.append(ReductionStage(kind, level, prec))
        return ReductionPlan(name, tuple(stages))


def canonical_stages(cross_level: int,
                     cross_precision: str) -> Tuple[ReductionStage, ...]:
    """The staged bracketing reaching ``cross_level``: fp32 RS/AG pairs
    below it (value-identity on already-reduced grads — the executor
    realizes only the compressed wire, comm/hierarchical.py), the
    middle allreduce at ``cross_precision``."""
    rs = [ReductionStage("reduce_scatter", i, "fp32")
          for i in range(cross_level)]
    ag = [ReductionStage("all_gather", i, "fp32")
          for i in reversed(range(cross_level))]
    mid = [ReductionStage("allreduce", cross_level, cross_precision)]
    return tuple(rs + mid + ag)


def validate_stages_split(
    stages, num_levels: int
) -> Tuple[List[str], List[str]]:
    """``(structural, precision)`` errors of a stage list against the
    canonical shape (both [] = well-formed) — split so the lint can map
    them to distinct codes (SHD130 vs SHD133) without string-matching
    the messages."""
    errs: List[str] = []
    if not stages:
        return ["plan has no stages"], []
    for i, s in enumerate(stages):
        if s.kind not in STAGE_KINDS:
            errs.append(f"stages[{i}] kind {s.kind!r} unknown")
        if not isinstance(s.level, int) or not (0 <= s.level < num_levels):
            errs.append(
                f"stages[{i}] level {s.level!r} outside the machine's "
                f"{num_levels} link level(s)")
        if s.precision not in STAGE_PRECISIONS:
            errs.append(f"stages[{i}] precision {s.precision!r} unknown")
    if errs:
        return errs, []
    ars = [s for s in stages if s.kind == "allreduce"]
    if len(ars) != 1:
        return [f"plan must have exactly one cross-level allreduce "
                f"(found {len(ars)})"], []
    want = canonical_stages(ars[0].level, ars[0].precision)
    got = tuple((s.kind, s.level) for s in stages)
    if got != tuple((s.kind, s.level) for s in want):
        return [
            f"stages {[(s.kind, s.level) for s in stages]} do not form "
            f"the canonical RS..AR..AG bracketing for cross level "
            f"{ars[0].level}"], []
    prec_errs = [
        f"{s.kind} at level {s.level} carries {s.precision} — "
        f"only the cross-level allreduce stage may compress "
        f"(the RS/AG pairs are value-identity anchors)"
        for s in stages
        if s.kind != "allreduce" and s.precision != "fp32"]
    return [], prec_errs


def validate_stages(stages, num_levels: int) -> List[str]:
    """Structural + precision errors of a stage list against the
    canonical shape ([] = well-formed).  Shared by the SHD130 lint and
    the builder."""
    structural, prec = validate_stages_split(stages, num_levels)
    return structural + prec


def enumerate_reduction_plans(
    num_levels: int, bucket_precision: str = "fp32"
) -> List[ReductionPlan]:
    """Candidate staged plans for a machine with ``num_levels`` link
    levels and a bucket at ``bucket_precision``.  A flat (single-level)
    machine has none — the flat ring stands and pricing/search stay
    bit-identical.  Cross precision is drawn from {fp32, the bucket's
    precision}: per-level wire precision composes with the
    sync-precision map without contradicting it (SHD123/SHD133)."""
    if num_levels <= 1:
        return []
    precs = ["fp32"]
    if bucket_precision not in (None, "fp32"):
        from flexflow_tpu.search.sync_schedule import wire_base

        # an int8_ef bucket's cross-slice stage runs the plain int8
        # wire: EF compensates the flat ENTRY quantization; the staged
        # exchange carries already-reduced shards the residual never
        # sees (and the raw collective only knows SYNC_PRECISIONS)
        precs.append(wire_base(bucket_precision))
    plans = []
    for cross in range(1, num_levels):
        for pc in precs:
            tag = f"staged_l{cross}" + ("" if pc == "fp32" else f"_{pc}")
            plans.append(ReductionPlan(tag, canonical_stages(cross, pc)))
    return plans


def assign_reduction_plans(schedule, synced, cost_model):
    """Per-bucket plan choice: price every bucket's candidate staged
    plans in the SAME fused-bucket currency the schedule search ranks
    with (``CostModel.bucket_sync_cost``) and attach the cheapest plan
    where it strictly beats the flat ring.  Returns ``(new_schedule,
    info)`` — ``new_schedule`` is None when no bucket improves (the
    flat ring stands; on a single-level machine this is always the
    case, keeping flat-topology searches bit-identical).  ``synced`` is
    the ``synced_weight_groups`` list the schedule was built from."""
    from flexflow_tpu.search.sync_schedule import SyncBucket, SyncSchedule

    num_levels = len(cost_model.levels())
    info: Dict = {"staged_buckets": 0, "flat_sync_s": 0.0,
                  "planned_sync_s": 0.0}
    if num_levels <= 1:
        return None, info
    parts_by_op = {node.op.name: parts for node, _mv, parts in synced}
    new_buckets = []
    changed = False
    for bucket in schedule.buckets:
        parts = [p for op in bucket.ops for p in parts_by_op.get(op, ())]
        flat = cost_model.bucket_sync_cost(parts, bucket.precision)
        # the bucket's candidate plans must reach EXACTLY the deepest
        # link level its replication groups span (the SHD131 rule): a
        # shallower plan leaves the coarse links mispriced, a deeper
        # one prices stages the wire never runs — and pricing ties
        # between them would otherwise let the lint gate reject the
        # search's own choice
        deepest = 0
        for _nbytes, replica, _spans, _n, key in parts:
            if replica <= 1:
                continue
            factors = cost_model.replica_level_split(key, replica)
            if factors is None:
                continue
            deepest = max(deepest, max(
                (i for i, f in enumerate(factors) if f > 1), default=0))
        best_plan, best_cost = None, flat
        for plan in enumerate_reduction_plans(num_levels, bucket.precision):
            if plan.cross_level != deepest:
                continue
            c = cost_model.bucket_sync_cost(parts, bucket.precision,
                                            plan=plan)
            if c < best_cost:
                best_plan, best_cost = plan, c
        info["flat_sync_s"] += flat
        info["planned_sync_s"] += best_cost
        if best_plan is not None:
            info["staged_buckets"] += 1
            changed = True
            new_buckets.append(SyncBucket(
                name=bucket.name, ops=bucket.ops,
                precision=bucket.precision, plan=best_plan))
        else:
            new_buckets.append(bucket)
    if not changed:
        return None, info
    return SyncSchedule(new_buckets, dict(schedule.meta)), info
