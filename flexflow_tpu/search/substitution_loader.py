"""Substitution-rule JSON loader + multi-node pattern engine.

Loads rule collections in the reference's format (reference:
include/flexflow/substitution_loader.h:15-60, substitutions/
graph_subst_3_v2.json: 640 TASO-derived rules, each a source pattern
graph srcOp[], a destination graph dstOp[], and output tensor mappings)
and compiles the expressible subset into rewrites over our PCG.

Pattern ops reference each other by (opId, tsId); opId == -1 denotes an
external input tensor.  Matching is backtracking subgraph isomorphism in
pattern topological order; a match is rejected when an unmapped internal
tensor escapes the pattern (the reference rejects the same way in
GraphXfer::create_new_graph, substitution.cc:576-760).

Supported destination ops: the four parallel ops (constructed from
PM_* parameters) and compute ops that clone a same-typed source op's
attributes (the reference's matchOpX convention, substitution.h:156).
Rules outside this subset are skipped and counted — the loader reports
``skipped`` so callers can see coverage honestly.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.core.graph import Edge, Graph, Node
from flexflow_tpu.core.optype import OperatorType
from flexflow_tpu.parallel.parallel_ops import (
    CombineOp,
    ReductionOp,
    RepartitionOp,
    ReplicateOp,
)

# reference op-type spellings -> our enum (substitution_loader.h
# NLOHMANN_JSON_SERIALIZE_ENUM(OperatorType, ...))
_OP_TYPES: Dict[str, OperatorType] = {
    "OP_NOOP": OperatorType.NOOP,
    "OP_CONV2D": OperatorType.CONV2D,
    "OP_DROPOUT": OperatorType.DROPOUT,
    "OP_LINEAR": OperatorType.LINEAR,
    "OP_BATCHMATMUL": OperatorType.BATCH_MATMUL,
    "OP_POOL2D_MAX": OperatorType.POOL2D,
    "OP_RELU": OperatorType.RELU,
    "OP_IDENTITY": OperatorType.IDENTITY,
    "OP_SIGMOID": OperatorType.SIGMOID,
    "OP_TANH": OperatorType.TANH,
    "OP_ELU": OperatorType.ELU,
    "OP_FLAT": OperatorType.FLAT,
    "OP_SOFTMAX": OperatorType.SOFTMAX,
    "OP_BATCHNORM": OperatorType.BATCHNORM,
    "OP_CONCAT": OperatorType.CONCAT,
    "OP_SPLIT": OperatorType.SPLIT,
    "OP_EMBEDDING": OperatorType.EMBEDDING,
    "OP_CACHE": OperatorType.CACHE,
    "OP_RESHAPE": OperatorType.RESHAPE,
    "OP_REVERSE": OperatorType.REVERSE,
    "OP_TRANSPOSE": OperatorType.TRANSPOSE,
    "OP_EW_ADD": OperatorType.EW_ADD,
    "OP_EW_MUL": OperatorType.EW_MUL,
    "OP_EW_SUB": OperatorType.EW_SUB,
    "OP_EW_DIV": OperatorType.EW_DIV,
    "OP_EW_MAX": OperatorType.EW_MAX,
    "OP_EW_MIN": OperatorType.EW_MIN,
    "OP_MULTIHEAD_ATTENTION": OperatorType.MULTIHEAD_ATTENTION,
    # MoE + scalar subset (reference enum substitution_loader.h:52-71)
    "OP_GROUP_BY": OperatorType.GROUP_BY,
    "OP_AGGREGATE": OperatorType.AGGREGATE,
    "OP_AGG_SPEC": OperatorType.AGGREGATE_SPEC,
    "OP_TOPK": OperatorType.TOPK,
    "OP_SCALAR_MULTIPLY": OperatorType.SCALAR_MUL,
    "OP_SCALAR_ADD": OperatorType.SCALAR_ADD,
    "OP_SCALAR_SUB": OperatorType.SCALAR_SUB,
    "OP_SCALAR_TRUE_DIV": OperatorType.SCALAR_TRUE_DIV,
    "OP_PARTITION": OperatorType.REPARTITION,
    "OP_REPARTITION": OperatorType.REPARTITION,
    "OP_COMBINE": OperatorType.COMBINE,
    "OP_REPLICATE": OperatorType.REPLICATE,
    "OP_REDUCE": OperatorType.REDUCTION,
    "OP_REDUCTION": OperatorType.REDUCTION,
}

_PARALLEL_TYPES = {
    OperatorType.REPARTITION,
    OperatorType.COMBINE,
    OperatorType.REPLICATE,
    OperatorType.REDUCTION,
}

# TASO ActiMode encoding used by the corpus' PM_ACTI values
_ACTI_MAP = {0: None, 1: "sigmoid", 2: "relu", 3: "tanh"}

# dst op types constructible from input shapes + pattern params alone —
# no same-typed source op ("donor") needed (e.g. TASO rules whose dst
# introduces a Concat/activation the source pattern lacks)
_DONORLESS_TYPES = {
    OperatorType.CONCAT,
    OperatorType.SPLIT,
    OperatorType.RELU,
    OperatorType.SIGMOID,
    OperatorType.TANH,
    OperatorType.ELU,
    OperatorType.IDENTITY,
    OperatorType.EW_ADD,
    OperatorType.EW_MUL,
    OperatorType.EW_SUB,
    OperatorType.EW_DIV,
    OperatorType.EW_MAX,
    OperatorType.EW_MIN,
}

_EW_BINARY_TYPES = {
    OperatorType.EW_ADD,
    OperatorType.EW_MUL,
    OperatorType.EW_SUB,
    OperatorType.EW_DIV,
    OperatorType.EW_MAX,
    OperatorType.EW_MIN,
}

_UNARY_TYPES = {
    OperatorType.RELU,
    OperatorType.SIGMOID,
    OperatorType.TANH,
    OperatorType.ELU,
    OperatorType.IDENTITY,
}


@dataclass
class PatternOp:
    """One node of a rule's source or destination pattern."""

    type: OperatorType
    inputs: List[Tuple[int, int]]  # (opId | -1 external, tsId)
    params: Dict[str, int] = field(default_factory=dict)

    def parallel_dim_degree(self) -> Tuple[Optional[int], Optional[int]]:
        p = self.params
        dim = p.get("PM_PARALLEL_DIM",
                    p.get("PM_REPARTITION_DIM",
                          p.get("PM_COMBINE_DIM",
                                p.get("PM_REPLICATE_DIM",
                                      p.get("PM_REDUCTION_DIM")))))
        deg = p.get("PM_PARALLEL_DEGREE",
                    p.get("PM_REPARTITION_DEGREE",
                          p.get("PM_COMBINE_DEGREE",
                                p.get("PM_REPLICATE_DEGREE",
                                      p.get("PM_REDUCTION_DEGREE")))))
        return dim, deg


def _logical_dim(pm_dim: int, ndim: int) -> int:
    """Reference dims are Legion-ordered (innermost first); ours are
    logical (outermost first) — mirror the index."""
    return max(0, min(ndim - 1, ndim - 1 - pm_dim))


@dataclass
class PatternRule:
    """A loaded rule, usable as a GraphXfer (same find_matches/apply
    duck type as search.substitution.GraphXfer).

    ``anchor_types`` follows the GraphXfer contract (ROADMAP PR 8's
    per-op-type seed index): the rule's ROOT pattern op — matched
    first by the backtracking engine — can only bind nodes of its own
    declared type, so ``find_matches`` consults the per-op-type index
    for every pattern position instead of sweeping ``graph.nodes``
    per position.  Identity with the unindexed full scan (as a match
    SET — the index enumerates candidates in topo order, the full
    scan in node-dict order) is asserted under
    ``FLEXFLOW_TPU_DELTA_CHECK``."""

    name: str
    src_ops: List[PatternOp]
    dst_ops: List[PatternOp]
    mapped_outputs: List[Tuple[int, int, int, int]]  # (srcOp, srcTs, dstOp, dstTs)
    anchor_types: Optional[frozenset] = None

    # -- matching ----------------------------------------------------------
    def find_matches(self, graph: Graph) -> List[Dict[int, int]]:
        """All bindings {pattern_op_index: node_guid}."""
        from flexflow_tpu.search.substitution import (
            DELTA_MATCH_CHECK,
            _INDEX_SKIPS,
            _op_type_index,
        )

        matches: List[Dict[int, int]] = []
        if self.anchor_types is None:
            self._extend(graph, {}, {}, 0, matches, limit=16)
            return matches
        idx, pos = _op_type_index(graph)
        root = self.src_ops[0].type
        _INDEX_SKIPS.inc(len(pos) - len(idx.get(root, ())))
        self._extend(graph, {}, {}, 0, matches, limit=16, index=idx)
        if DELTA_MATCH_CHECK:
            full: List[Dict[int, int]] = []
            self._extend(graph, {}, {}, 0, full, limit=16)
            if len(matches) < 16 and len(full) < 16:
                # un-truncated scans must find the same binding SET;
                # at the limit the two enumeration orders may keep
                # different 16, which is not a divergence
                a = sorted(tuple(sorted(m.items())) for m in matches)
                b = sorted(tuple(sorted(m.items())) for m in full)
                assert a == b, (
                    f"indexed find_matches diverged from the full scan "
                    f"for {self.name}: the root pattern type "
                    f"{root.value!r} does not cover the matcher")
        return matches

    def _extend(self, graph, binding, ext_inputs, i, out, limit,
                index=None):
        if len(out) >= limit:
            return
        if i == len(self.src_ops):
            if self._escape_check(graph, binding):
                out.append(dict(binding))
            return
        pat = self.src_ops[i]
        if index is None:
            cands = graph.nodes.items()
        else:
            # per-type topo-ordered candidates: every non-pat.type node
            # fails the type test below anyway — skip the sweep
            cands = ((n.guid, n) for n in index.get(pat.type, ()))
        for guid, node in cands:
            if guid in binding.values():
                continue
            if node.op.op_type is not pat.type:
                continue
            if not self._node_params_ok(node, pat):
                continue
            ok = True
            new_ext = dict(ext_inputs)
            in_edges = graph.in_edges[guid]
            for slot, (src_id, ts_id) in enumerate(pat.inputs):
                e = next((e for e in in_edges if e.dst_idx == slot), None)
                if e is None:
                    # no tensor edge at this slot.  The TASO corpus wires
                    # weights as explicit pattern inputs (linear = (x, w));
                    # our ops OWN their weights, so an external ref with no
                    # edge binds the op's own weight tensor instead.
                    # Externals are identified by their negative opId —
                    # tsId is 0 throughout the corpus: keying by tsId
                    # would conflate distinct externals (-1 vs -2) and
                    # only ever match rules whose externals coincide.
                    if src_id < 0 and node.op._weight_specs:
                        srcref = ("w", guid, slot)
                        if src_id in new_ext and new_ext[src_id] != srcref:
                            ok = False
                            break
                        new_ext[src_id] = srcref
                        continue
                    ok = False
                    break
                if src_id >= 0:
                    # must come from the already-bound pattern op
                    bound = binding.get(src_id)
                    if bound is None or e.src != bound or e.src_idx != ts_id:
                        ok = False
                        break
                else:
                    srcref = (e.src, e.src_idx)
                    if src_id in new_ext and new_ext[src_id] != srcref:
                        ok = False
                        break
                    new_ext[src_id] = srcref
            if not ok:
                continue
            binding[i] = guid
            self._extend(graph, binding, new_ext, i + 1, out, limit,
                         index=index)
            del binding[i]

    def _node_params_ok(self, node: Node, pat: PatternOp) -> bool:
        if pat.type in _PARALLEL_TYPES:
            dim, deg = pat.parallel_dim_degree()
            if deg is not None and node.op.attrs.get("degree") != deg:
                return False
            if (
                dim is not None
                and pat.type in (OperatorType.REPARTITION, OperatorType.COMBINE)
            ):
                ndim = node.op.output_shapes[0].ndim
                if node.op.attrs.get("dim") != _logical_dim(dim, ndim):
                    return False
        if "PM_ACTI" in pat.params and pat.type is OperatorType.LINEAR:
            # TASO rules distinguish fused-activation linears (e.g.
            # taso_rule_257 rewrites a relu twin differently); matching
            # a none-activation node with a relu pattern would rewrite
            # to a semantically different graph
            want = _ACTI_MAP.get(pat.params["PM_ACTI"], "?")
            if node.op.attrs.get("activation") != want:
                return False
        return True

    def _escape_check(self, graph, binding) -> bool:
        """Every tensor produced inside the pattern and consumed outside
        must be a mapped output."""
        mapped = {(s_op, s_ts) for s_op, s_ts, _, _ in self.mapped_outputs}
        bound_guids = set(binding.values())
        for p_idx, guid in binding.items():
            for e in graph.out_edges[guid]:
                if e.dst in bound_guids:
                    continue
                if (p_idx, e.src_idx) not in mapped:
                    return False
        return True

    # -- application -------------------------------------------------------
    def apply(self, graph: Graph, match: Dict[int, int]) -> Optional[Graph]:
        g = graph.copy()
        # resolve external inputs from the matched source ops; externals
        # with no tensor edge are the matched op's OWN weights (see
        # _extend) and resolve to their owner for donor lookup
        ext: Dict[int, Tuple[int, int]] = {}  # external opId -> tensor ref
        w_ext: Dict[int, int] = {}  # external opId -> owning node guid
        for p_idx, guid in match.items():
            pat = self.src_ops[p_idx]
            for slot, (src_id, ts_id) in enumerate(pat.inputs):
                if src_id < 0:
                    e = next(
                        (e for e in g.in_edges[guid] if e.dst_idx == slot), None
                    )
                    if e is None:
                        if graph.nodes[guid].op._weight_specs:
                            w_ext[src_id] = guid
                            continue
                        return None
                    ext[src_id] = (e.src, e.src_idx)

        # collect external consumers of mapped outputs before deletion,
        # remembering the shape each consumer expects
        rewires: List[Tuple[Edge, int, int, Tuple[int, ...]]] = []
        bound = set(match.values())
        for s_op, s_ts, d_op, d_ts in self.mapped_outputs:
            guid = match.get(s_op)
            if guid is None:
                return None
            old_shape = tuple(g.nodes[guid].op.output_shapes[s_ts].sizes)
            for e in list(g.out_edges[guid]):
                if e.dst not in bound and e.src_idx == s_ts:
                    rewires.append((e, d_op, d_ts, old_shape))

        # instantiate destination ops in index order (inputs may only
        # reference lower indices or externals, which holds for the
        # reference corpus)
        new_nodes: Dict[int, Node] = {}
        for d_idx, dpat in enumerate(self.dst_ops):
            in_refs = []
            donor_hint: Optional[int] = None
            for (src_id, ts_id) in dpat.inputs:
                if src_id < 0:
                    if src_id in ext:
                        in_refs.append(ext[src_id])
                    elif src_id in w_ext:
                        # weight slot: our dst op owns its weight — no
                        # edge; the weight's owner is the attr donor
                        donor_hint = w_ext[src_id]
                    else:
                        return None
                else:
                    dn = new_nodes.get(src_id)
                    if dn is None:
                        return None
                    in_refs.append((dn.guid, ts_id))
            in_shapes = []
            for (src_guid, src_idx) in in_refs:
                src_node = g.nodes.get(src_guid)  # includes new nodes
                if src_node is None or src_idx >= len(src_node.op.output_shapes):
                    return None
                in_shapes.append(src_node.op.output_shapes[src_idx])
            op = self._make_dst_op(dpat, in_shapes, match, graph, donor_hint,
                                   work_graph=g, in_refs=in_refs)
            if op is None:
                return None
            node = Node(g._next_guid, op)
            g._next_guid += 1
            g.add_node(node)
            for slot, (src_guid, src_idx) in enumerate(in_refs):
                e = Edge(src_guid, node.guid, src_idx, slot)
                g.out_edges[src_guid].append(e)
                g.in_edges[node.guid].append(e)
            new_nodes[d_idx] = node

        # delete matched source ops, then rewire external consumers
        for guid in match.values():
            g.remove_node(guid)
        for old_e, d_op, d_ts, old_shape in rewires:
            dn = new_nodes.get(d_op)
            if dn is None:
                return None
            if (d_ts >= len(dn.op.output_shapes)
                    or tuple(dn.op.output_shapes[d_ts].sizes) != old_shape):
                # the instantiated dst graph does not reproduce the
                # tensor this consumer was reading — reject instead of
                # silently corrupting downstream shapes
                return None
            ne = Edge(dn.guid, old_e.dst, d_ts, old_e.dst_idx)
            g.out_edges[dn.guid].append(ne)
            g.in_edges[old_e.dst].append(ne)
        g._invalidate()
        try:
            g.topo_order()
        except ValueError:
            return None
        return g

    def _donor_pattern_idx(self, dpat: PatternOp) -> Optional[int]:
        """Which source-pattern op donates attrs to ``dpat``: the unique
        same-typed param-consistent src op, or — with several
        candidates — the one sharing an external input id (the corpus
        wires each op's weight as a distinct external tensor ``-k``, so
        sharing the id identifies the pre-rewrite twin, the reference's
        matchOpX convention)."""

        # PM_ACTI is overridden from dpat at instantiation (see
        # _make_dst_op), so donors may legitimately differ on it (the
        # relu-fusion family, e.g. taso_rule_257's dst relu-linear
        # donates from the plain src linear)
        overridable = (
            {"PM_ACTI"} if dpat.type is OperatorType.LINEAR else set()
        )

        def params_consistent(s: PatternOp) -> bool:
            shared = (set(s.params) & set(dpat.params)) - overridable
            return all(s.params[k] == dpat.params[k] for k in shared)

        cands = [
            i for i, s in enumerate(self.src_ops)
            if s.type is dpat.type and params_consistent(s)
        ]
        if len(cands) == 1:
            return cands[0]
        # several candidates: the pre-rewrite twin is the one sharing an
        # external tensor id — externals are identified by their
        # (negative) opId; tsId is 0 throughout the corpus and
        # identifies nothing
        d_ext = {sid for (sid, ts) in dpat.inputs if sid < 0}
        ext_matches = [
            i for i in cands
            if d_ext & {sid for (sid, ts) in self.src_ops[i].inputs
                        if sid < 0}
        ]
        if len(ext_matches) == 1:
            return ext_matches[0]
        pool = ext_matches or cands
        if not pool:
            return None
        # still ambiguous: prefer an exact-param twin (e.g. the same
        # PM_ACTI); otherwise any candidate works IF the pool is
        # mutually param-identical modulo overridable keys (rule 257:
        # two linears sharing weight -4, differing only in fused acti) —
        # apply-time shape re-propagation rejects bad instantiations
        exact = [
            i for i in pool
            if self.src_ops[i].params == dpat.params
        ]
        if len(exact) == 1:
            return exact[0]
        first = self.src_ops[pool[0]]
        if all(
            {k: v for k, v in self.src_ops[i].params.items()
             if k not in overridable}
            == {k: v for k, v in first.params.items() if k not in overridable}
            for i in pool[1:]
        ):
            return pool[0]
        return None

    def _make_dst_op(self, dpat: PatternOp, in_shapes, match, src_graph,
                     donor_hint: Optional[int] = None,
                     work_graph=None, in_refs=None):
        if dpat.type in _PARALLEL_TYPES:
            dim, deg = dpat.parallel_dim_degree()
            if deg is None:
                return None
            shape = in_shapes[0]
            if dpat.type is OperatorType.REPARTITION:
                ld = _logical_dim(dim or 0, shape.ndim)
                if shape.sizes[ld] % deg != 0:
                    return None
                return RepartitionOp(_un("repartition"), [shape], dim=ld, degree=deg)
            if dpat.type is OperatorType.COMBINE:
                ld = _logical_dim(dim or 0, shape.ndim)
                return CombineOp(_un("combine"), [shape], dim=ld, degree=1)
            if dpat.type is OperatorType.REPLICATE:
                return ReplicateOp(_un("replicate"), [shape], degree=deg)
            return ReductionOp(_un("reduction"), [shape], degree=deg)
        # compute op: clone a source op's attributes.  Donor priority:
        # the weight owner bound to this dst op's weight slot, then the
        # external-id-matched pattern twin, then the unique same-typed
        # source; some types need no donor at all (shapes + params
        # suffice).
        donor = None
        if donor_hint is not None and (
            src_graph.nodes[donor_hint].op.op_type is dpat.type
        ):
            donor = src_graph.nodes[donor_hint].op
        if donor is None:
            di = self._donor_pattern_idx(dpat)
            if di is not None and di in match:
                donor = src_graph.nodes[match[di]].op
        if donor is not None:
            try:
                attrs = dict(donor.attrs)
                if "PM_ACTI" in dpat.params and dpat.type is OperatorType.LINEAR:
                    # the dst op's own declared activation wins over the
                    # donor's (e.g. taso_rule_257 fuses the src relu
                    # INTO the rewritten linear)
                    attrs["activation"] = _ACTI_MAP.get(
                        dpat.params["PM_ACTI"])
                return type(donor)(
                    _un(donor.name), list(in_shapes), **attrs
                )
            except Exception:
                return None
        if dpat.type not in _DONORLESS_TYPES or not in_shapes:
            return None
        try:
            if dpat.type is OperatorType.CONCAT:
                nd = dpat.params.get("PM_NUMDIM", in_shapes[0].ndim)
                ax = _logical_dim(dpat.params.get("PM_AXIS", 0), nd)
                from flexflow_tpu.ops.shape_ops import ConcatOp

                return ConcatOp(_un("concat"), list(in_shapes), axis=ax)
            if dpat.type is OperatorType.SPLIT:
                # batched-communication rules (taso_rule_419 family):
                # split sizes come from the upstream dst Concat this
                # Split undoes — trace through intervening parallel ops
                n_out = dpat.params.get("PM_NUM_OUTPUTS")
                if not n_out:
                    return None
                ax = _logical_dim(dpat.params.get("PM_AXIS", 0),
                                  in_shapes[0].ndim)
                from flexflow_tpu.ops.shape_ops import ConcatOp, SplitOp

                sizes = None
                if work_graph is not None and in_refs:
                    node = work_graph.nodes.get(in_refs[0][0])
                    for _ in range(8):
                        if node is None:
                            break
                        if isinstance(node.op, ConcatOp):
                            if node.op.attrs.get("axis") == ax and len(
                                    node.op.input_shapes) == n_out:
                                sizes = [s.sizes[ax]
                                         for s in node.op.input_shapes]
                            break
                        if node.op.op_type not in _PARALLEL_TYPES:
                            break
                        e = next((e for e in work_graph.in_edges[node.guid]
                                  if e.dst_idx == 0), None)
                        node = work_graph.nodes.get(e.src) if e else None
                if sizes is None:
                    if in_shapes[0].sizes[ax] % n_out != 0:
                        return None
                    sizes = [in_shapes[0].sizes[ax] // n_out] * n_out
                if sum(sizes) != in_shapes[0].sizes[ax]:
                    return None
                return SplitOp(_un("split"), [in_shapes[0]],
                               sizes=tuple(sizes), axis=ax)
            from flexflow_tpu.ops.elementwise import (
                ElementBinaryOp,
                ElementUnaryOp,
            )

            if dpat.type in _EW_BINARY_TYPES:
                if len(in_shapes) != 2:
                    return None
                return ElementBinaryOp(
                    _un(dpat.type.value), list(in_shapes),
                    binary_type=dpat.type,
                )
            if dpat.type in _UNARY_TYPES:
                return ElementUnaryOp(
                    _un(dpat.type.value), [in_shapes[0]],
                    unary_type=dpat.type,
                )
        except Exception:
            return None
        return None


def _un(base: str) -> str:
    from flexflow_tpu.search.substitution import _uname

    return _uname(base)


# ---------------------------------------------------------------------------
def load_rule_collection(path: str) -> Tuple[List[PatternRule], int]:
    """Parse a reference-format rule JSON.  Returns (usable rules,
    skipped count)."""
    with open(path) as f:
        data = json.load(f)
    raw_rules = data["rule"] if isinstance(data, dict) else data
    rules: List[PatternRule] = []
    skipped = 0
    for r in raw_rules:
        rule = _parse_rule(r)
        if rule is None:
            skipped += 1
        else:
            rules.append(rule)
    return rules, skipped


def _parse_rule(r: dict) -> Optional[PatternRule]:
    def parse_ops(lst) -> Optional[List[PatternOp]]:
        out = []
        for o in lst:
            t = _OP_TYPES.get(o.get("type"))
            if t is None:
                return None
            inputs = [(i["opId"], i["tsId"]) for i in o.get("input", [])]
            params = {p["key"]: p["value"] for p in o.get("para", [])}
            out.append(PatternOp(type=t, inputs=inputs, params=params))
        return out

    src = parse_ops(r.get("srcOp", []))
    dst = parse_ops(r.get("dstOp", []))
    if not src or dst is None:
        return None
    # dst wiring must be forward-referencing for one-pass instantiation
    for i, d in enumerate(dst):
        for (src_id, _) in d.inputs:
            if src_id >= i:
                return None
    # dst compute ops need an attr donor (unique same-type src op, or
    # an external-id-matched twin) unless the type is constructible
    # from shapes + params alone
    rule_probe = PatternRule(name="", src_ops=src, dst_ops=dst,
                             mapped_outputs=[])
    for d in dst:
        if d.type in _PARALLEL_TYPES or d.type in _DONORLESS_TYPES:
            continue
        if rule_probe._donor_pattern_idx(d) is None:
            return None
    mapped = [
        (m["srcOpId"], m["srcTsId"], m["dstOpId"], m["dstTsId"])
        for m in r.get("mappedOutput", [])
    ]
    if not mapped:
        return None
    return PatternRule(
        name=r.get("name", "json_rule"),
        src_ops=src,
        dst_ops=dst,
        mapped_outputs=mapped,
        # the root pattern op is matched FIRST by the backtracking
        # engine, so its type is a sound anchor: no match can exist in
        # a graph with no node of this type (per-op-type seed index;
        # identity asserted under FLEXFLOW_TPU_DELTA_CHECK)
        anchor_types=frozenset({src[0].type}),
    )


def load_substitution_json(path: str, max_rules: int = 0) -> List[PatternRule]:
    """Public entry: rules usable as GraphXfers (find_matches/apply).
    ``max_rules`` > 0 truncates (search-time control)."""
    rules, skipped = load_rule_collection(path)
    from flexflow_tpu.utils.logging import SEARCH_LOG as log

    log.log(
        f"substitution json {path}: loaded {len(rules)} rules, "
        f"skipped {skipped} outside the supported subset"
    )
    if max_rules > 0:
        rules = rules[:max_rules]
    return rules
