"""Search driver — Unity's outer loop, plus the legacy MCMC search.

Re-implements GraphSearchHelper (reference:
src/runtime/substitution.cc:1779-2470):

* ``optimize_strategy(return_graph=True)`` — the full Unity algorithm:
  recursively split large graphs at low-rewrite-traffic bottlenecks
  (find_split_node, :1879-2004), enumerate boundary shardings at each
  split (possible_split_output_tensor_shapes, :2372 — here: the
  bottleneck op's candidate MachineViews), and run a best-first
  substitution search over each small-enough segment (base_optimize,
  :2007-2089) with ``cost > alpha * best`` pruning and a pop budget,
  every candidate costed by the DP inner loop (SearchHelper).
* ``mcmc_optimize`` — FFModel::mcmc_optimize (reference:
  src/runtime/model.cc:3033-3122), simulated annealing over per-op views.
"""

from __future__ import annotations

import heapq
import math
import random
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.graph import Graph, Node
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.search.dp import SearchHelper, Strategy
from flexflow_tpu.search.simulator import Simulator
from flexflow_tpu.search.substitution import generate_all_pcg_xfers
from flexflow_tpu.search.views import candidate_views

MAX_BOUNDARY_VIEWS = 8


def _load_xfers(config: FFConfig, num_devices: int) -> list:
    xfers = list(generate_all_pcg_xfers(num_devices))
    if config.substitution_json:
        from flexflow_tpu.search.substitution_loader import load_substitution_json

        xfers += load_substitution_json(config.substitution_json)
    return xfers


class _UnityOptimizer:
    """One graph_optimize run: shared memo/caches (reference:
    cached_optimized_graphs, substitution.cc:2091-2188)."""

    def __init__(self, helper: SearchHelper, config: FFConfig, xfers: list):
        self.helper = helper
        self.config = config
        self.xfers = xfers
        self.cache: Dict[Tuple, Tuple[Graph, float, Strategy]] = {}

    # -- split-node choice (reference: find_split_node :1879-2004) ---------
    def find_split_node(self, graph: Graph) -> Optional[Node]:
        if graph.num_nodes <= self.config.base_optimize_threshold:
            return None
        bottlenecks = graph.bottlenecks()
        if not bottlenecks:
            return None
        # score edges by how many rewrite matches touch them — splitting
        # where no rewrite straddles keeps the segments' search spaces
        # independent
        edge_scores: Dict[Tuple[int, int], int] = {}
        for xf in self.xfers:
            for m in xf.find_matches(graph):
                guids = (
                    set(m.values()) if isinstance(m, dict) else {m.guid}
                )
                for g in guids:
                    for e in graph.in_edges[g]:
                        edge_scores[(e.src, e.dst)] = (
                            edge_scores.get((e.src, e.dst), 0) + 1
                        )
                    for e in graph.out_edges[g]:
                        edge_scores[(e.src, e.dst)] = (
                            edge_scores.get((e.src, e.dst), 0) + 1
                        )
        threshold = self.config.base_optimize_threshold
        best, best_key = None, None
        for bn in bottlenecks:
            weight = sum(
                edge_scores.get((e.src, e.dst), 0)
                for e in graph.out_edges[bn.guid]
            )
            try:
                pre, _post = graph.split_at_node(bn)
            except ValueError:
                continue
            size = pre.num_nodes
            # prefer low rewrite traffic, then pre-size closest to (but
            # under) the threshold (reference tie-break :1980-1999)
            under = size <= threshold
            key = (weight, 0 if under else 1, -size if under else size)
            if best_key is None or key < best_key:
                best, best_key = bn, key
        return best

    # -- boundary view enumeration (reference: :2372) ----------------------
    def _boundary_views(self, node: Node) -> List[MachineView]:
        views = candidate_views(
            node.op, self.helper.num_devices, max_views=MAX_BOUNDARY_VIEWS
        )
        return views[:MAX_BOUNDARY_VIEWS]

    # -- recursive sequence optimization (reference: :2190-2370) -----------
    def sequence_optimize(
        self, graph: Graph, fixed: Strategy
    ) -> Tuple[Graph, float, Strategy]:
        # node-id set included: isomorphic segments with different guids
        # must not share cached strategies/graphs (see dp.py memo note)
        key = (
            graph.hash(),
            frozenset(graph.nodes),
            tuple(sorted((g, v) for g, v in fixed.items() if g in graph.nodes)),
        )
        hit = self.cache.get(key)
        if hit is not None:
            return hit
        bn = self.find_split_node(graph)
        if bn is None or bn.guid in fixed:
            result = self.base_optimize(graph, fixed)
        else:
            try:
                pre, post = graph.split_at_node(bn)
            except ValueError:
                result = self.base_optimize(graph, fixed)
                self.cache[key] = result
                return result
            best: Tuple[Optional[Graph], float, Strategy] = (None, math.inf, {})
            best_bound = math.inf
            for v in self._boundary_views(bn):
                f2 = dict(fixed)
                f2[bn.guid] = v
                g_pre, c_pre, s_pre = self.sequence_optimize(pre, f2)
                if c_pre >= best_bound:
                    continue
                g_post, c_post, s_post = self.sequence_optimize(post, f2)
                # c_pre + c_post double-counts the pinned bottleneck and
                # ignores cross-segment overlap — it is only a pruning
                # bound; the merged graph's own simulation decides
                # (dp.graph_cost re-validates the same way)
                total = c_pre + c_post
                if total >= best_bound * 1.5:
                    continue
                best_bound = min(best_bound, total)
                merged_g, merged_s = _merge_split(
                    g_pre, s_pre, g_post, s_post, bn.guid
                )
                merged_s[bn.guid] = v
                c_true = self.helper.sim.simulate(merged_g, merged_s)
                if c_true < best[1]:
                    best = (merged_g, c_true, merged_s)
            if best[0] is None:
                result = self.base_optimize(graph, fixed)
            else:
                result = best  # type: ignore[assignment]
        self.cache[key] = result
        return result

    # -- best-first over substitutions (reference: :2007-2089) -------------
    def base_optimize(
        self, graph: Graph, fixed: Strategy
    ) -> Tuple[Graph, float, Strategy]:
        helper, config = self.helper, self.config
        best_cost, best_strategy = helper.graph_cost(graph, fixed)
        best_graph = graph
        counter = 0
        heap: list = [(best_cost, counter, graph)]
        seen = {graph.hash()}
        budget = config.search_budget
        pinned = set(fixed)
        while heap and budget > 0:
            cost, _, g = heapq.heappop(heap)
            if cost > config.search_alpha * best_cost:
                break
            budget -= 1
            for xf in self.xfers:
                for m in xf.find_matches(g):
                    g2 = xf.apply(g, m)
                    if g2 is None:
                        continue
                    # a rewrite must not consume a pinned boundary node
                    if any(p not in g2.nodes for p in pinned if p in g.nodes):
                        continue
                    h = g2.hash()
                    if h in seen:
                        continue
                    seen.add(h)
                    c2, s2 = helper.graph_cost(g2, fixed)
                    if c2 < best_cost:
                        best_cost, best_strategy, best_graph = c2, s2, g2
                    if c2 < config.search_alpha * best_cost:
                        counter += 1
                        heapq.heappush(heap, (c2, counter, g2))
        return best_graph, best_cost, best_strategy


def _merge_split(
    pre_g: Graph,
    pre_s: Strategy,
    post_g: Graph,
    post_s: Strategy,
    bn_guid: int,
) -> Tuple[Graph, Strategy]:
    """Union of the two optimized segments.  Original nodes are disjoint
    apart from the shared bottleneck; nodes INSERTED by rewrites may
    collide between segments (both sides allocate from the same starting
    guid) and are renumbered on the post side."""
    g = Graph()
    g._next_guid = max(pre_g._next_guid, post_g._next_guid)
    for guid, n in pre_g.nodes.items():
        g.nodes[guid] = n
        g.in_edges[guid] = list(pre_g.in_edges[guid])
        g.out_edges[guid] = list(pre_g.out_edges[guid])
    remap: Dict[int, int] = {}
    for guid in post_g.nodes:
        if guid in pre_g.nodes and guid != bn_guid:
            remap[guid] = g._next_guid
            g._next_guid += 1
    from flexflow_tpu.core.graph import Edge

    for guid, n in post_g.nodes.items():
        ng = remap.get(guid, guid)
        if ng not in g.nodes:
            g.nodes[ng] = n if ng == guid else Node(ng, n.op)
            g.in_edges.setdefault(ng, [])
            g.out_edges.setdefault(ng, [])
    for guid in post_g.nodes:
        for e in post_g.out_edges[guid]:
            ne = Edge(
                remap.get(e.src, e.src),
                remap.get(e.dst, e.dst),
                e.src_idx,
                e.dst_idx,
            )
            g.out_edges[ne.src].append(ne)
            g.in_edges[ne.dst].append(ne)
    strategy = dict(pre_s)
    for guid, v in post_s.items():
        strategy[remap.get(guid, guid)] = v
    g._invalidate()
    return g, strategy


def optimize_strategy(
    graph: Graph, config: FFConfig, return_graph: bool = False
) -> "Strategy | Tuple[Graph, Strategy]":
    """Find a good (graph, strategy).  With ``return_graph=True`` — the
    default compile path — the joint Unity search runs: graph rewrites
    compete with view assignment and the best REWRITTEN graph is
    returned for lowering.  With False only strategies on the original
    graph are explored (strategy-only mode, e.g. for export)."""
    from flexflow_tpu.utils.logging import SEARCH_LOG as log

    n = config.search_devices
    sim = Simulator(config.machine_spec, num_devices=n)
    helper = SearchHelper(sim, n)

    with log.enter(f"optimize_strategy: {graph.num_nodes} nodes, {n} devices"):
        best_cost, best_strategy = helper.graph_cost(graph)
        log.log(f"baseline DP-search cost: {best_cost * 1e3:.4f} ms/iter")
    best_graph = graph

    if return_graph and config.search_budget > 0:
        xfers = _load_xfers(config, n)
        opt = _UnityOptimizer(helper, config, xfers)
        with log.enter(f"unity outer loop: {len(xfers)} xfers"):
            g2, c2, s2 = opt.sequence_optimize(graph, {})
            if c2 < best_cost and s2:
                log.log(
                    f"substitution improved: {best_cost * 1e3:.4f}"
                    f" -> {c2 * 1e3:.4f} ms/iter"
                )
                best_cost, best_strategy, best_graph = c2, s2, g2

    if return_graph:
        return best_graph, best_strategy
    return best_strategy


def mcmc_optimize(
    graph: Graph,
    config: FFConfig,
    iterations: int = 500,
    temperature: float = 0.05,
    seed: int = 0,
) -> Strategy:
    """Legacy MLSys'19 search: random single-op view rewrites, accepted
    if better or with prob exp(-alpha*delta)
    (reference: model.cc:3033-3122 rewrite/mcmc_optimize)."""
    n = config.search_devices
    sim = Simulator(config.machine_spec, num_devices=n)
    rng = random.Random(seed)
    nodes = graph.topo_order()

    from flexflow_tpu.compiler.lowering import data_parallel_strategy

    current = dict(data_parallel_strategy(graph, n))
    cur_cost = sim.simulate(graph, current)
    best, best_cost = dict(current), cur_cost
    for _ in range(iterations):
        node = rng.choice(nodes)
        if node.op.fixed_machine_view() is not None:
            continue
        views = candidate_views(node.op, n)
        v = rng.choice(views)
        old = current.get(node.guid)
        current[node.guid] = v
        c = sim.simulate(graph, current)
        delta = c - cur_cost
        if delta <= 0 or rng.random() < math.exp(-delta / max(temperature * cur_cost, 1e-12)):
            cur_cost = c
            if c < best_cost:
                best, best_cost = dict(current), c
        else:
            if old is None:
                current.pop(node.guid, None)
            else:
                current[node.guid] = old
    return best
