"""Search driver — Unity's outer loop, plus the legacy MCMC search.

Re-implements GraphSearchHelper::graph_optimize / base_optimize
(reference: src/runtime/substitution.cc:1779-2089): best-first search
over the substitution space, each candidate graph costed by the DP
(SearchHelper), pruned by ``cost > alpha * best`` and a pop budget —
and FFModel::mcmc_optimize (reference: src/runtime/model.cc:3033-3122),
simulated annealing over per-op views.
"""

from __future__ import annotations

import heapq
import math
import random
from typing import Dict, Optional, Tuple

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.graph import Graph
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.search.dp import SearchHelper, Strategy
from flexflow_tpu.search.simulator import Simulator
from flexflow_tpu.search.substitution import generate_all_pcg_xfers
from flexflow_tpu.search.views import candidate_views


def optimize_strategy(
    graph: Graph, config: FFConfig, return_graph: bool = False
) -> "Strategy | Tuple[Graph, Strategy]":
    """Find a good (graph, strategy). With ``return_graph=False`` only
    strategies on the ORIGINAL graph are explored (no rewrites) — the
    common path, since degree-views already express DP/TP/row/head
    splits; with True, substitution variants compete too."""
    from flexflow_tpu.utils.logging import SEARCH_LOG as log

    n = config.search_devices
    sim = Simulator(config.machine_spec, num_devices=n)
    helper = SearchHelper(sim, n)

    with log.enter(f"optimize_strategy: {graph.num_nodes} nodes, {n} devices"):
        best_cost, best_strategy = helper.graph_cost(graph)
        log.log(f"baseline DP-search cost: {best_cost * 1e3:.4f} ms/iter")
    best_graph = graph

    if return_graph and config.search_budget > 0:
        xfers = generate_all_pcg_xfers(n)
        # best-first queue over rewritten graphs (substitution.cc:2007-2089)
        counter = 0
        heap: list = [(best_cost, counter, graph)]
        seen = {graph.hash()}
        budget = config.search_budget
        while heap and budget > 0:
            cost, _, g = heapq.heappop(heap)
            if cost > config.search_alpha * best_cost:
                break
            budget -= 1
            for xf in xfers:
                for m in xf.find_matches(g):
                    g2 = xf.apply(g, m)
                    if g2 is None:
                        continue
                    h = g2.hash()
                    if h in seen:
                        continue
                    seen.add(h)
                    c2, s2 = helper.graph_cost(g2)
                    if c2 < best_cost:
                        log.log(f"substitution improved: {best_cost * 1e3:.4f}"
                                f" -> {c2 * 1e3:.4f} ms/iter")
                        best_cost, best_strategy, best_graph = c2, s2, g2
                    if c2 < config.search_alpha * best_cost:
                        counter += 1
                        heapq.heappush(heap, (c2, counter, g2))

    if return_graph:
        return best_graph, best_strategy
    return best_strategy


def mcmc_optimize(
    graph: Graph,
    config: FFConfig,
    iterations: int = 500,
    temperature: float = 0.05,
    seed: int = 0,
) -> Strategy:
    """Legacy MLSys'19 search: random single-op view rewrites, accepted
    if better or with prob exp(-alpha*delta)
    (reference: model.cc:3033-3122 rewrite/mcmc_optimize)."""
    n = config.search_devices
    sim = Simulator(config.machine_spec, num_devices=n)
    rng = random.Random(seed)
    nodes = graph.topo_order()

    from flexflow_tpu.compiler.lowering import data_parallel_strategy

    current = dict(data_parallel_strategy(graph, n))
    cur_cost = sim.simulate(graph, current)
    best, best_cost = dict(current), cur_cost
    for _ in range(iterations):
        node = rng.choice(nodes)
        if node.op.fixed_machine_view() is not None:
            continue
        views = candidate_views(node.op, n)
        v = rng.choice(views)
        old = current.get(node.guid)
        current[node.guid] = v
        c = sim.simulate(graph, current)
        delta = c - cur_cost
        if delta <= 0 or rng.random() < math.exp(-delta / max(temperature * cur_cost, 1e-12)):
            cur_cost = c
            if c < best_cost:
                best, best_cost = dict(current), c
        else:
            if old is None:
                current.pop(node.guid, None)
            else:
                current[node.guid] = old
    return best
