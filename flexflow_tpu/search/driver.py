"""Search driver — Unity's outer loop, plus the legacy MCMC search.

Re-implements GraphSearchHelper (reference:
src/runtime/substitution.cc:1779-2470):

* ``optimize_strategy(return_graph=True)`` — the full Unity algorithm:
  recursively split large graphs at low-rewrite-traffic bottlenecks
  (find_split_node, :1879-2004), enumerate boundary shardings at each
  split (possible_split_output_tensor_shapes, :2372 — here: the
  bottleneck op's compact boundary views), and run a best-first
  substitution search over each small-enough segment (base_optimize,
  :2007-2089) with ``cost > alpha * best`` pruning and a pop budget,
  candidates ranked by a cheap strategy-extension estimate and only
  popped candidates paying for the full DP (a wall-clock-bounded
  variant of the reference's budget discipline).
* ``mcmc_optimize`` — FFModel::mcmc_optimize (reference:
  src/runtime/model.cc:3033-3122), simulated annealing over per-op views.

Scaling disciplines (round-3; the reference's equivalents cited inline):

- **Structural segment cache**: optimized segments are cached by
  guid-free structural key and *remapped* onto isomorphic segments
  (repeated transformer layers cost one optimization, not twelve) —
  the role of the reference's cached_optimized_graphs (:2091-2188),
  which can key purely by hash because its machine views don't carry
  node identity.
- **Split scores precomputed once**: find_split_node scores rewrite
  traffic from a single find_matches sweep over the original graph
  instead of re-matching every xfer at every recursion level.
- **Wall-clock deadline**: ``config.search_timeout_s`` bounds the
  whole joint search; on expiry every loop returns its best-so-far
  (the reference bounds work with the pop budget alone; a Python
  implementation needs the harder guarantee).
"""

from __future__ import annotations

import contextlib
import gc
import heapq
import math
import random
import time
from typing import Dict, List, Optional, Tuple

from flexflow_tpu.config import FFConfig
from flexflow_tpu.core.graph import Graph, Node
from flexflow_tpu.core.machine import MachineView
from flexflow_tpu.obs.events import BUS
from flexflow_tpu.obs.metrics import METRICS
from flexflow_tpu.search import decompose as _decompose
from flexflow_tpu.search.dp import (
    DP_PERSIST_MIN_NODES,
    SearchHelper,
    Strategy,
    _pair_views,
    canon_fixed_views,
    canonicalize_strategy,
    decode_strategy_rows,
    encode_strategy_rows,
)
from flexflow_tpu.search.simulator import Simulator
from flexflow_tpu.search.substitution import generate_all_pcg_xfers
from flexflow_tpu.search.views import boundary_views

_SEG_STAMPS = METRICS.counter("search.segments_stamped")
_SP_ROWS_SERVED = METRICS.counter("search.sp_rows_served")

# decomposition provenance of the LAST optimize_strategy call in this
# process (reset per run, cumulative over recursion levels): which
# decomposition each oversized (sub)graph took, how many bounded-width
# cuts/segments it produced, and how the segment solves were answered —
# merged into LAST_SEARCH_STATS / the search.perf event
LAST_DECOMPOSE: Dict[str, object] = {}

# production-scale threshold: above this node count the binary
# sequence_optimize recursion is replaced by the K-WAY chain
# decomposition (chain_optimize) — one bottleneck sweep, one segment
# solve per isomorphism class x boundary-view pair, a chain DP over
# boundary views, one final merge+simulate.  The binary recursion's
# per-level merge simulations and find_split_node sweeps are O(n^2)-ish
# at thousand-node scale; every zoo graph sits below this threshold
# (the native DP engine's own ceiling), so the bit-identical regression
# gate on the zoo holds trivially.
CHAIN_MIN_NODES = 256


@contextlib.contextmanager
def _relaxed_gc():
    """Raise the generational-GC thresholds for the duration of the
    substitution loop: candidate generation churns through thousands of
    acyclic container objects per second (graphs, snapshots, edge
    lists) that refcounting frees promptly, and the default gen-0
    cadence was a measured slice of search wall time.  Thresholds are
    restored on exit; nothing is disabled, so genuine cycles still
    collect."""
    prev = gc.get_threshold()
    gc.set_threshold(max(prev[0], 100_000), 1_000, 1_000)
    try:
        yield
    finally:
        gc.set_threshold(*prev)


def _worker_batches() -> int:
    """Process-lifetime count of match batches dispatched to the
    opt-in match-worker pool (search/match_workers.py) — 0 when the
    pool was never armed."""
    from flexflow_tpu.search import match_workers

    return match_workers.BATCHES.value


def _load_xfers(config: FFConfig, num_devices: int) -> list:
    xfers = list(generate_all_pcg_xfers(num_devices))
    if config.substitution_json:
        from flexflow_tpu.search.substitution_loader import load_substitution_json

        xfers += load_substitution_json(config.substitution_json)
    return xfers


class _UnityOptimizer:
    """One graph_optimize run: shared memo/caches (reference:
    cached_optimized_graphs, substitution.cc:2091-2188)."""

    def __init__(
        self,
        helper: SearchHelper,
        config: FFConfig,
        xfers: list,
        deadline: Optional[float] = None,
    ):
        self.helper = helper
        self.config = config
        self.xfers = xfers
        self.deadline = deadline
        # structural key -> (orig segment nodes/groups, optimized graph,
        # cost, strategy, fixed guid->view at store time)
        self.cache: Dict[Tuple, Tuple] = {}
        # sp-row serve memos: (row key, canonical served strategy) ->
        # lint verdict / ambiguous re-price (the SHD1xx lint and the
        # simulated cost are guid-renaming-invariant, so serves whose
        # remap lands on the same canonical form share them — same
        # discipline as the segment-cache stamp-lint memo)
        self._sp_lint_ok: Dict[Tuple, bool] = {}
        self._sp_cost_memo: Dict[Tuple, float] = {}
        self._edge_scores: Optional[Dict[Tuple[int, int], int]] = None
        # joint co-search depth gate: the exposed-comm joint currency is
        # only meaningful for WHOLE-graph candidates — a segment priced
        # in isolation gets charged its full exposed sync tail, which
        # the merged graph hides under the other segments' backward, so
        # joint-priced segment solves compose into provably worse
        # merges.  Interior recursion levels therefore rank in the
        # legacy scalar bound (identical trajectory to the sequential
        # pipeline) and every TOP-level grounding — substitution
        # proposals on the full graph, split/chain merges, the DP
        # floor — is re-validated jointly.
        self._depth = 0

    def _expired(self) -> bool:
        return self.deadline is not None and time.monotonic() > self.deadline

    # -- split-node choice (reference: find_split_node :1879-2004) ---------
    def _score_edges(self, graph: Graph) -> Dict[Tuple[int, int], int]:
        """One find_matches sweep over the top-level graph; recursion
        levels reuse the scores (segment guids are preserved by
        split_at_node, so edge keys stay valid)."""
        if self._edge_scores is None:
            from flexflow_tpu.search import match_workers

            scores: Dict[Tuple[int, int], int] = {}
            pooled = match_workers.find_all_matches(
                self.xfers, graph, self.config, self.helper.num_devices)
            for xi, xf in enumerate(self.xfers):
                ms = pooled[xi] if pooled is not None \
                    else xf.find_matches(graph)
                for m in ms:
                    guids = set(m.values()) if isinstance(m, dict) else {m.guid}
                    for g in guids:
                        for e in graph.in_edges.get(g, []):
                            scores[(e.src, e.dst)] = scores.get((e.src, e.dst), 0) + 1
                        for e in graph.out_edges.get(g, []):
                            scores[(e.src, e.dst)] = scores.get((e.src, e.dst), 0) + 1
            self._edge_scores = scores
        return self._edge_scores

    def find_split_node(self, graph: Graph) -> Optional[Node]:
        if graph.num_nodes <= self.config.base_optimize_threshold:
            return None
        bottlenecks = graph.bottlenecks()
        if not bottlenecks:
            return None
        # score edges by how many rewrite matches touch them — splitting
        # where no rewrite straddles keeps the segments' search spaces
        # independent
        edge_scores = self._edge_scores or {}
        threshold = self.config.base_optimize_threshold
        best, best_key = None, None
        for bn in bottlenecks:
            weight = sum(
                edge_scores.get((e.src, e.dst), 0)
                for e in graph.out_edges[bn.guid]
            )
            try:
                pre, _post = graph.split_at_node(bn)
            except ValueError:
                continue
            size = pre.num_nodes
            # prefer low rewrite traffic, then pre-size closest to (but
            # under) the threshold (reference tie-break :1980-1999)
            under = size <= threshold
            key = (weight, 0 if under else 1, -size if under else size)
            if best_key is None or key < best_key:
                best, best_key = bn, key
        return best

    # -- boundary view enumeration (reference: :2372) ----------------------
    def _boundary_views(self, node: Node) -> List[MachineView]:
        return boundary_views(node.op, self.helper.num_devices)

    # -- segment cache with isomorphic remapping ---------------------------
    def _cache_store(self, key, graph, fixed, result):
        g_opt, cost, strategy = result
        self.cache[key] = (
            dict(graph.node_hashes()),
            sorted(graph.nodes),
            g_opt,
            cost,
            dict(strategy),
            {g: v for g, v in fixed.items() if g in graph.nodes},
            # stamp-lint memo: {lint class -> verdict}, filled on the
            # first remapped serve of each class.  The SHD1xx lint is
            # guid-renaming-invariant, so serves sharing a lint class
            # share the verdict (the 10k-node sweep paid ~10k redundant
            # lints without this).  For entries whose hash groups are
            # all singletons the remap pairing is unique — one class;
            # AMBIGUOUS entries key the class by the served strategy's
            # canonical form, since a different pairing is a different
            # strategy and may lint differently (review finding)
            {},
            # ambiguity flag: True when any structural-hash group has
            # >1 member, i.e. a remapped serve would RE-PRICE (the
            # honest-cost rule).  Singleton-group entries serve their
            # stored cost to cost-only queries (_cache_cost) without
            # paying the remap — the dp-memo precedent
            len(set(graph.node_hashes().values())) != graph.num_nodes,
        )

    def _cache_load(self, key, graph, fixed):
        hit = self.cache.get(key)
        if hit is None:
            return None
        s_nh, s_guids, g_opt, cost, strategy, s_fixed, lint_memo, amb = hit
        if s_guids == sorted(graph.nodes):
            return g_opt, cost, dict(strategy)
        # isomorphic segment with different guids: pair nodes by
        # structural hash group (fixed guids first, so pins land on the
        # pinned nodes), remap the stored optimized graph + strategy
        nh = graph.node_hashes()
        cur_groups: Dict[int, List[int]] = {}
        for g in sorted(graph.nodes):
            cur_groups.setdefault(nh[g], []).append(g)
        stored_groups: Dict[int, List[int]] = {}
        for g in s_guids:
            stored_groups.setdefault(s_nh[g], []).append(g)
        mapping: Dict[int, int] = {}
        for h, s_list in stored_groups.items():
            c_list = cur_groups.get(h)
            if c_list is None or len(c_list) != len(s_list):
                return None
            used = set()
            s_pinned = [g for g in s_list if g in s_fixed]
            c_pinned = [g for g in c_list if g in fixed]
            for sg in s_pinned:
                match = next(
                    (cg for cg in c_pinned if fixed[cg] == s_fixed[sg]), None
                )
                if match is None:
                    return None
                mapping[sg] = match
                used.add(match)
                c_pinned.remove(match)
            s_rest = [g for g in s_list if g not in s_fixed]
            c_rest = [g for g in c_list if g not in used]
            for sg, cg in zip(s_rest, c_rest):
                mapping[sg] = cg
        g2, full = g_opt.remap(mapping, fresh_start=graph._next_guid)
        strat2 = {full[g]: v for g, v in strategy.items() if g in full}
        # the per-group pairing may not follow a single isomorphism when
        # hash groups have >1 member — re-simulate so the returned cost
        # is honest for the remapped strategy (code-review r3 finding)
        if any(len(v) > 1 for v in stored_groups.values()):
            cost = self.helper._price(g2, strat2)
        # segment STAMP: a solved segment transplanted onto an
        # isomorphic sibling (repeated transformer layers).  Stamped
        # strategies must still prove legal — the always-on SHD1xx gate
        # the fresh path passes; a lint failure costs one re-search of
        # this segment, never an illegal serve.  The verdict is linted
        # once per LINT CLASS and memoized (see _cache_store): the lint
        # is guid-renaming-invariant, so serves whose remap lands on
        # the same canonical strategy share it
        lkey = canonicalize_strategy(g2, strat2) if amb else True
        verdict = lint_memo.get(lkey)
        if verdict is None:
            from flexflow_tpu.analysis import errors_only, lint_strategy

            verdict = not errors_only(
                lint_strategy(g2, strat2, self.helper.num_devices))
            lint_memo[lkey] = verdict
        if not verdict:
            return None
        self.helper.segments_stamped += 1
        _SEG_STAMPS.inc()
        return g2, cost, strat2

    # -- k-way chain decomposition (PR 7; retained as the width-1
    # regression ORACLE for the series-parallel path below) ----------------
    def chain_optimize(
        self, graph: Graph, fixed: Strategy
    ) -> Optional[Tuple[Graph, float, Strategy]]:
        """Sequence optimization for graphs past the binary recursion's
        scale (> CHAIN_MIN_NODES — thousand-node stacked LLM PCGs): cut
        at every ``base_optimize_threshold``-spaced bottleneck in ONE
        pass, solve each segment per (in-view, out-view) boundary pair
        — the structural segment cache collapses the N isomorphic
        layers of a transformer stack to one solve per equivalence
        class x pair, stamped onto the rest — compose with a chain DP
        over boundary views, then merge once and simulate once.  The
        binary recursion pays a merge + full-graph simulation per level
        x view (O(n^2) at this scale: the 455-node GPT took 600+
        deadline-truncated seconds); this is O(classes x views^2)
        segment solves + O(n).  Returns None when the graph has no
        usable chain structure (caller falls back).

        NOTE: the production path is now ``sp_optimize`` — the
        series-parallel generalization whose bottleneck-rule cuts
        (decompose.chain_cuts) reproduce this function's cuts exactly,
        so chain-shaped graphs route through it as the width-1
        degenerate case.  This function is KEPT, un-rewired, as the
        bit-identity regression oracle (tests/test_decompose.py
        asserts sp_optimize == chain_optimize on chain-shaped graphs:
        digests, per-node views, exact sim-cost floats)."""
        bottlenecks = [b for b in graph.bottlenecks()
                       if b.guid not in fixed]
        if len(bottlenecks) < 8:
            return None
        order = {n.guid: i for i, n in enumerate(graph.topo_order())}
        threshold = max(4, self.config.base_optimize_threshold)
        cuts = []
        last = 0
        for bn in bottlenecks:
            at = order[bn.guid]
            if at - last >= threshold and at < len(order) - 1:
                cuts.append(bn)
                last = at
        if len(cuts) < 4:
            return None
        segments = []  # (segment graph, in-cut guid|None, out-cut guid|None)
        rest = graph
        try:
            for i, bn in enumerate(cuts):
                pre, rest = rest.split_at_node(bn)
                segments.append(
                    (pre, cuts[i - 1].guid if i else None, bn.guid))
        except ValueError:
            return None  # a residual edge crossed a cut — not a chain
        segments.append((rest, cuts[-1].guid, None))
        if BUS.enabled:
            BUS.emit(
                "search.chain", nodes=graph.num_nodes,
                segments=len(segments),
                max_segment=max(s[0].num_nodes for s in segments),
            )

        views_at = {bn.guid: self._boundary_views(bn) for bn in cuts}
        NO_PIN = (None,)  # chain ends have no boundary to enumerate

        def solve(seg, in_guid, u, out_guid, v):
            f2 = dict(fixed)
            if u is not None:
                f2[in_guid] = u
            if v is not None:
                f2[out_guid] = v
            return self.sequence_optimize(seg, f2)

        # chain DP over boundary views: state = out-view of segment i.
        # Segment costs double-count the shared cut node and ignore
        # cross-segment overlap — the same pruning-bound currency the
        # binary recursion sums; the merged graph's one simulation at
        # the end is the honest cost.
        prev: Dict[object, Tuple[float, tuple]] = {None: (0.0, ())}
        for seg, in_guid, out_guid in segments:
            out_views = views_at[out_guid] if out_guid else NO_PIN
            in_views = list(prev)
            if self._expired():
                # deadline: stop enumerating, keep the first live lane
                out_views = out_views[:1]
                in_views = in_views[:1]
            cur: Dict[object, Tuple[float, tuple]] = {}
            for v in out_views:
                best_c, best_path = math.inf, None
                for u in in_views:
                    c_in, path = prev[u]
                    if c_in >= best_c:
                        continue
                    _, c_seg, _ = solve(seg, in_guid, u, out_guid, v)
                    if c_in + c_seg < best_c:
                        best_c, best_path = c_in + c_seg, path + (u,)
                if best_path is not None and math.isfinite(best_c):
                    cur[v] = (best_c, best_path)
            if not cur:
                return None  # no feasible lane: fall back to recursion
            prev = cur
        # the last segment has no out boundary, so the final state is
        # the single un-pinned lane; path[i] is the in-view of segment
        # i (= the pin at cut i-1), path[0] the None chain start
        bound, path = prev[None]
        pins = path[1:] + (None,)

        merged_g, merged_s = None, {}
        for (seg, in_guid, out_guid), v in zip(segments, pins):
            u = merged_s.get(in_guid) if in_guid else None
            g_i, _, s_i = solve(seg, in_guid, u, out_guid, v)
            if merged_g is None:
                merged_g, merged_s = g_i, dict(s_i)
            else:
                merged_g, merged_s = _merge_split(
                    merged_g, merged_s, g_i, s_i, in_guid)
            if out_guid is not None:
                merged_s[out_guid] = v
        c_true = self.helper._price(merged_g, merged_s)
        if BUS.enabled:
            BUS.emit("search.chain_done", bound_s=bound, cost_s=c_true)
        return merged_g, c_true, merged_s

    # -- series-parallel decomposition (bounded-width cuts) ----------------
    def _record_decompose(self, **kw) -> None:
        d = LAST_DECOMPOSE
        d["decompose_calls"] = d.get("decompose_calls", 0) + 1
        if "decompose_mode" not in d and "mode" in kw:
            d["decompose_mode"] = kw["mode"]
        if kw.get("mode") == "fallback":
            d["decompose_fallbacks"] = d.get("decompose_fallbacks", 0) + 1
        d["decompose_cuts"] = d.get("decompose_cuts", 0) + kw.get("cuts", 0)
        d["sp_segments"] = d.get("sp_segments", 0) + kw.get("segments", 0)
        if kw.get("max_width"):
            d["decompose_max_width"] = max(
                d.get("decompose_max_width", 0), kw["max_width"])
        if BUS.enabled:
            BUS.emit("search.decompose", **kw)

    def sp_optimize(
        self, graph: Graph, fixed: Strategy
    ) -> Optional[Tuple[Graph, float, Strategy]]:
        """Series-parallel sequence optimization — ``chain_optimize``
        generalized to bounded-width frontier cuts (search/decompose.py)
        so graphs with NO bottleneck chain (multi-branch MoE trunks,
        persistent-skip stacks, disaggregated placement graphs) still
        decompose instead of degenerating to the binary recursion's
        whole-graph brute force.  Cut selection tries PR 7's bottleneck
        rule FIRST (mode "chain": width-1 cuts, bit-identical cuts and
        solves to ``chain_optimize`` — the degenerate case), then
        bounded-width frontiers (mode "sp"): the DP state becomes a
        TUPLE of boundary views, one per crossing node, with nodes that
        persist across consecutive cuts (skip connections) carrying one
        view through.  Segment solves ride the same memoized
        ``sequence_optimize`` recursion — the structural segment cache
        stamps isomorphism classes, and finished solves persist as
        guid-free sp-memo rows (cost_cache.py sp-row layer) a cold
        process can serve.  Emits ``search.decompose`` naming the
        chosen decomposition — or the fallback reason, so a silent
        degradation to binary recursion cannot happen."""
        threshold = max(4, self.config.base_optimize_threshold)
        cuts, mode = _decompose.find_series_cuts(graph, fixed, threshold)
        if cuts is None:
            self._record_decompose(
                nodes=graph.num_nodes, mode="fallback", reason=mode)
            return None
        segments = _decompose.split_series(graph, cuts)
        if segments is None:
            self._record_decompose(
                nodes=graph.num_nodes, mode="fallback",
                reason="stale_crossing")
            return None
        max_width = max(c.width for c in cuts)
        self._record_decompose(
            nodes=graph.num_nodes, mode=mode, cuts=len(cuts),
            max_width=max_width, segments=len(segments),
            max_segment=max(s[0].num_nodes for s in segments),
        )

        views_at = {
            g: self._boundary_views(graph.nodes[g])
            for c in cuts for g in c.crossing
        }

        def pin_views(seg, in_cross, u, out_cross, v):
            f2 = dict(fixed)
            if u is not None:
                for g, vv in zip(in_cross, u):
                    f2[g] = vv
            if v is not None:
                for g, vv in zip(out_cross, v):
                    f2[g] = vv
            return f2

        def solve(seg, in_cross, u, out_cross, v):
            f2 = pin_views(seg, in_cross, u, out_cross, v)
            served = self._serve_sp_row(seg, f2)
            if served is not None:
                return served
            res = self.sequence_optimize(seg, f2)
            self._persist_sp_row(seg, f2, res)
            return res

        def solve_cost(seg, in_cross, u, out_cross, v):
            """The DP enumeration needs only the segment COST — for
            unambiguous cached entries the stored cost IS the served
            cost (no re-price), so skip the remap/strategy
            materialization the merge replay will pay exactly once.
            In chain mode ambiguous/cold entries take the full solve,
            so every float the DP compares is identical to the PR 7
            path's (the bit-identity gate); in sp mode the stored cost
            also serves AMBIGUOUS entries — the DP total is a ranking
            bound either way (segment sums double-count the crossing
            nodes), and the merge replay still materializes, lints,
            and honestly re-simulates the composed winner."""
            f2 = pin_views(seg, in_cross, u, out_cross, v)
            key = (seg.hash(), canon_fixed_views(seg, f2))
            hit = self.cache.get(key)
            if hit is not None and (
                    mode != "chain" or not hit[7]
                    or hit[1] == sorted(seg.nodes)):
                return hit[3]
            return solve(seg, in_cross, u, out_cross, v)[1]

        # chain DP over boundary-view tuples: state = the out-cut's
        # view tuple (None at the chain ends).  Per-segment costs
        # double-count the shared crossing nodes and ignore
        # cross-segment overlap — the same pruning-bound currency the
        # chain path sums; the merged graph's one simulation at the
        # end is the honest cost.
        prev: Dict[object, Tuple[float, tuple]] = {None: (0.0, ())}
        for seg, in_cross, out_cross in segments:
            in_states = list(prev)
            if self._expired():
                in_states = in_states[:1]
            cur: Dict[object, Tuple[float, tuple]] = {}
            for u in in_states:
                c_in, path = prev[u]
                carry = dict(zip(in_cross, u)) if u is not None else None
                if out_cross:
                    v_states = _decompose.boundary_tuples(
                        views_at, out_cross, carry=carry)
                    if self._expired():
                        v_states = v_states[:1]
                else:
                    v_states = [None]
                for v in v_states:
                    got = cur.get(v)
                    if got is not None and c_in >= got[0]:
                        continue  # even a free segment cannot win
                    c_seg = solve_cost(seg, in_cross, u, out_cross, v)
                    total = c_in + c_seg
                    if (got is None or total < got[0]) and math.isfinite(
                            total):
                        cur[v] = (total, path + (u,))
            if not cur:
                self._record_decompose(
                    nodes=graph.num_nodes, mode="fallback",
                    reason="infeasible_lane")
                return None  # no feasible lane: binary recursion
            if len(cur) > _decompose.MAX_CUT_TUPLES:
                # beam: carried cut members multiply the state count
                # (each tower tail that persists across cuts keeps its
                # own view lanes) — keep the cheapest states.  Chain
                # cuts share no members, so chain-mode states never
                # exceed the per-node view count and the bit-identity
                # gate is untouched.  Stable sort: ties keep insertion
                # order, so the pruning is deterministic.
                keep = sorted(cur.items(), key=lambda kv: kv[1][0])
                cur = dict(keep[:_decompose.MAX_CUT_TUPLES])
            prev = cur
        if None not in prev:
            self._record_decompose(
                nodes=graph.num_nodes, mode="fallback",
                reason="infeasible_lane")
            return None
        bound, path = prev[None]
        pins = path[1:] + (None,)

        merged_g, merged_s = None, {}
        for (seg, in_cross, out_cross), v in zip(segments, pins):
            u = (
                tuple(merged_s[g] for g in in_cross)
                if in_cross else None
            )
            g_i, _, s_i = solve(seg, in_cross, u, out_cross, v)
            if merged_g is None:
                # the accumulator must be owned: g_i may be a cached
                # segment object the in-place merges below would corrupt
                merged_g, merged_s = g_i.copy(), dict(s_i)
            else:
                _decompose.merge_segment_into(
                    merged_g, merged_s, g_i, s_i, set(in_cross))
            if v is not None:
                for g, vv in zip(out_cross, v):
                    merged_s[g] = vv
        c_true = self.helper._price(merged_g, merged_s)
        if BUS.enabled:
            BUS.emit("search.decompose_done", mode=mode, bound_s=bound,
                     cost_s=c_true, segments=len(segments))
        return merged_g, c_true, merged_s

    # -- persistent sp-segment memo rows (cost_cache.py sp-row layer) ------
    def _sp_row_key(self, seg: Graph, f2: Strategy) -> str:
        """Guid-free persistent key for one SP segment solve: stable
        segment digest + stable pinned boundary views + every knob that
        changes the solve's answer beyond the cache's cost-surface
        signature (the segment solve runs the FULL unity recursion —
        substitutions included — so the rewrite-registry knobs join
        the DP-shape knobs)."""
        from hashlib import blake2b

        from flexflow_tpu.search.cost_cache import stable_graph_digest

        sub_digest = getattr(self, "_sub_digest", False)
        if sub_digest is False:
            sub_digest = None
            if self.config.substitution_json:
                import hashlib

                try:
                    with open(self.config.substitution_json, "rb") as f:
                        sub_digest = hashlib.sha256(
                            f.read()).hexdigest()[:12]
                except OSError:
                    sub_digest = "unreadable"
            self._sub_digest = sub_digest
        snh = seg.stable_node_digests()
        pins = tuple(sorted(
            (snh[g], tuple(v.dim_degrees), int(v.replica_degree),
             int(v.start_part))
            for g, v in f2.items() if g in seg.nodes
        ))
        knobs = (
            self.config.search_budget, self.config.search_alpha,
            self.config.base_optimize_threshold,
            self.helper.num_devices, sub_digest,
        )
        if self.helper.joint is not None:
            # joint-currency rows live under their own key family —
            # same extension-only discipline as the dp-row layer
            knobs = knobs + ("co_search",)
        tail = blake2b(repr((pins, knobs)).encode(),
                       digest_size=10).hexdigest()
        return stable_graph_digest(seg) + ":" + tail

    def _serve_sp_row(self, seg: Graph, f2: Strategy):
        """(graph, cost, strategy) from a persisted sp-segment memo row
        remapped onto this segment's guids, or None.  Same serving
        discipline as the persistent DP memo: rows LOADED from disk
        only (the in-process segment cache covers this run's own
        writes, so a cold cache stays inert and the chain bit-identity
        gate holds), the shared ``_pair_views`` pairing rule over
        stable digests, ambiguous pairings re-simulated for an honest
        cost, and the stamped strategy re-linted SHD1xx — a corrupt
        row costs one re-solve, never a wrong serve."""
        cc = self.helper.sim.cost_cache
        if (cc is None or not getattr(cc, "sp_loaded", False) or cc.stale
                or seg.num_nodes < DP_PERSIST_MIN_NODES):
            return None
        key = self._sp_row_key(seg, f2)
        row = cc.get_sp_row(key)
        if row is None:
            return None
        decoded = decode_strategy_rows(row)
        if decoded is None:
            return None
        cost, canon = decoded
        strategy, ambiguous = _pair_views(
            seg, seg.stable_node_digests(), canon, f2)
        if strategy is None or len(strategy) != seg.num_nodes:
            return None
        # lint + ambiguous re-price memoized per (row, canonical served
        # strategy): a remap landing on the same canonical form is the
        # same strategy up to isomorphism, so verdict and simulated
        # float are shared; a DIFFERENT pairing is a different class
        # and pays its own lint/price (review finding: the verdict is
        # exactly as pairing-dependent as the cost)
        mkey = (key, canonicalize_strategy(seg, strategy)) if ambiguous \
            else (key, True)
        if ambiguous:
            # interior currency: segment solves rank in the scalar
            # simulation (the driver's depth gate), so the honest
            # re-price for an ambiguous pairing is the scalar sim too
            got = self._sp_cost_memo.get(mkey)
            if got is None:
                got = self.helper.sim.simulate(seg, strategy)
                self._sp_cost_memo[mkey] = got
            cost = got
        if mkey not in self._sp_lint_ok:
            from flexflow_tpu.analysis import errors_only, lint_strategy

            self._sp_lint_ok[mkey] = not errors_only(
                lint_strategy(seg, strategy, self.helper.num_devices))
        if not self._sp_lint_ok[mkey]:
            return None
        self.helper.sp_rows_served += 1
        _SP_ROWS_SERVED.inc()
        return seg, cost, strategy

    def _persist_sp_row(self, seg: Graph, f2: Strategy, res) -> None:
        """Persist a finished segment solve as a guid-free sp-memo row.
        Only UN-REWRITTEN solves persist into the JSON layer (a
        rewritten segment graph cannot be expressed as digest-keyed
        strategy rows on the original segment; it still rides the
        in-process segment cache and the whole-result pickle layer)."""
        g_opt, cost, strategy = res
        cc = self.helper.sim.cost_cache
        if (cc is None or cc.stale or not math.isfinite(cost)
                or seg.num_nodes < DP_PERSIST_MIN_NODES or not strategy):
            return
        if sorted(g_opt.nodes) != sorted(seg.nodes):
            return  # rewritten: structure moved off the segment digest
        rows = encode_strategy_rows(seg, strategy)
        if rows is None:
            return
        cc.put_sp_row(self._sp_row_key(seg, f2), float(cost), rows)

    # -- recursive sequence optimization (reference: :2190-2370) -----------
    def sequence_optimize(
        self, graph: Graph, fixed: Strategy
    ) -> Tuple[Graph, float, Strategy]:
        """Depth-gated wrapper: interior recursion levels suspend the
        joint pricer (``SearchHelper.joint_scope`` — THE shared gate
        rule), the top level restores it."""
        top = self._depth == 0
        self._depth += 1
        try:
            with self.helper.joint_scope(top):
                return self._sequence_optimize(graph, fixed)
        finally:
            self._depth -= 1

    def _sequence_optimize(
        self, graph: Graph, fixed: Strategy
    ) -> Tuple[Graph, float, Strategy]:
        key = (graph.hash(), canon_fixed_views(graph, fixed))
        hit = self._cache_load(key, graph, fixed)
        if hit is not None:
            return hit
        if graph.num_nodes > CHAIN_MIN_NODES:
            decomposed = self.sp_optimize(graph, fixed)
            if decomposed is not None:
                self._cache_store(key, graph, fixed, decomposed)
                return decomposed
        bn = self.find_split_node(graph)
        if bn is None or bn.guid in fixed:
            result = self.base_optimize(graph, fixed)
        else:
            try:
                pre, post = graph.split_at_node(bn)
            except ValueError:
                result = self.base_optimize(graph, fixed)
                self._cache_store(key, graph, fixed, result)
                return result
            if BUS.enabled:
                BUS.emit(
                    "search.split", op=bn.op.name,
                    pre_nodes=pre.num_nodes, post_nodes=post.num_nodes,
                    boundary_views=len(self._boundary_views(bn)),
                )
            best: Tuple[Optional[Graph], float, Strategy] = (None, math.inf, {})
            best_bound = math.inf
            for v in self._boundary_views(bn):
                f2 = dict(fixed)
                f2[bn.guid] = v
                g_pre, c_pre, s_pre = self.sequence_optimize(pre, f2)
                if c_pre >= best_bound:
                    continue
                g_post, c_post, s_post = self.sequence_optimize(post, f2)
                # c_pre + c_post double-counts the pinned bottleneck and
                # ignores cross-segment overlap — it is only a pruning
                # bound; the merged graph's own simulation decides
                # (dp.graph_cost re-validates the same way)
                total = c_pre + c_post
                if total >= best_bound * 1.5:
                    continue
                best_bound = min(best_bound, total)
                merged_g, merged_s = _merge_split(
                    g_pre, s_pre, g_post, s_post, bn.guid
                )
                merged_s[bn.guid] = v
                c_true = self.helper._price(merged_g, merged_s)
                if c_true < best[1]:
                    best = (merged_g, c_true, merged_s)
                if self._expired():
                    break
            if best[0] is None:
                result = self.base_optimize(graph, fixed)
            else:
                result = best  # type: ignore[assignment]
        self._cache_store(key, graph, fixed, result)
        return result

    # -- best-first over substitutions (reference: :2007-2089) -------------
    def base_optimize(
        self, graph: Graph, fixed: Strategy
    ) -> Tuple[Graph, float, Strategy]:
        """Two-tier best-first search: every candidate gets a cheap
        estimate (simulate under the parent's optimized strategy
        extended with default views for inserted nodes); only popped
        candidates — at most ``search_budget`` — pay for the full DP.
        The reference full-costs every candidate (substitution.cc:
        2007-2089) because its DP is C++ with measured-cost caches; the
        estimate keeps identical best-first structure at tractable cost."""
        helper, config = self.helper, self.config
        best_cost, best_strategy = helper.graph_cost(graph, fixed)
        best_graph = graph
        counter = 0
        # heap entries: (estimate, counter, graph, parent_strategy)
        heap: list = [(best_cost, counter, graph, best_strategy)]
        seen = {graph.hash()}
        budget = config.search_budget
        pinned = set(fixed)
        while heap and budget > 0 and not self._expired():
            est, _, g, parent_s = heapq.heappop(heap)
            if est > config.search_alpha * best_cost:
                break
            budget -= 1
            if g is not graph:
                # full DP for the popped candidate (tier 2)
                cost, strat = helper.graph_cost(g, fixed)
                if BUS.enabled:
                    BUS.emit(
                        "search.candidate", cost_s=cost, est_s=est,
                        best_s=best_cost, improved=cost < best_cost,
                        nodes=g.num_nodes,
                    )
                if cost < best_cost:
                    best_cost, best_strategy, best_graph = cost, strat, g
                parent_s = strat
            # arm the delta baseline on the popped parent: every child
            # candidate's tier-1 estimate below is then an incremental
            # re-cost of the substitution's dirty cone instead of a
            # full O(nodes+edges) schedule derivation (the reference's
            # SIMULATE_DELTA discipline, simulator.h).  Priming the
            # parent's ancestor hashes makes the children's dedup
            # hashing incremental the same way.
            g.prime_delta_hashes()
            self.helper.sim.set_baseline(
                g, self._estimate_strategy(g, parent_s, fixed))
            emit = BUS.enabled  # per-candidate events are chatty: one
            # branch when telemetry is off, full accept/reject
            # provenance when it is on
            # delta-aware matching (ROADMAP PR 3 follow-up): a popped
            # candidate re-matches only the dirty region around its
            # substitution, seeded by the parent's matches (attached at
            # push time below) + the changed-guid sets.  All xfers'
            # matches are collected BEFORE applying any, so every child
            # inherits the complete parent-match payload.
            parent_matches = getattr(g, "_parent_match_guids", None)
            matches_by_xfer: List[list] = []
            match_payload: Dict[int, List[int]] = {}
            pooled = None
            if parent_matches is None:
                # parent-less pops pay a full per-xfer sweep — the
                # opt-in match-worker pool fans it out across processes
                # (serial path when FLEXFLOW_TPU_MATCH_WORKERS is off)
                from flexflow_tpu.search import match_workers

                pooled = match_workers.find_all_matches(
                    self.xfers, g, self.config, self.helper.num_devices)
            for xi, xf in enumerate(self.xfers):
                delta_fn = getattr(xf, "find_matches_delta", None)
                if pooled is not None:
                    ms = pooled[xi]
                    if delta_fn is not None:
                        match_payload[xi] = [n.guid for n in ms]
                elif delta_fn is not None:
                    ms = delta_fn(
                        g,
                        parent_matches.get(xi) if parent_matches else None)
                    match_payload[xi] = [n.guid for n in ms]
                else:
                    # dict-match xfers (BatchEmbeddingsXfer) group over
                    # the WHOLE graph — no local delta applies
                    ms = xf.find_matches(g)
                matches_by_xfer.append(ms)
            for xi, xf in enumerate(self.xfers):
                for m in matches_by_xfer[xi]:
                    g2 = xf.apply(g, m)
                    if g2 is None:
                        if emit:
                            BUS.emit("search.substitution", xfer=xf.name,
                                     action="invalid")
                        continue
                    # a rewrite must not consume a pinned boundary node
                    if any(p not in g2.nodes for p in pinned if p in g.nodes):
                        if emit:
                            BUS.emit("search.substitution", xfer=xf.name,
                                     action="pinned")
                        continue
                    h = g2.hash()
                    if h in seen:
                        if emit:
                            BUS.emit("search.substitution", xfer=xf.name,
                                     action="duplicate")
                        continue
                    seen.add(h)
                    e2 = self._estimate(g2, parent_s, fixed)
                    if e2 < config.search_alpha * best_cost:
                        counter += 1
                        g2._parent_match_guids = match_payload
                        heapq.heappush(heap, (e2, counter, g2, parent_s))
                        if emit:
                            BUS.emit("search.substitution", xfer=xf.name,
                                     action="pushed", est_s=e2,
                                     best_s=best_cost)
                    elif emit:
                        BUS.emit("search.substitution", xfer=xf.name,
                                 action="pruned", est_s=e2,
                                 best_s=best_cost)
                if self._expired():
                    break
        self.helper.sim.clear_baseline()
        return best_graph, best_cost, best_strategy

    @staticmethod
    def _estimate_strategy(graph: Graph, parent_s: Strategy,
                           fixed: Strategy) -> Strategy:
        """The estimate's view resolution — parent strategy where guids
        survive, default/fixed views for inserted nodes.  ONE rule
        shared by the estimate and its delta baseline, so an unchanged
        node always resolves to the identical view object and the
        dirty-set diff stays at the substitution's true footprint."""
        strat: Strategy = {}
        for guid, node in graph.nodes.items():
            v = fixed.get(guid) or parent_s.get(guid)
            if v is None:
                v = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            strat[guid] = v
        return strat

    def _estimate(self, graph: Graph, parent_s: Strategy, fixed: Strategy) -> float:
        """Cheap candidate cost: parent strategy where guids survive,
        default/fixed views for inserted nodes, one simulation — served
        as a delta re-cost of the substitution's dirty cone against the
        popped parent's armed baseline (simulate_rewrite) whenever the
        candidate carries its changed-guid sets; full simulation
        otherwise."""
        sim = self.helper.sim
        fixed_get = fixed.get
        parent_get = parent_s.get

        def resolve(node):
            v = fixed_get(node.guid) or parent_get(node.guid)
            if v is None:
                v = node.op.fixed_machine_view() or MachineView.trivial(
                    node.op.output_shapes[0].ndim
                )
            return v

        got = sim.simulate_rewrite(graph, resolve)
        if got is not None:
            return got
        return sim.simulate(
            graph, self._estimate_strategy(graph, parent_s, fixed))


def _merge_split(
    pre_g: Graph,
    pre_s: Strategy,
    post_g: Graph,
    post_s: Strategy,
    bn_guid: int,
) -> Tuple[Graph, Strategy]:
    """Union of the two optimized segments.  Original nodes are disjoint
    apart from the shared bottleneck; nodes INSERTED by rewrites may
    collide between segments (both sides allocate from the same starting
    guid) and are renumbered on the post side."""
    g = Graph()
    g._next_guid = max(pre_g._next_guid, post_g._next_guid)
    for guid, n in pre_g.nodes.items():
        g.nodes[guid] = n
        g.in_edges[guid] = list(pre_g.in_edges[guid])
        g.out_edges[guid] = list(pre_g.out_edges[guid])
    remap: Dict[int, int] = {}
    for guid in post_g.nodes:
        if guid in pre_g.nodes and guid != bn_guid:
            remap[guid] = g._next_guid
            g._next_guid += 1
    from flexflow_tpu.core.graph import Edge

    for guid, n in post_g.nodes.items():
        ng = remap.get(guid, guid)
        if ng not in g.nodes:
            g.nodes[ng] = n if ng == guid else Node(ng, n.op)
            g.in_edges.setdefault(ng, [])
            g.out_edges.setdefault(ng, [])
    for guid in post_g.nodes:
        for e in post_g.out_edges[guid]:
            ne = Edge(
                remap.get(e.src, e.src),
                remap.get(e.dst, e.dst),
                e.src_idx,
                e.dst_idx,
            )
            g.out_edges[ne.src].append(ne)
            g.in_edges[ne.dst].append(ne)
    strategy = dict(pre_s)
    for guid, v in post_s.items():
        strategy[remap.get(guid, guid)] = v
    g._invalidate()
    return g, strategy


# perf observability of the LAST optimize_strategy call in this
# process: bench_search splits its per-model timing into calibration
# vs search and records the delta/cache hit rates from here
LAST_SEARCH_STATS: Dict[str, object] = {}

# the gradient-sync schedule the LAST optimize_strategy chose (and
# gated) under config.sync_schedule="search" — compile() adopts it for
# the strategy the search just returned instead of re-running the
# choice; None when the mode is off or the monolithic baseline won
LAST_SYNC_SCHEDULE = None

# the per-group optimizer-state sharding map the LAST optimize_strategy
# chose under config.co_search (search/comm_plan.py choose_zero_groups):
# op names whose ZeRO-1 reduce-scatter/all-gather placement genuinely
# shrinks the update term — compile() adopts it the way it adopts
# LAST_SYNC_SCHEDULE; () when co-search is off or nothing qualifies
LAST_ZERO_GROUPS: tuple = ()

# the serving provenance of the LAST optimize_strategy run under
# config.objective="serve" (search/serving.py): the SHD16x-gated
# objective + SLO budget + frame geometry + predicted p99 + per-device
# KV residency — compile() persists it as __meta__.serving behind the
# digest gate (fflint strategy checks it stdlib-only, STR209); None
# under the default train objective
LAST_SERVING_META = None

# KV-lane provenance of the last serve-objective search: chosen pool
# dtype + scale layout + prefix-sharing residency accounting — compile()
# persists it as __meta__.kv behind the digest gate (fflint strategy
# checks it stdlib-only, STR213; SHD168/169 re-lint at import); None
# when the lane is unarmed (kv_precision="off" and no declared sharing)
LAST_KV_META = None


def _kv_candidate_graph(graph, dtype: str):
    """A pricing CLONE of ``graph`` whose decode ops carry
    ``kv_dtype=dtype`` — the caller's graph (and the frontend digest
    the strategy export is keyed to) is never mutated; attr ADOPTION
    happens in model.py, after the export meta is computed on the
    export side and after the SHD168/169 re-lint passes on the import
    side.  fp32 adds no attr (extension-only discipline), so the
    original graph IS the fp32 candidate."""
    if dtype == "fp32":
        return graph
    from flexflow_tpu.core.graph import Node
    from flexflow_tpu.core.optype import OperatorType

    g2 = graph.copy()
    for guid, node in list(g2.nodes.items()):
        op = node.op
        if op.op_type != OperatorType.DECODE_ATTENTION:
            continue
        a = op.attrs
        clone = type(op)(
            op.name, op.input_shapes,
            embed_dim=a["embed_dim"], num_heads=a["num_heads"],
            page_size=a["page_size"], pages_per_seq=a["pages_per_seq"],
            num_pages=a["num_pages"], use_kernel=a["use_kernel"],
            kv_dtype=dtype, kernel_initializer=op._kernel_init,
        )
        g2.nodes[guid] = Node(guid, clone)
    g2._invalidate()
    return g2


def _choose_kv_precision(graph, strategy, config, serving, calibration):
    """The KV-lane decision for a finished serve-objective result:
    price the pool-dtype candidates (fp32/bf16/int8 under
    ``kv_precision="search"``, the single pinned dtype otherwise)
    through the SAME p99 currency the search ranked in — each
    candidate's decode cache stream shrinks with the dtype while the
    quantize-overhead term (KV_QUANT_PASSES, the EQuARX discipline
    wire precision already pays) charges the write path — and return
    the ``__meta__.kv`` provenance block, or None when the lane is
    unarmed.  Pricing uses fresh simulators with the persistent cost
    cache detached (lane probes are result provenance, not the
    search's cost surface)."""
    lane = getattr(config, "kv_precision", "off")
    sharing = int(getattr(serving, "shared_prefix_pages", 0) or 0) \
        if serving is not None else 0
    if serving is None or not strategy or (lane == "off" and not sharing):
        return None
    from flexflow_tpu.search.serving import kv_residency_bytes
    from flexflow_tpu.search.simulator import Simulator

    if lane == "search":
        cands = ["fp32", "bf16", "int8"]
    elif lane == "off":
        cands = ["fp32"]  # sharing armed alone: pool dtype stays put
    else:
        cands = [lane]
    priced = {}
    graphs = {}
    for dt in cands:
        g = _kv_candidate_graph(graph, dt)
        graphs[dt] = g
        sim = Simulator(
            config.machine_spec, num_devices=config.search_devices,
            calibration=calibration, inference=True, serving=serving,
        )
        priced[dt] = sim.simulate(g, strategy)
    best = min(cands, key=lambda d: priced[d])
    meta = {
        "dtype": best,
        "searched": lane == "search",
        "scale_layout": "page_slot" if best == "int8" else "none",
        "shared_prefix_pages": sharing,
        "shared_residency_factor": serving.shared_residency_factor(),
        "predicted_p99_step_ms": {
            d: round(t * 1e3, 6) for d, t in sorted(priced.items())},
        "kv_bytes_per_device": kv_residency_bytes(
            graphs[best], strategy, config.search_devices,
            serving=serving),
    }
    BUS.emit(
        "search.kv", dtype=best, searched=lane == "search",
        shared_prefix_pages=sharing,
        p99_ms={d: round(t * 1e3, 6) for d, t in sorted(priced.items())},
        kv_bytes_per_device=meta["kv_bytes_per_device"],
    )
    return meta


def _build_sync_schedule(graph, strategy, sim, config, joint=None):
    """Choose + legality-gate the gradient-sync schedule for a search
    result (search/sync_schedule.py) — runs on BOTH the fresh and the
    cache-served paths of ``optimize_strategy``, so every result this
    function hands out carries a linted schedule (or None).  The gate
    (SHD12x) is always-on inside ``choose_sync_schedule``; a failure
    there is a builder bug and raises.

    Under co-search (``joint`` bound) the schedule is SERVED from the
    JointPricer's comm-plan memo — the plan the winning strategy was
    actually priced with — instead of re-running the sweep, and the
    memoized per-group optimizer-sharding choice lands in
    ``LAST_ZERO_GROUPS``.  Served plans (memo or disk) still pass the
    full SHD12x/SHD14x legality gates against THIS (graph, strategy):
    a corrupt persisted plan costs one re-search, never an illegal
    artifact."""
    global LAST_SYNC_SCHEDULE, LAST_ZERO_GROUPS
    LAST_SYNC_SCHEDULE = None
    LAST_ZERO_GROUPS = ()
    if getattr(config, "sync_schedule", "off") != "search" or not strategy:
        return None
    from flexflow_tpu.search.sync_precision import choose_sync_precision
    from flexflow_tpu.search.sync_schedule import (
        choose_sync_schedule,
        lint_gate,
    )

    if joint is not None:
        entry = joint.plan_for(graph, strategy, sim)
        schedule = None
        if entry is not None and entry.adopted:
            schedule = entry.schedule
            lint_gate(graph, strategy, schedule, entry.pmap,
                      cost_model=sim.cost)
        if entry is not None and entry.zero:
            from flexflow_tpu.analysis import (
                AnalysisError,
                emit_findings,
                errors_only,
                lint_zero_map,
            )

            bad = errors_only(lint_zero_map(
                graph, strategy, entry.zero, sim.cost))
            if bad:
                # a served zero map that fails the always-on gate is a
                # plan bug (or a corrupt persisted row): fail loudly
                # like every other artifact this tree produces
                emit_findings(bad)
                raise AnalysisError(
                    "co-search produced an illegal per-group "
                    "optimizer-sharding map", bad)
            LAST_ZERO_GROUPS = tuple(entry.zero)
        LAST_SEARCH_STATS["sync_schedule"] = {
            "buckets": len(schedule.buckets) if schedule is not None else 0,
            "co_search": True,
            "zero_groups": len(LAST_ZERO_GROUPS),
        }
        if BUS.enabled:
            BUS.emit(
                "search.zero_groups", groups=list(LAST_ZERO_GROUPS),
                credit_s=entry.zero_credit if entry is not None else 0.0,
            )
        LAST_SYNC_SCHEDULE = schedule
        return schedule

    pmap = {}
    if getattr(config, "sync_precision", "fp32") != "fp32":
        pmap = choose_sync_precision(graph, strategy, sim.cost)
    schedule, info = choose_sync_schedule(graph, strategy, sim, pmap, config)
    LAST_SEARCH_STATS["sync_schedule"] = {
        "buckets": info.get("buckets", 0),
        "monolithic_s": info.get("monolithic_s"),
        "scheduled_s": info.get("scheduled_s"),
    }
    if schedule is not None:
        from flexflow_tpu.utils.logging import SEARCH_LOG

        SEARCH_LOG.log(
            f"sync schedule: {len(schedule.buckets)} buckets beat the "
            f"monolithic sync "
            f"({info['monolithic_s'] * 1e3:.4f} -> "
            f"{info['scheduled_s'] * 1e3:.4f} ms/iter simulated)"
        )
    LAST_SYNC_SCHEDULE = schedule
    return schedule


def _lint_findings(graph, strategy, num_devices):
    """Error-level static-analysis findings for a search result: graph
    well-formedness + strategy/sharding legality (flexflow_tpu/analysis).
    The always-on gate of ``optimize_strategy`` — a few propagate calls
    per node, negligible next to the search itself."""
    from flexflow_tpu.analysis import check_graph, errors_only, lint_strategy

    return errors_only(
        check_graph(graph) + lint_strategy(graph, strategy, num_devices))


def _serve_cached_search(cache, graph: Graph, config: FFConfig):
    """Remap a cached search result onto the caller's graph.  The
    digest key is guid-free (stable_graph_digest), so the stored
    original-graph topo guid sequence is positionally isomorphic to
    the caller's — original nodes map 1:1, rewrite-inserted nodes get
    fresh guids (Graph.remap)."""
    got = cache.get_search_result(graph, config)
    if got is None:
        return None
    orig_topo, best_graph, strategy, cost = got
    caller_topo = [n.guid for n in graph.topo_order()]
    if len(orig_topo) != len(caller_topo):
        return None
    pos = dict(zip(orig_topo, caller_topo))
    if best_graph is None:
        # un-rewritten result: strategies transfer positionally onto
        # the caller's (structurally identical) graph
        strat2 = {pos[g]: v for g, v in strategy.items() if g in pos}
        return graph, strat2, cost
    mapping = {og: cg for og, cg in pos.items() if og in best_graph.nodes}
    g2, full = best_graph.remap(mapping, fresh_start=graph._next_guid)
    strat2 = {full[g]: v for g, v in strategy.items() if g in full}
    return g2, strat2, cost


def load_calibration(config: FFConfig):
    """The CalibrationTable at config.calibration_file, or None.  The
    platform-coherence check (measured records must come from the
    backend the machine model describes) runs in optimize_strategy so
    it can log; callers that need the coherent table directly use
    coherent_calibration."""
    if not config.calibration_file:
        return None
    import os

    from flexflow_tpu.search.calibration import CalibrationTable

    if not os.path.exists(config.calibration_file):
        return None
    return CalibrationTable.load(config.calibration_file)


def coherent_calibration(config: FFConfig):
    """load_calibration + the same platform-coherence rule the search
    applies — so OTHER scorers (e.g. compile's pipeline proposal) rank
    in the SAME cost currency as the search that just ran."""
    calibration = load_calibration(config)
    if calibration is not None and calibration.backend not in (
            None, config.machine_spec.platform):
        return None
    return calibration


def optimize_strategy(
    graph: Graph, config: FFConfig, return_graph: bool = False
) -> "Strategy | Tuple[Graph, Strategy]":
    """Find a good (graph, strategy).  With ``return_graph=True`` — the
    default compile path — the joint Unity search runs: graph rewrites
    compete with view assignment and the best REWRITTEN graph is
    returned for lowering.  With False only strategies on the original
    graph are explored (strategy-only mode, e.g. for export).

    ``config.verify`` arms the post-rewrite invariant checker for THIS
    search only (same checks as FLEXFLOW_TPU_VERIFY=1, scoped instead
    of process-sticky)."""
    if getattr(config, "verify", False):
        from flexflow_tpu.analysis.invariants import scoped_verify

        with scoped_verify(True):
            return _optimize_strategy(graph, config, return_graph)
    return _optimize_strategy(graph, config, return_graph)


def _optimize_strategy(
    graph: Graph, config: FFConfig, return_graph: bool = False
) -> "Strategy | Tuple[Graph, Strategy]":
    global LAST_SERVING_META, LAST_KV_META
    from flexflow_tpu.utils.logging import SEARCH_LOG as log

    t_start = time.monotonic()
    # re-entrant discipline: the always-on controller re-runs this
    # mid-training and reads LAST_SEARCH_STATS afterwards — a search
    # that raises part-way must not leave the PREVIOUS run's stats
    # (e.g. a stale result_cache_hit) for that consumer to misread
    LAST_SEARCH_STATS.clear()
    LAST_DECOMPOSE.clear()
    # snapshot the delta-matching counters so search.perf reports THIS
    # search's rescan shrink, not the process-lifetime aggregate
    from flexflow_tpu.search import substitution as _subst

    match_base = (
        _subst._SCANS.value, _subst._DELTA_SCANS.value,
        _subst._DELTA_NODES.value, _subst._DELTA_SKIPPED.value,
        _subst._INDEX_SKIPS.value, _subst._VEC_SKIPS.value,
        _worker_batches(),
    )
    t_cal = 0.0  # seconds spent probing/persisting calibration — split
    # out of the reported search time (bench satellite: the two were
    # conflated in one search_seconds number)
    n = config.search_devices
    calibration = load_calibration(config)
    target = config.machine_spec.platform
    if calibration is not None and calibration.backend not in (None, target):
        # measured records are only coherent with a simulator whose
        # machine model describes the backend they were probed on —
        # e.g. CPU dense milliseconds would poison a TPU-modeled search
        # (searching a TPU strategy FROM a CPU host with a TPU-probed
        # table is fine: the reference's search-on-small-machine
        # pattern, graph.cc:1535-1540)
        log.log(
            f"ignoring calibration probed on {calibration.backend!r} "
            f"(machine model is {config.machine_spec.name!r})"
        )
        BUS.emit("calibration.ignored", backend=calibration.backend,
                 machine=config.machine_spec.name)
        calibration = None
    reprobe = False
    if calibration is not None and getattr(calibration, "stale", False):
        # automatic re-probe policy (ROADMAP PR 2 follow-up): a
        # DriftReport flagged this table stale (measured steps drifted
        # past --drift-threshold).  When the live backend matches the
        # machine model, RE-PROBE instead of only warning — drop the
        # drifted records and measure fresh inside the calibration
        # budget; otherwise the stale table must not keep seeding
        # searches, so fall back to the analytic roofline.
        import jax

        live = jax.devices()[0].platform
        ratio = getattr(calibration, "stale_ratio", None)
        attempts = getattr(calibration, "reprobes", 0)
        cap = getattr(type(calibration), "MAX_AUTO_REPROBES", 2)
        if attempts >= cap:
            # re-probing keeps reproducing the same drift: the gap is
            # in the cost MODEL, not the measurements — stop burning
            # the calibration budget every compile and fall back to
            # the roofline (a healthy calibrated fit resets the count)
            log.log(
                f"calibration table still drift-stale after {attempts} "
                f"auto re-probes (measured/predicted "
                f"{ratio if ratio else '?'}): persistent cost-model "
                f"gap — using the analytic roofline; re-probe manually "
                f"with --calibrate if the machine changed"
            )
            BUS.emit("calibration.reprobe", backend=live, ratio=ratio,
                     deferred=True, attempts=attempts)
            calibration = None
        elif live == target:
            log.log(
                f"calibration table is drift-stale "
                f"(measured/predicted {ratio if ratio else '?'}): "
                f"re-probing on the live backend "
                f"(attempt {attempts + 1}/{cap})"
            )
            BUS.emit("calibration.reprobe", backend=live, ratio=ratio,
                     deferred=False, attempts=attempts)
            calibration.begin_reprobe()
            reprobe = True
        else:
            log.log(
                f"calibration table is drift-stale but the live backend "
                f"({live!r}) cannot re-probe for "
                f"{config.machine_spec.name!r}: using the analytic "
                f"roofline until a re-probe runs on the modeled backend"
            )
            BUS.emit("calibration.reprobe", backend=live, ratio=ratio,
                     deferred=True)
            calibration = None
    can_probe = False
    if config.calibrate or reprobe:
        # probe this graph's (op, view) costs on the live backend before
        # ranking — the reference's default (it measures lazily inside
        # the search, simulator.cc:515-554; model.cu:38-74).  Probes
        # resume from the loaded table; with calibration_file set they
        # persist, so repeat compiles pay nothing.
        import jax

        live = jax.devices()[0].platform
        can_probe = live == target
        if not can_probe:
            log.log(
                f"calibrate requested but the live backend ({live!r}) "
                f"does not match the machine model "
                f"({config.machine_spec.name!r}): keeping the analytic "
                f"roofline.  Probe on the modeled backend and pass "
                f"--calibration-file instead."
            )
        else:
            from flexflow_tpu.search.calibration import calibrate_graph

            with log.enter(
                f"calibrating (op, view) costs on the live backend "
                f"(budget {config.calibration_budget_s:.0f}s)"
            ):
                t0 = time.monotonic()
                calibration = calibrate_graph(
                    graph, n, calibration,
                    time_budget_s=config.calibration_budget_s)
                t_cal += time.monotonic() - t0
                log.log(f"{len(calibration)} measured records")
            if config.calibration_file:
                calibration.save(config.calibration_file)
    serving = None
    if getattr(config, "objective", "train") == "serve":
        # serving objective (search/serving.py): derive the arrival
        # model from the graph's own decode ops and arm it at SIM
        # CONSTRUCTION (before the cost cache computes its signature) —
        # the whole search then ranks in the p99 decode-latency
        # currency.  A serve search of a graph with no decode ops
        # degenerates to train pricing; say so instead of silently
        # renaming the objective.
        if config.comp_mode != "inference":
            # a decode step runs no backward and no gradient sync:
            # pricing the p99 currency with training costs would mint
            # an SLO number for a step that never executes — refuse
            # loudly (the same discipline as the serve+co_search guard)
            raise ValueError(
                "objective='serve' requires comp_mode='inference' "
                "(set FFConfig.comp_mode or pass "
                "model.compile(comp_mode='inference')): a decode step "
                "has no backward, so the training currency would price "
                "an SLO the serving step never runs")
        from flexflow_tpu.search.serving import serving_spec_for

        serving = serving_spec_for(graph, config)
        if serving is None:
            log.log(
                "objective='serve' on a graph with no decode-attention "
                "ops: nothing is ragged here — pricing falls back to "
                "the train (mean step) currency"
            )
    sim = Simulator.for_config(config, calibration=calibration,
                               serving=serving)
    floor_sim = sim  # the sim the champion-vs-DP floor must score with
    helper = SearchHelper(sim, n)
    joint = None
    if getattr(config, "co_search", False):
        # joint strategy x comm-plan co-search (search/comm_plan.py):
        # bind one comm-plan memo to this search — every candidate the
        # helper or the unity loop grounds is then priced with its
        # best sync schedule/precision/zero plan through the
        # exposed-comm simulation instead of the legacy per-node
        # overlap credit
        from flexflow_tpu.search.comm_plan import JointPricer

        joint = JointPricer(config, cost_cache=sim.cost_cache)
        helper.joint = joint

    def _price(s, g, st):
        """Candidate grounding in the search's currency: joint
        exposed-comm under co-search, legacy scalar otherwise."""
        if joint is not None:
            return joint.price(s, g, st)
        return s.simulate(g, st)

    BUS.emit(
        "search.begin", nodes=graph.num_nodes, devices=n,
        budget=config.search_budget, timeout_s=config.search_timeout_s,
        calibrated=calibration is not None,
    )

    # persistent search-result cache: the search is a deterministic
    # pure function of (graph structure, knobs, cost surface), so a
    # warm cache serves the finished (graph, strategy) — bench sweeps,
    # CI, and repeat compiles skip the whole search
    cache = sim.cost_cache
    if cache is not None and return_graph:
        served = _serve_cached_search(cache, graph, config)
        if served is not None:
            best_graph, best_strategy, best_cost = served
            # gate the served result on the same static analysis the
            # fresh search passes: a corrupt pickled graph or an
            # illegal strategy must cost one recompute, not be reused
            # forever (the PR-3 cache serves whole search results)
            bad = _lint_findings(best_graph, best_strategy, n)
            if bad:
                from flexflow_tpu.analysis import emit_findings

                emit_findings(bad)
                log.log(
                    f"cost cache: served search result FAILED the "
                    f"static-analysis gate ({bad[0]}); dropping the "
                    f"entry and searching fresh"
                )
                cache.drop_search_result(graph, config)
                served = None
        if served is not None and serving is not None:
            # serve objective: served artifacts pass the SAME always-on
            # SHD16x serving gate as fresh results — an over-budget or
            # geometry-incoherent entry costs one re-search, never an
            # illegal serve
            from flexflow_tpu.analysis import (
                emit_findings,
                errors_only,
                lint_serving,
            )

            sfind = lint_serving(best_graph, best_strategy, serving,
                                 floor_sim.cost,
                                 predicted_p99_s=best_cost)
            emit_findings(sfind)
            sbad = errors_only(sfind)
            if sbad:
                log.log(
                    f"cost cache: served search result FAILED the "
                    f"serving gate ({sbad[0]}); dropping the entry and "
                    f"searching fresh"
                )
                cache.drop_search_result(graph, config)
                served = None
        _served_kv_meta = None
        if served is not None and serving is not None:
            # KV lane (kv_precision / shared-prefix residency): served
            # results pass the SAME always-on SHD168/169 gate as fresh
            # ones before the provenance block is recorded — a served
            # entry that cannot carry a legal __meta__.kv costs one
            # re-search, never an illegal artifact
            _served_kv_meta = _choose_kv_precision(
                best_graph, best_strategy, config, serving, calibration)
            if _served_kv_meta is not None:
                from flexflow_tpu.analysis import (
                    emit_findings,
                    errors_only,
                    lint_kv,
                )

                kfind = lint_kv(best_graph, best_strategy,
                                _served_kv_meta, serving=serving)
                emit_findings(kfind)
                kbad = errors_only(kfind)
                if kbad:
                    log.log(
                        f"cost cache: served search result FAILED the "
                        f"KV-lane gate ({kbad[0]}); dropping the entry "
                        f"and searching fresh"
                    )
                    cache.drop_search_result(graph, config)
                    served = None
        if served is not None:
            log.log(
                f"cost cache: served searched strategy "
                f"({best_cost * 1e3:.4f} ms/iter) for {graph.num_nodes}-"
                f"node graph — skipping the search"
            )
            LAST_SERVING_META = None
            LAST_KV_META = _served_kv_meta
            if serving is not None:
                from flexflow_tpu.search.serving import kv_residency_bytes

                LAST_SERVING_META = {
                    "objective": "serve",
                    "p99_budget_ms": serving.p99_budget_ms,
                    "max_seqs": serving.max_seqs,
                    "page_size": serving.page_size,
                    "pages_per_seq": serving.pages_per_seq,
                    "quantile": serving.quantile,
                    "predicted_p99_step_ms": round(best_cost * 1e3, 6),
                    "kv_bytes_per_device": kv_residency_bytes(
                        best_graph, best_strategy, n, serving=serving),
                }
            _emit_search_done(
                floor_sim, best_graph, graph, best_strategy, best_cost,
                kept_dp=False, helper=helper, t_start=t_start,
                t_cal=t_cal, result_cache_hit=True,
                match_base=match_base,
            )
            # cache-served results pass the SAME schedule choice + gate
            # as fresh ones — the persisted artifact never skips it
            _build_sync_schedule(best_graph, best_strategy, sim, config,
                                 joint=joint)
            return best_graph, best_strategy
    with log.enter(f"optimize_strategy: {graph.num_nodes} nodes, {n} devices"):
        if (return_graph and config.search_budget > 0
                and graph.num_nodes > CHAIN_MIN_NODES):
            # production scale: the flat whole-graph DP recursion is
            # super-linear past the native engine's ceiling (a 1014-node
            # GPT did not finish it in 880 s).  Seed with the batch-
            # parallel floor; the chain decomposition inside the unity
            # loop carries the real per-segment DP, and the champion-
            # vs-DP floor below still gates the final answer.
            from flexflow_tpu.compiler.lowering import (
                data_parallel_strategy as _dps,
            )

            best_strategy = _dps(graph, n)
            best_cost = _price(sim, graph, best_strategy)
            log.log(
                f"baseline data-parallel cost: {best_cost * 1e3:.4f} "
                f"ms/iter (whole-graph DP deferred to the segment "
                f"chain search at this scale)")
        else:
            best_cost, best_strategy = helper.graph_cost(graph)
            log.log(f"baseline DP-search cost: {best_cost * 1e3:.4f} ms/iter")
    BUS.emit("search.baseline", cost_s=best_cost)
    best_graph = graph
    search_expired = False

    if return_graph and config.search_budget > 0:
        xfers = _load_xfers(config, n)
        deadline = (
            time.monotonic() + config.search_timeout_s
            if config.search_timeout_s > 0
            else None
        )
        opt = _UnityOptimizer(helper, config, xfers, deadline=deadline)
        with _relaxed_gc(), log.enter(f"unity outer loop: {len(xfers)} xfers"):
            opt._score_edges(graph)
            g2, c2, s2 = opt.sequence_optimize(graph, {})
            if (c2 < best_cost and s2 and can_probe
                    and calibration is not None and g2 is not graph):
                # rewrites can introduce ops the pre-rewrite probe pass
                # never measured; comparing measured originals (lone-op
                # probes are upper bounds) against roofline rewrites
                # (optimistic) biases acceptance toward rewrites.  Probe
                # the rewritten graph's new (op, view)s — inside the
                # remaining --search-timeout budget — and re-SCORE both
                # candidate (graph, strategy) pairs with the same table
                # before accepting (a bounded re-simulation, not two
                # fresh full searches).
                from flexflow_tpu.search.calibration import calibrate_graph

                budget = config.calibration_budget_s
                if deadline is not None:
                    budget = min(budget, max(0.0, deadline - time.monotonic()))
                n_before = len(calibration)
                ncl_before = calibration.num_clusters
                if budget > 0:
                    t0 = time.monotonic()
                    calibrate_graph(g2, n, calibration, time_budget_s=budget)
                    t_cal += time.monotonic() - t0
                if (len(calibration) > n_before
                        or calibration.num_clusters > ncl_before):
                    # cluster-only growth counts: a rewrite with fully
                    # pre-measured (op, view)s can still gain fusion-
                    # chain records, which simulate() consults
                    log.log(
                        f"probed {len(calibration) - n_before} rewritten-"
                        f"graph records + "
                        f"{calibration.num_clusters - ncl_before} clusters; "
                        f"re-scoring on equal footing"
                    )
                    if config.calibration_file:
                        calibration.save(config.calibration_file)
                    sim2 = Simulator.for_config(config, calibration=calibration,
                                                serving=serving)
                    floor_sim = sim2  # sim's _node_costs cache predates
                    # the new probes; the floor must not mix tables
                    best_cost = _price(sim2, graph, best_strategy)
                    c2 = _price(sim2, g2, s2)
            if c2 < best_cost and s2:
                log.log(
                    f"substitution improved: {best_cost * 1e3:.4f}"
                    f" -> {c2 * 1e3:.4f} ms/iter"
                )
                best_cost, best_strategy, best_graph = c2, s2, g2
            search_expired = opt._expired()

    # Champion-vs-DP floor: the simulator's fidelity is finite, so a
    # predicted win below the uncertainty margin is noise — and executing
    # a mixed-view strategy for a noise-level win pays real GSPMD
    # resharding that plain DP never pays.  DP is always in the search
    # space, so this can only replace a sub-margin champion, never a
    # genuine winner (the osdi22ae-class wins predict 1.2x-790x).
    from flexflow_tpu.compiler.lowering import data_parallel_strategy

    dp_strategy = data_parallel_strategy(graph, n)
    dp_cost = _price(floor_sim, graph, dp_strategy)
    margin = max(0.0, config.search_improvement_margin)
    kept_dp = math.isfinite(dp_cost) and best_cost > dp_cost * (1.0 - margin)
    BUS.emit("search.floor", kept_dp=kept_dp, dp_cost_s=dp_cost,
             searched_cost_s=best_cost, margin=margin)
    if kept_dp:
        log.log(
            f"searched win {(1.0 - best_cost / dp_cost) * 100:.2f}% is "
            f"below the {margin * 100:.0f}% uncertainty margin: "
            f"keeping plain data parallelism"
        )
        best_cost, best_strategy, best_graph = dp_cost, dp_strategy, graph

    # static-analysis gate (flexflow_tpu/analysis): the returned (graph,
    # strategy) must pass graph invariants + the sharding legality lint
    # BEFORE it is persisted or handed to the lowering.  A failure here
    # is a search bug, not a user error — fail loudly instead of letting
    # the cost cache serve a corrupt result forever.  Non-finite results
    # (nothing feasible fits) are deliberately NOT fatal: compile's
    # staged-pipeline fallback consumes them — findings are still
    # emitted and logged so the drift is visible.
    bad = _lint_findings(best_graph, best_strategy, n) if best_strategy \
        else []
    if bad:
        from flexflow_tpu.analysis import AnalysisError, emit_findings

        emit_findings(bad)
        if math.isfinite(best_cost):
            raise AnalysisError(
                "optimize_strategy produced an illegal (graph, strategy) "
                "pair", bad)
        log.log(
            f"static analysis: infeasible search result also fails the "
            f"legality lint ({bad[0]}); returning it for the compile "
            f"fallbacks, NOT persisting"
        )

    # serving gate (objective="serve", always-on like the strategy
    # lint above): the result must be a LEGAL serving artifact — frame
    # geometry coherent with the spec, KV residency within HBM, decode
    # views the executor's fixed frames can shard (SHD160-162; SHD163
    # warns on a blown SLO) — before it is returned or persisted.
    LAST_SERVING_META = None
    LAST_KV_META = None
    if serving is not None and best_strategy and math.isfinite(best_cost):
        from flexflow_tpu.analysis import (
            AnalysisError,
            emit_findings,
            errors_only,
            lint_kv,
            lint_serving,
        )
        from flexflow_tpu.search.serving import kv_residency_bytes

        sfind = lint_serving(best_graph, best_strategy, serving,
                             floor_sim.cost, predicted_p99_s=best_cost)
        emit_findings(sfind)
        sbad = errors_only(sfind)
        if sbad:
            raise AnalysisError(
                "serve-objective search produced an illegal serving "
                "artifact", sbad)
        kv = kv_residency_bytes(best_graph, best_strategy, n,
                                serving=serving)
        LAST_SERVING_META = {
            "objective": "serve",
            "p99_budget_ms": serving.p99_budget_ms,
            "max_seqs": serving.max_seqs,
            "page_size": serving.page_size,
            "pages_per_seq": serving.pages_per_seq,
            "quantile": serving.quantile,
            "predicted_p99_step_ms": round(best_cost * 1e3, 6),
            "kv_bytes_per_device": kv,
        }
        BUS.emit("search.serve", p99_s=best_cost,
                 budget_ms=serving.p99_budget_ms,
                 kv_bytes_per_device=kv, kept_dp=kept_dp)
        # KV lane (kv_precision / shared-prefix residency): choose the
        # pool dtype in the same p99 currency and gate the provenance
        # block on SHD168/169 — always-on, like the serving gate above
        LAST_KV_META = _choose_kv_precision(
            best_graph, best_strategy, config, serving, calibration)
        if LAST_KV_META is not None:
            kfind = lint_kv(best_graph, best_strategy, LAST_KV_META,
                            serving=serving)
            emit_findings(kfind)
            kbad = errors_only(kfind)
            if kbad:
                LAST_KV_META = None
                raise AnalysisError(
                    "KV-precision lane produced an illegal __meta__.kv "
                    "artifact", kbad)

    # persist: cost rows accumulated this search + the finished result
    # (only complete searches — a deadline-truncated result is not the
    # pure function's value and must not be served forever)
    cache = floor_sim.cost_cache
    if cache is not None:
        if (return_graph and not search_expired and math.isfinite(best_cost)
                and not bad):
            payload = (
                [nd.guid for nd in graph.topo_order()],
                best_graph if best_graph is not graph else None,
                dict(best_strategy),
                best_cost,
            )
            cache.put_search_result(graph, config, payload, best_cost)
        cache.save()

    _emit_search_done(
        floor_sim, best_graph, graph, best_strategy, best_cost,
        kept_dp=kept_dp, helper=helper, t_start=t_start, t_cal=t_cal,
        result_cache_hit=False, match_base=match_base,
    )

    if best_strategy and math.isfinite(best_cost):
        _build_sync_schedule(best_graph, best_strategy, floor_sim, config,
                             joint=joint)
    else:
        global LAST_SYNC_SCHEDULE, LAST_ZERO_GROUPS
        LAST_SYNC_SCHEDULE = None
        LAST_ZERO_GROUPS = ()

    if return_graph:
        return best_graph, best_strategy
    return best_strategy


def _emit_search_done(
    floor_sim, best_graph, graph, best_strategy, best_cost, kept_dp,
    helper, t_start, t_cal, result_cache_hit, match_base=(0, 0, 0, 0, 0),
) -> None:
    """Search-completion telemetry: the final result/summary events
    plus the search-perf roll-up (delta-vs-full simulation counts,
    delta-matching rescan shrink, and persistent-cache hit rates) that
    bench_search and ffobs report."""
    from flexflow_tpu.search import substitution as _subst

    sim = helper.sim
    cache = floor_sim.cost_cache or sim.cost_cache
    stats = {
        "search_seconds": round(
            max(0.0, time.monotonic() - t_start - t_cal), 3),
        "calibration_seconds": round(t_cal, 3),
        "full_sims": sim.full_sims + (
            floor_sim.full_sims if floor_sim is not sim else 0),
        "delta_sims": sim.delta_sims + (
            floor_sim.delta_sims if floor_sim is not sim else 0),
        "delta_bails": sim.delta_bails + (
            floor_sim.delta_bails if floor_sim is not sim else 0),
        # delta-aware find_matches (ROADMAP PR 3 follow-up): full-scan
        # calls vs dirty-region rescans, and the node-visit shrink the
        # rescans bought (skipped = clean nodes served from the parent)
        "match_full_scans": _subst._SCANS.value - match_base[0],
        "match_delta_scans": _subst._DELTA_SCANS.value - match_base[1],
        "match_nodes_rescanned": _subst._DELTA_NODES.value - match_base[2],
        "match_nodes_skipped": _subst._DELTA_SKIPPED.value - match_base[3],
        # per-op-type seed index (ROADMAP PR 7 follow-up): matcher
        # calls skipped because the node's op type cannot anchor the
        # xfer's pattern
        "match_index_skips": _subst._INDEX_SKIPS.value - (
            match_base[4] if len(match_base) > 4 else 0),
        "cache_row_hits": cache.row_hits if cache else 0,
        "cache_row_misses": cache.row_misses if cache else 0,
        "result_cache_hit": bool(result_cache_hit),
        # segment-reuse mechanics (ROADMAP item 3): incremental native
        # ctx assembly, persisted DP memo rows, and isomorphic-segment
        # stamping — the counters the scale sweep and ffobs report
        "ctx_patch_hits": helper.ctx_patch_hits,
        "ctx_rebuilds": helper.ctx_rebuilds,
        "segments_stamped": helper.segments_stamped,
        "dp_rows_served": helper.dp_rows_served,
        "dp_memo_hits": helper.memo_hits,
        "dp_memo_misses": helper.memo_misses,
        # series-parallel decomposition (ROADMAP item 4): which
        # decomposition each oversized (sub)graph took, the bounded-
        # width cut counts, and the sp-memo-row serves — the counters
        # the --sp-scale sweep and ffobs report
        "sp_rows_served": helper.sp_rows_served,
        "match_vec_skips": _subst._VEC_SKIPS.value - (
            match_base[5] if len(match_base) > 5 else 0),
        "match_worker_batches": _worker_batches() - (
            match_base[6] if len(match_base) > 6 else 0),
        **LAST_DECOMPOSE,
    }
    if helper.joint is not None:
        # joint strategy x comm-plan co-search: how often the candidate
        # pricing SERVED a memoized plan vs paid the full
        # choose_sync_schedule sweep (the ≥80% serve-rate acceptance
        # gate reads exactly these)
        stats["comm_plan_serves"] = helper.joint.serves
        stats["comm_plan_searches"] = helper.joint.searches
    LAST_SEARCH_STATS.clear()
    LAST_SEARCH_STATS.update(stats)
    if not BUS.enabled:
        return
    BUS.emit(
        "search.result", cost_s=best_cost,
        rewritten=best_graph is not graph,
        nodes=best_graph.num_nodes, kept_dp=kept_dp,
        table=floor_sim.strategy_table_rows(best_graph, best_strategy),
    )
    BUS.emit(
        "dp.summary", memo_hits=helper.memo_hits,
        memo_misses=helper.memo_misses,
        native_hits=helper.native_hits,
        greedy_hits=helper.greedy_hits,
    )
    BUS.emit("search.perf", **stats)


def mcmc_optimize(
    graph: Graph,
    config: FFConfig,
    iterations: int = 500,
    temperature: float = 0.05,
    seed: int = 0,
) -> Strategy:
    """Legacy MLSys'19 search: random single-op view rewrites, accepted
    if better or with prob exp(-alpha*delta)
    (reference: model.cc:3033-3122 rewrite/mcmc_optimize)."""
    from flexflow_tpu.search.views import candidate_views

    n = config.search_devices
    sim = Simulator.for_config(config)
    rng = random.Random(seed)
    nodes = graph.topo_order()

    from flexflow_tpu.compiler.lowering import data_parallel_strategy

    current = dict(data_parallel_strategy(graph, n))
    cur_cost = sim.simulate(graph, current)
    best, best_cost = dict(current), cur_cost
    # single-op rewrites on a fixed graph are the ideal delta-simulation
    # case: each proposal perturbs one node (plus its consumers' edge
    # xfers), so re-cost rides the armed baseline; re-arm on accept
    sim.set_baseline(graph, current)
    for _ in range(iterations):
        node = rng.choice(nodes)
        if node.op.fixed_machine_view() is not None:
            continue
        views = candidate_views(node.op, n)
        v = rng.choice(views)
        old = current.get(node.guid)
        current[node.guid] = v
        c = sim.simulate(graph, current)
        delta = c - cur_cost
        if delta <= 0 or rng.random() < math.exp(-delta / max(temperature * cur_cost, 1e-12)):
            cur_cost = c
            sim.set_baseline(graph, current)
            if c < best_cost:
                best, best_cost = dict(current), c
        else:
            if old is None:
                current.pop(node.guid, None)
            else:
                current[node.guid] = old
    return best
