"""Graph substitutions — Unity's outer loop rewrites.

Re-implements the GraphXfer machinery (reference:
src/runtime/substitution.cc:491-760 find_matches/run;
:1619-1758 generate_all_pcg_xfers) as first-class rewrite objects:
a matcher over PCG nodes plus an apply() that produces a new Graph
with parallel ops inserted/removed.

Note on expressiveness: in this framework the DP assigns partition
degrees directly, so the classic "partition_X_combine" xfers do not
*enable* parallelism (they make data movement explicit instead of
implicit GSPMD resharding).  They are kept because (a) explicit
movement nodes give the search control over WHERE resharding happens
(e.g. combine early while the tensor is small), and (b) the
simplification xfers (fusing/cancelling adjacent parallel ops,
reference: parallel_op.cc:25-58 join algebra) clean up searched graphs.
"""

from __future__ import annotations

import weakref
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from flexflow_tpu.analysis import invariants as _invariants
from flexflow_tpu.core.graph import Edge, Graph, Node
from flexflow_tpu.core.optype import OperatorType
from flexflow_tpu.core.ptensor import ParallelTensorShape
from flexflow_tpu.obs.metrics import METRICS
from flexflow_tpu.parallel.parallel_ops import (
    CombineOp,
    ReductionOp,
    RepartitionOp,
    ReplicateOp,
)

Match = Node

# obs telemetry: match-machinery volume (the per-candidate accept/
# reject provenance is emitted by the driver, which owns the decision)
_SCANS = METRICS.counter("substitution.find_matches_calls")
_MATCHES = METRICS.counter("substitution.matches_found")
_APPLIES = METRICS.counter("substitution.applies")
# delta-aware matching (ROADMAP PR 3 follow-up): per-pop rescans of the
# DIRTY REGION only — these counters prove the shrink (search.perf)
_DELTA_SCANS = METRICS.counter("substitution.delta_match_calls")
_DELTA_NODES = METRICS.counter("substitution.delta_match_nodes_scanned")
_DELTA_SKIPPED = METRICS.counter("substitution.delta_match_nodes_skipped")
# per-op-type seed index (ROADMAP PR 7 follow-up): matcher calls skipped
# because the node's op type cannot anchor the pattern (search.perf
# match_index_skips) — at thousand-node scale candidate generation is
# the dominant per-pop cost, and most of it was matchers returning
# False on the very first op-type check
_INDEX_SKIPS = METRICS.counter("substitution.match_index_skips")
# vectorized matcher core (ROADMAP item 4): anchor-typed candidates
# additionally pruned by numpy predicate columns (divisibility,
# predecessor/successor op-type guards) BEFORE the python matcher runs
# — the matcher confirms survivors, so the filter only has to be a
# sound superset, and the FLEXFLOW_TPU_DELTA_CHECK full-scan oracle
# proves it per xfer
_VEC_SKIPS = METRICS.counter("substitution.match_vec_skips")

# below this candidate count the numpy mask costs more than the
# matcher calls it saves — zoo-scale graphs keep the exact PR 7 path
VEC_MIN_CANDS = 16

# how many undirected hops around the changed-guid seed sets a rescan
# covers.  Every built-in matcher reads only its node's edge lists plus
# properties of DIRECT neighbors (their op attrs — immutable per guid —
# and their edge-list lengths), so radius 1 is sufficient; 2 is the
# safety margin for future matchers.  The FLEXFLOW_TPU_DELTA_CHECK
# oracle asserts delta == full at runtime.
DELTA_MATCH_RADIUS = 2


def _delta_check_enabled() -> bool:
    import os

    return os.environ.get("FLEXFLOW_TPU_DELTA_CHECK", "") not in ("", "0")


DELTA_MATCH_CHECK = _delta_check_enabled()


def _op_type_index(graph: Graph):
    """``(op type -> topo-ordered node list, guid -> topo position)``
    for ``graph``, cached on the graph instance keyed by the identity
    of its ``topo_order()`` list — any structural change invalidates
    the topo cache (``Graph._invalidate``), so a fresh topo list means
    a fresh index; COW clones start without the attribute and build
    their own.  One O(nodes) sweep amortized over every anchor-typed
    xfer's ``find_matches`` on this graph."""
    topo = graph.topo_order()
    cached = getattr(graph, "_op_type_index", None)
    if cached is not None and cached[0] is topo:
        return cached[1], cached[2]
    idx: Dict[OperatorType, List[Node]] = {}
    pos: Dict[int, int] = {}
    for i, n in enumerate(topo):
        idx.setdefault(n.op.op_type, []).append(n)
        pos[n.guid] = i
    graph._op_type_index = (topo, idx, pos)
    return idx, pos


def _match_columns(graph: Graph):
    """Per-node numpy predicate columns over the topo order — the
    vectorized matcher core's shared input.  One O(nodes + edges)
    python sweep, cached on the graph instance keyed by the identity of
    its ``topo_order()`` list (the ``_op_type_index`` discipline: any
    structural change invalidates the topo cache, so a fresh topo list
    means fresh columns), then every anchor-typed xfer's
    ``vec_filter`` is pure numpy over row slices.  Columns cover the
    cheap checks every factory matcher leads with: output-dim sizes
    (divisibility), in/out edge counts, distinct-successor counts, and
    the predecessor/successor op-type guards."""
    topo = graph.topo_order()
    cached = getattr(graph, "_match_cols", None)
    if cached is not None and cached[0] is topo:
        return cached[1]
    import numpy as np

    n = len(topo)
    max_nd = 1
    for node in topo:
        nd = len(node.op.output_shapes[0].sizes)
        if nd > max_nd:
            max_nd = nd
    ndim = np.zeros(n, dtype=np.int64)
    sizes = np.zeros((n, max_nd), dtype=np.int64)
    n_in = np.zeros(n, dtype=np.int64)
    n_out = np.zeros(n, dtype=np.int64)
    n_succ = np.zeros(n, dtype=np.int64)
    max_replica = np.zeros(n, dtype=np.int64)
    pred_has_repartition = np.zeros(n, dtype=bool)
    pred_has_replicate = np.zeros(n, dtype=bool)
    pred_all_combine = np.zeros(n, dtype=bool)
    succ_all_parallel = np.zeros(n, dtype=bool)
    succ_all_repartition = np.zeros(n, dtype=bool)
    succ_has_combine = np.zeros(n, dtype=bool)
    succ_has_act = np.zeros(n, dtype=bool)
    act_is_none = np.zeros(n, dtype=bool)
    in_edges, out_edges, nodes = graph.in_edges, graph.out_edges, graph.nodes
    T = OperatorType
    for i, node in enumerate(topo):
        op = node.op
        sz = op.output_shapes[0].sizes
        ndim[i] = len(sz)
        sizes[i, :len(sz)] = sz
        g = node.guid
        ie, oe = in_edges[g], out_edges[g]
        n_in[i] = len(ie)
        n_out[i] = len(oe)
        max_replica[i] = op.max_replica_degree()
        act_is_none[i] = getattr(op, "attrs", {}).get("activation") is None
        all_comb = bool(ie)
        for e in ie:
            pt = nodes[e.src].op.op_type
            if pt is T.REPARTITION:
                pred_has_repartition[i] = True
            elif pt is T.REPLICATE:
                pred_has_replicate[i] = True
            if pt is not T.COMBINE:
                all_comb = False
        pred_all_combine[i] = all_comb
        all_par = all_rep = bool(oe)
        succs = set()
        for e in oe:
            succs.add(e.dst)
            st = nodes[e.dst].op.op_type
            if st is T.COMBINE:
                succ_has_combine[i] = True
            if st in _FUSABLE_ACTS:
                succ_has_act[i] = True
            if not st.is_parallel_op():
                all_par = False
            if st is not T.REPARTITION:
                all_rep = False
        n_succ[i] = len(succs)
        succ_all_parallel[i] = all_par
        succ_all_repartition[i] = all_rep
    cols = {
        "ndim": ndim, "sizes": sizes, "n_in": n_in, "n_out": n_out,
        "n_succ": n_succ, "max_replica": max_replica,
        "pred_has_repartition": pred_has_repartition,
        "pred_has_replicate": pred_has_replicate,
        "pred_all_combine": pred_all_combine,
        "succ_all_parallel": succ_all_parallel,
        "succ_all_repartition": succ_all_repartition,
        "succ_has_combine": succ_has_combine,
        "succ_has_act": succ_has_act,
        "act_is_none": act_is_none,
    }
    graph._match_cols = (topo, cols)
    return cols


def _mark(g: Graph, ins=(), outs=()) -> None:
    """Record which guids a rewrite perturbed on the working graph:
    ``ins`` = nodes whose in-edge list changed (every NEW node guid
    must appear here), ``outs`` = nodes whose out-edge list changed.
    Supersets are safe — the delta simulator only does extra work for
    over-marked nodes, never returns a different float."""
    touched = getattr(g, "_delta_touched", None)
    if touched is None:
        touched = (set(), set())
        g._delta_touched = touched
    touched[0].update(ins)
    touched[1].update(outs)


def _finish_rewrite(parent: Graph, g: Optional[Graph],
                    name: Optional[str] = None) -> Optional[Graph]:
    """Promote the working-graph touched sets into the changed-guid
    annotation delta consumers read (``g._changed_vs`` = parent weakref
    + changed-in/changed-out guid frozensets) — the dirty-frontier seed
    the delta simulator and the delta graph hash both key on.  Rewrites
    built outside this module (substitution_loader JSON rules) carry no
    sets; consumers fall back to a structural diff.

    Under verification (``FLEXFLOW_TPU_VERIFY=1`` / ``--verify``) every
    rewrite result passes the full graph-invariant check here — the ONE
    chokepoint all ``GraphXfer.apply`` paths flow through — so a splice
    that leaves a dangling edge, a doubly-fed slot, or a shape
    disagreement with re-inference fails loudly at the rewrite, not
    three layers later in a simulated cost."""
    if g is None:
        return None
    touched = getattr(g, "_delta_touched", None)
    if touched is not None:
        g._changed_vs = (
            weakref.ref(parent), frozenset(touched[0]), frozenset(touched[1])
        )
    if _invariants.verification_enabled():
        _invariants.assert_graph_ok(
            g, context=f"after rewrite {name or 'unnamed'!r}")
    return g


@dataclass
class GraphXfer:
    """A rewrite: match a node, produce a rewritten graph.

    ``anchor_types`` — the op types a match can ANCHOR on (the matcher
    provably returns False for every other type, because its first
    check is the type test).  When set, ``find_matches`` consults the
    per-op-type seed index instead of calling the matcher on every
    node: only nodes whose type can anchor the pattern are scanned,
    the rest count into ``match_index_skips``.  ``None`` (rewrites
    built outside this module, e.g. substitution_loader JSON rules
    whose matcher shape is unknown) keeps the full scan.  Identity
    with the unindexed scan is asserted under FLEXFLOW_TPU_DELTA_CHECK.
    """

    name: str
    matcher: Callable[[Graph, Node], bool]
    apply_fn: Callable[[Graph, Node], Optional[Graph]]
    anchor_types: Optional[frozenset] = None
    # vectorized candidate filter: ``vec_filter(cols, rows) -> bool
    # mask`` over ``_match_columns`` row indices.  A SOUND SUPERSET of
    # the matcher (never drops a true match — the matcher still
    # confirms every survivor); factories derive it from the same
    # predicates their matcher leads with, and the DELTA_CHECK oracle
    # asserts indexed+filtered == full scan.
    vec_filter: Optional[Callable] = None

    def _vec_prune(self, graph: Graph, cands: List[Match],
                   pos) -> List[Match]:
        if self.vec_filter is None or len(cands) < VEC_MIN_CANDS:
            return cands
        import numpy as np

        cols = _match_columns(graph)
        rows = np.fromiter((pos[n.guid] for n in cands),
                           dtype=np.int64, count=len(cands))
        mask = self.vec_filter(cols, rows)
        kept = [n for n, k in zip(cands, mask) if k]
        _VEC_SKIPS.inc(len(cands) - len(kept))
        return kept

    def find_matches(self, graph: Graph) -> List[Match]:
        _SCANS.inc()
        if self.anchor_types is None:
            out = [n for n in graph.topo_order() if self.matcher(graph, n)]
        else:
            idx, pos = _op_type_index(graph)
            cands: List[Node] = []
            for t in self.anchor_types:
                cands.extend(idx.get(t, ()))
            if len(self.anchor_types) > 1:
                # per-type lists are topo-ordered; a multi-type anchor
                # set needs the merged topo order the full scan yields
                cands.sort(key=lambda n: pos[n.guid])
            _INDEX_SKIPS.inc(len(pos) - len(cands))
            cands = self._vec_prune(graph, cands, pos)
            out = [n for n in cands if self.matcher(graph, n)]
            if DELTA_MATCH_CHECK:
                full = [n for n in graph.topo_order()
                        if self.matcher(graph, n)]
                assert [n.guid for n in out] == [n.guid for n in full], (
                    f"indexed find_matches diverged from the full scan "
                    f"for {self.name}: the declared anchor_types "
                    f"{sorted(t.value for t in self.anchor_types)} do "
                    f"not cover the matcher"
                )
        if out:
            _MATCHES.inc(len(out))
        return out

    def find_matches_delta(
        self, graph: Graph, parent_match_guids: Optional[List[int]]
    ) -> List[Match]:
        """Matches of ``graph`` computed incrementally from its rewrite
        parent's matches: only the DIRTY REGION — the changed-guid seed
        sets ``GraphXfer.apply`` attached (``graph._changed_vs``),
        expanded ``DELTA_MATCH_RADIUS`` undirected hops — is rescanned;
        a parent match surviving OUTSIDE that region still matches (the
        matcher reads only its local neighborhood, all of it unchanged)
        and a parent non-match outside it still does not.  Identical
        result to ``find_matches``, in the same topo order — asserted
        at runtime under FLEXFLOW_TPU_DELTA_CHECK=1.  Falls back to the
        full scan when no parent matches or seed sets are available
        (ROADMAP PR 3 follow-up: delta-aware find_matches)."""
        cv = getattr(graph, "_changed_vs", None)
        if parent_match_guids is None or cv is None:
            return self.find_matches(graph)
        nodes = graph.nodes
        region = {g for g in cv[1] if g in nodes}
        region.update(g for g in cv[2] if g in nodes)
        frontier = set(region)
        for _ in range(DELTA_MATCH_RADIUS):
            nxt = set()
            for g in frontier:
                for e in graph.in_edges.get(g, ()):
                    nxt.add(e.src)
                for e in graph.out_edges.get(g, ()):
                    nxt.add(e.dst)
            nxt -= region
            if not nxt:
                break
            region |= nxt
            frontier = nxt
        if 2 * len(region) >= len(nodes):
            return self.find_matches(graph)  # no shrink to win
        topo = graph.topo_order()
        pos = {n.guid: i for i, n in enumerate(topo)}
        hits = {
            g for g in parent_match_guids if g in nodes and g not in region
        }
        anchors = self.anchor_types
        idx_skips = 0
        cands: List[Node] = []
        for g in region:
            # the seed index rule applies inside the dirty region too:
            # a node whose type cannot anchor the pattern never matches
            # (the DELTA_CHECK oracle below proves it per xfer)
            if anchors is not None and nodes[g].op.op_type not in anchors:
                idx_skips += 1
                continue
            cands.append(nodes[g])
        if idx_skips:
            _INDEX_SKIPS.inc(idx_skips)
        # the vectorized predicate filter feeds the delta scan too —
        # hits is a set re-sorted below, so pruning order is free
        for n in self._vec_prune(graph, cands, pos):
            if self.matcher(graph, n):
                hits.add(n.guid)
        out = [nodes[g] for g in sorted(hits, key=pos.__getitem__)]
        _DELTA_SCANS.inc()
        _DELTA_NODES.inc(len(region))
        _DELTA_SKIPPED.inc(len(nodes) - len(region))
        if out:
            _MATCHES.inc(len(out))
        if DELTA_MATCH_CHECK:
            full = [n for n in topo if self.matcher(graph, n)]
            assert [n.guid for n in out] == [n.guid for n in full], (
                f"delta find_matches diverged from full for {self.name}: "
                f"{[n.guid for n in out]} != {[n.guid for n in full]}"
            )
        return out

    def apply(self, graph: Graph, match: Match) -> Optional[Graph]:
        _APPLIES.inc()
        return _finish_rewrite(graph, self.apply_fn(graph, match), self.name)


# ---------------------------------------------------------------------------
# The splice helpers below are the ONLY audited paths for raw edge-list
# surgery: _insert_before/_insert_after splice a node into an edge
# (COPY-ON-WRITE: the clone shares every untouched edge list with the
# parent and REPLACES — never mutates — the few lists the splice
# changes), and _bypass_node deletes a node and bridges its input to
# every consumer (in-place; rewrites that delete must work on a full
# graph.copy()).  Rewrites compose these instead of hand-rolling edge
# lists, so the delta marks, cache invalidation, and the
# no-consumer-reads-a-deleted-guid assertion live in one place — and
# verification (_finish_rewrite) checks the composed result.


def _bypass_node(g: Graph, guid: int) -> Optional[List[Edge]]:
    """Checked delete-and-bridge splice: remove ``guid`` (a node with a
    single meaningful input edge — the parallel-op/identity shape) and
    reconnect its producer to every consumer, preserving consumer input
    slots.  Returns the bridged edges, or None when the node is not
    bypassable (no input edge) so the caller's apply can decline the
    match instead of corrupting the graph.  MUTATES ``g`` in place:
    callers must pass a full copy(), never a COW clone."""
    in_list = g.in_edges.get(guid)
    if not in_list:
        return None
    up = in_list[0]
    out_edges = list(g.out_edges.get(guid, ()))
    g.remove_node(guid)
    bridged: List[Edge] = []
    for e in out_edges:
        # the audited contract of every delete-style rewrite: no
        # surviving consumer may be left reading a deleted guid
        assert e.dst in g.nodes, (
            f"_bypass_node({guid}): consumer {e.dst} was already deleted"
        )
        ne = Edge(up.src, e.dst, up.src_idx, e.dst_idx)
        g.out_edges[ne.src].append(ne)
        g.in_edges[ne.dst].append(ne)
        bridged.append(ne)
    g._invalidate()
    _mark(g, ins=[e.dst for e in out_edges], outs=(up.src,))
    return bridged


def _insert_before(graph: Graph, node: Node, dst_idx: int, make_op,
                   cow: bool = True) -> Optional[Graph]:
    """New graph with ``make_op(input_shape)`` spliced into the edge
    feeding input ``dst_idx`` of ``node``.  Pass ``cow=False`` when the
    caller will afterwards MUTATE the result in place (remove_node) —
    in-place surgery on a COW clone would corrupt the shared parent."""
    edges = [e for e in graph.in_edges[node.guid] if e.dst_idx == dst_idx]
    if not edges:
        return None
    e = edges[0]
    src_shape = graph.nodes[e.src].op.output_shapes[e.src_idx]
    new_op = make_op(src_shape)
    if new_op is None:
        return None
    g = graph.copy_cow() if cow else graph.copy()
    mid = Node(g._next_guid, new_op)
    g._next_guid += 1
    e1 = Edge(e.src, mid.guid, e.src_idx, 0)
    e2 = Edge(mid.guid, node.guid, 0, e.dst_idx)
    g.nodes[mid.guid] = mid
    g.in_edges[mid.guid] = [e1]
    g.out_edges[mid.guid] = [e2]
    g.in_edges[node.guid] = [
        x for x in g.in_edges[node.guid] if x is not e] + [e2]
    g.out_edges[e.src] = [
        x for x in g.out_edges[e.src] if x is not e] + [e1]
    g._invalidate()  # direct edge-list surgery bypasses add_edge
    _mark(g, ins=(mid.guid, node.guid), outs=(e.src,))
    return g


def _insert_after(graph: Graph, node: Node, out_idx: int, make_op,
                  copy: bool = True) -> Optional[Graph]:
    """``copy=False`` splices into ``graph`` itself — for two-step
    rewrites whose first step already produced a fresh (COW) clone;
    the discarded intermediate was pure overhead.  Either way the
    surgery replaces edge lists, honoring the COW discipline."""
    g = graph.copy_cow() if copy else graph
    shape = node.op.output_shapes[out_idx]
    new_op = make_op(shape)
    if new_op is None:
        return None
    mid = Node(g._next_guid, new_op)
    g._next_guid += 1
    g.nodes[mid.guid] = mid
    old_out = g.out_edges[node.guid]
    outs = [e for e in old_out if e.src_idx == out_idx]
    e1 = Edge(node.guid, mid.guid, out_idx, 0)
    g.out_edges[node.guid] = [
        e for e in old_out if e.src_idx != out_idx] + [e1]
    mid_out = []
    for e in outs:
        ne = Edge(mid.guid, e.dst, 0, e.dst_idx)
        mid_out.append(ne)
        g.in_edges[e.dst] = [
            x for x in g.in_edges[e.dst] if x is not e] + [ne]
    g.in_edges[mid.guid] = [e1]
    g.out_edges[mid.guid] = mid_out
    g._invalidate()
    _mark(g, ins=[mid.guid] + [e.dst for e in outs], outs=(node.guid,))
    return g


_xfer_counter = [0]


def _uname(base: str) -> str:
    _xfer_counter[0] += 1
    return f"{base}_x{_xfer_counter[0]}"


_PROTO_CACHE: Dict[Tuple, object] = {}


def _proto_op(cls, base: str, shape, **kw):
    """Construct-or-clone a parallel-op descriptor.  Operator.__init__
    re-derives output shapes and weight specs — two such constructions
    per candidate across tens of thousands of candidates was a real
    slice of the search — but every instance of (class, logical input
    shape, attrs) is structurally identical except for its unique debug
    name, so later instances clone a cached prototype and stamp a fresh
    name.  Safe because operators are immutable descriptors (ops/base
    docstring); the attrs dict is still copied per clone as insurance."""
    key = (cls, shape.sizes, shape.dtype.value,
           tuple(sorted(kw.items())))
    proto = _PROTO_CACHE.get(key)
    if proto is None:
        proto = cls(_uname(base), [shape], **kw)
        _PROTO_CACHE[key] = proto
        return proto
    clone = object.__new__(cls)
    clone.__dict__.update(proto.__dict__)
    clone.name = _uname(base)
    clone.attrs = dict(proto.attrs)
    return clone


# ---------------------------------------------------------------------------
def make_partition_combine_xfer(
    op_type: OperatorType, degree: int, dim: int = 0
) -> GraphXfer:
    """Repartition(input, dim) → op → Combine — the
    create_partition_*_combine family (reference: substitution.cc:70-115,
    generated per divisor degree :1648-1712)."""

    def matcher(graph: Graph, node: Node) -> bool:
        if node.op.op_type is not op_type:
            return False
        if node.op.op_type.is_parallel_op():
            return False
        out = node.op.output_shapes[0]
        if dim >= out.ndim or out.sizes[dim] % degree != 0:
            return False
        # skip if already wrapped
        preds = [graph.nodes[e.src].op.op_type for e in graph.in_edges[node.guid]]
        return OperatorType.REPARTITION not in preds

    def apply_fn(graph: Graph, node: Node) -> Optional[Graph]:
        g = _insert_before(
            graph,
            node,
            0,
            lambda s: _proto_op(RepartitionOp, "repartition", s,
                                dim=dim, degree=degree)
            if dim < s.ndim and s.sizes[dim] % degree == 0
            else None,
        )
        if g is None:
            return None
        return _insert_after(
            g,
            g.nodes[node.guid],
            0,
            lambda s: _proto_op(CombineOp, "combine", s, dim=dim, degree=1),
            copy=False,
        )

    def vec_filter(c, rows):
        # exactly the matcher's leading predicates, vectorized: dim in
        # range, divisible size, no Repartition predecessor (the types
        # this factory anchors on are never parallel ops)
        if dim >= c["sizes"].shape[1]:
            return c["ndim"][rows] > dim  # all-False mask, right shape
        return (
            (c["ndim"][rows] > dim)
            & (c["sizes"][rows, dim] % degree == 0)
            & ~c["pred_has_repartition"][rows]
        )

    return GraphXfer(
        name=f"partition_{op_type.value}_combine_d{degree}_dim{dim}",
        matcher=matcher,
        apply_fn=apply_fn,
        anchor_types=frozenset({op_type}),
        vec_filter=vec_filter,
    )


def make_replicate_reduce_xfer(op_type: OperatorType, degree: int) -> GraphXfer:
    """Replicate(input) → op(contraction-split) → Reduction — the
    create_replicate_linear_combine / replicate_attention_reduce family
    (reference: substitution.cc:76-93)."""

    def matcher(graph: Graph, node: Node) -> bool:
        if node.op.op_type is not op_type:
            return False
        if node.op.max_replica_degree() % degree != 0 or degree < 2:
            return False
        preds = [graph.nodes[e.src].op.op_type for e in graph.in_edges[node.guid]]
        return OperatorType.REPLICATE not in preds

    def apply_fn(graph: Graph, node: Node) -> Optional[Graph]:
        g = _insert_before(
            graph,
            node,
            0,
            lambda s: _proto_op(ReplicateOp, "replicate", s, degree=degree),
        )
        if g is None:
            return None
        return _insert_after(
            g,
            g.nodes[node.guid],
            0,
            lambda s: _proto_op(ReductionOp, "reduction", s, degree=degree),
            copy=False,
        )

    def vec_filter(c, rows):
        return (
            (c["max_replica"][rows] % degree == 0)
            & ~c["pred_has_replicate"][rows]
        )

    return GraphXfer(
        name=f"replicate_{op_type.value}_reduce_d{degree}",
        matcher=matcher,
        apply_fn=apply_fn,
        anchor_types=frozenset({op_type}),
        vec_filter=vec_filter,
    )


def make_simplify_xfer() -> GraphXfer:
    """Cancel a Repartition directly followed by its inverse Combine
    (reference: graph simplification / fuse_parallel_ops,
    parallel_op.cc:25-58)."""

    def matcher(graph: Graph, node: Node) -> bool:
        if node.op.op_type is not OperatorType.REPARTITION:
            return False
        succs = graph.successors(node.guid)
        return (
            len(succs) == 1
            and graph.nodes[succs[0]].op.op_type is OperatorType.COMBINE
            and graph.nodes[succs[0]].op.attrs.get("dim")
            == node.op.attrs.get("dim")
        )

    def apply_fn(graph: Graph, node: Node) -> Optional[Graph]:
        g = graph.copy()
        comb_guid = g.successors(node.guid)[0]
        # bypass the repartition (bridging its input to the combine),
        # then the combine — two audited splices, same final edges as
        # the old one-shot surgery
        if _bypass_node(g, node.guid) is None:
            return None
        if _bypass_node(g, comb_guid) is None:
            return None
        return g

    return GraphXfer(
        name="cancel_repartition_combine", matcher=matcher, apply_fn=apply_fn,
        anchor_types=frozenset({OperatorType.REPARTITION}),
        # sole successor which is a Combine; the dim equality stays
        # with the matcher
        vec_filter=lambda c, rows: (
            (c["n_succ"][rows] == 1) & c["succ_has_combine"][rows]
        ),
    )


_FUSABLE_ACTS = {
    OperatorType.RELU: "relu",
    OperatorType.SIGMOID: "sigmoid",
    OperatorType.TANH: "tanh",
    OperatorType.GELU: "gelu",
}


def make_linear_activation_fusion_xfer() -> GraphXfer:
    """Fuse Linear followed by a sole-consumer activation into the
    Linear's fused-activation attribute (reference: the generated
    linear_relu fusion xfer, substitution.cc:1619-1758).  XLA fuses the
    kernels either way — the win is a smaller PCG for the search."""

    def matcher(graph: Graph, node: Node) -> bool:
        if node.op.op_type is not OperatorType.LINEAR:
            return False
        if node.op.attrs.get("activation") is not None:
            return False
        succs = graph.successors(node.guid)
        if len(succs) != 1 or len(graph.out_edges[node.guid]) != 1:
            return False
        nxt = graph.nodes[succs[0]].op
        return nxt.op_type in _FUSABLE_ACTS

    def apply_fn(graph: Graph, node: Node) -> Optional[Graph]:
        from flexflow_tpu.ops.linear import LinearOp

        g = graph.copy()
        act_guid = g.successors(node.guid)[0]
        act_name = _FUSABLE_ACTS[g.nodes[act_guid].op.op_type]
        fused = LinearOp(
            _uname(f"{node.op.name}_{act_name}"),
            list(node.op.input_shapes),
            out_dim=node.op.attrs["out_dim"],
            activation=act_name,
            use_bias=node.op.attrs["use_bias"],
            kernel_initializer=node.op._kernel_init,
            bias_initializer=node.op._bias_init,
            param_dtype=node.op.attrs.get("param_dtype", "float32"),
        )
        out_edges = list(g.out_edges[act_guid])
        in_edges = list(g.in_edges[node.guid])
        g.remove_node(node.guid)
        g.remove_node(act_guid)
        nn = Node(g._next_guid, fused)
        g._next_guid += 1
        g.add_node(nn)
        for e in in_edges:
            ne = Edge(e.src, nn.guid, e.src_idx, e.dst_idx)
            g.out_edges[e.src].append(ne)
            g.in_edges[nn.guid].append(ne)
        for e in out_edges:
            ne = Edge(nn.guid, e.dst, 0, e.dst_idx)
            g.out_edges[nn.guid].append(ne)
            g.in_edges[e.dst].append(ne)
        g._invalidate()
        _mark(g, ins=[nn.guid] + [e.dst for e in out_edges],
              outs=[nn.guid] + [e.src for e in in_edges])
        return g

    return GraphXfer(
        name="fuse_linear_activation", matcher=matcher, apply_fn=apply_fn,
        anchor_types=frozenset({OperatorType.LINEAR}),
        vec_filter=lambda c, rows: (
            c["act_is_none"][rows]
            & (c["n_succ"][rows] == 1) & (c["n_out"][rows] == 1)
            & c["succ_has_act"][rows]
        ),
    )


def make_parallel_chain_fusion_xfer() -> GraphXfer:
    """Collapse chains of adjacent parallel ops: a Repartition / Combine
    / Replicate whose every consumer is itself a parallel op is
    redundant — all four are identity computations whose only content is
    the sharding constraint, and the downstream op re-constrains.  This
    is the FusedParallelOp join algebra (reference:
    src/runtime/parallel_op.cc:25-58, fused_parallel_op.cc) expressed as
    deletion: the fused chain IS the last op's constraint."""

    _SPLICEABLE = {
        OperatorType.REPARTITION,
        OperatorType.COMBINE,
        OperatorType.REPLICATE,
    }

    def matcher(graph: Graph, node: Node) -> bool:
        if node.op.op_type not in _SPLICEABLE:
            return False
        outs = graph.out_edges[node.guid]
        if not outs or not graph.in_edges[node.guid]:
            return False
        return all(
            graph.nodes[e.dst].op.op_type.is_parallel_op() for e in outs
        )

    def apply_fn(graph: Graph, node: Node) -> Optional[Graph]:
        g = graph.copy()
        if _bypass_node(g, node.guid) is None:
            return None
        return g

    return GraphXfer(
        name="fuse_parallel_op_chain", matcher=matcher, apply_fn=apply_fn,
        anchor_types=frozenset(_SPLICEABLE),
        vec_filter=lambda c, rows: (
            (c["n_out"][rows] > 0) & (c["n_in"][rows] > 0)
            & c["succ_all_parallel"][rows]
        ),
    )


def make_combine_concat_sink_xfer() -> GraphXfer:
    """N branches each ending Combine(dim d) feeding one Concat: drop
    the per-branch combines and combine ONCE after the concat — the
    branches stay sharded through the concat and the expensive gather
    happens on the concatenated tensor a single time (reference:
    create_combine_inception / create_partition_concat_combine,
    substitution.cc:1693-1758)."""

    def matcher(graph: Graph, node: Node) -> bool:
        if node.op.op_type is not OperatorType.CONCAT:
            return False
        in_edges = graph.in_edges[node.guid]
        if len(in_edges) < 2:
            return False
        keys = set()
        for e in in_edges:
            p = graph.nodes[e.src]
            if p.op.op_type is not OperatorType.COMBINE:
                return False
            if len(graph.out_edges[e.src]) != 1:
                return False
            keys.add((p.op.attrs["dim"], p.op.attrs["degree"]))
        if len(keys) != 1:  # uniform (dim, degree) or the sunk combine
            return False  # would express a different sharding
        return next(iter(keys))[0] != node.op.attrs.get("axis")

    def apply_fn(graph: Graph, node: Node) -> Optional[Graph]:
        g = graph.copy()
        dim = degree = None
        for e in list(g.in_edges[node.guid]):
            comb = g.nodes[e.src]
            dim = comb.op.attrs["dim"]
            degree = comb.op.attrs["degree"]
            if _bypass_node(g, comb.guid) is None:
                return None
        return _insert_after(
            g,
            g.nodes[node.guid],
            0,
            lambda s: _proto_op(CombineOp, "combine", s,
                                dim=dim, degree=degree),
            copy=False,
        )

    return GraphXfer(
        name="sink_combine_through_concat", matcher=matcher, apply_fn=apply_fn,
        anchor_types=frozenset({OperatorType.CONCAT}),
        vec_filter=lambda c, rows: (
            (c["n_in"][rows] >= 2) & c["pred_all_combine"][rows]
        ),
    )


_HOISTABLE_UNARY = {
    OperatorType.RELU,
    OperatorType.SIGMOID,
    OperatorType.TANH,
    OperatorType.GELU,
    OperatorType.EXP,
    OperatorType.IDENTITY,
}


def make_unary_hoist_partition_xfer() -> GraphXfer:
    """A unary op fanning out to k branches that each immediately
    Repartition the same way: hoist ONE Repartition above the unary and
    delete the k copies — the shared activation is resharded once,
    before the cheap elementwise op (reference:
    leading_relu_branch_partition, substitution.cc:1735-1748)."""

    def matcher(graph: Graph, node: Node) -> bool:
        if node.op.op_type not in _HOISTABLE_UNARY:
            return False
        outs = graph.out_edges[node.guid]
        if len(outs) < 2:
            return False
        keys = set()
        for e in outs:
            c = graph.nodes[e.dst]
            if c.op.op_type is not OperatorType.REPARTITION:
                return False
            keys.add((c.op.attrs["dim"], c.op.attrs["degree"]))
        if len(keys) != 1:
            return False
        # not already partitioned above
        preds = [graph.nodes[e.src].op.op_type for e in graph.in_edges[node.guid]]
        return OperatorType.REPARTITION not in preds

    def apply_fn(graph: Graph, node: Node) -> Optional[Graph]:
        reps = [graph.nodes[e.dst] for e in graph.out_edges[node.guid]]
        dim = reps[0].op.attrs["dim"]
        degree = reps[0].op.attrs["degree"]
        g = _insert_before(
            graph,
            node,
            0,
            lambda s: _proto_op(RepartitionOp, "repartition", s,
                                dim=dim, degree=degree)
            if dim < s.ndim and s.sizes[dim] % degree == 0
            else None,
            cow=False,  # the rep deletions below mutate in place
        )
        if g is None:
            return None
        for rep in reps:
            if _bypass_node(g, rep.guid) is None:
                return None
        return g

    return GraphXfer(
        name="hoist_partition_above_unary", matcher=matcher, apply_fn=apply_fn,
        anchor_types=frozenset(_HOISTABLE_UNARY),
        vec_filter=lambda c, rows: (
            (c["n_out"][rows] >= 2) & c["succ_all_repartition"][rows]
            & ~c["pred_has_repartition"][rows]
        ),
    )


_PARTITION_DIMS = {
    OperatorType.LINEAR: (0, 1),
    OperatorType.MULTIHEAD_ATTENTION: (0, 1),  # dim 1 = sequence (SP)
    OperatorType.EW_ADD: (0, 1),
    OperatorType.RELU: (0,),
    OperatorType.CONCAT: (0,),
    OperatorType.SOFTMAX: (0,),
    OperatorType.CONV2D: (0,),
    OperatorType.POOL2D: (0,),
    OperatorType.FLAT: (0,),
    OperatorType.LAYERNORM: (0,),
    OperatorType.EMBEDDING: (0,),
}


def generate_all_pcg_xfers(num_devices: int) -> List[GraphXfer]:
    """All rewrites for the device count, one per divisor degree —
    mirrors generate_all_pcg_xfers (reference: substitution.cc:1619-1758):
    partition/combine families per op type and dim, replicate/reduce
    (row- and head-parallel), branch combining for inception-style PCGs,
    partition hoisting, linear+activation fusion, and the parallel-op
    chain simplifications."""
    degrees = [d for d in range(2, num_devices + 1) if num_devices % d == 0]
    xfers: List[GraphXfer] = [
        BatchEmbeddingsXfer(),
        make_simplify_xfer(),
        make_parallel_chain_fusion_xfer(),
        make_linear_activation_fusion_xfer(),
        make_combine_concat_sink_xfer(),
        make_unary_hoist_partition_xfer(),
    ]
    for d in degrees:
        for t, dims in _PARTITION_DIMS.items():
            for dim in dims:
                xfers.append(make_partition_combine_xfer(t, d, dim=dim))
        xfers.append(make_replicate_reduce_xfer(OperatorType.LINEAR, d))
        xfers.append(make_replicate_reduce_xfer(OperatorType.MULTIHEAD_ATTENTION, d))
    return xfers


class BatchEmbeddingsXfer:
    """Fuse K parallel same-signature embeddings into
    Stack(ids) -> BatchedEmbedding -> Unstack (TPU-native branch
    batching; no reference equivalent — the reference PLACES each
    table's subgraph on different GPUs instead, mapper.cc:371-475,
    which pure-SPMD GSPMD cannot express.  Sharding the stacked branch
    dim realizes the same table parallelism).  Duck-typed like
    GraphXfer (find_matches/apply)."""

    name = "batch_parallel_embeddings"
    # same contract as GraphXfer.anchor_types: the scan below provably
    # only reads EMBEDDING nodes, so the per-op-type seed index serves
    # it (and analysis/proofgen synthesizes its proof graphs from it)
    anchor_types = frozenset({OperatorType.EMBEDDING})

    def find_matches(self, graph: Graph) -> List[Dict[int, int]]:
        idx, pos = _op_type_index(graph)
        embeds = idx.get(OperatorType.EMBEDDING, [])
        _INDEX_SKIPS.inc(len(pos) - len(embeds))
        groups: Dict[Tuple, List[int]] = {}
        for n in embeds:  # per-type lists are topo-ordered — identical
            groups.setdefault(n.op.signature(), []).append(n.guid)
        return [
            {i: g for i, g in enumerate(gs)}
            for gs in groups.values()
            if len(gs) >= 2
        ]

    def apply(self, graph: Graph, match: Dict[int, int]) -> Optional[Graph]:
        from flexflow_tpu.ops.embedding import BatchedEmbeddingOp
        from flexflow_tpu.ops.shape_ops import StackOp, UnstackOp

        g = graph.copy()
        guids = [match[i] for i in range(len(match))]
        ops = [g.nodes[gu].op for gu in guids]
        a = ops[0].attrs
        id_srcs = []
        for gu in guids:
            e = next((e for e in g.in_edges[gu] if e.dst_idx == 0), None)
            if e is None:
                return None
            id_srcs.append((e.src, e.src_idx))
        in_shapes = [g.nodes[s].op.output_shapes[si] for s, si in id_srcs]

        stack = Node(g._next_guid, StackOp(_uname("stack_ids"), in_shapes))
        g._next_guid += 1
        g.add_node(stack)
        for slot, (s, si) in enumerate(id_srcs):
            e = Edge(s, stack.guid, si, slot)
            g.out_edges[s].append(e)
            g.in_edges[stack.guid].append(e)

        be = Node(
            g._next_guid,
            BatchedEmbeddingOp(
                _uname("batched_embed"),
                [stack.op.output_shapes[0]],
                num_tables=len(guids),
                num_entries=a["num_entries"],
                out_dim=a["out_dim"],
                aggr=a["aggr"],
                kernel_initializer=ops[0]._kernel_init,
                param_dtype=a["param_dtype"],
            ),
        )
        g._next_guid += 1
        g.add_node(be)
        e = Edge(stack.guid, be.guid, 0, 0)
        g.out_edges[stack.guid].append(e)
        g.in_edges[be.guid].append(e)

        un = Node(
            g._next_guid, UnstackOp(_uname("unstack"), [be.op.output_shapes[0]])
        )
        g._next_guid += 1
        g.add_node(un)
        e = Edge(be.guid, un.guid, 0, 0)
        g.out_edges[be.guid].append(e)
        g.in_edges[un.guid].append(e)

        consumers = []
        for k, gu in enumerate(guids):
            for old in list(g.out_edges[gu]):
                ne = Edge(un.guid, old.dst, k, old.dst_idx)
                g.out_edges[un.guid].append(ne)
                g.in_edges[old.dst].append(ne)
                consumers.append(old.dst)
        for gu in guids:
            g.remove_node(gu)
        g._invalidate()
        try:
            g.topo_order()
        except ValueError:
            return None
        new = (stack.guid, be.guid, un.guid)
        _mark(g, ins=list(new) + consumers,
              outs=list(new) + [s for s, _ in id_srcs])
        return _finish_rewrite(graph, g, self.name)
