"""Predicted-vs-measured drift reporting.

The entire search rests on ``Simulator.simulate``'s fidelity; a
``DriftReport`` makes that falsifiable per run: the simulator's
predicted step breakdown (``breakdown=`` dict from ``simulate``)
against ``StepProfiler`` measurements, per phase.  Drift beyond
``threshold`` flags the strategy as mispredicted — and, when the
prediction consulted a measured CalibrationTable, flags the TABLE as
stale (the ROADMAP's calibration-staleness follow-up needs exactly
this signal).

Phase semantics are honest about what is measurable: the executed
step is ONE fused XLA program, so only the total step time has a
measured counterpart; the predicted compute/sync split and the host
``dispatch``/``wait`` phases are recorded single-sided (``ratio``
None) rather than invented.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class DriftReport:
    predicted_s: float
    measured_s: float
    ratio: float  # measured / predicted (>1: slower than predicted)
    threshold: float
    stale: bool
    calibrated: bool = False
    calibration_stale: bool = False
    phases: Dict[str, dict] = field(default_factory=dict)
    # per-bucket rows of a gradient-sync SCHEDULE's predicted lanes
    # (search/sync_schedule.py): issue/sync/exposed seconds per bucket.
    # The executed step is one fused XLA program, so each bucket's
    # measured side stays None (honesty rule above) — the schedule's
    # overlap claim is verified by the measured STEP delta between the
    # scheduled and monolithic programs (bench_search --sync-schedule),
    # not by inventing per-bucket host timings.
    sync_buckets: list = field(default_factory=list)

    def to_dict(self) -> dict:
        out = {
            "predicted_s": self.predicted_s,
            "measured_s": self.measured_s,
            "ratio": self.ratio,
            "threshold": self.threshold,
            "stale": self.stale,
            "calibrated": self.calibrated,
            "calibration_stale": self.calibration_stale,
            "phases": self.phases,
        }
        if self.sync_buckets:
            out["sync_buckets"] = self.sync_buckets
        return out

    def __str__(self) -> str:
        flag = (" STALE-CALIBRATION" if self.calibration_stale
                else " STALE" if self.stale else "")
        return (
            f"predicted={self.predicted_s * 1e3:.3f}ms "
            f"measured={self.measured_s * 1e3:.3f}ms "
            f"ratio={self.ratio:.2f}{flag}"
        )


def _phase(predicted_s: Optional[float], measured_s: Optional[float]) -> dict:
    ratio = None
    if (predicted_s and measured_s and predicted_s > 0
            and math.isfinite(predicted_s)):
        ratio = measured_s / predicted_s
    return {"predicted_s": predicted_s, "measured_s": measured_s,
            "ratio": ratio}


def build_drift_report(
    predicted: Dict[str, float],
    measured_step_s: float,
    measured_phases: Optional[Dict[str, dict]] = None,
    threshold: float = 0.5,
    calibrated: bool = False,
) -> Optional[DriftReport]:
    """``predicted`` is a ``Simulator.simulate(breakdown=...)`` dict
    (``total_s``/``compute_end_s``/``comm_end_s``/...); ``measured_phases``
    is ``StepProfiler.phase_summary()``.  None when there is nothing
    comparable (no finite prediction or measurement)."""
    total = predicted.get("total_s")
    if (not total or not math.isfinite(total) or not measured_step_s
            or not math.isfinite(measured_step_s)):
        return None
    ratio = measured_step_s / total
    stale = ratio > 1.0 + threshold or ratio < 1.0 / (1.0 + threshold)
    phases: Dict[str, dict] = {
        "step": _phase(total, measured_step_s),
        "compute": _phase(predicted.get("compute_end_s"), None),
        "sync": _phase(predicted.get("comm_end_s"), None),
    }
    if predicted.get("sync_exposed_s") is not None:
        # the EXPOSED sync tail the schedule search minimizes — the
        # single-sided prediction whose measured counterpart is the
        # scheduled-vs-monolithic step delta
        phases["sync_exposed"] = _phase(predicted["sync_exposed_s"], None)
    # per-link-level predicted comm rows (hierarchical topologies): the
    # slow DCN class's share is visible separately from intra-slice
    # traffic, so drift on the cross-slice links can be attributed
    # without un-mixing one aggregate number.  Single-sided like the
    # other sub-step phases (one fused program has no per-link timer).
    for name, secs in (predicted.get("sync_levels_s") or {}).items():
        phases[f"sync_{name}"] = _phase(secs, None)
    for name, stats in (measured_phases or {}).items():
        phases[name] = _phase(None, stats.get("mean_s"))
    buckets = []
    for row in predicted.get("sync_buckets") or []:
        buckets.append({
            "name": row.get("name"),
            # the STABLE lane id shared with comm_schedule records and
            # the executed step's trace annotations — what a real
            # device_trace capture tag-matches against
            # (obs/trace_ingest.apply_lane_measurements fills the
            # measured fields below from a matched capture)
            "lane": row.get("lane") or f"bucket:{row.get('name')}:sync",
            "precision": row.get("precision"),
            "plan": row.get("plan"),
            "ops": len(row.get("ops") or []),
            "predicted_ready_s": row.get("ready_s"),
            "predicted_issue_s": row.get("start_s"),
            "predicted_sync_s": row.get("sync_s"),
            "predicted_exposed_s": row.get("exposed_s"),
            "predicted_levels_s": row.get("levels") or {},
            # None until a device-trace capture is matched — the fused
            # program has no per-bucket host timer without one
            "measured_s": None,
            "measured_issue_s": None,
        })
    return DriftReport(
        predicted_s=float(total),
        measured_s=float(measured_step_s),
        ratio=float(ratio),
        threshold=float(threshold),
        stale=bool(stale),
        calibrated=bool(calibrated),
        calibration_stale=bool(stale and calibrated),
        phases=phases,
        sync_buckets=buckets,
    )
