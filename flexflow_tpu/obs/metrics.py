"""In-process metrics registry: counters, gauges, histograms.

Always-on (an increment is a python int add — cheaper than the
branchy alternatives) and in-memory only; the event bus persists a
snapshot on demand (``METRICS.emit_snapshot()``) and ``model.fit``
routes its step-profile summary through here instead of ad-hoc
prints.  Metric objects are stable across ``reset()`` so modules may
cache them at import time.
"""

from __future__ import annotations

import random as _random
import threading
import zlib as _zlib
from typing import Dict, List


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Bounded-sample histogram: exact count/sum/min/max, percentiles
    from a SEEDED RESERVOIR (Vitter's algorithm R) over the whole
    stream.  The previous first-``max_samples`` window froze a
    long-running serving process's p99 on its first minutes of
    traffic; the reservoir keeps a uniform sample of everything
    observed at the same bounded memory.  The seed derives from the
    metric name, so a replayed stream reproduces the identical summary
    (deterministic under test)."""

    __slots__ = ("name", "count", "sum", "min", "max", "_samples",
                 "max_samples", "_rng")

    def __init__(self, name: str, max_samples: int = 4096):
        self.name = name
        self.max_samples = max_samples
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._samples: List[float] = []
        self._rng = _random.Random(
            _zlib.crc32(name.encode("utf-8", "ignore")))

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if len(self._samples) < self.max_samples:
            self._samples.append(v)
        else:
            # algorithm R: keep each of the `count` observations with
            # probability max_samples/count — a uniform sample of the
            # whole stream, not a frozen prefix
            j = self._rng.randrange(self.count)
            if j < self.max_samples:
                self._samples[j] = v

    def summary(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0}
        s = sorted(self._samples)

        def pct(p: float) -> float:
            return s[min(len(s) - 1, int(p * (len(s) - 1) + 0.5))]

        return {
            "count": self.count,
            "sum": self.sum,
            "mean": self.sum / self.count,
            "min": self.min,
            "max": self.max,
            "p50": pct(0.50),
            "p95": pct(0.95),
            "p99": pct(0.99),
        }


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": {
                    k: h.summary() for k, h in self._histograms.items()
                },
            }

    def reset(self) -> None:
        """Zero every metric IN PLACE — cached metric objects held by
        instrumented modules stay valid."""
        with self._lock:
            for c in self._counters.values():
                c.value = 0
            for g in self._gauges.values():
                g.value = 0.0
            for h in self._histograms.values():
                h.count = 0
                h.sum = 0.0
                h.min = float("inf")
                h.max = float("-inf")
                h._samples.clear()
                # re-seed so a replay after reset() reproduces the
                # identical reservoir (the determinism contract)
                h._rng = _random.Random(
                    _zlib.crc32(h.name.encode("utf-8", "ignore")))

    def emit_snapshot(self) -> None:
        """Persist the current snapshot through the event bus (no-op
        when the bus is disabled)."""
        from flexflow_tpu.obs.events import BUS

        if BUS.enabled:
            BUS.emit("metrics.snapshot", **self.snapshot())


METRICS = MetricsRegistry()
