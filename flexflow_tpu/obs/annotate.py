"""Named trace annotations: the search's lane vocabulary stamped onto
real execution.

The simulator prices weight-gradient sync as LANES — per-bucket
collective records named ``bucket:<name>:sync`` (scheduled) or
``<op>:sync`` (monolithic) in ``Simulator.simulate``'s
``comm_schedule``/``sync_buckets`` output.  This module stamps the same
identifiers onto the EXECUTED program so a real
``runtime.profiler.device_trace`` capture carries them and
``obs/trace_ingest.py`` can match measured events to predicted lanes
by TAG EQUALITY — never by fuzzy kernel names:

* ``phase_span(tag)`` — a host-side ``jax.profiler.TraceAnnotation``
  around dispatch-level phases (``ff.phase/step``,
  ``ff.phase/decode_frame``); armed only while a capture is active
  (``arm()``/``disarm()``, driven by ``runtime.profiler.device_trace``
  and ``model.fit``'s capture window), one boolean check otherwise.
* ``lane_stamp(tag, dep)`` — an ordered ``io_callback`` INSIDE the
  jitted step that (a) emits a zero-length ``TraceAnnotation`` marker
  into the live trace at the moment the runtime reaches that point of
  the dataflow and (b) records the host timestamp in ``LANES``.  A
  bucket's collective is bracketed by ``<tag>#issue``/``<tag>#done``
  markers whose data dependences (payload → issue → collective →
  done) pin them to the lane's real execution window.  Stamps are
  lowered only when ``FFConfig.device_trace_dir`` is set — the default
  program is byte-identical to history (zero cost when the bus/trace
  is off).

CPU-mesh caveat (honesty rule): the host trace carries these named
scopes and the markers measure host-observed issue/completion of the
lane's thunks; ICI/DCN wire behavior stays simulated until the same
capture runs on a real TPU, where ``scope()``'s ``jax.named_scope``
additionally prefixes the lane tag onto the lowered HLO (visible in
the xplane device rows).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List

LANE_PREFIX = "ff.lane/"
PHASE_PREFIX = "ff.phase/"
STEP_PHASE = PHASE_PREFIX + "step"
DECODE_PHASE = PHASE_PREFIX + "decode_frame"
PREFILL_PHASE = PHASE_PREFIX + "prefill_chunk"
ISSUE_MARK = "#issue"
DONE_MARK = "#done"

# host-annotation arming: flipped by the device_trace context manager /
# fit's capture window.  The disarmed fast path is one module-global
# load + branch — the same contract as the event bus.
_ARMED = False


def arm() -> None:
    global _ARMED
    _ARMED = True


def disarm() -> None:
    global _ARMED
    _ARMED = False


def armed() -> bool:
    return _ARMED


_NULL = contextlib.nullcontext()


def lane_tag(lane_id: str) -> str:
    """The annotation tag for a simulator lane id (e.g.
    ``bucket:b0:sync`` -> ``ff.lane/bucket:b0:sync``)."""
    return LANE_PREFIX + lane_id


def parse_tag(name: str):
    """``(lane_id, marker)`` for a lane tag (marker ``"issue"``/
    ``"done"``/``None`` for a plain span), or None when ``name`` is not
    a lane tag."""
    if not name.startswith(LANE_PREFIX):
        return None
    body = name[len(LANE_PREFIX):]
    for mark, label in ((ISSUE_MARK, "issue"), (DONE_MARK, "done")):
        if body.endswith(mark):
            return body[: -len(mark)], label
    return body, None


def phase_span(tag: str):
    """Context manager: a host TraceAnnotation when a capture is
    armed, a shared null context otherwise (one boolean on the off
    path)."""
    if not _ARMED:
        return _NULL
    import jax

    return jax.profiler.TraceAnnotation(tag)


def scope(lane_id: str):
    """Tracing-time ``jax.named_scope`` carrying the lane tag — zero
    runtime cost (HLO metadata only); a TPU xplane capture shows the
    lane's ops under this prefix."""
    import jax

    return jax.named_scope(lane_tag(lane_id))


class LaneRecorder:
    """Host-side lane stamp buffer: (tag, perf_counter seconds) rows in
    arrival order, appended by the ``lane_stamp`` callbacks.  The
    trace-file ingest is the primary consumer of lane timings; this
    buffer is the in-process cross-check (and the only measured side
    when no capture is running)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.rows: List[tuple] = []

    def record(self, tag: str, t: float) -> None:
        with self._lock:
            self.rows.append((tag, t))

    def clear(self) -> None:
        with self._lock:
            self.rows.clear()

    def spans(self) -> Dict[str, List[tuple]]:
        """lane_id -> [(issue_t, done_t), ...] paired in arrival
        order; unpaired stamps are dropped."""
        with self._lock:
            rows = list(self.rows)
        open_t: Dict[str, float] = {}
        out: Dict[str, List[tuple]] = {}
        for tag, t in rows:
            parsed = parse_tag(tag)
            if parsed is None:
                continue
            lane, marker = parsed
            if marker == "issue":
                open_t[lane] = t
            elif marker == "done" and lane in open_t:
                out.setdefault(lane, []).append((open_t.pop(lane), t))
        return out


LANES = LaneRecorder()


def lane_stamp(lane_id: str, marker: str, dep):
    """A host-callback stamp inside a jitted program: returns a
    float32 scalar (always 0.0) that depends on ``dep``; callers MUST
    thread the result into downstream live values — that data
    dependence both pins the stamp's execution point (after ``dep``,
    before its consumers) and keeps it from being dead-code
    eliminated.  At run time the callback records
    ``time.perf_counter`` into ``LANES`` and emits a marker
    ``TraceAnnotation`` so an active ``device_trace`` capture carries
    the tag.  ``pure_callback`` rather than the ordered ``io_callback``
    on purpose: the ordered-effect token changes the jitted program's
    entry parameters, which the 0.4.x SPMD sharding-propagation pass
    rejects on the sharded train step.  Call only from lowering code
    that is itself gated (``FFConfig.device_trace_dir``)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    tag = lane_tag(lane_id) + (ISSUE_MARK if marker == "issue"
                               else DONE_MARK)

    def _cb(_x):
        LANES.record(tag, time.perf_counter())
        with jax.profiler.TraceAnnotation(tag):
            pass
        return np.float32(0.0)

    return jax.pure_callback(_cb, jax.ShapeDtypeStruct((), jnp.float32),
                             dep)


def lane_stamps_armed(config) -> bool:
    """Whether the lowering should thread lane stamps into the step:
    opt-in via ``FFConfig.device_trace_dir`` (the capture consumer) —
    the default program stays byte-identical to history."""
    return bool(getattr(config, "device_trace_dir", None))
