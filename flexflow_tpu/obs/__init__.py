"""Unified observability for the search/compile/runtime stack.

Three pieces, all stdlib-light so ``import flexflow_tpu.obs`` stays
cheap and tooling (tools/ffobs.py) can read artifacts without jax:

* ``events`` — a structured-event bus with a JSONL sink.  Gated by
  ``FLEXFLOW_TPU_OBS=<path>`` or ``FFConfig.obs_log_file``; every
  ``emit()`` is a single boolean check when disabled, so the
  instrumented hot paths (search candidate loops, fit steps) pay
  near-zero overhead off.
* ``metrics`` — an in-process registry of counters/gauges/histograms
  (DP memo hit rates, substitution match counts, fit step stats) that
  replaces ad-hoc ``print(f"PROFILE ...")`` reporting.
* ``trace``/``drift`` — Chrome-trace (Perfetto-loadable) export of the
  SIMULATED task timeline, and ``DriftReport``: predicted-vs-measured
  step-time comparison that flags calibration staleness.
* ``annotate``/``trace_ingest`` — the MEASURED side of the loop:
  ``jax.profiler`` annotations keyed by phase and sync-bucket lane id
  stamped onto the executed step, and a parser that matches a real
  ``device_trace`` capture back to the simulator's predicted lanes by
  tag (``LaneDriftReport``).
* ``exposition`` — Prometheus text rendering of the metrics registry
  (+ optional stdlib HTTP endpoint, ``FLEXFLOW_TPU_METRICS_PORT``).
* ``tracing``/``flight``/``slo`` — request-scoped span trees for the
  serving fleet (trace ids minted at enqueue, Chrome-trace export,
  ``FLEXFLOW_TPU_TRACE``), an always-on bounded flight recorder
  dumped to a post-mortem JSONL on faults/fallbacks/exit
  (``FLEXFLOW_TPU_FLIGHT_DIR``), and multi-window SLO burn-rate
  computation feeding the controller an earlier trigger than raw
  p99 drift.

The reference has no analogue (its search logs through
RecursiveLogger only); GSPMD-style sharding-decision introspection and
predicted-timeline artifacts are what operators actually debug with.
"""

from flexflow_tpu.obs.drift import DriftReport, build_drift_report  # noqa: F401
from flexflow_tpu.obs.events import BUS, EventBus, validate_event  # noqa: F401
from flexflow_tpu.obs.exposition import (  # noqa: F401
    maybe_start_from_env as _maybe_start_metrics,
    render_prometheus,
    start_metrics_server,
)
from flexflow_tpu.obs.flight import FLIGHT, FlightRecorder  # noqa: F401
from flexflow_tpu.obs.metrics import METRICS, MetricsRegistry  # noqa: F401
from flexflow_tpu.obs.slo import burn_rates, first_fire_indices  # noqa: F401
from flexflow_tpu.obs.trace import write_chrome_trace  # noqa: F401
from flexflow_tpu.obs.trace_ingest import (  # noqa: F401
    LaneDriftReport,
    apply_lane_measurements,
    build_lane_drift_report,
)
from flexflow_tpu.obs.tracing import (  # noqa: F401
    Span,
    TRACER,
    Tracer,
    forest_stats,
    span_forest,
)

__all__ = [
    "BUS",
    "EventBus",
    "FLIGHT",
    "FlightRecorder",
    "METRICS",
    "MetricsRegistry",
    "DriftReport",
    "LaneDriftReport",
    "Span",
    "TRACER",
    "Tracer",
    "apply_lane_measurements",
    "build_drift_report",
    "build_lane_drift_report",
    "burn_rates",
    "first_fire_indices",
    "forest_stats",
    "render_prometheus",
    "span_forest",
    "start_metrics_server",
    "validate_event",
    "write_chrome_trace",
]

# FLEXFLOW_TPU_METRICS_PORT arms the exposition endpoint process-wide
_maybe_start_metrics()
